"""Headline benchmark: BASELINE config 3 (PBT, small CNN, CIFAR-10).

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "trials/sec/chip", "vs_baseline": N}

Unit of work ("trial") = one PBT member-generation: steps_per_gen
training steps + a full validation eval for one population member.
Both sides do identical work on identical shapes:

- TPU side: the fused on-device PBT sweep (train/fused_pbt.py) —
  population x generations member-generations in one XLA program on
  the real chip. A structurally-identical warmup run (1 generation)
  populates the compile cache first so the measurement is steady-state
  throughput, which is what a >1-generation sweep experiences.
- Baseline: the CPU process-pool backend evaluating the same member-
  generations — one process per trial, the same execution model as the
  reference's per-rank MPI workers (no MPI exists in this container;
  see BASELINE.md — the reference itself has no published numbers).
  The pool is warmed with a 1-step round first so worker spawn/import
  time is excluded; the baseline gets its batch-parallelism for free.

vs_baseline = tpu_trials_per_sec / cpu_trials_per_sec_per_worker_pool.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def bench_tpu(population, generations, steps, seed):
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        "/tmp/jax_cache_tpu" if jax.default_backend() != "cpu" else "/tmp/jax_cache_cpu",
    )
    from mpi_opt_tpu.ops.pbt import PBTConfig
    from mpi_opt_tpu.train.fused_pbt import fused_pbt
    from mpi_opt_tpu.workloads import get_workload

    wl = get_workload("cifar10_cnn")
    log(f"[bench] tpu side: backend={jax.default_backend()} pop={population} "
        f"gens={generations} steps={steps}")
    # warmup is an IDENTICAL invocation: generations is a static jit arg
    # (scan length), so only the same-arg call guarantees the measured
    # run is a pure cache hit / steady-state execution
    t0 = time.perf_counter()
    fused_pbt(wl, population=population, generations=generations, steps_per_gen=steps, seed=seed)
    log(f"[bench] warmup (compile+run) {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    result = fused_pbt(
        wl, population=population, generations=generations, steps_per_gen=steps, seed=seed
    )
    wall = time.perf_counter() - t0
    trials = population * generations
    log(f"[bench] tpu: {trials} member-gens in {wall:.2f}s -> "
        f"{trials/wall:.3f} trials/s/chip; best={result['best_score']:.3f}")
    return trials / wall


def bench_cpu_baseline(steps, seed, n_workers):
    """Reference-architecture stand-in: process-per-trial evaluation."""
    import jax

    from mpi_opt_tpu.backends.cpu import CPUBackend
    from mpi_opt_tpu.trial import Trial
    from mpi_opt_tpu.workloads import get_workload

    wl = get_workload("cifar10_cnn")
    space = wl.default_space()
    be = CPUBackend(wl, n_workers=n_workers, seed=seed)

    def make_trials(base_id, budget):
        out = []
        for i in range(n_workers):
            key = jax.random.fold_in(jax.random.key(seed), base_id + i)
            unit = __import__("numpy").asarray(space.sample_unit(key, 1))[0]
            out.append(
                Trial(
                    trial_id=base_id + i,
                    params=space.materialize_row(unit),
                    unit=unit,
                    budget=budget,
                )
            )
        return out

    log(f"[bench] cpu baseline: warming {n_workers}-process pool")
    t0 = time.perf_counter()
    # warm with the SAME budget: train_segment's scan length is a static
    # jit arg, so a budget=1 warmup would leave the full compile inside
    # the measured window and understate the baseline
    be.evaluate(make_trials(0, steps))
    log(f"[bench] pool warm in {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    be.evaluate(make_trials(1000, steps))
    wall = time.perf_counter() - t0
    be.close()
    log(f"[bench] cpu: {n_workers} member-gens in {wall:.2f}s -> "
        f"{n_workers/wall:.4f} trials/s ({n_workers} procs)")
    return n_workers / wall


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--population", type=int, default=32)
    p.add_argument("--generations", type=int, default=4)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--workers", type=int, default=min(8, os.cpu_count() or 8))
    p.add_argument("--skip-baseline", action="store_true")
    args = p.parse_args()

    tpu_tps = bench_tpu(args.population, args.generations, args.steps, args.seed)
    if args.skip_baseline:
        cpu_tps = None
        vs = 1.0
    else:
        cpu_tps = bench_cpu_baseline(args.steps, args.seed, args.workers)
        vs = tpu_tps / cpu_tps
    print(
        json.dumps(
            {
                "metric": "pbt_cifar10_cnn_member_generations_per_sec_per_chip",
                "value": round(tpu_tps, 4),
                "unit": "trials/sec/chip",
                "vs_baseline": round(vs, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
