"""Headline benchmark: the north-star PBT sweep (small CNN, CIFAR-10).

Prints exactly ONE JSON line on stdout. Required keys:
    {"metric": ..., "value": N, "unit": "trials/sec/chip", "vs_baseline": N}
plus honesty/utilization extras: mfu, flops accounting, BOTH baseline
normalizations, and wall-clock-to-target-accuracy (the second metric of
record in BASELINE.json).

Unit of work ("trial") = one PBT member-generation: steps_per_gen
training steps + a full validation eval for one population member.
Both sides do identical work on identical shapes.

- TPU side: the fused on-device PBT sweep (train/fused_pbt.py) —
  population x generations member-generations in one XLA program on
  the real chip. A structurally-identical warmup run (same static args)
  populates the compile cache first so the measurement is steady-state
  throughput, which is what a >1-generation sweep experiences.
  The default population is 256 — the north-star sweep size
  (BASELINE.json: "256-member PBT CIFAR-10 CNN sweep").

- Baseline: the CPU process-pool backend evaluating the same member-
  generations — one process per trial, the same execution model as the
  reference's per-rank MPI workers (no MPI exists in this container;
  see BASELINE.md — the reference itself has no published numbers).
  The pool is warmed first so worker spawn/import/compile time is
  excluded.

Baseline normalizations (both reported; the headline ``vs_baseline`` is
the HONEST one):
- ``vs_baseline`` / ``vs_8rank_equiv``: TPU throughput vs an 8-rank
  pool extrapolated LINEARLY from the measured per-process rate
  (8 x per-proc trials/sec). This box has os.cpu_count()=1, so a real
  8-worker pool would timeshare one core; linear extrapolation is the
  generous-to-the-baseline stand-in for the north star's "8-rank MPI",
  assuming perfect scaling and zero MPI overhead.
- ``vs_measured_pool``: TPU throughput vs the pool as actually measured
  on this box (the round-1 number's definition).

MFU: sweep FLOPs (composed from single-trip XLA cost-analysis pieces —
see utils/flops.py for why whole-program counts can't be trusted)
divided by (wall x chip bf16 peak), and also divided by the *measured*
matmul cap of this device (tunneled chips deliver far below nominal;
see PERF_NOTES.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def bench_tpu(args):
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        "/tmp/jax_cache_tpu" if jax.default_backend() != "cpu" else "/tmp/jax_cache_cpu",
    )
    from mpi_opt_tpu.train.fused_pbt import fused_pbt
    from mpi_opt_tpu.utils.flops import mfu, population_sweep_flops
    from mpi_opt_tpu.utils.profiling import profile_window
    from mpi_opt_tpu.workloads import get_workload

    wl = get_workload("cifar10_cnn")
    population, generations, steps = args.population, args.generations, args.steps
    log(f"[bench] tpu side: backend={jax.default_backend()} pop={population} "
        f"gens={generations} steps={steps} member_chunk={args.member_chunk} "
        f"gen_chunk={args.gen_chunk}")
    kw = dict(
        population=population,
        generations=generations,
        steps_per_gen=steps,
        seed=args.seed,
        member_chunk=args.member_chunk,
        gen_chunk=args.gen_chunk,
    )
    # warmup is an IDENTICAL invocation: generations is a static jit arg
    # (scan length), so only the same-arg call guarantees the measured
    # run is a pure cache hit / steady-state execution
    t0 = time.perf_counter()
    fused_pbt(wl, **kw)
    log(f"[bench] warmup (compile+run) {time.perf_counter()-t0:.1f}s")
    with profile_window(args.profile_dir):
        t0 = time.perf_counter()
        result = fused_pbt(wl, **kw)
        wall = time.perf_counter() - t0
    trials = population * generations
    tps = trials / wall
    # flops accounting AFTER the timed window (it lowers/compiles tiny
    # one-member programs — that must not count against the sweep)
    flops = population_sweep_flops(
        wl, population, generations, steps, n_evals=generations
    )

    # wall-clock to target val-acc (metric of record #2)
    from mpi_opt_tpu.utils.metrics import wall_to_target as _wtt

    curve = [float(v) for v in result["best_curve"]]
    wall_to_target = _wtt(curve, wall, args.target_acc)

    util = mfu(flops, wall, jax.devices()[0])
    cap_tf = measure_platform_cap() if jax.default_backend() == "tpu" else None
    log(f"[bench] tpu: {trials} member-gens in {wall:.2f}s -> {tps:.3f} trials/s/chip; "
        f"best={result['best_score']:.3f} curve={[round(v, 3) for v in curve]}")
    if flops:
        log(f"[bench] flops={flops:.3e} ({flops/wall/1e12:.1f} TFLOP/s, "
            f"mfu={'-' if util is None else round(util, 4)} of nominal peak, "
            f"platform cap {cap_tf and round(cap_tf, 1)} TF/s)")
    return {
        "platform_matmul_tflops": round(cap_tf, 1) if cap_tf else None,
        "mfu_vs_platform_cap": (
            round(flops / wall / 1e12 / cap_tf, 4) if flops and cap_tf else None
        ),
        "tps": tps,
        "wall": wall,
        "best": float(result["best_score"]),
        "curve": curve,
        "wall_to_target": wall_to_target,
        "flops": flops,
        "mfu": util,
        "device": jax.devices()[0].device_kind,
    }


def measure_platform_cap(iters=8):
    """Measured matmul throughput cap of THIS device (TF/s).

    bf16 4096^3 matmuls chained inside one program — ideal MXU shapes,
    ~1.1 TFLOP per dispatch so tunnel dispatch overhead is noise. On
    nominal hardware this approaches the datasheet peak; on virtualized
    /tunneled devices it is the *real* ceiling (measured 2026-07-30 on
    this container's tunneled v5e: 64.8 TF/s vs 394 nominal), and MFU
    against nominal peak alone would wildly understate how much of the
    attainable machine the sweep uses. Reported alongside nominal-peak
    MFU, never instead of it.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    M = 4096
    a = jax.random.normal(jax.random.key(0), (M, M), jnp.bfloat16)
    b = jax.random.normal(jax.random.key(1), (M, M), jnp.bfloat16) * 0.01

    @jax.jit
    def step(b):
        for _ in range(8):
            b = (a @ b) * 1e-3
        return b.astype(jnp.bfloat16)

    b1 = step(b)
    np.asarray(b1[0, 0])
    t0 = time.perf_counter()
    for _ in range(iters):
        b1 = step(b1)
    np.asarray(b1[0, 0])
    dt = (time.perf_counter() - t0) / iters
    return 8 * 2 * M**3 / dt / 1e12


def bench_cpu_baseline(steps, seed, n_workers):
    """Reference-architecture stand-in: process-per-trial evaluation."""
    import jax

    from mpi_opt_tpu.backends.cpu import CPUBackend
    from mpi_opt_tpu.trial import Trial
    from mpi_opt_tpu.workloads import get_workload

    wl = get_workload("cifar10_cnn")
    space = wl.default_space()
    be = CPUBackend(wl, n_workers=n_workers, seed=seed)

    def make_trials(base_id, budget):
        out = []
        for i in range(n_workers):
            key = jax.random.fold_in(jax.random.key(seed), base_id + i)
            unit = __import__("numpy").asarray(space.sample_unit(key, 1))[0]
            out.append(
                Trial(
                    trial_id=base_id + i,
                    params=space.materialize_row(unit),
                    unit=unit,
                    budget=budget,
                )
            )
        return out

    log(f"[bench] cpu baseline: warming {n_workers}-process pool")
    t0 = time.perf_counter()
    # warm with the SAME budget: train_segment's scan length is a static
    # jit arg, so a budget=1 warmup would leave the full compile inside
    # the measured window and understate the baseline
    be.evaluate(make_trials(0, steps))
    log(f"[bench] pool warm in {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    be.evaluate(make_trials(1000, steps))
    wall = time.perf_counter() - t0
    be.close()
    pool_tps = n_workers / wall
    log(f"[bench] cpu: {n_workers} member-gens in {wall:.2f}s -> "
        f"{pool_tps:.4f} trials/s ({n_workers} procs)")
    return pool_tps


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--population", type=int, default=256)
    p.add_argument("--generations", type=int, default=4)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--member-chunk", type=int, default=32)
    p.add_argument(
        "--gen-chunk",
        type=int,
        default=1,
        help="generations per program launch (tunneled chips kill >60s programs)",
    )
    p.add_argument("--target-acc", type=float, default=0.70)
    p.add_argument("--workers", type=int, default=min(8, os.cpu_count() or 8))
    p.add_argument("--skip-baseline", action="store_true")
    p.add_argument("--profile-dir", default=None)
    args = p.parse_args()

    tpu = bench_tpu(args)
    record = {
        "metric": "pbt_cifar10_cnn_member_generations_per_sec_per_chip",
        "value": round(tpu["tps"], 4),
        "unit": "trials/sec/chip",
        "population": args.population,
        "generations": args.generations,
        "steps_per_gen": args.steps,
        "device": tpu["device"],
        "best_val_acc": round(tpu["best"], 4),
        "target_acc": args.target_acc,
        "wall_to_target_s": (
            round(tpu["wall_to_target"], 2) if tpu["wall_to_target"] is not None else None
        ),
        "flops_total": tpu["flops"],
        "tflops_per_sec": (
            round(tpu["flops"] / tpu["wall"] / 1e12, 2) if tpu["flops"] else None
        ),
        "mfu": round(tpu["mfu"], 4) if tpu["mfu"] is not None else None,
        "platform_matmul_tflops": tpu["platform_matmul_tflops"],
        "mfu_vs_platform_cap": tpu["mfu_vs_platform_cap"],
    }
    if args.skip_baseline:
        record["vs_baseline"] = 1.0
        record["baseline"] = "skipped"
    else:
        pool_tps = bench_cpu_baseline(args.steps, args.seed, args.workers)
        per_proc = pool_tps / args.workers
        rank8 = 8.0 * per_proc
        record["cpu_pool_workers"] = args.workers
        record["cpu_pool_trials_per_sec"] = round(pool_tps, 4)
        record["vs_measured_pool"] = round(tpu["tps"] / pool_tps, 2)
        record["vs_8rank_equiv"] = round(tpu["tps"] / rank8, 2)
        # the headline number is the HONEST normalization: vs an 8-rank
        # pool extrapolated linearly from the measured per-process rate
        record["vs_baseline"] = record["vs_8rank_equiv"]
        record["baseline"] = (
            f"8-rank equivalent = 8 x measured per-process CPU rate "
            f"({per_proc:.4f} trials/s/proc, {args.workers}-proc pool, "
            f"cpu_count={os.cpu_count()})"
        )
    print(json.dumps(record))


if __name__ == "__main__":
    main()
