"""Headline benchmark: the north-star PBT sweep (small CNN, CIFAR-10).

Prints exactly ONE JSON line on stdout. Required keys:
    {"metric": ..., "value": N, "unit": "trials/sec/chip", "vs_baseline": N}
plus honesty/utilization extras: mfu, flops accounting, BOTH baseline
normalizations, and wall-clock-to-target-accuracy (the second metric of
record in BASELINE.json).

Unit of work ("trial") = one PBT member-generation: steps_per_gen
training steps + a full validation eval for one population member.
Both sides do identical work on identical shapes.

- TPU side: the fused on-device PBT sweep (train/fused_pbt.py) —
  population x generations member-generations in one XLA program on
  the real chip. A structurally-identical warmup run (same static args)
  populates the compile cache first so the measurement is steady-state
  throughput, which is what a >1-generation sweep experiences.
  The default population is 256 — the north-star sweep size
  (BASELINE.json: "256-member PBT CIFAR-10 CNN sweep").

- Baseline: a torch-CPU member-generation — the reference's actual
  per-rank stack (torch/keras on CPU over MPI), same layer shapes,
  batch, and eval size, single-threaded like one MPI rank. Measured
  directly (~80 s/member-gen on this box, fast enough to measure
  live). This is deliberately the STRONGEST honest baseline available:
  our own CPU backend (XLA:CPU) executes conv training at ~0.7 GFLOP/s
  on this host vs torch's ~46 GFLOP/s — a pathology of XLA:CPU codegen
  here, not a property of the reference — so using it as the
  denominator would inflate the speedup ~65x. The jax-pool protocol
  remains available via --baseline-pool (cached in CPU_BASELINE.json;
  takes ~40 min first-ever). Full story: PERF_NOTES.md.

Baseline normalizations (both reported; the headline ``vs_baseline`` is
the 8-rank one):
- ``vs_baseline`` / ``vs_8rank_equiv``: TPU throughput vs an 8-rank
  pool at 8x the measured single-rank rate. This box has
  os.cpu_count()=1, so a real 8-rank pool would timeshare one core;
  linear scaling is the generous-to-the-baseline stand-in for the
  north star's "8-rank MPI" (zero MPI overhead charged).
- ``vs_one_rank``: TPU throughput vs the single measured rank.

MFU: sweep FLOPs (composed from single-trip XLA cost-analysis pieces —
see utils/flops.py for why whole-program counts can't be trusted)
divided by (wall x chip bf16 peak), and also divided by the *measured*
matmul cap of this device (tunneled chips deliver far below nominal;
see PERF_NOTES.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def bench_tpu(args):
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        "/tmp/jax_cache_tpu" if jax.default_backend() != "cpu" else "/tmp/jax_cache_cpu",
    )
    from mpi_opt_tpu.train.fused_pbt import fused_pbt
    from mpi_opt_tpu.utils.flops import mfu, population_sweep_flops
    from mpi_opt_tpu.utils.profiling import profile_window
    from mpi_opt_tpu.workloads import get_workload

    wl = get_workload("cifar10_cnn")
    population, generations, steps = args.population, args.generations, args.steps
    log(f"[bench] tpu side: backend={jax.default_backend()} pop={population} "
        f"gens={generations} steps={steps} member_chunk={args.member_chunk} "
        f"gen_chunk={args.gen_chunk}")
    kw = dict(
        population=population,
        generations=generations,
        steps_per_gen=steps,
        seed=args.seed,
        member_chunk=args.member_chunk,
        gen_chunk=args.gen_chunk,
    )
    # span tracing across warmup + measurement (opt-out: --no-trace):
    # the attribution JSON rides in the bench record, so BENCH_r06+
    # carries compile-vs-train-vs-save seconds — including the warmup
    # compile wall the ROADMAP wants measured — beside trials/s
    trace_prior = trace_metrics = trace_path = None
    if not args.no_trace:
        import tempfile

        from mpi_opt_tpu.obs import trace as _trace
        from mpi_opt_tpu.utils.metrics import MetricsLogger

        trace_path = args.trace_file or os.path.join(
            tempfile.mkdtemp(prefix="bench_trace_"), "bench.jsonl"
        )
        trace_metrics = MetricsLogger(path=trace_path)
        trace_prior = _trace.configure(trace_metrics)
    # warmup is an IDENTICAL invocation: generations is a static jit arg
    # (scan length), so only the same-arg call guarantees the measured
    # run is a pure cache hit / steady-state execution
    t0 = time.perf_counter()
    fused_pbt(wl, **kw)
    log(f"[bench] warmup (compile+run) {time.perf_counter()-t0:.1f}s")
    with profile_window(args.profile_dir):
        t0 = time.perf_counter()
        result = fused_pbt(wl, **kw)
        wall = time.perf_counter() - t0
    trace_rep = None
    if trace_prior is not None:
        from mpi_opt_tpu.obs import trace as _trace

        _trace.deconfigure(trace_prior)
        trace_metrics.close()
    # device-memory watermark (obs/memory.py): sampled AFTER the
    # measured run while the sweep's state is still resident, and
    # BEFORE the cap probe below — peak_bytes_in_use is process-
    # lifetime and cannot be reset, so the probe's ~100 MiB matmul
    # buffers would otherwise wear into the sweep's recorded watermark
    # (the number the wave-size/bf16 planning consumes)
    from mpi_opt_tpu.obs import memory as _obs_memory

    device_memory = _obs_memory.watermark()
    if device_memory is not None:
        log(f"[bench] device memory: {device_memory}")
    # the cap is measured AFTER tracing deconfigures (its probe compiles
    # must not pollute the attribution) and BEFORE the attribution is
    # built, so the embedded roofline is judged against the MEASURED
    # roof of this very device, not a calibration-table stand-in
    cap_tf = measure_platform_cap() if jax.default_backend() == "tpu" else None
    if trace_prior is not None:
        from mpi_opt_tpu.obs.report import bench_attribution

        trace_rep = bench_attribution(trace_path, peak_tflops=cap_tf)
        log(f"[bench] trace stream {trace_path}: coverage {trace_rep['coverage']}")
        # intra-phase verdicts (ISSUE 11): the embed carries the full
        # bubbles/staging/roofline sections; the log shows the headline
        bub, roof = trace_rep.get("bubbles"), trace_rep.get("roofline")
        if bub is not None and bub.get("idle_frac") is not None:
            log(f"[bench] idle fraction {bub['idle_frac']:.1%} "
                f"({bub['idle_s']}s over {bub['gaps']} gap(s); "
                f"by cause: {bub['by_cause']})")
        stg = trace_rep.get("staging")
        if stg is not None and stg.get("overlap_frac") is not None:
            log(f"[bench] staging overlap {stg['overlap_frac']:.1%} "
                f"(hidden {stg['overlap_s']}s of {stg['transfer_s']}s)")
        if roof is not None:
            if roof.get("mxu_frac") is not None:
                log(f"[bench] roofline: {roof['bound']} "
                    f"(MXU {roof['mxu_frac']:.1%}, cap {roof['peak_tflops']} "
                    f"TF/s [{roof['peak_source']}])")
            else:
                log(f"[bench] roofline: {roof['bound']} (no platform cap — "
                    "measured on TPU backends only; MXU fraction unavailable)")
    trials = population * generations
    tps = trials / wall
    # flops accounting AFTER the timed window (it lowers/compiles tiny
    # one-member programs — that must not count against the sweep)
    flops = population_sweep_flops(
        wl, population, generations, steps, n_evals=generations
    )

    # wall-clock to target val-acc (metric of record #2): launch-granular
    # — launch boundaries use their measured durations, only generations
    # inside one launch are prorated (utils.metrics)
    from mpi_opt_tpu.utils.metrics import sweep_wall_to_target as _wtt

    curve = [float(v) for v in result["best_curve"]]
    wall_to_target = _wtt(result, wall, args.target_acc)

    util = mfu(flops, wall, jax.devices()[0])
    log(f"[bench] tpu: {trials} member-gens in {wall:.2f}s -> {tps:.3f} trials/s/chip; "
        f"best={result['best_score']:.3f} curve={[round(v, 3) for v in curve]}")
    if flops:
        log(f"[bench] flops={flops:.3e} ({flops/wall/1e12:.1f} TFLOP/s, "
            f"mfu={'-' if util is None else round(util, 4)} of nominal peak, "
            f"platform cap {cap_tf and round(cap_tf, 1)} TF/s)")
    return {
        "platform_matmul_tflops": round(cap_tf, 1) if cap_tf else None,
        "mfu_vs_platform_cap": (
            round(flops / wall / 1e12 / cap_tf, 4) if flops and cap_tf else None
        ),
        "tps": tps,
        "wall": wall,
        "best": float(result["best_score"]),
        "curve": curve,
        "wall_to_target": wall_to_target,
        "flops": flops,
        "mfu": util,
        "device": jax.devices()[0].device_kind,
        "trace": trace_rep,
        "trace_stream": trace_path if args.trace_file else None,
        "device_memory": device_memory,
    }


def measure_platform_cap(iters=4, loops=200):
    """Measured matmul throughput cap of THIS device (TF/s).

    bf16 4096^3 matmuls looped inside ONE program with only a scalar
    serial dependency between iterations, fetched once — so neither
    dispatch nor the tunnel's per-fetch round trip (~20-90 ms measured)
    touches the number. On nominal hardware this approaches the
    datasheet peak; on virtualized/tunneled devices it is the *real*
    ceiling, and MFU against nominal peak alone would wildly understate
    how much of the attainable machine the sweep uses. Reported
    alongside nominal-peak MFU, never instead of it.

    History: round 2 used an 8-deep ``b = (a @ b) * 1e-3`` chain and
    read 64.8 TF/s; the full-matrix dependency plus the elementwise
    rescale pass serialized enough HBM traffic to hide ~2.4x of the
    machine — this probe reads ~157 TF/s on the same device
    (probes/probe_mxu_pack.py discovered the gap). The cap must be the
    strongest attainable measurement or "vs cap" ratios flatter us.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    M = 4096
    a = jax.random.normal(jax.random.key(0), (M, M), jnp.bfloat16)
    b = jax.random.normal(jax.random.key(1), (M, M), jnp.bfloat16) * 0.01

    @jax.jit
    def step(a, b):
        def body(i, s):
            x = a + s  # scalar serial dependency: no hoisting, no chain
            y = x @ b
            return jnp.sum(y).astype(jnp.bfloat16) * jnp.bfloat16(1e-9)

        return jax.lax.fori_loop(0, loops, body, jnp.bfloat16(0))

    float(step(a, b))  # warm (compile)
    t0 = time.perf_counter()
    for _ in range(iters):
        s = step(a, b)
    float(s)
    dt = (time.perf_counter() - t0) / iters
    return loops * 2 * M**3 / dt / 1e12


def bench_cpu_baseline_torch(steps, seed, measure_steps=20):
    """Reference-fidelity baseline: one MPI rank's member-generation in
    torch on CPU (the reference stack), single-threaded.

    Same work as one TPU-side member-generation: ``steps`` SGD+momentum
    steps on a SmallCNN of identical layer shapes at batch 256, plus a
    full 2048-image validation eval. Per-step cost is steady-state
    constant on CPU, so we measure ``measure_steps`` and scale — stated
    in the provenance. Augmentation is omitted on this side (the TPU
    side pays for it), which favors the baseline, i.e. is conservative
    for the reported speedup.

    Returns (trials_per_sec, provenance_str).
    """
    import torch
    import torch.nn.functional as tF
    from torch import nn

    torch.manual_seed(seed)
    torch.set_num_threads(1)  # one rank = one core, like the MPI reference

    w, n_classes, batch, n_val = 32, 10, 256, 2048

    class TorchSmallCNN(nn.Module):
        # mirrors models/cnn.py SmallCNN: conv32-conv32-pool-conv64-
        # conv64-pool-fc128-fc10, GroupNorm(8)
        def __init__(self):
            super().__init__()
            chans = [3, w, w, 2 * w, 2 * w]
            self.blocks = nn.ModuleList(
                nn.ModuleList([
                    nn.Conv2d(chans[i], chans[i + 1], 3, padding=1),
                    nn.GroupNorm(8, chans[i + 1]),
                ])
                for i in range(4)
            )
            self.fc1 = nn.Linear(2 * w * 8 * 8, 4 * w)
            self.fc2 = nn.Linear(4 * w, n_classes)

        def forward(self, x):
            for i, (conv, gn) in enumerate(self.blocks):
                x = tF.relu(gn(conv(x)))
                if i % 2 == 1:
                    x = tF.max_pool2d(x, 2)
            x = x.flatten(1)
            return self.fc2(tF.relu(self.fc1(x)))

    model = TorchSmallCNN()
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9, weight_decay=1e-4)
    g = torch.Generator().manual_seed(seed)
    x = torch.randn(batch, 3, 32, 32, generator=g)
    y = torch.randint(0, n_classes, (batch,), generator=g)

    def step():
        opt.zero_grad()
        tF.cross_entropy(model(x), y).backward()
        opt.step()

    step(); step()  # warm (allocator, oneDNN primitive caches)
    t0 = time.perf_counter()
    for _ in range(measure_steps):
        step()
    per_step = (time.perf_counter() - t0) / measure_steps

    model.eval()
    vx = torch.randn(n_val, 3, 32, 32, generator=g)
    with torch.no_grad():
        model(vx[:batch])  # warm
        t0 = time.perf_counter()
        for i in range(0, n_val, batch):
            model(vx[i : i + batch])
        eval_s = time.perf_counter() - t0

    member_gen_s = steps * per_step + eval_s
    tps = 1.0 / member_gen_s
    provenance = (
        f"torch-CPU single-thread (reference per-rank stack), same layer "
        f"shapes/batch/eval: {per_step:.2f}s/step x {steps} + {eval_s:.1f}s "
        f"eval = {member_gen_s:.1f}s/member-gen (per-step measured over "
        f"{measure_steps} steady-state steps)"
    )
    log(f"[bench] cpu baseline (torch): {provenance} -> {tps:.5f} trials/s/rank")
    return tps, provenance


def bench_cpu_baseline(steps, seed, n_workers, cache_path="CPU_BASELINE.json",
                       b_small=2, b_large=12):
    """Reference-architecture stand-in: process-per-trial evaluation,
    genuinely on CPU (the pool worker pins the platform).

    A real 100-step member-generation takes this box's single core tens
    of minutes (round 1's '5.79s' baseline was secretly running on the
    TPU through the then-unpinned inline path — fixed since, and the
    honest number is ~400x slower). Measuring cost(steps) directly is
    therefore infeasible inside a bench run; instead we measure
    cost(b_small) and cost(b_large) warm (the per-step cost on one core
    is strictly linear — no batching/caching effects across steps) and
    extrapolate: cost(S) = cost(b_small) + slope * (S - b_small), where
    the intercept carries the fixed per-trial work (final eval +
    dispatch). The result, with its full provenance, is cached in
    ``cache_path`` so repeat bench runs (e.g. the driver's) don't repay
    a multi-minute measurement; delete the file to re-measure.
    """
    import json as _json
    import os as _os

    import jax

    from mpi_opt_tpu.backends.cpu import CPUBackend
    from mpi_opt_tpu.trial import Trial
    from mpi_opt_tpu.workloads import get_workload

    # cache key covers everything that changes the measured number: the
    # workload/model, the measurement protocol (b_small/b_large +
    # extrapolation scheme, versioned), and the run shape — a stale
    # cache must re-measure, not silently feed the headline vs_baseline
    # (ADVICE round 2)
    workload_name = "cifar10_cnn"
    protocol = 2  # bump when the measurement scheme changes
    cache_key = {
        "steps": steps,
        "n_workers": n_workers,
        "workload": workload_name,
        "b_small": b_small,
        "b_large": b_large,
        "protocol": protocol,
    }
    if _os.path.exists(cache_path):
        with open(cache_path) as f:
            rec = _json.load(f)
        if all(rec.get(k) == v for k, v in cache_key.items()):
            log(f"[bench] cpu baseline from {cache_path}: "
                f"{rec['pool_trials_per_sec']:.6f} trials/s ({rec['provenance']})")
            return rec["pool_trials_per_sec"]

    wl = get_workload(workload_name)
    space = wl.default_space()
    be = CPUBackend(wl, n_workers=n_workers, seed=seed)

    def make_trials(base_id, budget):
        out = []
        for i in range(n_workers):
            key = jax.random.fold_in(jax.random.key(seed), base_id + i)
            unit = __import__("numpy").asarray(space.sample_unit(key, 1))[0]
            out.append(
                Trial(
                    trial_id=base_id + i,
                    params=space.materialize_row(unit),
                    unit=unit,
                    budget=budget,
                )
            )
        return out

    def timed_eval(base_id, budget):
        """Wall for one batch of n_workers PARALLEL trials (pool.map):
        with perfect scaling this equals one trial's cost, and the pool
        completes n_workers trials per such wall."""
        t0 = time.perf_counter()
        be.evaluate(make_trials(base_id, budget))
        return time.perf_counter() - t0

    log(f"[bench] cpu baseline: warming {n_workers}-process pool "
        f"(compiles budget={b_small}/{b_large} programs; slow first-ever)")
    t0 = time.perf_counter()
    timed_eval(0, b_small)  # compile+run small program
    timed_eval(100, b_large)  # compile+run large program
    log(f"[bench] pool warm in {time.perf_counter()-t0:.1f}s")
    c_small = timed_eval(200, b_small)
    c_large = timed_eval(300, b_large)
    be.close()
    slope = max((c_large - c_small) / (b_large - b_small), 0.0)
    c_steps = c_small + slope * (steps - b_small)
    # the pool finishes n_workers parallel trials per c_steps of wall
    pool_tps = n_workers / c_steps
    provenance = (
        f"linear extrapolation: batch-wall({b_small})={c_small:.1f}s, "
        f"batch-wall({b_large})={c_large:.1f}s -> {slope:.2f}s/step, "
        f"batch-wall({steps})={c_steps:.1f}s for {n_workers} parallel "
        f"trials, measured on a platform-pinned CPU pool"
    )
    log(f"[bench] cpu: {provenance} -> {pool_tps:.6f} trials/s ({n_workers} procs)")
    rec = {
        **cache_key,
        "cost_small_s": round(c_small, 2),
        "cost_large_s": round(c_large, 2),
        "slope_s_per_step": round(slope, 3),
        "cost_steps_s": round(c_steps, 2),
        "pool_trials_per_sec": pool_tps,
        "provenance": provenance,
    }
    # tmp+replace: a Ctrl-C mid-dump must not leave a torn cache
    # file that every later bench run trips over (sweeplint
    # atomic-write — the same idiom as service/spool status writes)
    tmp = f"{cache_path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            _json.dump(rec, f, indent=1)
        os.replace(tmp, cache_path)
    except OSError as e:
        log(f"[bench] could not cache baseline: {e}")
    finally:
        if os.path.exists(tmp):  # failed mid-write: no orphan debris
            os.unlink(tmp)
    return pool_tps


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--population", type=int, default=256)
    p.add_argument("--generations", type=int, default=4)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--member-chunk", type=int, default=32)
    p.add_argument(
        "--gen-chunk",
        type=int,
        default=1,
        help="generations per program launch (tunneled chips kill >60s programs)",
    )
    p.add_argument("--target-acc", type=float, default=0.70)
    p.add_argument("--workers", type=int, default=min(8, os.cpu_count() or 8))
    p.add_argument("--skip-baseline", action="store_true")
    p.add_argument(
        "--baseline-pool",
        action="store_true",
        help="use the jax-CPU process pool as the baseline instead of the "
        "torch reference stack (slow + understates the reference on this "
        "host; see PERF_NOTES.md)",
    )
    p.add_argument("--profile-dir", default=None)
    p.add_argument(
        "--no-trace",
        action="store_true",
        help="measure without span tracing (drops the phase breakdown)",
    )
    p.add_argument(
        "--trace-file",
        default=None,
        help="keep the span-trace stream here (default: a temp file — "
        "only the attribution lands in the record)",
    )
    args = p.parse_args()

    from mpi_opt_tpu.obs.diff import BENCH_SCHEMA_VERSION

    tpu = bench_tpu(args)
    record = {
        # versioned record shape: the BENCH_r0*.json drift gate
        # (tests/test_bench_schema.py) and `trace --diff`'s trajectory
        # loading both key on it — bump obs/diff.py BENCH_SCHEMA_VERSION
        # when the shape changes, never drift silently
        "schema_version": BENCH_SCHEMA_VERSION,
        "metric": "pbt_cifar10_cnn_member_generations_per_sec_per_chip",
        "value": round(tpu["tps"], 4),
        "unit": "trials/sec/chip",
        "population": args.population,
        "generations": args.generations,
        "steps_per_gen": args.steps,
        "device": tpu["device"],
        "best_val_acc": round(tpu["best"], 4),
        "target_acc": args.target_acc,
        "wall_to_target_s": (
            round(tpu["wall_to_target"], 2) if tpu["wall_to_target"] is not None else None
        ),
        "flops_total": tpu["flops"],
        "tflops_per_sec": (
            round(tpu["flops"] / tpu["wall"] / 1e12, 2) if tpu["flops"] else None
        ),
        "mfu": round(tpu["mfu"], 4) if tpu["mfu"] is not None else None,
        "platform_matmul_tflops": tpu["platform_matmul_tflops"],
        "mfu_vs_platform_cap": tpu["mfu_vs_platform_cap"],
        # span-trace phase attribution (obs/): compile vs train vs save
        # seconds + achieved TF/s per launch + time-to-first-trial, plus
        # the round-8 intra-phase sections (bubbles/staging/roofline) —
        # None under --no-trace
        "trace": tpu["trace"],
        "trace_stream": tpu["trace_stream"],
        # device-memory watermark (obs/memory.py): peak/steady HBM with
        # its accounting source — None only in a jax-less environment
        "device_memory": tpu["device_memory"],
    }
    if args.skip_baseline:
        record["vs_baseline"] = 1.0
        record["baseline"] = "skipped"
    else:
        if args.baseline_pool:
            pool_tps = bench_cpu_baseline(args.steps, args.seed, args.workers)
            per_rank = pool_tps / args.workers
            prov = (
                f"jax-CPU {args.workers}-proc pool (XLA:CPU runs convs at "
                f"~0.7 GFLOP/s on this host — understates the reference ~65x; "
                f"PERF_NOTES.md)"
            )
        else:
            per_rank, prov = bench_cpu_baseline_torch(args.steps, args.seed)
        rank8 = 8.0 * per_rank
        record["cpu_rank_trials_per_sec"] = round(per_rank, 5)
        record["vs_one_rank"] = round(tpu["tps"] / per_rank, 2)
        record["vs_8rank_equiv"] = round(tpu["tps"] / rank8, 2)
        # the headline number is the HONEST normalization: one chip vs an
        # 8-rank pool at the measured single-rank rate (linear scaling
        # assumed for the baseline — generous to it: zero MPI overhead)
        record["vs_baseline"] = record["vs_8rank_equiv"]
        record["baseline"] = f"8-rank equivalent = 8 x single-rank rate; rank = {prov}"
    print(json.dumps(record))


if __name__ == "__main__":
    main()
