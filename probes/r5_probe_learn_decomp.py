import sys, time, shutil
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
from mpi_opt_tpu.workloads.vision import Cifar100ResNet18
from mpi_opt_tpu.train.fused_pbt import fused_pbt

wl = Cifar100ResNet18()
# warm the launch program (uncheckpointed 1-gen)
t0 = time.perf_counter()
fused_pbt(wl, population=64, generations=1, steps_per_gen=50, seed=0,
          member_chunk=8, gen_chunk=1, snapshot_last=False)
print(f"warm 1-gen {time.perf_counter()-t0:.1f}s", flush=True)

ckpt = "/tmp/probe_learn_ck"
shutil.rmtree(ckpt, ignore_errors=True)
t0 = time.perf_counter()
res = fused_pbt(wl, population=64, generations=4, steps_per_gen=50, seed=0,
                member_chunk=8, gen_chunk=1, checkpoint_dir=ckpt,
                snapshot_every=2, snapshot_last=False)
wall = time.perf_counter() - t0
print(f"4-gen checkpointed sweep: {wall:.1f}s  launch_walls={['%.1f' % w for w in res['launch_walls']]}", flush=True)
shutil.rmtree(ckpt, ignore_errors=True)
