"""Where does the config-2 driver path's wall go? Count evaluate()
calls, their batch sizes/rem spans, and per-call wall on the real chip."""
import sys, time
sys.path.insert(0, "/root/repo")

import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

from mpi_opt_tpu.algorithms import get_algorithm
from mpi_opt_tpu.backends import get_backend
from mpi_opt_tpu.driver import run_search
from mpi_opt_tpu.workloads import get_workload

wl = get_workload("fashion_mlp")
asha = lambda s: get_algorithm("asha")(
    wl.default_space(), seed=s, max_trials=64, min_budget=10, max_budget=270, eta=3)

be = get_backend("tpu", wl, population=64, seed=0)
run_search(asha(0), be)  # warmup compiles
be.reset()

calls = []
orig = be.evaluate
def spy(trials):
    t0 = time.perf_counter()
    rems = sorted({max(0, t.budget - be._trained.get(t.trial_id, 0)) for t in trials})
    out = orig(trials)
    calls.append((len(trials), rems, time.perf_counter() - t0))
    return out
be.evaluate = spy
t0 = time.perf_counter()
res = run_search(asha(0), be)
wall = time.perf_counter() - t0
be.close()
print(f"total wall {wall:.2f}s n_evals {res.n_evals} evaluate_calls {len(calls)}")
for n, rems, w in calls:
    print(f"  n={n:3d} rems={rems} wall={w:.3f}s")
print(f"sum of evaluate walls: {sum(w for _,_,w in calls):.2f}s")
