"""Driver BOHB on the TPU slot pool, round-3 protocol (warm + reset +
timed), after the round-4 host_ops fix. Round-3 recorded 1.07
trials/s/chip (388.5 s for the 415-trial R=270 plan, 703 evaluations)."""
import sys, time
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
from mpi_opt_tpu.algorithms import get_algorithm
from mpi_opt_tpu.backends import get_backend
from mpi_opt_tpu.driver import run_search
from mpi_opt_tpu.workloads import get_workload

wl = get_workload("fashion_mlp")
bohb = lambda s: get_algorithm("bohb")(wl.default_space(), seed=s, max_budget=270, eta=3)
be = get_backend("tpu", wl, population=64, seed=0)
t0 = time.perf_counter()
run_search(bohb(0), be)
print(f"warmup {time.perf_counter()-t0:.1f}s", flush=True)
be.reset()
res = run_search(bohb(0), be)
be.close()
print(f"driver BOHB: {res.n_trials} trials, {res.n_evals} evals, "
      f"{res.wall_s:.2f}s = {res.n_trials/res.wall_s:.2f} trials/s/chip, "
      f"best={res.best.score:.4f}")
