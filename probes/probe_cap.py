import time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

# platform cap probe: ideal MXU shapes, work >> dispatch overhead
M = K = N = 4096
a = jax.random.normal(jax.random.key(0), (M, K), jnp.bfloat16)
b = jax.random.normal(jax.random.key(1), (K, N), jnp.bfloat16) * 0.01

@jax.jit
def step(b):
    # 8 chained matmuls: 8 * 137 GFLOP = 1.1 TFLOP per dispatch
    for _ in range(8):
        b = (a @ b) * 1e-3
    return b.astype(jnp.bfloat16)

b1 = step(b); np.asarray(b1[0, 0])
t0 = time.perf_counter()
iters = 10
for _ in range(iters):
    b1 = step(b1)
np.asarray(b1[0, 0])
dt = (time.perf_counter() - t0) / iters
fl = 8 * 2 * M * K * N
print(f"square {M}: {dt*1e3:.2f} ms/dispatch ({fl/dt/1e12:.1f} TF/s of 394 peak)")
