"""Ablate the member train step: where do the 36ms/step go?"""
import time, jax, jax.numpy as jnp, numpy as np, flax.linen as nn
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
from mpi_opt_tpu.models import SmallCNN
from mpi_opt_tpu.train import PopulationTrainer, OptHParams
from mpi_opt_tpu.data import load_dataset

P, B, STEPS = 32, 256, 50
d = load_dataset("cifar10", n_train=4096, n_val=512)
tx, ty = jnp.asarray(d["train_x"]), jnp.asarray(d["train_y"])

class NoNormCNN(nn.Module):
    n_classes: int = 10
    width: int = 32
    dtype: jnp.dtype = jnp.bfloat16
    @nn.compact
    def __call__(self, x):
        w = self.width
        x = x.astype(self.dtype)
        for i, ch in enumerate((w, w, 2*w, 2*w)):
            x = nn.Conv(ch, (3,3), padding="SAME", dtype=self.dtype, name=f"conv{i}")(x)
            x = nn.relu(x)
            if i % 2 == 1:
                x = nn.max_pool(x, (2,2), strides=(2,2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4*w, dtype=self.dtype, name="fc1")(x))
        return nn.Dense(self.n_classes, dtype=self.dtype, name="fc2")(x).astype(jnp.float32)

def run(model, augment, label):
    tr = PopulationTrainer(
        apply_fn=lambda p, x: model.apply({"params": p}, x),
        init_fn=lambda r, x: model.init(r, x)["params"],
        batch_size=B, augment=augment, donate=False)
    st = tr.init_population(jax.random.key(0), tx[:2], P)
    hp = OptHParams.defaults(P)
    st2, l = tr.train_segment(st, hp, tx, ty, jax.random.key(1), STEPS)
    np.asarray(l)
    t0 = time.time()
    st2, l = tr.train_segment(st, hp, tx, ty, jax.random.key(2), STEPS)
    np.asarray(l)
    dt = (time.time()-t0)/STEPS
    print(f"{label}: {dt*1e3:.2f} ms/step ({P*1000/ (dt*1e3):.0f} member-steps/s)")

run(SmallCNN(), True,  "GN  + aug (current)")
run(SmallCNN(), False, "GN  no-aug")
run(NoNormCNN(), True, "noGN + aug")
run(NoNormCNN(), False,"noGN no-aug")
