import jax, jax.numpy as jnp
from mpi_opt_tpu.workloads import get_workload
from mpi_opt_tpu.train.population import OptHParams

wl = get_workload("cifar10_cnn")
tr = wl.make_trainer(donate=False)
d = wl.data()
tx, ty = jnp.asarray(d["train_x"]), jnp.asarray(d["train_y"])
print("batch_size:", tr.batch_size, "train_x:", tx.shape, tx.dtype)
P = 8
key = jax.random.key(0)
state = tr.init_population(key, tx[:2], P)
hp = OptHParams.defaults(P)
# cost of a 1-step segment
jf = tr.train_segment  # functools.partial(jit(...), self)
c = jf.func.lower(jf.args[0], state, hp, tx, ty, key, steps=1).compile().cost_analysis()
if isinstance(c, (list, tuple)): c = c[0]
print("train_segment P=8 steps=1 flops:", c.get("flops"), "bytes accessed:", c.get("bytes accessed"))
print("per member-step GFLOP:", c.get("flops")/P/1e9)
