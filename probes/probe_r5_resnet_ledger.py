"""Round-5: config-5 (ResNet-18 CIFAR-100 population) perf ledger,
held to the config-3 standard (VERDICT r4 weak #2).

Phase 1 of the ledger: baseline + ablation + trace capture.
- segment wall at the bench shape (pop=64, member_chunk=8, remat,
  batch 128, 50-step segments; medians of 3, fetch-once barrier);
- GroupNorm -> identity ablation (COST only — the no-norm model's
  learning is not comparable and isn't claimed);
- relu cost isolated the same way (GN+relu is the fusion candidate);
- a profiler trace of one segment for the leaf-op decomposition
  (parsed by probe_traceparse.py pointed at /tmp/prof_r5_resnet);
- MFU bookkeeping from utils.flops at the measured wall.

Run on the REAL chip, idle host (PERF_NOTES measurement rules).
"""

import statistics
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

from mpi_opt_tpu.train.population import OptHParams
from mpi_opt_tpu.workloads import get_workload

POP, STEPS, REPS, CHUNK = 64, 50, 3, 8


def fresh_workload():
    wl = get_workload("cifar100_resnet18")
    return wl


def segment_wall(wl, label, trace_dir=None):
    from mpi_opt_tpu.train.common import workload_arrays

    trainer, space, tx, ty, vx, vy = workload_arrays(wl, CHUNK)
    st = trainer.init_population(jax.random.key(0), tx[:2], POP)
    hp = OptHParams.defaults(POP, lr=0.05)
    st, losses = trainer.train_segment(st, hp, tx, ty, jax.random.key(1), STEPS)
    np.asarray(losses)  # warm barrier
    walls = []
    for i in range(REPS):
        t0 = time.perf_counter()
        st, losses = trainer.train_segment(
            st, hp, tx, ty, jax.random.fold_in(jax.random.key(2), i), STEPS
        )
        np.asarray(losses)
        walls.append(time.perf_counter() - t0)
    med = statistics.median(walls)
    print(
        f"{label:22s}: {med:.3f}s  {['%.3f' % w for w in walls]}  "
        f"({POP * STEPS / med:.1f} member-steps/s)",
        flush=True,
    )
    if trace_dir:
        with jax.profiler.trace(trace_dir):
            st, losses = trainer.train_segment(
                st, hp, tx, ty, jax.random.key(9), STEPS
            )
            np.asarray(losses)
    return med


def main():
    print(f"device: {jax.devices()[0].device_kind}", flush=True)
    import flax.linen as nn

    base = segment_wall(fresh_workload(), "baseline", trace_dir="/tmp/prof_r5_resnet")

    # GN -> identity (params vanish too: pure cost ablation)
    orig_gn = nn.GroupNorm.__call__
    nn.GroupNorm.__call__ = lambda self, x: x
    try:
        no_gn = segment_wall(fresh_workload(), "gn=identity")
    finally:
        nn.GroupNorm.__call__ = orig_gn

    # relu -> identity (the other half of the fusion candidate)
    orig_relu = nn.relu
    nn.relu = lambda x: x
    try:
        no_relu = segment_wall(fresh_workload(), "relu=identity")
    finally:
        nn.relu = orig_relu

    print(
        f"GN share   : {(base - no_gn) / base * 100:.1f}% of segment "
        f"({base - no_gn:.3f}s)",
        flush=True,
    )
    print(
        f"relu share : {(base - no_relu) / base * 100:.1f}% of segment "
        f"({base - no_relu:.3f}s)",
        flush=True,
    )

    # MFU bookkeeping at the measured baseline
    from mpi_opt_tpu.utils.flops import population_sweep_flops

    wl = fresh_workload()
    # one "generation" = the timed segment; n_evals=0 — the timed
    # window contains no eval
    fl = population_sweep_flops(wl, POP, 1, STEPS, n_evals=0)
    print(
        f"MFU: {fl / base / 157e12:.3f} of 157 TF/s measured cap "
        f"({fl / base / 1e12:.1f} TF/s achieved, {fl / 1e12:.1f} TF total)",
        flush=True,
    )


if __name__ == "__main__":
    main()
