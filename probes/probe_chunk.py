import sys, time
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
from mpi_opt_tpu.train.fused_pbt import fused_pbt
from mpi_opt_tpu.workloads import get_workload
wl = get_workload("cifar10_cnn")
for chunk in (32, 64, 128):
    kw = dict(population=256, generations=2, steps_per_gen=100, seed=0,
              member_chunk=chunk, gen_chunk=1)
    try:
        t0 = time.perf_counter(); fused_pbt(wl, **kw)
        warm = time.perf_counter() - t0
        t0 = time.perf_counter(); r = fused_pbt(wl, **kw)
        wall = time.perf_counter() - t0
        print(f"chunk={chunk}: {512/wall:.2f} trials/s (wall {wall:.1f}s, warm {warm:.0f}s, best {r['best_score']:.3f})", flush=True)
    except Exception as e:
        print(f"chunk={chunk}: FAIL {type(e).__name__} {str(e)[:90]}", flush=True)
