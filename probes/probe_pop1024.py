"""Probe: single-chip population ceiling for the north-star CNN sweep.

The headline measures pop=256 (BASELINE north_star). This charts the
throughput curve up to pop=1024 — 4x the north-star population on ONE
chip. Measured result (PERF_NOTES.md "single-chip population
envelope"): throughput is flat 857->874 member-steps/s through
pop=512, then pop=1024 RESOURCE_EXHAUSTs — 4.5 GB of params+momentum
plus the update's transient double-residency tips the 16 GB chip, so
bigger populations shard over the mesh's 'pop' axis (the design's
scaling path; BASELINE config 5 puts pop=1024 on a v4-32).

Run: python probes/probe_pop1024.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

from mpi_opt_tpu.train.fused_pbt import fused_pbt  # noqa: E402
from mpi_opt_tpu.workloads import get_workload  # noqa: E402

wl = get_workload("cifar10_cnn")
for pop in (256, 512, 1024):
    kw = dict(
        population=pop,
        generations=1,
        steps_per_gen=100,
        seed=0,
        member_chunk=32,
        gen_chunk=1,
    )
    t0 = time.perf_counter()
    fused_pbt(wl, **kw)  # warm/compile
    warm = time.perf_counter() - t0
    t0 = time.perf_counter()
    res = fused_pbt(wl, **kw)
    wall = time.perf_counter() - t0
    rate = pop * 100 / wall
    print(
        f"pop={pop}: warm {warm:.1f}s, timed {wall:.1f}s = "
        f"{rate:.0f} member-steps/s ({pop / wall:.2f} member-gens/s) "
        f"best={res['best_score']:.3f}",
        flush=True,
    )
