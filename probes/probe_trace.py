import time, jax, numpy as np
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
from mpi_opt_tpu.train.fused_pbt import fused_pbt
from mpi_opt_tpu.workloads import get_workload
from mpi_opt_tpu.utils.profiling import profile_window
wl = get_workload("cifar10_cnn")
r = fused_pbt(wl, population=32, generations=2, steps_per_gen=100, seed=0)  # warm
r = None
with profile_window("/tmp/prof_fused"):
    r = fused_pbt(wl, population=32, generations=2, steps_per_gen=100, seed=0)
print("done")
