import sys, time
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
import jax.numpy as jnp
import numpy as np

# single contiguous buffers at several sizes: is the 15 MB/s per-byte or per-transfer?
for mb in (64, 512, 2048):
    x = jnp.ones((mb * 1024 * 1024 // 4,), jnp.float32)
    x.block_until_ready() if hasattr(x, "block_until_ready") else np.asarray(x[:1])
    t0 = time.perf_counter()
    h = jax.device_get(x)
    w = time.perf_counter() - t0
    print(f"{mb:5d} MB single buffer: {w:.1f}s = {mb/w:.1f} MB/s", flush=True)
