"""Round-4 perf-ledger close-out: the two traced slices left unattacked.

(a) `convert_reduce` fusions (~16% of device time, round-2 trace): the
    f32 loss path around bf16 compute — logits upcast, f32 log_softmax,
    f32 mean. A/B: compute log_softmax in bf16 (mean still f32) and
    measure BOTH wall and learning, pool-swap-probe protocol.
(b) GroupNorm's share of the ~1.7x non-MXU factor: wall with GroupNorm
    replaced by identity (a COST measurement — the no-norm model's
    learning is not comparable, and isn't claimed to be).

Config-3 shapes (SmallCNN, pop=32, batch 256, 100-step segments), real
chip, fetch-once harness per PERF_NOTES measurement rules.
"""
import statistics
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

from mpi_opt_tpu.train.population import OptHParams, PopulationTrainer
from mpi_opt_tpu.workloads import get_workload

POP, STEPS, REPS = 32, 100, 3


def segment_wall(wl):
    from mpi_opt_tpu.train.common import workload_arrays

    trainer, space, tx, ty, vx, vy = workload_arrays(wl)
    st = trainer.init_population(jax.random.key(0), tx[:2], POP)
    hp = OptHParams.defaults(POP, lr=0.05)
    # warm (compile) + timed medians; fetch of the final loss is the barrier
    st, losses = trainer.train_segment(st, hp, tx, ty, jax.random.key(1), STEPS)
    np.asarray(losses)
    walls = []
    for i in range(REPS):
        t0 = time.perf_counter()
        st, losses = trainer.train_segment(st, hp, tx, ty, jax.random.fold_in(jax.random.key(2), i), STEPS)
        np.asarray(losses)
        walls.append(time.perf_counter() - t0)
    return statistics.median(walls), walls


def learn_score(wl):
    from mpi_opt_tpu.train.fused_pbt import fused_pbt

    res = fused_pbt(wl, population=POP, generations=2, steps_per_gen=STEPS, seed=0, gen_chunk=1)
    return res["best_score"]


def loss_bf16(self, params, hp, key, bx, by):
    """_member_loss with the softmax in bf16: kills the logits upcast +
    f32 log_softmax convert_reduce pair; only the final mean runs f32."""
    from mpi_opt_tpu.train.population import _augment

    if self.augment and bx.ndim == 4:
        bx = _augment(key, bx, hp.flip_prob, hp.shift)
    logits = self.apply_fn(params, bx)
    logp = jax.nn.log_softmax(logits.astype(jnp.bfloat16))
    picked = jnp.take_along_axis(logp, by[:, None], axis=1)
    return -jnp.mean(picked.astype(jnp.float32))


def main():
    print(f"device: {jax.devices()[0].device_kind}")

    wl_a = get_workload("cifar10_cnn")
    base_w, base_walls = segment_wall(wl_a)
    base_learn = learn_score(get_workload("cifar10_cnn"))
    print(f"A baseline      : {base_w:.3f}s {['%.3f' % w for w in base_walls]}  learn2g={base_learn:.4f}")

    orig = PopulationTrainer._member_loss
    PopulationTrainer._member_loss = loss_bf16
    try:
        wl_b = get_workload("cifar10_cnn")
        wl_b._fused_cache = None
        b_w, b_walls = segment_wall(wl_b)
        wl_b2 = get_workload("cifar10_cnn")
        wl_b2._fused_cache = None
        b_learn = learn_score(wl_b2)
    finally:
        PopulationTrainer._member_loss = orig
    print(f"B bf16 softmax  : {b_w:.3f}s {['%.3f' % w for w in b_walls]}  learn2g={b_learn:.4f}  "
          f"wall {100 * (1 - b_w / base_w):+.1f}%")

    import flax.linen as nn

    orig_gn = nn.GroupNorm
    nn.GroupNorm = lambda **kw: (lambda x: x)  # identity: pure cost measurement
    try:
        wl_c = get_workload("cifar10_cnn")
        wl_c._fused_cache = None
        c_w, c_walls = segment_wall(wl_c)
    finally:
        nn.GroupNorm = orig_gn
    print(f"C no-GroupNorm  : {c_w:.3f}s {['%.3f' % w for w in c_walls]}  "
          f"GN share of segment wall ~{100 * (1 - c_w / base_w):.1f}%")


if __name__ == "__main__":
    main()
