#!/usr/bin/env bash
# Tier-1 verify — the single entrypoint for CI and local gates.
#
# Exactly the ROADMAP.md tier-1 command: single-process (-p no:xdist),
# chaos tests included, slow tests excluded, 870 s budget, with the
# DOTS_PASSED count extracted from the progress lines (the driver's
# no-worse-than-seed gate reads it).
#
# Usage: probes/tier1.sh            # run + report
#        T1_LOG=/tmp/my.log probes/tier1.sh   # custom log path
set -o pipefail
cd "$(dirname "$0")/.."
T1_LOG="${T1_LOG:-/tmp/_t1.log}"
rm -f "$T1_LOG"
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee "$T1_LOG"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$T1_LOG" | tr -cd . | wc -c)"
exit $rc
