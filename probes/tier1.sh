#!/usr/bin/env bash
# Tier-1 verify — the single entrypoint for CI and local gates.
#
# Exactly the ROADMAP.md tier-1 command: single-process (-p no:xdist),
# chaos tests included, slow tests excluded, 870 s budget, with the
# DOTS_PASSED count extracted from the progress lines (the driver's
# no-worse-than-seed gate reads it) — followed by the fsck corruption
# drill: a tiny checkpointed sweep is bit-rotted, `fsck` must flag it
# (exit 1), and `--repair` + `--resume` must recover (ISSUE 5).
#
# Usage: probes/tier1.sh            # run + report
#        T1_LOG=/tmp/my.log probes/tier1.sh   # custom log path
#        T1_SKIP_FSCK_DRILL=1 probes/tier1.sh # skip the fsck drill
#        T1_SKIP_FUSED_LEDGER_DRILL=1 probes/tier1.sh # skip the ledger drill
#        T1_SKIP_SERVICE_DRILL=1 probes/tier1.sh # skip the sweep-service drill
#        T1_SKIP_FLEET_DRILL=1 probes/tier1.sh # skip the fleet-federation drill
#        T1_SKIP_TRACE_DRILL=1 probes/tier1.sh # skip the span-trace drill
#        T1_SKIP_PERFDIFF_DRILL=1 probes/tier1.sh # skip the trace-diff gate drill
#        T1_SKIP_TIMELINE_DRILL=1 probes/tier1.sh # skip the timeline/bubble drill
#        T1_SKIP_LINT_DRILL=1 probes/tier1.sh # skip the sweeplint drill
#        T1_SKIP_RACE_DRILL=1 probes/tier1.sh # skip the racelint/lock-order drill
#        T1_SKIP_OOM_DRILL=1 probes/tier1.sh # skip the device-OOM backoff drill
#        T1_SKIP_ENGINE_DRILL=1 probes/tier1.sh # skip the shared-engine chaos drill
#        T1_SKIP_ENOSPC_DRILL=1 probes/tier1.sh # skip the disk-full drill
#        T1_SKIP_CORPUS_DRILL=1 probes/tier1.sh # skip the corpus/auto-warm-start drill
#        T1_SKIP_FRONTDOOR_DRILL=1 probes/tier1.sh # skip the HTTP front-door drill
#        T1_SKIP_PARETO_DRILL=1 probes/tier1.sh # skip the multi-objective drill
#        T1_SKIP_SPMD_DRILL=1 probes/tier1.sh # skip the multi-process SPMD drill
set -o pipefail
cd "$(dirname "$0")/.."
T1_LOG="${T1_LOG:-/tmp/_t1.log}"
rm -f "$T1_LOG"
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee "$T1_LOG"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$T1_LOG" | tr -cd . | wc -c)"

# -- fsck corruption drill (snapshot-integrity layer, utils/integrity.py) --
if [ -z "$T1_SKIP_FSCK_DRILL" ]; then
    drill_rc=0
    D=$(mktemp -d /tmp/_t1_fsck.XXXXXX)
    run_sweep() {
        timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
            --workload quadratic --algorithm random --trials 6 --budget 3 \
            --workers 1 --seed 0 --checkpoint-dir "$D/ck" "$@" >/dev/null 2>&1
    }
    fsck() {
        timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
            fsck "$D/ck" "$@" >/dev/null 2>&1
    }
    run_sweep || drill_rc=1
    fsck || drill_rc=1                      # clean tree must audit clean
    env JAX_PLATFORMS=cpu python -c \
        "from mpi_opt_tpu.workloads.chaos import inject_corrupt_save; \
         inject_corrupt_save('$D/ck')" || drill_rc=1
    fsck; [ $? -eq 1 ] || drill_rc=1        # corruption must be FLAGGED
    fsck --repair; [ $? -eq 1 ] || drill_rc=1  # found + repaired contract
    run_sweep --resume || drill_rc=1        # last-good fallback recovers
    fsck || drill_rc=1                      # post-recovery tree is clean
    rm -rf "$D"
    if [ $drill_rc -eq 0 ]; then
        echo "FSCK_DRILL=pass"
    else
        echo "FSCK_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- fused-ledger drill (boundary-granular durability, ledger/fused.py) --
# A fused TPE sweep is hard-killed MID-JOURNAL of its second batch (the
# real append-kill shape: boundary 1 half-written), then:
#   fsck --ledger must FLAG the torn boundary (exit 1),
#   fsck --repair truncates it (and quarantines any torn snapshot step),
#   --resume re-trains only the incomplete boundary (verifying the
#   completed one against its records) and re-journals it,
#   report --validate and fsck --ledger must then exit 0.
if [ -z "$T1_SKIP_FUSED_LEDGER_DRILL" ]; then
    fl_rc=0
    FD=$(mktemp -d /tmp/_t1_fled.XXXXXX)
    fused_sweep() {
        timeout -k 10 180 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
            --workload fashion_mlp --algorithm tpe --fused --no-mesh \
            --trials 6 --population 3 --budget 2 --seed 0 \
            --checkpoint-dir "$FD/ck" --ledger "$FD/sweep.jsonl" \
            "$@" >/dev/null 2>&1
    }
    ledger_fsck() {
        timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
            fsck "$FD/ck" --ledger "$FD/sweep.jsonl" "$@" >/dev/null 2>&1
    }
    # kill the sweep after 1 member record of boundary 1 hit the disk
    timeout -k 10 180 env JAX_PLATFORMS=cpu python - "$FD" >/dev/null 2>&1 <<'PYEOF'
import os, sys
import mpi_opt_tpu.ledger.store as ls
orig = ls.SweepLedger._write_line
n = [0]
def dying_write(self, rec):
    orig(self, rec)
    n[0] += 1
    if n[0] == 5:  # header + batch 0's 3 records + 1 of batch 1: die
        os._exit(137)
ls.SweepLedger._write_line = dying_write
from mpi_opt_tpu.cli import main
d = sys.argv[1]
main(["--workload", "fashion_mlp", "--algorithm", "tpe", "--fused",
      "--no-mesh", "--trials", "6", "--population", "3", "--budget", "2",
      "--seed", "0", "--checkpoint-dir", f"{d}/ck",
      "--ledger", f"{d}/sweep.jsonl"])
PYEOF
    [ $? -eq 137 ] || fl_rc=1                 # the kill must have landed
    ledger_fsck; [ $? -eq 1 ] || fl_rc=1      # torn boundary must be FLAGGED
    ledger_fsck --repair; [ $? -eq 1 ] || fl_rc=1  # found + repaired contract
    fused_sweep --resume || fl_rc=1           # verify prefix + re-journal
    timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        report --validate "$FD/sweep.jsonl" >/dev/null 2>&1 || fl_rc=1
    ledger_fsck || fl_rc=1                    # post-recovery audit is clean
    rm -rf "$FD"
    if [ $fl_rc -eq 0 ]; then
        echo "FUSED_LEDGER_DRILL=pass"
    else
        echo "FUSED_LEDGER_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- sweep-service drill (resident multi-tenant scheduler, service/) --
# Queue 3 sweeps on a spool, cancel the 3rd before it runs, start a
# server and SIGTERM it mid-work (the active tenant drains at a
# boundary and parks — exit 0, queue preserved on disk), restart the
# server to completion, then assert: both live jobs `done`, the
# cancelled one never ran, every tenant ledger passes report
# --validate, and every tenant checkpoint tree audits fsck-clean.
if [ -z "$T1_SKIP_SERVICE_DRILL" ]; then
    sv_rc=0
    SD=$(mktemp -d /tmp/_t1_svc.XXXXXX)
    mop() { env JAX_PLATFORMS=cpu python -m mpi_opt_tpu "$@"; }
    submit_job() {  # $1=tenant $2=seed $3=trials -> job id on stdout
        mop submit --state-dir "$SD" --tenant "$1" -- \
            --workload quadratic --algorithm random --trials "$3" \
            --budget 3 --workers 1 --seed "$2" \
            | python -c 'import json,sys; print(json.load(sys.stdin)["job"])'
    }
    J1=$(submit_job alice 0 24) || sv_rc=1
    J2=$(submit_job bob 1 6) || sv_rc=1
    J3=$(submit_job carol 2 6) || sv_rc=1
    mop cancel "$J3" --state-dir "$SD" >/dev/null 2>&1 || sv_rc=1
    timeout -k 10 180 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        serve --state-dir "$SD" --slice-boundaries 2 \
        >/dev/null 2>&1 &
    SRV=$!
    sleep 10                       # let it get mid-slice on the big job
    kill -TERM "$SRV" 2>/dev/null
    wait "$SRV"; [ $? -eq 0 ] || sv_rc=1   # graceful drain, not a crash
    timeout -k 10 240 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        serve --state-dir "$SD" --slice-boundaries 2 --drain-on-empty \
        >/dev/null 2>&1 || sv_rc=1
    mop status --state-dir "$SD" --json >"$SD/_status.json" 2>/dev/null || sv_rc=1
    env J1="$J1" J2="$J2" J3="$J3" python - "$SD/_status.json" <<'PYEOF' || sv_rc=1
import json, os, sys
st = {j["job"]: j for j in json.load(open(sys.argv[1]))["jobs"]}
assert st[os.environ["J1"]]["state"] == "done", st
assert st[os.environ["J2"]]["state"] == "done", st
assert st[os.environ["J3"]]["state"] == "cancelled", st
assert st[os.environ["J3"]].get("slices") in (0, None), st  # never ran
PYEOF
    timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        report "$SD" --validate >/dev/null 2>&1 || sv_rc=1
    for ck in "$SD"/tenants/*/ckpt; do
        [ -d "$ck" ] || continue
        timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
            fsck "$ck" >/dev/null 2>&1 || sv_rc=1
    done
    rm -rf "$SD"
    if [ $sv_rc -eq 0 ]; then
        echo "SERVICE_DRILL=pass"
    else
        echo "SERVICE_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- fleet-federation drill (multi-server spool, service/leases.py) --
# Two servers, one spool: srv-a (driven in-process so the kill lands at
# an exact boundary) SIGKILLs itself mid-slice of the first tenant;
# survivor srv-b claims the dead holder's lease immediately (pid+/proc
# start-time fast path — no TTL wait, even with 600 s left on the
# lease), resumes via the ordinary --resume machinery, and finishes
# BOTH tenants. Asserts: both done, the orphan counted >= 1 takeover
# and finished on srv-b, its ledger is record-identical to an
# uninterrupted solo run with every trial id unique (nothing executed
# twice), and report --validate + per-tenant fsck audit clean.
if [ -z "$T1_SKIP_FLEET_DRILL" ]; then
    ft_rc=0
    FS=$(mktemp -d /tmp/_t1_fleet.XXXXXX)
    fmop() { env JAX_PLATFORMS=cpu python -m mpi_opt_tpu "$@"; }
    fleet_submit() {  # $1=tenant $2=seed $3=trials -> job id on stdout
        fmop submit --state-dir "$FS" --tenant "$1" -- \
            --workload quadratic --algorithm random --trials "$3" \
            --budget 3 --workers 1 --seed "$2" \
            | python -c 'import json,sys; print(json.load(sys.stdin)["job"])'
    }
    FJ1=$(fleet_submit alice 0 24) || ft_rc=1
    FJ2=$(fleet_submit bob 1 6) || ft_rc=1
    # server srv-a: SIGKILL itself at boundary 3 of the first slice
    timeout -k 10 180 env JAX_PLATFORMS=cpu python - "$FS" >/dev/null 2>&1 <<'PYEOF'
import os, signal, sys
from mpi_opt_tpu.service.scheduler import SweepService
def boom(t, stage, n):
    if n == 3:
        os.kill(os.getpid(), signal.SIGKILL)
svc = SweepService(sys.argv[1], server_id="srv-a", slice_boundaries=100,
                   lease_ttl=600, poll_seconds=0.05, on_boundary=boom)
sys.exit(svc.serve())
PYEOF
    [ $? -eq 137 ] || ft_rc=1             # the SIGKILL must have landed
    timeout -k 10 240 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        serve --state-dir "$FS" --server-id srv-b --slice-boundaries 2 \
        --lease-ttl 600 --drain-on-empty >/dev/null 2>&1 || ft_rc=1
    fmop status --state-dir "$FS" --json >"$FS/_status.json" 2>/dev/null || ft_rc=1
    env FJ1="$FJ1" FJ2="$FJ2" python - "$FS/_status.json" <<'PYEOF' || ft_rc=1
import json, os, sys
st = {j["job"]: j for j in json.load(open(sys.argv[1]))["jobs"]}
a, b = st[os.environ["FJ1"]], st[os.environ["FJ2"]]
assert a["state"] == "done" and b["state"] == "done", st
assert (a.get("takeovers") or 0) >= 1, a   # the orphan changed hands
assert a.get("server") == "srv-b", a       # ...and finished on the survivor
PYEOF
    # record-identity: the taken-over tenant's ledger == a solo run's
    timeout -k 10 180 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        --workload quadratic --algorithm random --trials 24 --budget 3 \
        --workers 1 --seed 0 --ledger "$FS/solo.jsonl" >/dev/null 2>&1 || ft_rc=1
    env FJ1="$FJ1" python - "$FS" <<'PYEOF' || ft_rc=1
import json, os, sys
keep = ("trial_id", "params", "status", "score", "step")
def records(p):
    return [{k: r[k] for k in keep}
            for r in map(json.loads, open(p).read().splitlines()[1:])]
d = sys.argv[1]
got = records(os.path.join(d, "tenants", os.environ["FJ1"], "ledger.jsonl"))
want = records(os.path.join(d, "solo.jsonl"))
assert got == want, "takeover ledger diverged from the solo run"
ids = [r["trial_id"] for r in got]
assert len(ids) == len(set(ids)) == 24, "a trial executed twice"
PYEOF
    timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        report "$FS" --validate >/dev/null 2>&1 || ft_rc=1
    for ck in "$FS"/tenants/*/ckpt; do
        [ -d "$ck" ] || continue
        timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
            fsck "$ck" >/dev/null 2>&1 || ft_rc=1
    done
    rm -rf "$FS"
    if [ $ft_rc -eq 0 ]; then
        echo "FLEET_DRILL=pass"
    else
        echo "FLEET_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- span-trace drill (observability layer, obs/) --
# Run a tiny fused sweep with tracing into a metrics stream, render it
# with `trace --json`, and assert: compile + train + save spans present,
# the attributed self-seconds sum sanely against the measured wall, and
# time-to-first-trial is reported — the schema/behavior gate for the
# phase-attribution pipeline end to end.
if [ -z "$T1_SKIP_TRACE_DRILL" ]; then
    tr_rc=0
    TD=$(mktemp -d /tmp/_t1_trace.XXXXXX)
    timeout -k 10 180 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        --workload fashion_mlp --algorithm pbt --fused --no-mesh \
        --population 4 --generations 3 --steps-per-generation 2 --seed 0 \
        --checkpoint-dir "$TD/ck" --metrics-file "$TD/m.jsonl" --trace \
        >/dev/null 2>&1 || tr_rc=1
    timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        trace "$TD/m.jsonl" --json >"$TD/trace.json" 2>/dev/null || tr_rc=1
    python - "$TD/trace.json" <<'PYEOF' || tr_rc=1
import json, sys
rep = json.load(open(sys.argv[1]))
ph = rep["phases"]
for need in ("compile", "train", "save"):
    assert need in ph and ph[need]["count"] > 0, (need, sorted(ph))
wall = rep["wall_s"]
total = sum(p["self_s"] for p in ph.values())
# attributed self-seconds must sum sanely against the measured wall
# (single stream, no background thread here: a small epsilon only)
assert 0 < total <= wall * 1.05 + 0.5, (total, wall)
assert rep["coverage"] and rep["coverage"] > 0.3, rep["coverage"]
assert rep["time_to_first_trial_s"] is not None, rep
PYEOF
    rm -rf "$TD"
    if [ $tr_rc -eq 0 ]; then
        echo "TRACE_DRILL=pass"
    else
        echo "TRACE_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- perf-diff gate drill (trace diffing + regression gate, obs/diff.py) --
# Two short traced fused sweeps — the second with a 0.25 s sleep shimmed
# into every train phase (the seeded regression). `trace --diff --json
# --gate` must exit 1 on the regressed pair and 0 for a run diffed
# against itself: the end-to-end rc contract every future perf round's
# CI verdict rides on. No TPU needed.
if [ -z "$T1_SKIP_PERFDIFF_DRILL" ]; then
    pd_rc=0
    PD=$(mktemp -d /tmp/_t1_pdiff.XXXXXX)
    # --gen-chunk 1: one launch (= one train span) per generation — the
    # noise model needs repeated spans to measure the phase's spread
    timeout -k 10 180 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        --workload fashion_mlp --algorithm pbt --fused --no-mesh \
        --population 4 --generations 3 --steps-per-generation 2 \
        --gen-chunk 1 --seed 0 \
        --metrics-file "$PD/base.jsonl" --trace >/dev/null 2>&1 || pd_rc=1
    # the regressed run: identical sweep, train-phase shim sleeps 0.25 s
    timeout -k 10 180 env JAX_PLATFORMS=cpu python - "$PD" >/dev/null 2>&1 <<'PYEOF'
import contextlib, sys, time
from mpi_opt_tpu.obs import trace as _tr
_orig = _tr.span
@contextlib.contextmanager
def slowed(name, **attrs):
    with _orig(name, **attrs) as sp:
        if name == "train":
            time.sleep(0.25)
        yield sp
_tr.span = slowed
from mpi_opt_tpu.cli import main
d = sys.argv[1]
sys.exit(main(["--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
               "--no-mesh", "--population", "4", "--generations", "3",
               "--steps-per-generation", "2", "--gen-chunk", "1", "--seed", "0",
               "--metrics-file", f"{d}/new.jsonl", "--trace"]))
PYEOF
    [ $? -eq 0 ] || pd_rc=1
    printf '{"default": 10.0, "phases": {"train": 0.5}}' > "$PD/tol.json"
    # a run diffed against itself gates clean (rc 0)...
    timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        trace --diff "$PD/base.jsonl" "$PD/base.jsonl" --json \
        --gate "$PD/tol.json" >/dev/null 2>&1 || pd_rc=1
    # ...and the seeded train-phase slowdown must trip the gate (rc 1)
    # with the regression attributed to the train phase in the JSON
    timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        trace --diff "$PD/base.jsonl" "$PD/new.jsonl" --json \
        --gate "$PD/tol.json" >"$PD/diff.json" 2>/dev/null
    [ $? -eq 1 ] || pd_rc=1
    python - "$PD/diff.json" <<'PYEOF' || pd_rc=1
import json, sys
rep = json.load(open(sys.argv[1]))
assert rep["tool"] == "tracediff", rep
assert rep["gate"]["ok"] is False, rep["gate"]
assert "train" in rep["significant_regressions"], rep["significant_regressions"]
assert any("train" in v for v in rep["gate"]["violations"]), rep["gate"]
PYEOF
    rm -rf "$PD"
    if [ $pd_rc -eq 0 ]; then
        echo "PERFDIFF_DRILL=pass"
    else
        echo "PERFDIFF_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- timeline/bubble drill (intra-phase observability, obs/timeline+bubbles) --
# A traced wave-scheduled fused sweep (staging engine active, so
# overlap evidence exists) exported with `trace --timeline`: the JSON
# must validate against the trace-event schema (the same validator the
# tier-1 test runs — Perfetto-loadable structure), every span must land
# as an X event, and the bubble analysis must obey its accounting
# invariant: busy + idle == wall (per rank, summed) within tolerance.
if [ -z "$T1_SKIP_TIMELINE_DRILL" ]; then
    tl_rc=0
    TL=$(mktemp -d /tmp/_t1_tline.XXXXXX)
    timeout -k 10 180 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        --workload fashion_mlp --algorithm pbt --fused --no-mesh \
        --population 4 --generations 2 --steps-per-generation 2 \
        --wave-size 2 --seed 0 \
        --metrics-file "$TL/m.jsonl" --trace >/dev/null 2>&1 || tl_rc=1
    timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        trace "$TL/m.jsonl" --timeline "$TL/tl.json" --json \
        >"$TL/trace.json" 2>/dev/null || tl_rc=1
    python - "$TL/tl.json" "$TL/trace.json" <<'PYEOF' || tl_rc=1
import json, sys
from mpi_opt_tpu.obs.timeline import validate_timeline
doc = json.load(open(sys.argv[1]))
problems = validate_timeline(doc)
assert problems == [], problems
rep = json.load(open(sys.argv[2]))
xs = [e for e in doc["traceEvents"] if e["ph"] == "X" and e.get("cat") == "span"]
assert len(xs) == rep["span_records"], (len(xs), rep["span_records"])
bub = rep["bubbles"]
# the accounting invariant: busy + idle == wall (small epsilon only)
assert abs(bub["busy_s"] + bub["idle_s"] - bub["wall_s"]) < 0.05, bub
assert bub["idle_frac"] is not None
# the wave sweep staged, so overlap evidence must be in the stream
stg = rep["staging"]
assert stg is not None and stg["drains"] >= 2, stg
assert rep["roofline"]["bound"] in ("compute-bound", "transfer-bound", "bubble-bound")
PYEOF
    rm -rf "$TL"
    if [ $tl_rc -eq 0 ]; then
        echo "TIMELINE_DRILL=pass"
    else
        echo "TIMELINE_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- device-OOM drill (adaptive wave backoff, utils/resources.py) --
# Chaos drill A: a wave-mode fused PBT sweep with a synthetic XLA
# RESOURCE_EXHAUSTED injected at wave 3 (generation 2, wave 1) must
# COMPLETE via automatic wave-size backoff — the wave halves, the
# generation re-runs — with a ledger record-identical to an unfaulted
# run's (wave mode is bit-identical at any wave size, which is what
# makes the backoff safe), and both journals must pass report
# --validate.
if [ -z "$T1_SKIP_OOM_DRILL" ]; then
    om_rc=0
    OD=$(mktemp -d /tmp/_t1_oom.XXXXXX)
    timeout -k 10 240 env JAX_PLATFORMS=cpu python - "$OD" >/dev/null 2>&1 <<'PYEOF' || om_rc=1
import json, sys
from mpi_opt_tpu.cli import main
d = sys.argv[1]
args = ["--workload", "fashion_mlp", "--algorithm", "pbt", "--fused",
        "--no-mesh", "--population", "4", "--generations", "2",
        "--steps-per-generation", "2", "--seed", "0", "--wave-size", "2"]
assert main(args + ["--ledger", f"{d}/clean.jsonl"]) == 0
from mpi_opt_tpu.workloads.chaos import inject_oom
inj, un = inject_oom(at_launch=3, kind="wave")  # gen 2, wave 1
try:
    assert main(args + ["--ledger", f"{d}/oom.jsonl", "--oom-backoff", "2"]) == 0
finally:
    un()
assert inj.faults_fired == 1, inj.faults_fired  # the OOM really struck
keep = ("trial_id", "member", "boundary", "params", "status", "score", "step")
rec = lambda p: [{k: r.get(k) for k in keep}
                 for r in map(json.loads, open(p).read().splitlines()[1:])]
assert rec(f"{d}/clean.jsonl") == rec(f"{d}/oom.jsonl"), "ledger diverged"
PYEOF
    for L in clean oom; do
        timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
            report --validate "$OD/$L.jsonl" >/dev/null 2>&1 || om_rc=1
    done
    rm -rf "$OD"
    if [ $om_rc -eq 0 ]; then
        echo "OOM_DRILL=pass"
    else
        echo "OOM_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- shared-engine chaos drill (train/engine.py, all-algorithm waves) --
# The OOM drill above exercises PBT; this one proves the SAME engine
# contracts hold for the other boundary ops. (a) Fused SHA in wave
# mode with a RESOURCE_EXHAUSTED injected at its second rung must
# complete via wave-halving with a ledger record-identical to an
# unfaulted wave run's. (b) Fused TPE's wave mode must be
# record-identical to its resident mode (the bit-identity that makes
# the backoff safe, checked at the ledger). Both ledgers must pass
# report --validate.
if [ -z "$T1_SKIP_ENGINE_DRILL" ]; then
    eg_rc=0
    GD=$(mktemp -d /tmp/_t1_engine.XXXXXX)
    timeout -k 10 300 env JAX_PLATFORMS=cpu python - "$GD" >/dev/null 2>&1 <<'PYEOF' || eg_rc=1
import json, sys
from mpi_opt_tpu.cli import main
d = sys.argv[1]
keep = ("trial_id", "member", "boundary", "params", "status", "score", "step")
rec = lambda p: [{k: r.get(k) for k in keep}
                 for r in map(json.loads, open(p).read().splitlines()[1:])]

# (a) SHA rung-cut boundary: OOM at the second rung's wave (launch 3:
# rung 1 runs two waves of 4, rung 2's single wave is ordinal 3)
sha = ["--workload", "fashion_mlp", "--algorithm", "asha", "--fused",
       "--no-mesh", "--trials", "8", "--min-budget", "2",
       "--max-budget", "4", "--eta", "2", "--seed", "0",
       "--wave-size", "4"]
assert main(sha + ["--ledger", f"{d}/sha_clean.jsonl"]) == 0
from mpi_opt_tpu.workloads.chaos import inject_oom
inj, un = inject_oom(at_launch=3, kind="wave")
try:
    assert main(sha + ["--ledger", f"{d}/sha_oom.jsonl",
                       "--oom-backoff", "2"]) == 0
finally:
    un()
assert inj.faults_fired == 1, inj.faults_fired
assert rec(f"{d}/sha_clean.jsonl") == rec(f"{d}/sha_oom.jsonl"), "sha diverged"

# (b) TPE re-suggest boundary: waves must be invisible in the record
tpe = ["--workload", "fashion_mlp", "--algorithm", "tpe", "--fused",
       "--no-mesh", "--trials", "8", "--population", "4", "--budget", "2",
       "--seed", "0"]
assert main(tpe + ["--ledger", f"{d}/tpe_res.jsonl"]) == 0
assert main(tpe + ["--ledger", f"{d}/tpe_wave.jsonl",
                   "--wave-size", "2"]) == 0
assert rec(f"{d}/tpe_res.jsonl") == rec(f"{d}/tpe_wave.jsonl"), "tpe diverged"
PYEOF
    for L in sha_clean sha_oom tpe_res tpe_wave; do
        timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
            report --validate "$GD/$L.jsonl" >/dev/null 2>&1 || eg_rc=1
    done
    rm -rf "$GD"
    if [ $eg_rc -eq 0 ]; then
        echo "ENGINE_DRILL=pass"
    else
        echo "ENGINE_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- disk-full drill (ENOSPC prune-then-park, utils/resources.py) --
# Chaos drill B: an injected ENOSPC during a snapshot save (a disk
# that fills and STAYS full) gets exactly one retention-prune retry
# (the oldest superseded step reclaimed, the newest verified step
# never touched) and then parks with exit 74 — no torn step, nothing
# quarantined. After the injector clears, the ordinary --resume
# completes and fsck + report --validate exit 0.
if [ -z "$T1_SKIP_ENOSPC_DRILL" ]; then
    en_rc=0
    ED=$(mktemp -d /tmp/_t1_enospc.XXXXXX)
    timeout -k 10 180 env JAX_PLATFORMS=cpu python - "$ED" >/dev/null 2>&1 <<'PYEOF' || en_rc=1
import sys
from mpi_opt_tpu.cli import main
from mpi_opt_tpu.workloads.chaos import inject_enospc
d = sys.argv[1]
args = ["--workload", "quadratic", "--algorithm", "random", "--trials", "8",
        "--budget", "3", "--workers", "1", "--seed", "0",
        "--checkpoint-dir", f"{d}/ck", "--ledger", f"{d}/sweep.jsonl"]
inj, un = inject_enospc(fail_from=2, op="snapshot_save")
try:
    rc = main(args)
finally:
    un()
assert rc == 74, rc                    # classified park, not a traceback
assert inj.faults_fired == 2, inj.faults_fired  # first hit + ONE prune retry
assert main(args + ["--resume"]) == 0  # disk "freed": ordinary resume
PYEOF
    timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        fsck "$ED/ck" >/dev/null 2>&1 || en_rc=1
    timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        report --validate "$ED/sweep.jsonl" >/dev/null 2>&1 || en_rc=1
    rm -rf "$ED"
    if [ $en_rc -eq 0 ]; then
        echo "ENOSPC_DRILL=pass"
    else
        echo "ENOSPC_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- corpus drill (cross-sweep knowledge layer, corpus/; ISSUE 14) --
# Index a two-ledger mini-corpus (one exact-hash sweep ledger + one
# fabricated fuzzy-match ledger over a different-bounds space), run a
# sweep with `--warm-start auto:CORPUS`, and assert: the warm_start
# event names BOTH sources (exact + fuzzy), the sweep's ledger is
# record-identical to a manually-pointed `--warm-start exact.jsonl`
# run (the fuzzy prior is down-weighted low-fidelity evidence, never a
# seed-point hijacker), a deleted-ledger stale index entry degrades to
# a corpus_skip event (rc 0, not an error), and a suggestion server
# completes live suggest→report round trips over its spool.
if [ -z "$T1_SKIP_CORPUS_DRILL" ]; then
    cp_rc=0
    CP=$(mktemp -d /tmp/_t1_corpus.XXXXXX)
    timeout -k 10 240 env JAX_PLATFORMS=cpu python - "$CP" >/dev/null 2>&1 <<'PYEOF' || cp_rc=1
import json, os, sys, threading
from mpi_opt_tpu.cli import main
d = sys.argv[1]
C = os.path.join(d, "corpus"); os.makedirs(C)
base = ["--workload", "quadratic", "--algorithm", "random", "--budget", "3",
        "--workers", "1"]
assert main(base + ["--trials", "6", "--seed", "0",
                    "--ledger", f"{C}/exact.jsonl"]) == 0
# the fuzzy prior: same workload + dim names, different bounds (a
# different hash), every score BELOW the exact best
from mpi_opt_tpu.ledger import SweepLedger
from mpi_opt_tpu.space import LogUniform, SearchSpace, Uniform
from mpi_opt_tpu.trial import TrialResult
fz = SearchSpace({"lr": LogUniform(0.0005, 8.0), "reg": Uniform(0.0, 2.0)})
led = SweepLedger(f"{C}/fuzzy.jsonl")
led.ensure_header({"algorithm": "tpe", "workload": "quadratic",
                   "backend": "cpu", "seed": 1,
                   "space_hash": fz.space_hash()}, space_spec=fz.spec())
for i, (lr, reg, s) in enumerate([(0.01, 0.2, -5.0), (0.1, 0.4, -4.0),
                                  (1.0, 0.6, -6.0)]):
    led.record_trial(TrialResult(trial_id=i, score=s, step=3, wall_time=0.1),
                     fz.canonical_params({"lr": lr, "reg": reg}))
led.close()
assert main(["corpus", "index", C]) == 0
# auto vs manual: record-identical sweep ledgers
assert main(base + ["--trials", "5", "--seed", "7",
                    "--ledger", f"{d}/auto.jsonl",
                    "--warm-start", f"auto:{C}",
                    "--metrics-file", f"{d}/m.jsonl"]) == 0
assert main(base + ["--trials", "5", "--seed", "7",
                    "--ledger", f"{d}/manual.jsonl",
                    "--warm-start", f"{C}/exact.jsonl"]) == 0
keep = ("trial_id", "params", "status", "score", "step")
rec = lambda p: [{k: r[k] for k in keep}
                 for r in map(json.loads, open(p).read().splitlines()[1:])]
assert rec(f"{d}/auto.jsonl") == rec(f"{d}/manual.jsonl"), "auto != manual"
ws = [json.loads(l) for l in open(f"{d}/m.jsonl") if '"warm_start"' in l]
kinds = {s["match"] for s in ws[0]["sources"]}
assert kinds == {"exact", "fuzzy"}, ws  # both priors were picked
# stale index entry (deleted ledger) degrades to a corpus_skip event
os.unlink(f"{C}/fuzzy.jsonl")
assert main(base + ["--trials", "3", "--seed", "9",
                    "--ledger", f"{d}/stale.jsonl",
                    "--warm-start", f"auto:{C}",
                    "--metrics-file", f"{d}/m2.jsonl"]) == 0
skips = [json.loads(l) for l in open(f"{d}/m2.jsonl") if '"corpus_skip"' in l]
assert skips and "deleted" in skips[0]["reason"], skips
# suggestion service: live suggest→report round trips over the spool
from mpi_opt_tpu.corpus import client
from mpi_opt_tpu.corpus.serve import SuggestServer, serve_loop
from mpi_opt_tpu.utils.metrics import null_logger
from mpi_opt_tpu.workloads import get_workload
space = get_workload("quadratic").default_space()
server = SuggestServer(space, seed=0)
S = os.path.join(d, "sugg")
th = threading.Thread(target=lambda: serve_loop(
    server, S, null_logger(), poll_seconds=0.01, idle_timeout=60))
th.start()
try:
    ans = client.round_trip(S, {"op": "suggest", "n": 4}, timeout=60)
    assert len(ans["params"]) == 4, ans
    for p in ans["params"]:
        r = client.round_trip(S, {"op": "report", "params": p,
                                  "score": 0.5, "budget": 1}, timeout=30)
        assert r["ok"], r
finally:
    client.request_stop(S)
    th.join(timeout=60)
assert not th.is_alive()
PYEOF
    for L in auto manual stale; do
        timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
            report --validate "$CP/$L.jsonl" >/dev/null 2>&1 || cp_rc=1
    done
    rm -rf "$CP"
    if [ $cp_rc -eq 0 ]; then
        echo "CORPUS_DRILL=pass"
    else
        echo "CORPUS_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- front-door drill (overload-safe HTTP transport, service/http.py; ISSUE 16) --
# The overload + exactly-once acceptance in one pass. Against a live
# front door with a 1-deep admission queue: a 24-thread suggest storm
# must be ANSWERED — typed 503 sheds for the overflow (never a hang),
# bounded queue wait for the admitted. Then the durability half: one
# report batch lands, a second is in flight WHILE the server is
# SIGKILLed, a restarted server (--resume, same journal, new port)
# absorbs the client's idempotent retries THROUGH seeded network
# faults (refused connect + torn reply), a key reused with a different
# body is refused 409 — and the ledger must hold exactly ONE record
# per (idem_key, idem_op), passing report --validate.
if [ -z "$T1_SKIP_FRONTDOOR_DRILL" ]; then
    fd_rc=0
    FDD=$(mktemp -d /tmp/_t1_fdoor.XXXXXX)
    timeout -k 10 420 env JAX_PLATFORMS=cpu python - "$FDD" >/dev/null 2>&1 <<'PYEOF' || fd_rc=1
import json, os, signal, subprocess, sys, threading, time
d = sys.argv[1]
spool, led = os.path.join(d, "spool"), os.path.join(d, "suggest.jsonl")
env = dict(os.environ, JAX_PLATFORMS="cpu")

def start_server(extra=()):
    return subprocess.Popen(
        [sys.executable, "-m", "mpi_opt_tpu", "--workload", "quadratic",
         "--suggest-serve", spool, "--suggest-idle-timeout", "120",
         "--http-port", "0", "--http-queue", "1", "--seed", "0",
         "--ledger", led, *extra],
        cwd=os.getcwd(), env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

from mpi_opt_tpu.corpus import transport
from mpi_opt_tpu.corpus.client import discover_url
from mpi_opt_tpu.service.http import endpoint_path

def wait_url(pid, timeout=120):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            doc = json.load(open(endpoint_path(spool)))
            if doc.get("pid") == pid:
                return doc["url"]
        except (OSError, ValueError):
            pass
        time.sleep(0.05)
    raise TimeoutError(f"no endpoint from pid {pid}")

def ready(t):
    probe = transport.envelope([{"op": "suggest", "n": 1}], client="probe")
    transport.call_with_retries(t, "/v1/batch", probe, retries=8, backoff_s=0.25)

a = start_server()
url = wait_url(a.pid)
t = transport.HttpTransport(url, timeout=30)
ready(t)
params = t.call("/v1/batch", transport.envelope(
    [{"op": "suggest", "n": 6}], client="drill"))["results"][0]["params"]

# -- overload: 24 threads x 8 raw calls against a 1-deep queue --------
lock = threading.Lock()
stats = {"shed": 0, "answered": 0}
waits, problems = [], []
def storm(i):
    tr = transport.HttpTransport(url, timeout=30)
    for _ in range(8):
        try:
            ans = tr.call("/v1/batch", transport.envelope(
                [{"op": "suggest", "n": 64}], client=f"storm-{i}"))
            with lock:
                stats["answered"] += 1
                waits.append(float(ans["queue_wait_s"]))
        except (transport.Overloaded, transport.BreakerOpen):
            with lock:
                stats["shed"] += 1
        except transport.TransportFault as e:
            with lock:
                problems.append(f"storm-{i}: {type(e).__name__}: {e}")
threads = [threading.Thread(target=storm, args=(i,), daemon=True)
           for i in range(24)]
for th in threads:
    th.start()
for th in threads:
    th.join(timeout=180)
assert not any(th.is_alive() for th in threads), "a storm call HUNG"
assert not problems, problems[:3]
assert stats["shed"] >= 1, stats       # overload produced typed 503s
assert stats["answered"] >= 1, stats   # ...while admitted work was served
waits.sort()
p95 = waits[min(len(waits) - 1, int(0.95 * len(waits)))]
assert p95 < 10.0, f"admitted p95 queue wait {p95}s"  # bounded, 1-deep queue

# -- exactly-once: batch 1 lands, batch 2 in flight at SIGKILL --------
def report_env(key, ps):
    return transport.envelope(
        [{"op": "report", "params": p, "score": 0.5, "budget": 1} for p in ps],
        key=key, client="drill")
e1, e2 = report_env("drill-k1", params[:3]), report_env("drill-k2", params[3:])
ans1 = transport.call_with_retries(t, "/v1/batch", e1, retries=4)
assert not any(r.get("error") for r in ans1["results"]), ans1
killer = threading.Timer(0.05, lambda: a.kill())
killer.start()
try:
    transport.call_with_retries(t, "/v1/batch", e2, retries=2, backoff_s=0.05)
except transport.TransportFault:
    pass  # died mid-request: EITHER way the retry below must be exactly-once
killer.join()
assert a.wait(timeout=60) == -signal.SIGKILL

b = start_server(["--resume"])
url2 = wait_url(b.pid)
t2 = transport.HttpTransport(url2, timeout=30)
ready(t2)
# the client's idempotent retries, through seeded refuse+torn faults
from mpi_opt_tpu.workloads.chaos import inject_net
injector, uninstall = inject_net(refuse=1, torn=1, seed=3)
try:
    ans2 = transport.call_with_retries(t2, "/v1/batch", e2, retries=8,
                                       backoff_s=0.05)
finally:
    uninstall()
assert not any(r.get("error") for r in ans2["results"]), ans2
assert injector.faults_fired["refuse"] == 1 and injector.faults_fired["torn"] == 1
# batch 1's retry into the RESTART answers from the journal, no re-journal
re1 = transport.call_with_retries(t2, "/v1/batch", e1, retries=4)
assert all(r.get("journal_replayed") for r in re1["results"]), re1
# same key, different body: refused loudly, never replayed
try:
    t2.call("/v1/batch", report_env("drill-k1", params[3:]))
    raise AssertionError("key reuse with a different body was accepted")
except transport.KeyConflict:
    pass
t2.call("/v1/stop", {})
assert b.wait(timeout=120) == 0
recs = [json.loads(line) for line in open(led).read().splitlines()[1:]]
seen = [(r.get("idem_key"), r.get("idem_op")) for r in recs
        if r.get("idem_key")]
assert sorted(seen) == sorted(set(seen)), "a report journaled TWICE"
assert sorted(seen) == [("drill-k1", 0), ("drill-k1", 1), ("drill-k1", 2),
                        ("drill-k2", 0), ("drill-k2", 1), ("drill-k2", 2)], seen
PYEOF
    timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        report --validate "$FDD/suggest.jsonl" >/dev/null 2>&1 || fd_rc=1
    rm -rf "$FDD"
    if [ $fd_rc -eq 0 ]; then
        echo "FRONTDOOR_DRILL=pass"
    else
        echo "FRONTDOOR_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- Pareto drill (multi-objective subsystem, objectives/; ISSUE 17) --
# A 2-objective fused ASHA sweep (accuracy:max,params:min on digits_mlp,
# rungs [2,4,8] -> 11 member records) is hard-killed MID-JOURNAL of its
# second rung, then: fsck --ledger must FLAG the torn boundary (exit 1),
# --repair truncates it, --resume completes the sweep, and the resumed
# ledger's `report --json` Pareto block (front membership, vectors,
# hypervolume) must be IDENTICAL to an unkilled reference run's —
# crash-recovery of the vector journal, not just the scalar one. The
# report must also answer a --best-under constraint with exit 0, and the
# recovered tree/ledger must pass fsck + report --validate clean.
if [ -z "$T1_SKIP_PARETO_DRILL" ]; then
    po_rc=0
    PO=$(mktemp -d /tmp/_t1_pareto.XXXXXX)
    mo_sweep() {  # $1=ledger $2=ckpt-dir, then extra args
        timeout -k 10 300 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
            --workload digits_mlp --algorithm asha --fused --no-mesh \
            --trials 6 --min-budget 2 --max-budget 8 --eta 2 --seed 0 \
            --objectives accuracy:max,params:min \
            --checkpoint-dir "$2" --ledger "$1" "${@:3}" >/dev/null 2>&1
    }
    mo_front() {  # $1=ledger -> canonical multi_objective JSON on stdout
        timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
            report "$1" --json 2>/dev/null \
            | python -c 'import json, sys; print(json.dumps(
                json.load(sys.stdin)["ledgers"][0]["multi_objective"],
                sort_keys=True))'
    }
    mo_sweep "$PO/ref.jsonl" "$PO/rck" || po_rc=1
    mo_front "$PO/ref.jsonl" >"$PO/ref_mo.json" || po_rc=1
    # kill the sweep after 1 member record of rung 1 hit the disk
    timeout -k 10 300 env JAX_PLATFORMS=cpu python - "$PO" >/dev/null 2>&1 <<'PYEOF'
import os, sys
import mpi_opt_tpu.ledger.store as ls
orig = ls.SweepLedger._write_line
n = [0]
def dying_write(self, rec):
    orig(self, rec)
    n[0] += 1
    if n[0] == 8:  # header + rung 0's 6 records + 1 of rung 1: die
        os._exit(137)
ls.SweepLedger._write_line = dying_write
from mpi_opt_tpu.cli import main
d = sys.argv[1]
main(["--workload", "digits_mlp", "--algorithm", "asha", "--fused",
      "--no-mesh", "--trials", "6", "--min-budget", "2", "--max-budget", "8",
      "--eta", "2", "--seed", "0",
      "--objectives", "accuracy:max,params:min",
      "--checkpoint-dir", f"{d}/kck", "--ledger", f"{d}/killed.jsonl"])
PYEOF
    [ $? -eq 137 ] || po_rc=1                 # the kill must have landed
    pareto_fsck() {
        timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
            fsck "$PO/kck" --ledger "$PO/killed.jsonl" "$@" >/dev/null 2>&1
    }
    pareto_fsck; [ $? -eq 1 ] || po_rc=1      # torn boundary must be FLAGGED
    pareto_fsck --repair; [ $? -eq 1 ] || po_rc=1  # found + repaired contract
    mo_sweep "$PO/killed.jsonl" "$PO/kck" --resume || po_rc=1
    mo_front "$PO/killed.jsonl" >"$PO/killed_mo.json" || po_rc=1
    cmp -s "$PO/ref_mo.json" "$PO/killed_mo.json" || po_rc=1  # front identical
    timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        report "$PO/killed.jsonl" --best-under "params<=5000" \
        >/dev/null 2>&1 || po_rc=1
    timeout -k 10 120 env JAX_PLATFORMS=cpu python -m mpi_opt_tpu \
        report --validate "$PO/killed.jsonl" >/dev/null 2>&1 || po_rc=1
    pareto_fsck || po_rc=1                    # post-recovery audit is clean
    rm -rf "$PO"
    if [ $po_rc -eq 0 ]; then
        echo "PARETO_DRILL=pass"
    else
        echo "PARETO_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- sweeplint drill (static-analysis layer, analysis/) --
# The full invariant-checker suite over the repo at HEAD: exit 0 and
# ZERO non-baselined findings (the committed baseline is empty by
# policy — true positives are fixed, deliberate cases carry inline
# `# sweeplint: disable` reasons), with the JSON schema the CI gate
# parses. A finding here means a refactor regressed one of the
# machine-checked contracts (see README: Static analysis).
if [ -z "$T1_SKIP_LINT_DRILL" ]; then
    lint_rc=0
    LJ=$(mktemp /tmp/_t1_lint.XXXXXX.json)
    timeout -k 10 120 python -m mpi_opt_tpu \
        lint --json --baseline sweeplint-baseline.json >"$LJ" 2>/dev/null \
        || lint_rc=1
    python - "$LJ" <<'PYEOF' || lint_rc=1
import json, sys
rep = json.load(open(sys.argv[1]))
assert rep["ok"] is True, rep["findings"] or rep["errors"]
assert rep["tool"] == "sweeplint" and rep["findings"] == [], rep
assert rep["files_scanned"] > 50, rep["files_scanned"]  # scan saw the tree
PYEOF
    rm -f "$LJ"
    if [ $lint_rc -eq 0 ]; then
        echo "LINT_DRILL=pass"
    else
        echo "LINT_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- racelint drill (concurrency contracts, ISSUE 15) --
# Two halves of one guard-rail. Static: the five concurrency-contract
# checkers (guarded-by / beat-path-nonblocking / signal-safety /
# lock-order / fsync-before-rename) run with the whole suite over the
# repo — ok==true, 0 findings, 0 baselined entries (fix-or-disable
# policy), >95 files scanned, and the project symbol table actually
# discovered locks + thread entries (an empty table would be vacuously
# green). Runtime: a seeded A->B / B->A inversion over tracked locks
# must trip the lock-order sanitizer through the exact snapshot/leaks
# path the autouse fixture runs, and a consistent order must not.
if [ -z "$T1_SKIP_RACE_DRILL" ]; then
    race_rc=0
    RJ=$(mktemp /tmp/_t1_race.XXXXXX.json)
    timeout -k 10 120 python -m mpi_opt_tpu \
        lint --json --baseline sweeplint-baseline.json >"$RJ" 2>/dev/null \
        || race_rc=1
    python - "$RJ" <<'PYEOF' || race_rc=1
import json, sys
rep = json.load(open(sys.argv[1]))
assert rep["ok"] is True, rep["findings"] or rep["errors"]
assert rep["findings"] == [] and rep["baselined"] == [], rep
assert rep["files_scanned"] > 95, rep["files_scanned"]
ids = {c["id"] for c in rep["checks"]}
for need in ("guarded-by", "beat-path-nonblocking", "signal-safety",
             "lock-order", "fsync-before-rename"):
    assert need in ids, sorted(ids)
proj = rep["project"]
assert len(proj["locks"]) >= 5, proj["locks"]          # table saw the engine
assert proj["thread_entries"], proj                     # staging thread found
assert proj["signal_handlers"], proj                    # ShutdownGuard found
assert proj["lock_order"]["cycles"] == [], proj["lock_order"]
PYEOF
    timeout -k 10 120 env JAX_PLATFORMS=cpu python - <<'PYEOF' >/dev/null 2>&1 || race_rc=1
import sys
sys.path.insert(0, "tests")
import sanitizers
sanitizers.install_lock_order_tracker()
a = sanitizers.tracked_lock("drill-a")
b = sanitizers.tracked_lock("drill-b")
# seeded inversion: the sanitizer must trip through snapshot/leaks —
# the same path the autouse fixture judges every tier-1 test by
before = sanitizers.snapshot()
with a:
    with b:
        pass
with b:
    with a:
        pass
problems = sanitizers.leaks(before)
assert any("lock-order inversion" in p for p in problems), problems
# and a consistent order stays silent in a fresh window
before = sanitizers.snapshot()
with a:
    with b:
        pass
with a:
    with b:
        pass
assert sanitizers.leaks(before) == [], sanitizers.leaks(before)
PYEOF
    rm -f "$RJ"
    if [ $race_rc -eq 0 ]; then
        echo "RACE_DRILL=pass"
    else
        echo "RACE_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi

# -- SPMD drill (rank-fault-tolerant multi-process, parallel/coord.py; ISSUE 20) --
# The full escalation ladder as a real 2-rank launch.py run: a chaos
# rank-kill SIGKILLs rank 1 at its second boundary, the survivor
# freezes in the agreement barrier (last beat `boundary:*`), the
# supervisor classifies the COLLECTIVE WEDGE (rank_wedge event),
# TERM-drains the survivor after --term-grace, and restarts BOTH ranks
# coordinated (--resume, fresh coord epoch) — completing with a ledger
# record-identical to an unkilled 2-rank reference run's. Slow-marked
# in pytest (two supervised multi-rank sweeps), so tier-1 runs it here
# as a drill instead of inside the 870 s suite budget.
if [ -z "$T1_SKIP_SPMD_DRILL" ]; then
    sp_rc=0
    timeout -k 10 580 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_coord.py -q -m slow -k escalates \
        -p no:cacheprovider -p no:xdist -p no:randomly \
        >/dev/null 2>&1 || sp_rc=1
    if [ $sp_rc -eq 0 ]; then
        echo "SPMD_DRILL=pass"
    else
        echo "SPMD_DRILL=FAIL"
        rc=$(( rc == 0 ? 1 : rc ))
    fi
fi
exit $rc
