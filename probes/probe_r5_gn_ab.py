"""Round-5: A/B the fused Pallas GN+ReLU kernel inside the ResNet
population segment (pop=64, member_chunk=8, remat, 50 steps) on the
real chip — wall AND a 2-gen learning sanity check, per the
pool-swap-probe protocol."""
import statistics
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

from mpi_opt_tpu.train.population import OptHParams
from mpi_opt_tpu.workloads.vision import Cifar100ResNet18

POP, STEPS, REPS, CHUNK = 64, 50, 3, 8


def segment_wall(wl, label):
    from mpi_opt_tpu.train.common import workload_arrays

    trainer, space, tx, ty, vx, vy = workload_arrays(wl, CHUNK)
    st = trainer.init_population(jax.random.key(0), tx[:2], POP)
    hp = OptHParams.defaults(POP, lr=0.05)
    t0 = time.perf_counter()
    st, losses = trainer.train_segment(st, hp, tx, ty, jax.random.key(1), STEPS)
    np.asarray(losses)
    warm = time.perf_counter() - t0
    walls = []
    for i in range(REPS):
        t0 = time.perf_counter()
        st, losses = trainer.train_segment(
            st, hp, tx, ty, jax.random.fold_in(jax.random.key(2), i), STEPS
        )
        np.asarray(losses)
        walls.append(time.perf_counter() - t0)
    med = statistics.median(walls)
    print(f"{label:18s}: {med:.3f}s (warm {warm:.0f}s) {['%.3f' % w for w in walls]} "
          f"({POP*STEPS/med:.1f} member-steps/s)", flush=True)
    return med


def learn2g(wl, label):
    from mpi_opt_tpu.train.fused_pbt import fused_pbt

    res = fused_pbt(wl, population=32, generations=2, steps_per_gen=100,
                    seed=0, gen_chunk=1, member_chunk=CHUNK, snapshot_last=False)
    print(f"{label:18s}: learn2g best={res['best_score']:.4f}", flush=True)


print(f"device: {jax.devices()[0].device_kind}", flush=True)
base = segment_wall(Cifar100ResNet18(pallas_gn=False), "xla-gn")
pal = segment_wall(Cifar100ResNet18(pallas_gn=True), "pallas-gn")
print(f"delta: {(base-pal)/base*100:+.1f}% ({base-pal:+.3f}s)", flush=True)
learn2g(Cifar100ResNet18(pallas_gn=False), "xla-gn")
learn2g(Cifar100ResNet18(pallas_gn=True), "pallas-gn")
