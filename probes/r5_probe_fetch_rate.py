import sys, time
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
import numpy as np
from mpi_opt_tpu.workloads.vision import Cifar100ResNet18
from mpi_opt_tpu.train.common import workload_arrays

wl = Cifar100ResNet18()
trainer, space, tx, ty, vx, vy = workload_arrays(wl, 8)
st = trainer.init_population(jax.random.key(0), tx[:2], 64)
leaves = jax.tree.leaves({"p": st.params, "m": st.momentum})
nbytes = sum(l.nbytes for l in leaves)
print(f"pool bytes: {nbytes/1e9:.2f} GB, {len(leaves)} leaves", flush=True)
t0 = time.perf_counter()
host = jax.device_get({"p": st.params, "m": st.momentum})
w = time.perf_counter() - t0
print(f"device_get: {w:.1f}s = {nbytes/w/1e6:.1f} MB/s", flush=True)
