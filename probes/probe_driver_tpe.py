"""Where does config-4's driver wall go?"""
import sys, time
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
from mpi_opt_tpu.algorithms import get_algorithm
from mpi_opt_tpu.backends import get_backend
from mpi_opt_tpu.driver import run_search
from mpi_opt_tpu.workloads import get_workload

wl = get_workload("tabular_mlp")
space = wl.default_space()
cls = get_algorithm("tpe")
be = get_backend("tpu", wl, population=64, seed=0)
run_search(cls(space, seed=1, max_trials=192, budget=30), be)
be.reset()

algo = cls(space, seed=0, max_trials=256, budget=30)
t_nb = t_rb = t_ev = 0.0
nb0, rb0, ev0 = algo.next_batch, algo.report_batch, be.evaluate
calls = []
def nb(n):
    global t_nb; t0=time.perf_counter(); out=nb0(n); t_nb += time.perf_counter()-t0; return out
def rb(r):
    global t_rb; t0=time.perf_counter(); out=rb0(r); t_rb += time.perf_counter()-t0; return out
def ev(ts):
    global t_ev; t0=time.perf_counter(); out=ev0(ts); d=time.perf_counter()-t0; t_ev += d; calls.append((len(ts), d)); return out
algo.next_batch, algo.report_batch, be.evaluate = nb, rb, ev
t0 = time.perf_counter()
res = run_search(algo, be)
wall = time.perf_counter()-t0
be.close()
print(f"wall {wall:.2f}s nb {t_nb:.2f}s rb {t_rb:.2f}s ev {t_ev:.2f}s calls {calls}")
