"""Fused config-3 5x slowdown: reproduce with minimal sweep, timed per launch."""
import sys, time
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
from mpi_opt_tpu.train.fused_pbt import fused_pbt
from mpi_opt_tpu.workloads import get_workload

wl = get_workload("cifar10_cnn")
kw = dict(population=32, generations=2, steps_per_gen=100, seed=0, gen_chunk=2)
for i in range(3):
    t0 = time.perf_counter()
    res = fused_pbt(wl, **kw)
    print(f"run {i}: {time.perf_counter()-t0:.1f}s launch_walls={[round(w,1) for w in res['launch_walls']]}")
