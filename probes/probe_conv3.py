import time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

conv1 = lambda xi, wi: jax.lax.conv_general_dilated(xi, wi, (1,1), "SAME", dimension_numbers=("NHWC","HWIO","NHWC"))

def bench(P, B, HW, C, O, n=10):
    x = jax.random.normal(jax.random.key(0), (P, B, HW, HW, C), jnp.bfloat16)
    w = jax.random.normal(jax.random.key(1), (P, 3, 3, C, O), jnp.bfloat16) * 0.05
    fn = jax.vmap(conv1)
    loss = lambda x, w: jnp.sum(fn(x, w) ** 2).astype(jnp.float32)

    @jax.jit
    def step(x, w):
        gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
        # chain: output feeds next input so iterations can't collapse
        return x + 1e-6 * gx, w + 1e-6 * gw

    x1, w1 = step(x, w)
    np.asarray(jnp.sum(w1))  # force full completion
    t0 = time.perf_counter()
    for _ in range(n):
        x1, w1 = step(x1, w1)
    np.asarray(jnp.sum(w1))  # host fetch = real barrier
    dt = (time.perf_counter() - t0) / n
    fl = 3 * 2 * P*B*HW*HW*9*C*O
    print(f"P={P} B={B} {HW}x{HW} C={C} O={O}: {dt*1e3:.2f} ms ({fl/dt/1e12:.1f} TF/s)", flush=True)

bench(32, 256, 32, 32, 32)
bench(32, 256, 32, 64, 64)
bench(32, 128, 32, 128, 128)
bench(32, 256, 16, 64, 64)
bench(32, 256, 16, 128, 128)
