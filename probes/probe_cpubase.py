import sys, time
sys.path.insert(0, "/root/repo")
sys.argv = ["bench.py"]
import bench
t0 = time.perf_counter()
tps = bench.bench_cpu_baseline(steps=100, seed=0, n_workers=1)
print(f"POOL_TPS {tps} total_wall {time.perf_counter()-t0:.1f}s")
