"""Is XLA's GroupNorm already at the bandwidth floor at config-3 shapes?

GN fwd+bwd at the workload's activation shape, fori-loop fetch-once
harness. Floor = minimum HBM passes (fwd: read x + write y; bwd: read
x, dy + write dx) at the platform's measured effective bandwidth
(~100-200 GB/s, PERF_NOTES). If measured ~ floor, a fused Pallas GN
has no headroom; if >> floor, XLA is making extra passes worth fusing.
"""
import statistics, sys, time
sys.path.insert(0, "/root/repo")
import flax.linen as nn
import jax, jax.numpy as jnp
import numpy as np
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

P, B, H, W, C = 32, 256, 32, 32, 32
gn = nn.GroupNorm(num_groups=8, dtype=jnp.bfloat16)
key = jax.random.key(0)
x = jax.random.normal(key, (P, B, H, W, C), jnp.bfloat16)
params = jax.vmap(lambda k: gn.init(k, jnp.zeros((B, H, W, C), jnp.bfloat16)))(
    jax.random.split(key, P))

def loss(p, x):
    y = jax.vmap(lambda pm, xm: gn.apply(pm, xm))(p, x)
    return jnp.sum(nn.relu(y).astype(jnp.float32)) * 1e-9

ITERS = 20
@jax.jit
def run(p, x):
    def body(i, acc):
        l, grads = jax.value_and_grad(loss, argnums=(0, 1))(p, x + acc * 1e-20)
        return acc + l + jnp.sum(grads[1][0, 0, 0, 0, 0].astype(jnp.float32))
    return jax.lax.fori_loop(0, ITERS, body, 0.0)

float(run(params, x))  # compile
walls = []
for _ in range(3):
    t0 = time.perf_counter(); float(run(params, x)); walls.append(time.perf_counter() - t0)
per_iter = statistics.median(walls) / ITERS
gb = P * B * H * W * C * 2 / 1e9  # one pass over the activation, bf16
# fwd: read x, write y (2 passes) + bwd: read x, read dy, write dx (3)
floor_gb = 5 * gb
print(f"per-iter {per_iter*1e3:.1f} ms; activation pass = {gb:.2f} GB; "
      f"5-pass floor at 150 GB/s = {floor_gb/150*1e3:.1f} ms; "
      f"implied bw if floor-bound = {floor_gb/per_iter:.0f} GB/s")
