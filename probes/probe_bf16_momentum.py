"""Probe: does storing momentum in bf16 buy back optimizer-update bandwidth?

Round-2 trace: the per-layer SGD+momentum update fusions are ~26% of
device time and run at the platform's measured effective HBM bandwidth
(PERF_NOTES.md "Trace-level breakdown") — not fusible further, so the
only lever is BYTES. Momentum stored bf16 cuts the update's traffic
from 20 B/elem (read g,m,p + write m,p at f32) to 16 B/elem — a ~20%
cut on a 26% slice, ~5% end-to-end ceiling. Worth one measured A/B:
throughput AND learning (bf16 momentum rounds small gradient
accumulations to zero; the probe must show the curve is intact, not
just that it's faster — the pool-swap probe died on exactly that).

A/B on the real chip, north-star shapes (SmallCNN, pop=256, batch 256):
  python probes/probe_bf16_momentum.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

from mpi_opt_tpu.train.fused_pbt import fused_pbt  # noqa: E402
from mpi_opt_tpu.workloads import get_workload  # noqa: E402


def run(momentum_dtype, pop=256, gens=2, steps=100):
    wl = get_workload("cifar10_cnn")
    kw = dict(
        population=pop,
        generations=gens,
        steps_per_gen=steps,
        seed=0,
        # bench.py's north-star settings: unchunked pop>=128 fails at the
        # remote compiler (PERF_NOTES.md "remote-compiler limits")
        member_chunk=32,
        gen_chunk=1,
    )
    # the env knob is part of workload_arrays' trainer cache key, so
    # each arm gets its own trainer without manual cache surgery
    os.environ["MPI_OPT_TPU_MOMENTUM_DTYPE"] = momentum_dtype
    try:
        fused_pbt(wl, **kw)  # warm
        t0 = time.perf_counter()
        res = fused_pbt(wl, **kw)
        wall = time.perf_counter() - t0
    finally:
        os.environ.pop("MPI_OPT_TPU_MOMENTUM_DTYPE", None)
    curve = [round(float(v), 4) for v in res["best_curve"]]
    rate = pop * gens / wall
    print(f"momentum={momentum_dtype}: {wall:.1f}s = {rate:.2f} member-gens/s "
          f"best={res['best_score']:.4f} curve={curve}", flush=True)
    return wall, res


if __name__ == "__main__":
    run("float32")
    run("bfloat16")
    run("float32")  # repeat to bound run-to-run noise
