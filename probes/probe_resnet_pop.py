import sys, time
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
from mpi_opt_tpu.train.fused_pbt import fused_pbt
from mpi_opt_tpu.workloads import get_workload
wl = get_workload("cifar100_resnet18")
for pop in (96, 128):
    kw = dict(population=pop, generations=2, steps_per_gen=50, seed=0,
              member_chunk=8, gen_chunk=1)
    try:
        t0 = time.perf_counter(); fused_pbt(wl, **kw)
        warm = time.perf_counter() - t0
        t0 = time.perf_counter(); r = fused_pbt(wl, **kw)
        wall = time.perf_counter() - t0
        print(f"pop={pop}: OK {pop*2/wall:.3f} trials/s (wall {wall:.1f}s warm {warm:.0f}s)", flush=True)
    except Exception as e:
        print(f"pop={pop}: FAIL {type(e).__name__} {str(e)[:100]}", flush=True)
        break
