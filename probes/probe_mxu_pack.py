"""Does packing population members into MXU lanes help? (VERDICT r3 item 4)

The population conv is block-diagonal as a bilinear form: member m's
output needs member m's activations AND member m's weights, so any
dense-matmul packing of k members into the 128-lane dimension must
either (a) replicate the K (reduction) dimension k-fold with a
block-diagonal weight matrix — doing k x the FLOPs — or (b) give each
member its own matmul with N = Cout lanes. There is no formulation
where k members share one LHS: the lane fill gained is exactly paid
back in wasted MACs. This probe measures that equivalence on the real
chip rather than asserting it:

  t_single   : [M, 288] @ [288, 32]    — one member's conv-as-matmul
               (Cout=32 fills 32/128 lanes; the production economics)
  t_packed   : [M, 1152] @ [1152, 128] — 4 members block-diag packed
               (full lanes, 4x K; one packed step does 4 members' work)
  t_ideal    : [M, 288] @ [288, 128]   — the impossible target: full
               lanes WITHOUT the K replication (what packing would
               need to cost to be a win)

Refutation criterion: if t_packed >= ~4 x t_single (same useful-FLOP
rate), lane packing cannot beat per-member matmuls, and the XLA
dilated-conv lowering (measured on par with grouped conv and 9x better
than materialized im2col — probes/probe_conv2.py, probe_conv3.py) is
already at the structural limit for Cout=32 convs.

Run from /root/repo: python probes/probe_mxu_pack.py
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np


def timed(fn, *args, iters=30):
    """Median wall of fn(*args) with a host-fetch barrier (PERF_NOTES:
    block_until_ready does not reliably block under the axon plugin)."""
    out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0][0, 0])  # warm + barrier
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        np.asarray(jax.tree.leaves(out)[0][0, 0])
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def chain(k, n, reps=16):
    """A jitted chain of `reps` independent [M,k]@[k,n] matmuls so the
    per-dispatch overhead (~3-5 ms, PERF_NOTES) is amortized."""
    M = 8192
    key = jax.random.key(0)
    a = jax.random.normal(key, (reps, M, k), jnp.bfloat16)
    b = jax.random.normal(key, (reps, k, n), jnp.bfloat16) * 0.01

    @jax.jit
    def step(a, b):
        # independent matmuls (not a chain through one buffer): mirrors
        # the per-layer convs of independent members
        return jnp.einsum("rmk,rkn->rmn", a, b)

    t = timed(step, a, b)
    useful = 2 * reps * M * k * n
    return t, useful


def main():
    print(f"device: {jax.devices()[0].device_kind}", flush=True)
    t_single, f_single = chain(288, 32)
    t_packed, f_packed = chain(1152, 128)  # 4-member block-diag: useful FLOPs = f/4
    t_ideal, f_ideal = chain(288, 128)

    # per-member-conv cost under each scheme
    per_single = t_single  # 16 convs of 1 member each -> 16 member-convs
    per_packed = t_packed / 4  # each packed matmul does 4 members
    rate = lambda f, t: f / t / 1e12
    print(
        f"single (N=32, 25% lanes): {t_single*1e3:8.2f} ms "
        f"{rate(f_single, t_single):6.1f} TF/s useful"
    )
    print(
        f"packed (N=128, 4x K):     {t_packed*1e3:8.2f} ms "
        f"{rate(f_packed/4, t_packed):6.1f} TF/s useful "
        f"({rate(f_packed, t_packed):5.1f} raw)"
    )
    print(
        f"ideal  (N=128, 1x K):     {t_ideal*1e3:8.2f} ms "
        f"{rate(f_ideal, t_ideal):6.1f} TF/s useful (unreachable bound)"
    )
    ratio = per_packed / per_single
    print(f"\npacked/single cost per member-conv: {ratio:.2f}x "
          f"({'packing LOSES' if ratio > 0.95 else 'packing WINS'})")


if __name__ == "__main__":
    main()
