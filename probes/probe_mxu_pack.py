"""Does packing population members into MXU lanes help? (VERDICT r3 item 4)

The population conv is block-diagonal as a bilinear form: member m's
output needs member m's activations AND member m's weights, so any
dense-matmul packing of k members into the 128-lane dimension must
either (a) replicate the K (reduction) dimension k-fold with a
block-diagonal weight matrix — doing k x the MACs — or (b) give each
member its own matmul with N = Cout lanes. There is no formulation
where k members share one LHS: the lane fill gained is exactly paid
back in wasted MACs. This probe measures that equivalence on the real
chip rather than asserting it.

Measured 2026-07-30 (this container's tunneled v5e):

    single member   [8192,288]@[288,32]    : 14.3 TF/s useful
    4-pack blockdiag [8192,1152]@[1152,128]: 57.1 raw = 14.3 TF/s useful
    same-K full-lane [8192,288]@[288,128]  : 23.9 TF/s (unreachable bound)
    cap             4096^3                 : 157  TF/s

packed == single to three digits -> packing refuted; see PERF_NOTES.md
"Round 3 — MXU member-packing refuted by measurement". The same run
exposed that the round-2 platform-cap probe underread the machine 2.4x
(64.8 vs 157 TF/s) — bench.py's measure_platform_cap now uses this
harness's pattern.

Harness notes (both matter, both measured today):
- the tunnel's per-FETCH round trip is 20-90 ms; loop the work inside
  one program behind a scalar serial dependency and fetch once;
- `x = a + s` (s the carried scalar) defeats loop-invariant hoisting
  without serializing through the full result matrix the way round 2's
  `b = (a @ b) * 1e-3` chain did.

Run from /root/repo: python probes/probe_mxu_pack.py
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np


def rate(M, K, N, loops, iters=4):
    """TF/s of [M,K]@[K,N] bf16 matmuls, `loops` per program, one fetch."""
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.bfloat16)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.bfloat16) * 0.01

    @jax.jit
    def step(a, b):
        def body(i, s):
            x = a + s
            y = x @ b
            return jnp.sum(y).astype(jnp.bfloat16) * jnp.bfloat16(1e-9)

        return jax.lax.fori_loop(0, loops, body, jnp.bfloat16(0))

    float(step(a, b))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        s = step(a, b)
    float(s)
    t = (time.perf_counter() - t0) / iters
    return 2 * M * K * N * loops / t / 1e12


def main():
    print(f"device: {jax.devices()[0].device_kind}", flush=True)
    r_single = rate(8192, 288, 32, 8000)
    r_packed = rate(8192, 1152, 128, 2000)  # one packed matmul = 4 members
    r_ideal = rate(8192, 288, 128, 2000)
    r_cap = rate(4096, 4096, 4096, 200)
    print(f"single (N=32, 25% lanes):     {r_single:6.1f} TF/s useful")
    print(f"packed (N=128, 4x K): raw     {r_packed:6.1f} -> useful {r_packed/4:6.1f} TF/s")
    print(f"ideal  (N=128, 1x K, bound):  {r_ideal:6.1f} TF/s")
    print(f"cap    (4096^3):              {r_cap:6.1f} TF/s")
    win = r_packed / 4 / r_single
    print(f"\npacked/single useful rate: {win:.2f}x "
          f"({'packing WINS' if win > 1.05 else 'packing refuted'})")


if __name__ == "__main__":
    main()
