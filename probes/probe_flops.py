import jax, jax.numpy as jnp
import numpy as np

def cost(fn, *args):
    c = jax.jit(fn).lower(*args).compile().cost_analysis()
    if isinstance(c, (list, tuple)): c = c[0]
    return c.get("flops")

A = jnp.zeros((1024, 1024), jnp.bfloat16); B = jnp.zeros((1024, 1024), jnp.bfloat16)
print("matmul flops:", cost(lambda a, b: a @ b, A, B), "expected 2.15e9")
x = jnp.zeros((256, 32, 32, 32), jnp.bfloat16)
w = jnp.zeros((3, 3, 32, 32), jnp.bfloat16)
conv = lambda x, w: jax.lax.conv_general_dilated(x, w, (1,1), "SAME", dimension_numbers=("NHWC","HWIO","NHWC"))
print("conv flops:", cost(conv, x, w), "expected 4.8e9")
# vmapped conv over 8 members
wv = jnp.zeros((8, 3, 3, 32, 32), jnp.bfloat16)
xv = jnp.zeros((8, 256, 32, 32, 32), jnp.bfloat16)
vconv = jax.vmap(conv)
print("vmap conv flops:", cost(vconv, xv, wv), "expected 3.9e10")
