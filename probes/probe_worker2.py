import sys, time, os
sys.path.insert(0, "/root/repo")
os.environ["MPI_OPT_TPU_CPU_CACHE_DIR"] = "/tmp/jax_cache_cpu_native"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_cpu_native")
from mpi_opt_tpu.workloads import get_workload

wl = get_workload("cifar10_cnn")
p = {"lr": 0.1, "momentum": 0.9, "weight_decay": 1e-4, "flip_prob": 0.2, "shift": 2.0}
for budget in (5, 5, 25):  # first 5 includes compile; second is pure exec
    t0 = time.perf_counter()
    s = wl.evaluate(p, budget=budget, seed=0)
    print(f"evaluate(budget={budget}): {time.perf_counter()-t0:.1f}s score={s:.3f}", flush=True)
