import sys, time, jax, numpy as np
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
from mpi_opt_tpu.train.fused_pbt import fused_pbt
from mpi_opt_tpu.workloads import get_workload
wl = get_workload("cifar10_cnn")
G, S = 2, 100
for P in (32, 128, 256, 512):
    try:
        t0 = time.time()
        r = fused_pbt(wl, population=P, generations=G, steps_per_gen=S, seed=0)
        cold = time.time()-t0
        r = None
        t0 = time.time()
        r = fused_pbt(wl, population=P, generations=G, steps_per_gen=S, seed=0)
        dt = time.time()-t0
        print(f"P={P}: cold {cold:.1f}s warm {dt:.2f}s -> {P*G/dt:.2f} member-gens/s", flush=True)
        r = None
    except Exception as e:
        print(f"P={P} FAIL {type(e).__name__} {str(e)[:120]}", flush=True)
