import sys, time, os
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_cpu")
from mpi_opt_tpu.workloads import get_workload

t0 = time.perf_counter()
wl = get_workload("cifar10_cnn")
d = wl.data()
print(f"data gen: {time.perf_counter()-t0:.1f}s train={d['train_x'].shape}", flush=True)
t0 = time.perf_counter()
score = wl.evaluate({"lr": 0.1, "momentum": 0.9, "weight_decay": 1e-4,
                     "flip_prob": 0.2, "shift": 2.0}, budget=5, seed=0)
print(f"evaluate(budget=5): {time.perf_counter()-t0:.1f}s score={score:.3f}", flush=True)
t0 = time.perf_counter()
score = wl.evaluate({"lr": 0.1, "momentum": 0.9, "weight_decay": 1e-4,
                     "flip_prob": 0.2, "shift": 2.0}, budget=100, seed=0)
print(f"evaluate(budget=100): {time.perf_counter()-t0:.1f}s score={score:.3f}", flush=True)
