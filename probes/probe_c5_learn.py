"""Probe: does the ResNet-18/CIFAR-100-synthetic config learn, and how fast?

Round-2 verdict item 3: config 5's bench showed best val-acc 0.0239
(chance = 0.01) after 2 gens x 50 steps — a throughput demo. Before
paying for the full pop=64 learning sweep, chart the trajectory at a
smaller population to calibrate generations needed (and the dataset's
difficulty, if the curve is flat).

Run on the real chip: python probes/probe_c5_learn.py [pop] [gens] [steps]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

from mpi_opt_tpu.train.fused_pbt import fused_pbt  # noqa: E402
from mpi_opt_tpu.workloads import get_workload  # noqa: E402

pop = int(sys.argv[1]) if len(sys.argv) > 1 else 32
gens = int(sys.argv[2]) if len(sys.argv) > 2 else 10
steps = int(sys.argv[3]) if len(sys.argv) > 3 else 50

wl = get_workload("cifar100_resnet18")
t0 = time.perf_counter()
res = fused_pbt(
    wl,
    population=pop,
    generations=gens,
    steps_per_gen=steps,
    seed=0,
    member_chunk=8,
    gen_chunk=1,
)
wall = time.perf_counter() - t0
curve = [round(float(v), 4) for v in res["best_curve"]]
print(f"pop={pop} gens={gens} steps={steps} wall={wall:.1f}s")
print(f"best={res['best_score']:.4f}")
print(f"curve={curve}")
print(f"launch_walls={[round(w, 1) for w in res['launch_walls']]}")
print(f"best_params={ {k: round(v, 4) if isinstance(v, float) else v for k, v in res['best_params'].items()} }")
