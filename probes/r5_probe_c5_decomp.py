import sys, statistics, time
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
import numpy as np
from mpi_opt_tpu.train.population import OptHParams
from mpi_opt_tpu.workloads.vision import Cifar100ResNet18
from mpi_opt_tpu.train.common import workload_arrays

POP, STEPS = 64, 50
wl = Cifar100ResNet18()
trainer, space, tx, ty, vx, vy = workload_arrays(wl, 8)
print("val set:", vx.shape, flush=True)
st = trainer.init_population(jax.random.key(0), tx[:2], POP)
hp = OptHParams.defaults(POP, lr=0.05)

# warm all three programs
st, losses = trainer.train_segment(st, hp, tx, ty, jax.random.key(1), STEPS)
scores = trainer.eval_population(st, vx, vy); np.asarray(scores)
st2 = trainer.gather_members(st, jax.numpy.arange(POP)[::-1]); np.asarray(jax.tree.leaves(st2.params)[0][:1, :1])
st = st2

def med(fn, n=3):
    walls = []
    for i in range(n):
        t0 = time.perf_counter(); fn(i); walls.append(time.perf_counter() - t0)
    return statistics.median(walls), walls

def _train(i):
    global st
    st, losses = trainer.train_segment(st, hp, tx, ty, jax.random.fold_in(jax.random.key(2), i), STEPS)
    np.asarray(losses)
t, tw = med(_train)
print(f"train 50 steps : {t:.3f}s {['%.2f' % w for w in tw]}", flush=True)

def _eval(i):
    np.asarray(trainer.eval_population(st, vx, vy))
t, ew = med(_eval)
print(f"eval_population: {t:.3f}s {['%.2f' % w for w in ew]}", flush=True)

def _gather(i):
    global st
    st = trainer.gather_members(st, jax.numpy.arange(POP)[::-1])
    np.asarray(jax.tree.leaves(st.params)[0][:1, :1])
t, gw = med(_gather)
print(f"exploit gather : {t:.3f}s {['%.2f' % w for w in gw]}", flush=True)

# whole fused generation for reference (train+eval+exploit in ONE program)
from mpi_opt_tpu.train.fused_pbt import run_fused_pbt
from mpi_opt_tpu.train.common import HParamsFn
import jax.numpy as jnp
hf = HParamsFn(space, wl)
disc = tuple(bool(b) for b in space.discrete_mask())
unit = jnp.full((POP, space.dim), 0.5, jnp.float32)
key = jax.random.key(3)
out = run_fused_pbt(trainer, st, unit, hf, tx, ty, vx, vy, key, disc, 1, STEPS)
np.asarray(out[3])  # warm
st, unit, key = out[0], out[1], out[2]
def _gen(i):
    global st, unit, key
    st, unit, key, best, mean, fs = run_fused_pbt(trainer, st, unit, hf, tx, ty, vx, vy, key, disc, 1, STEPS)
    np.asarray(best)
t, fw = med(_gen)
print(f"fused 1-gen    : {t:.3f}s {['%.2f' % w for w in fw]}", flush=True)
