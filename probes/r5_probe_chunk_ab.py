import sys, statistics, time
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
import numpy as np
from mpi_opt_tpu.train.population import OptHParams
from mpi_opt_tpu.workloads.vision import Cifar100ResNet18
from mpi_opt_tpu.train.common import workload_arrays

POP, STEPS = 64, 50
for chunk in (8, 16, 32, 0):
    try:
        wl = Cifar100ResNet18()
        trainer, space, tx, ty, vx, vy = workload_arrays(wl, chunk)
        st = trainer.init_population(jax.random.key(0), tx[:2], POP)
        hp = OptHParams.defaults(POP, lr=0.05)
        t0 = time.perf_counter()
        st, losses = trainer.train_segment(st, hp, tx, ty, jax.random.key(1), STEPS)
        np.asarray(losses)
        warm = time.perf_counter() - t0
        walls = []
        for i in range(3):
            t0 = time.perf_counter()
            st, losses = trainer.train_segment(st, hp, tx, ty, jax.random.fold_in(jax.random.key(2), i), STEPS)
            np.asarray(losses)
            walls.append(time.perf_counter() - t0)
        med = statistics.median(walls)
        print(f"member_chunk={chunk:3d}: {med:.3f}s (warm {warm:.0f}s) "
              f"{['%.2f' % w for w in walls]} ({POP*STEPS/med:.1f} member-steps/s)", flush=True)
    except Exception as e:
        print(f"member_chunk={chunk:3d}: FAIL {type(e).__name__} {str(e)[:140]}", flush=True)
