import time
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
from mpi_opt_tpu.train.fused_pbt import fused_pbt
from mpi_opt_tpu.workloads import get_workload
wl = get_workload("cifar10_cnn")
# warm small, then probe single-program execution length at pop=128
for gens, steps in [(2, 20), (4, 100), (8, 100)]:
    t0 = time.perf_counter()
    try:
        r = fused_pbt(wl, population=128, generations=gens, steps_per_gen=steps, seed=0, member_chunk=32)
        print(f"g={gens} s={steps}: OK wall={time.perf_counter()-t0:.1f}s best={r['best_score']:.3f}", flush=True)
    except Exception as e:
        print(f"g={gens} s={steps}: FAIL {type(e).__name__} {str(e)[:100]}", flush=True)
        break
