import time, jax, jax.numpy as jnp, numpy as np
from functools import partial
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
P, B, H, W, Cin, Cout = 32, 256, 32, 32, 32, 32
N = 100
k = jax.random.key(0)
def conv(x, w):
    return jax.lax.conv_general_dilated(x, w, (1,1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
x_shared = jax.random.normal(k, (B, H, W, Cin), jnp.bfloat16)
x_member = jax.random.normal(k, (P, B, H, W, Cin), jnp.bfloat16)
w = jax.random.normal(k, (P, 3, 3, Cin, Cout), jnp.bfloat16)
xbig = x_member.reshape(P*B, H, W, Cin)
wone = w[0]

def repeat(body):
    @jax.jit
    def f(x, w):
        def step(c, _):
            # fold the loop counter in so XLA can't hoist the conv
            return c + body(x, w), None
        out, _ = jax.lax.scan(step, jnp.zeros((), jnp.float32), None, length=N)
        return out
    return f

f_shared = repeat(lambda x, w: jax.vmap(conv, in_axes=(None, 0))(x, w).astype(jnp.float32).sum())
f_member = repeat(lambda x, w: jax.vmap(conv, in_axes=(0, 0))(x, w).astype(jnp.float32).sum())
f_big    = repeat(lambda x, w: conv(x, w).astype(jnp.float32).sum())

flops = 2*9*Cin*Cout*H*W*B*P
for name, f, a in (("vmap shared-x", f_shared, (x_shared, w)),
                   ("vmap member-x", f_member, (x_member, w)),
                   ("one big conv (ub)", f_big, (xbig, wone))):
    float(f(*a))  # compile+warm
    t0 = time.time(); float(f(*a)); dt = (time.time()-t0)/N
    print(f"{name}: {dt*1e3:.3f} ms/iter -> {flops/dt/1e12:.1f} TFLOP/s")
