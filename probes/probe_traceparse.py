"""Parse a jax.profiler chrome trace (vm.trace.json.gz) into a leaf-op
time breakdown. Usage: python - < probes/probe_traceparse.py  (edit PATH)."""
import gzip, json, collections, sys, glob

path = sorted(glob.glob("/tmp/prof_r2/plugins/profile/*/vm.trace.json.gz"))[-1]
with gzip.open(path) as f:
    tr = json.load(f)
events = tr.get("traceEvents", [])
pids = {e["pid"]: e["args"].get("name", "") for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"}
agg, cnt = collections.Counter(), collections.Counter()
for e in events:
    if e.get("ph") != "X" or "dur" not in e: continue
    if "TPU" not in str(pids.get(e["pid"], "")): continue
    n = e.get("name", "?")
    if n.startswith(("jit_", "while", "body", "condition")): continue
    agg[n] += e["dur"]; cnt[n] += 1
tot = sum(agg.values())
print(f"device leaf total: {tot/1e6:.3f}s ({path})")
for n, d in agg.most_common(30):
    print(f"{d/1e6:8.3f}s  x{cnt[n]:5}  {n}")
