"""A/B: nn.max_pool (select-and-scatter VJP) vs reshape+reduce-max
(elementwise tie-splitting VJP) in the SmallCNN population sweep.

Motivation: the round-2 trace showed select-and-scatter (max-pool
backward) at ~8% of device time, making the reshape variant look like
free throughput. Measured verdict on the real chip (2026-07-30,
pop=64 x 2 gens x 100 steps, seed 0, identical everything else):

    nn.max_pool     : 15.6 s, best_curve [0.311, 0.548]
    reshape+max     : 17.7 s, best_curve [0.166, 0.211]

i.e. the "optimization" was 14% SLOWER (the 6-D reshaped reduce under
vmap lowers worse than reduce-window) and collapsed learning (in bf16,
post-GroupNorm activations tie inside 2x2 windows often enough that
the split-among-ties subgradient materially dilutes the signal
select-and-scatter's send-to-first keeps concentrated). Both effects
refute the swap; SmallCNN keeps nn.max_pool.

Run from /root/repo: python probes/probe_pool_ab.py {old|new}
"""

import sys
import time

sys.path.insert(0, ".")


def main(mode):
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
    import mpi_opt_tpu.models.cnn as cnn

    if mode == "new":  # the refuted variant
        import jax.numpy as jnp

        def reshape_pool(x):
            b, h, w, c = x.shape
            return x.reshape(b, h // 2, 2, w // 2, 2, c).max(axis=(2, 4))

        import flax.linen as nn

        nn.max_pool_orig = nn.max_pool
        cnn.nn.max_pool = lambda x, *_a, **_k: reshape_pool(x)

    from mpi_opt_tpu.train.fused_pbt import fused_pbt
    from mpi_opt_tpu.workloads import get_workload

    wl = get_workload("cifar10_cnn")
    kw = dict(population=64, generations=2, steps_per_gen=100, seed=0,
              member_chunk=32, gen_chunk=1)
    fused_pbt(wl, **kw)  # warm
    t0 = time.time()
    r = fused_pbt(wl, **kw)
    wall = time.time() - t0
    print(f"{mode}: wall={wall:.2f}s "
          f"curve={[round(float(v), 3) for v in r['best_curve']]}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "old")
