"""Yardstick for probe_gn_floor: pure streaming op (y = 2x + 1) at the
same shape gives the platform's real bandwidth for this access pattern;
GN's pass count = GN time / per-pass time."""
import statistics, sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

P, B, H, W, C = 32, 256, 32, 32, 32
x = jax.random.normal(jax.random.key(0), (P, B, H, W, C), jnp.bfloat16)
ITERS = 40
@jax.jit
def run(x):
    def body(i, acc):
        y = x * (2.0 + acc * 1e-20) + 1.0          # read x, write y
        return acc + y[0, 0, 0, 0, 0].astype(jnp.float32) * 1e-9
    return jax.lax.fori_loop(0, ITERS, body, 0.0)
float(run(x))
walls = []
for _ in range(3):
    t0 = time.perf_counter(); float(run(x)); walls.append(time.perf_counter() - t0)
per = statistics.median(walls) / ITERS
gb = x.size * 2 / 1e9
print(f"stream per-iter {per*1e3:.2f} ms for {2*gb:.2f} GB (r+w) -> {2*gb/per:.0f} GB/s; "
      f"one-pass time {per/2*1e3:.2f} ms/pass-GBset")
