"""Measure save-side digest cost: serial vs per-shard parallel hashing.

PR-5 follow-up ("re-measure save-side digest cost on multi-GB pools —
could digest per-shard async"): utils.integrity.tree_digest now fans
leaf hashing out over a thread pool when the tree crosses
MPI_OPT_TPU_DIGEST_PARALLEL_BYTES (hashlib releases the GIL for large
buffers, so shards hash genuinely parallel). This probe times both
paths on a synthetic pool shaped like a wave-scheduled population
(many same-sized param shards) and checks the digests agree.

Run: JAX_PLATFORMS=cpu python probes/probe_digest_cost.py [total_mb]
"""

import os
import sys
import time

import numpy as np

from mpi_opt_tpu.utils import integrity


def bench(tree, serial: bool, reps: int = 3) -> float:
    # the env knob flips the path: an absurd threshold forces serial
    old = integrity._PARALLEL_DIGEST_BYTES
    integrity._PARALLEL_DIGEST_BYTES = (1 << 62) if serial else (1 << 20)
    try:
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            d = integrity.tree_digest(tree)
            best = min(best, time.perf_counter() - t0)
        return best, d
    finally:
        integrity._PARALLEL_DIGEST_BYTES = old


def main():
    total_mb = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    n_leaves = 16
    per = total_mb * (1 << 20) // n_leaves // 4
    rng = np.random.default_rng(0)
    tree = {f"layer_{i}": rng.standard_normal(per).astype(np.float32) for i in range(n_leaves)}
    t_serial, d1 = bench(tree, serial=True)
    t_par, d2 = bench(tree, serial=False)
    assert d1 == d2, "parallel digest must equal serial"
    gbps = total_mb / 1024 / t_par
    print(
        f"pool={total_mb}MB x {n_leaves} shards  serial={t_serial:.3f}s  "
        f"parallel={t_par:.3f}s  speedup={t_serial / t_par:.2f}x  "
        f"({gbps:.2f} GB/s, {os.cpu_count()} cores)"
    )


if __name__ == "__main__":
    main()
