"""Compare population-conv strategies: P members, each its own 3x3 kernel."""
import time, functools
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

P, B, H, W, C, O = 32, 256, 32, 32, 32, 32
kx = jax.random.key(0)
x = jax.random.normal(kx, (P, B, H, W, C), jnp.bfloat16)
w = jax.random.normal(jax.random.key(1), (P, 3, 3, C, O), jnp.bfloat16) * 0.05

conv1 = lambda xi, wi: jax.lax.conv_general_dilated(xi, wi, (1,1), "SAME", dimension_numbers=("NHWC","HWIO","NHWC"))

def strat_vmap(x, w):
    return jax.vmap(conv1)(x, w)

def strat_grouped(x, w):
    # members as feature groups: [B,H,W,P*C] conv [3,3,C,P*O] fgc=P
    xg = jnp.transpose(x, (1,2,3,0,4)).reshape(B,H,W,P*C)
    wg = jnp.transpose(w, (1,2,0,3,4)).reshape(3,3,C,P*O)
    # note w layout per group: HWIO with I=C per group
    wg = w.transpose(1,2,3,0,4).reshape(3,3,C,P*O)  # [3,3,C,P,O] -> groups on O
    yg = jax.lax.conv_general_dilated(xg, wg, (1,1), "SAME",
        dimension_numbers=("NHWC","HWIO","NHWC"), feature_group_count=P)
    return jnp.transpose(yg.reshape(B,H,W,P,O), (3,0,1,2,4))

def strat_im2col(x, w):
    pat = jax.vmap(lambda xi: jax.lax.conv_general_dilated_patches(
        xi, (3,3), (1,1), "SAME", dimension_numbers=("NHWC","HWIO","NHWC")))(x)  # [P,B,H,W,9C]
    wf = w.transpose(0,3,1,2,4).reshape(P, C*9, O)  # patches order: C,ky,kx? -> match below
    # conv_general_dilated_patches channel order is (C, kh, kw) flattened
    return jnp.einsum("pbhwk,pko->pbhwo", pat, wf)

def bench(name, fn):
    loss = lambda x, w: jnp.sum(fn(x, w) ** 2).astype(jnp.float32)
    g = jax.jit(jax.grad(loss, argnums=(0, 1)))
    try:
        r = g(x, w); jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(10):
            r = g(x, w)
        jax.block_until_ready(r)
        dt = (time.perf_counter() - t0) / 10
        fl = 3 * 2 * P*B*H*W*9*C*O  # fwd+bwd approx 3x fwd
        print(f"{name}: {dt*1e3:.2f} ms/iter  ({fl/dt/1e12:.1f} TF/s eff)")
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__} {str(e)[:100]}")

# correctness check fwd
y0 = strat_vmap(x, w); y1 = strat_grouped(x, w); y2 = strat_im2col(x, w)
import numpy as np
print("grouped maxdiff:", float(jnp.abs(y0-y1).max()))
print("im2col  maxdiff:", float(jnp.abs(y0-y2).max()))
bench("vmap(conv)   ", strat_vmap)
bench("grouped fgc=P", strat_grouped)
bench("im2col matmul", strat_im2col)
