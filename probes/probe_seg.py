import time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
from mpi_opt_tpu.workloads import get_workload
from mpi_opt_tpu.train.population import OptHParams
wl = get_workload("cifar10_cnn")
d = wl.data()
tx, ty = jnp.asarray(d["train_x"]), jnp.asarray(d["train_y"])
for P, chunk in ((32, 0), (64, 0), (128, 32), (256, 32)):
    tr = wl.make_trainer(donate=False, member_chunk=chunk)
    state = tr.init_population(jax.random.key(0), tx[:2], P)
    hp = OptHParams.defaults(P)
    key = jax.random.key(1)
    st, loss = tr.train_segment(state, hp, tx, ty, key, steps=50)
    np.asarray(loss)  # warmup same static args
    t0 = time.perf_counter()
    st, loss = tr.train_segment(st, hp, tx, ty, key, steps=50)
    np.asarray(loss)
    dt = time.perf_counter() - t0
    ms = P * 50
    print(f"P={P} chunk={chunk}: {dt:.2f}s, {ms/dt:.0f} msteps/s "
          f"({ms/dt*36.6e9/1e12:.1f} TF/s)", flush=True)
