"""Reference-fidelity baseline arbitration: the same member-generation
work (SmallCNN-equivalent, batch 256, CIFAR shapes) in torch on CPU.
If torch is much faster than our jax-CPU worker, the jax-CPU baseline
understates the reference and must not be used as the denominator."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

torch.manual_seed(0)
torch.set_num_threads(1)  # one rank = one core, like the MPI reference

class SmallCNN(nn.Module):
    def __init__(self, w=32, n_classes=10):
        super().__init__()
        self.c = nn.ModuleList()
        chans = [3, w, w, 2*w, 2*w]
        for i in range(4):
            self.c.append(nn.Conv2d(chans[i], chans[i+1], 3, padding=1))
            self.c.append(nn.GroupNorm(8, chans[i+1]))
        self.fc1 = nn.Linear(2*w*8*8, 4*w)
        self.fc2 = nn.Linear(4*w, n_classes)
    def forward(self, x):
        for i in range(4):
            x = F.relu(self.c[2*i+1](self.c[2*i](x)))
            if i % 2 == 1:
                x = F.max_pool2d(x, 2)
        x = x.flatten(1)
        return self.fc2(F.relu(self.fc1(x)))

model = SmallCNN()
opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9, weight_decay=1e-4)
x = torch.randn(256, 3, 32, 32)
y = torch.randint(0, 10, (256,))

# warmup
for _ in range(2):
    opt.zero_grad(); F.cross_entropy(model(x), y).backward(); opt.step()
t0 = time.perf_counter()
n = 10
for _ in range(n):
    opt.zero_grad(); F.cross_entropy(model(x), y).backward(); opt.step()
dt = (time.perf_counter() - t0) / n
print(f"torch cpu train step (batch 256): {dt:.2f}s -> {36.6/dt:.1f} GFLOP/s", flush=True)

# eval 2048
model.eval()
vx = torch.randn(2048, 3, 32, 32)
with torch.no_grad():
    model(vx[:256])  # warm
    t0 = time.perf_counter()
    for i in range(0, 2048, 256):
        model(vx[i:i+256])
    ev = time.perf_counter() - t0
print(f"torch cpu eval 2048: {ev:.2f}s", flush=True)
print(f"torch cpu member-gen (100 steps + eval): {100*dt + ev:.1f}s "
      f"({1/(100*dt+ev):.5f} trials/s)", flush=True)
