"""Split the driver-path wall: next_batch vs report_batch vs evaluate.
Also: is a host CPU jax backend available under the axon plugin?"""
import sys, time
sys.path.insert(0, "/root/repo")

import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

try:
    print("cpu devices:", jax.devices("cpu"))
except Exception as e:
    print("cpu backend unavailable:", type(e).__name__, e)
print("default:", jax.devices())

from mpi_opt_tpu.algorithms import get_algorithm
from mpi_opt_tpu.backends import get_backend
from mpi_opt_tpu.driver import run_search
from mpi_opt_tpu.workloads import get_workload

wl = get_workload("fashion_mlp")
asha = lambda s: get_algorithm("asha")(
    wl.default_space(), seed=s, max_trials=64, min_budget=10, max_budget=270, eta=3)

be = get_backend("tpu", wl, population=64, seed=0)
run_search(asha(0), be)
be.reset()

algo = asha(0)
t_nb = t_rb = 0.0
nb0, rb0 = algo.next_batch, algo.report_batch
def nb(n):
    global t_nb; t0 = time.perf_counter(); out = nb0(n); t_nb += time.perf_counter() - t0; return out
def rb(r):
    global t_rb; t0 = time.perf_counter(); out = rb0(r); t_rb += time.perf_counter() - t0; return out
algo.next_batch, algo.report_batch = nb, rb
t0 = time.perf_counter()
res = run_search(algo, be)
wall = time.perf_counter() - t0
be.close()
print(f"wall {wall:.2f}s  next_batch {t_nb:.2f}s  report_batch {t_rb:.2f}s")
