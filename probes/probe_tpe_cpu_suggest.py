"""Is tpe_suggest-on-CPU compiling repeatedly / slowly under the axon process?"""
import sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
import numpy as np
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
from mpi_opt_tpu.ops.tpe import TPEConfig, tpe_suggest

cfg = TPEConfig()
fn = jax.jit(tpe_suggest, static_argnames=("n_suggest", "cfg"))
obs = np.zeros((512, 8), np.float32); sc = np.zeros(512, np.float32); va = np.zeros(512, bool)
cpu = jax.devices("cpu")[0]
with jax.default_device(cpu):
    for i in range(4):
        t0 = time.perf_counter()
        k = jax.random.fold_in(jax.random.key(0), i)
        out, _ = fn(k, obs, sc, va, n_suggest=64, cfg=cfg)
        np.asarray(out)
        print(f"call {i}: {time.perf_counter()-t0:.2f}s  device={out.devices()}")
