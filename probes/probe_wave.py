"""Probe: wave-scheduled PBT beyond the single-chip residency envelope.

The round-3 envelope (PERF_NOTES "single-chip population envelope"):
pop=1024 SmallCNN is 4.5 GB of params+momentum and RESOURCE_EXHAUSTs at
warmup, while throughput is flat to pop=512. This probe (a) re-runs the
pop=1024 config WITH --wave-size so the population that could not run
at all completes on one chip, and (b) measures the staging overlap
efficiency: how much of the host<->device transfer time the
double-buffered background engine hid behind wave compute
(stage_overlap_s / stage_transfer_s; the un-hidden remainder is
stage_wait_s, paid at generation barriers).

An A/B at a resident-capable population (512, wave 256) also reports
the wave-mode overhead vs the resident scan — the cost of buying the
envelope.

Run: python probes/probe_wave.py [pop] [wave]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

from mpi_opt_tpu.train.fused_pbt import fused_pbt  # noqa: E402
from mpi_opt_tpu.workloads import get_workload  # noqa: E402

pop = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
wave = int(sys.argv[2]) if len(sys.argv) > 2 else 256

wl = get_workload("cifar10_cnn")
kw = dict(generations=2, steps_per_gen=100, seed=0, member_chunk=32)

# A: resident baseline at half the target (the biggest size that fits)
t0 = time.perf_counter()
res = fused_pbt(wl, population=min(pop, 512), **kw)
res_wall = time.perf_counter() - t0
print(
    f"resident pop={min(pop, 512)}: wall={res_wall:.1f}s "
    f"best={res['best_score']:.4f}",
    flush=True,
)

# B: wave-scheduled at the target population (beyond residency when
# pop=1024 on one chip)
t0 = time.perf_counter()
wav = fused_pbt(wl, population=pop, wave_size=wave, **kw)
wav_wall = time.perf_counter() - t0
xfer = wav["stage_transfer_s"]
hidden = wav["stage_overlap_s"]
eff = hidden / xfer if xfer > 0 else float("nan")
print(
    f"wave pop={pop} wave={wave} ({wav['n_waves']} waves): "
    f"wall={wav_wall:.1f}s best={wav['best_score']:.4f} "
    f"staged={wav['staged_bytes'] / 1e9:.2f} GB "
    f"transfer={xfer:.1f}s hidden={hidden:.1f}s wait={wav['stage_wait_s']:.1f}s "
    f"overlap_efficiency={eff:.2%}",
    flush=True,
)
ms = pop * kw["generations"] * kw["steps_per_gen"] / wav_wall
print(f"member-steps/s (wave): {ms:.0f}", flush=True)
