import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
from mpi_opt_tpu.workloads import get_workload
from mpi_opt_tpu.utils.flops import population_sweep_flops
import mpi_opt_tpu.utils.flops as F

# unwrap the try/except to see the real error
import traceback
wl = get_workload("cifar100_resnet18")
try:
    trainer = wl.make_trainer(donate=False)
    from mpi_opt_tpu.train.population import OptHParams
    import jax.numpy as jnp
    d = wl.data()
    tx, ty = jnp.asarray(d["train_x"]), jnp.asarray(d["train_y"])
    vx, vy = jnp.asarray(d["val_x"])[:1024], jnp.asarray(d["val_y"])[:1024]
    key = jax.random.key(0)
    state = trainer.init_population(key, tx[:2], 1)
    hp = OptHParams.defaults(1)
    jf = trainer.train_segment
    f_step = F.compiled_flops(jf, state, hp, tx, ty, key, steps=1)
    print("f_step:", f_step)
    f_eval = F.compiled_flops(type(trainer).eval_population, trainer, state, vx, vy, eval_chunk=1024)
    print("f_eval:", f_eval)
except Exception:
    traceback.print_exc()
