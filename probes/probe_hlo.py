import jax, jax.numpy as jnp, re
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")
from mpi_opt_tpu.workloads import get_workload
from mpi_opt_tpu.train.population import OptHParams
wl = get_workload("cifar10_cnn")
tr = wl.make_trainer(donate=False)
d = wl.data()
tx, ty = jnp.asarray(d["train_x"]), jnp.asarray(d["train_y"])
P = 32
state = tr.init_population(jax.random.key(0), tx[:2], P)
hp = OptHParams.defaults(P)
jf = tr.train_segment
txt = jf.func.lower(jf.args[0], state, hp, tx, ty, jax.random.key(1), steps=1).compile().as_text()
convs = [l.strip() for l in txt.splitlines() if "convolution(" in l or "%convolution" in l and "fusion" not in l]
for l in convs[:20]:
    print(l[:240])
print("n conv lines:", len(convs))
