import time, sys
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_tpu")

def timed(name, fl_per_iter, step, init, n=20):
    print(f"compiling {name} ...", flush=True)
    @jax.jit
    def run(c):
        return jax.lax.fori_loop(0, n, lambda i, c: step(c), c)
    t0 = time.perf_counter()
    c = run(init); jax.tree.map(lambda a: np.asarray(jnp.ravel(a)[0]), c)
    print(f"  compile+first {time.perf_counter()-t0:.1f}s", flush=True)
    t0 = time.perf_counter()
    c = run(c)
    jax.tree.map(lambda a: np.asarray(jnp.ravel(a)[0]), c)
    dt = (time.perf_counter() - t0) / n
    print(f"{name}: {dt*1e3:.3f} ms/iter ({fl_per_iter/dt/1e12:.1f} TF/s)", flush=True)

def mm(P, M, K, N):
    a = jax.random.normal(jax.random.key(0), (P, M, K), jnp.bfloat16)
    b = jax.random.normal(jax.random.key(1), (P, K, N), jnp.bfloat16) * 0.01
    def step(b):
        y = jnp.einsum("pmk,pkn->pmn", a, b)
        return (b + 1e-6 * y[:, :K, :]).astype(b.dtype)
    timed(f"mm P={P} M={M} K={K} N={N}", 2*P*M*K*N, step, b)

mm(32, 8192, 288, 32)
mm(32, 8192, 288, 128)
mm(1, 8192, 2048, 2048)
