"""Measure ALL FIVE BASELINE.json configs on this container's hardware.

``bench.py`` stays the driver-run headline (one JSON line, north-star
PBT sweep); this script fills in the rest of BASELINE.md's table — the
reference published no numbers, so these measured values ARE the
baseline column for this repo.

Emits one JSON line per config on stdout and writes the full set to
``BENCH_ALL.json``. Run: ``python bench_all.py [--configs 1,2,3,4,5]``.

Per-config definitions (from BASELINE.json `configs`):
1. random search, 16 trials, sklearn LogisticRegression on digits —
   single-process CPU path (trials/sec).
2. ASHA early-stopping, 64-trial sweep, 2-layer MLP on Fashion-MNIST —
   the fused on-device successive-halving path (train/fused_asha.py),
   rung cuts as on-device top_k (trials/sec/chip).
3. PBT population=32, small CNN on CIFAR-10 — fused PBT at the
   config's own population (bench.py's headline uses the north-star
   256); metric of record is wall-clock to target val-acc.
4. vectorized TPE acquisition, 256-trial surrogate sweep on UCI
   tabular — two numbers: the acquisition kernel's suggest throughput
   (the "vectorized" claim, measured on the jitted kernel) and the
   end-to-end 256-trial search (suggest+train+report) trials/sec/chip.
5. PBT population=1024, ResNet-18, CIFAR-100 — BASELINE puts this on a
   v4-32; one chip caps the resident population (models/resnet.py
   documents the memory math: pop=64 with member_chunk=8 fits a 16G
   v5e, stored-backward — remat off since round 5, an 18% win).
   Measured at the single-chip cap, reported per chip with the cap
   stated.
6. (beyond BASELINE — ISSUE 14) the suggestion-service tenant: a
   resident ``--suggest-serve`` server answering suggest→report
   round trips over the filesystem spool from the batched TPE
   acquisition kernel. Two numbers: suggestions/s over the whole
   conversation and the p95 request round-trip — the serving-side
   counterpart of config 4's raw acquisition throughput (that number
   is kernel-only; this one pays the full client→spool→server→spool
   loop an EXTERNAL sweep actually experiences). Not in the default
   --configs set (BASELINE parity); run with ``--configs 6``.
7. (beyond BASELINE — ISSUE 16) the HTTP front door: config 6's
   conversation through the batched wire protocol under ``burst``
   concurrent clients. Run with ``--configs 7``.
8. (beyond BASELINE — ISSUE 17) multi-objective fused PBT:
   2-objective (accuracy:max, params:min) Pareto selection inside the
   compiled boundary op, population=8 on digits_mlp. Two numbers:
   trials/s/chip with the MO exploit in the loop (comparable to the
   scalar fused families) and the final front's hypervolume at budget
   (the sweep-quality number a throughput regression can't hide
   behind). Run with ``--configs 8``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def traced_config(fn, trace_dir, config_id: int):
    """Run one config under span tracing (obs/trace.py) and attach the
    phase-attribution JSON to its record — BENCH_r06+ carries a
    compile/train/save breakdown beside trials/s instead of one opaque
    wall number, plus the round-8 intra-phase sections (device-idle
    ``bubbles``, staging ``overlap_frac``, the ``roofline`` verdict) so
    every trajectory round is diffable/gateable on idle fraction, MXU
    utilization, and overlap efficiency, not just phase walls.
    ``trace_dir=None`` runs untraced (--no-trace). Either way the
    record leaves versioned (``schema_version``) and carrying the
    device-memory watermark — the drift gate and the trajectory diff
    both depend on the shape being declared, not inferred."""
    from mpi_opt_tpu.obs import memory as obs_memory

    # per-config watermark window: the live-array fallback's peak is a
    # process-lifetime running max — without the reset, config 5's
    # record would wear config 1's (possibly much larger) footprint
    # forever in BENCH_ALL.json
    obs_memory.reset_peak()
    if trace_dir is None:
        return _finish_record(fn())
    import os

    from mpi_opt_tpu.obs import trace as _trace
    from mpi_opt_tpu.obs.report import bench_attribution
    from mpi_opt_tpu.utils.metrics import MetricsLogger

    os.makedirs(trace_dir, exist_ok=True)
    path = os.path.join(trace_dir, f"config{config_id}.jsonl")
    metrics = MetricsLogger(path=path)
    prior = _trace.configure(metrics)
    try:
        rec = fn()
    finally:
        _trace.deconfigure(prior)
        metrics.close()
    rec["trace"] = bench_attribution(path)
    rec["trace_stream"] = path
    roof = (rec["trace"] or {}).get("roofline")
    if roof is not None:
        mxu, idle = roof.get("mxu_frac"), roof.get("idle_frac")
        log(f"[bench_all] config {config_id} roofline: {roof['bound']}"
            + (f" (MXU {mxu:.1%})" if mxu is not None else " (no platform cap)")
            + (f", idle {idle:.1%}" if idle is not None else ""))
    return _finish_record(rec)


def _finish_record(rec: dict) -> dict:
    """Stamp the versioned-record fields every config record carries:
    ``schema_version`` (obs/diff.py owns the number and the validator)
    and the post-run ``device_memory`` watermark (obs/memory.py) —
    sampled HERE, right after the config's sweeps, while its state is
    still resident."""
    from mpi_opt_tpu.obs import memory as obs_memory
    from mpi_opt_tpu.obs.diff import BENCH_SCHEMA_VERSION

    rec.setdefault("schema_version", BENCH_SCHEMA_VERSION)
    rec.setdefault("trace", None)
    rec.setdefault("device_memory", obs_memory.watermark())
    return rec


def median_walls(fn, repeats: int = 5):
    """(median_wall, all_walls) over ``repeats`` timed calls of ``fn``.

    Configs whose whole timed sweep lasts ~1 s (2 and 4's fused paths)
    are at the mercy of per-launch tunnel jitter (PERF_NOTES.md round 3:
    20-90 ms per round trip); a single draw moved config 2's headline
    20% between otherwise-identical runs. The median of 5 is the
    reported value; every wall is recorded so the spread is visible.
    """
    import statistics

    walls = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        walls.append(time.perf_counter() - t0)
    return statistics.median(walls), walls


def timed_region(fn, warm_wall: float, min_s: float = 8.0, regions: int = 3):
    """(median_region_wall, region_walls, k): run ``fn`` k times
    back-to-back inside each timed region, k sized from the measured
    warm wall so every region lasts >= ``min_s`` seconds.

    VERDICT r4 weak #1: a sub-second timed sweep on this platform
    measures launch amortization plus tunnel state, not sweep
    throughput — per-launch jitter is 20-90 ms and the same code drew
    30.8 vs 68.9 trials/s in different session windows. Stretching the
    region to >= ~8 s of identical back-to-back sweeps makes the number
    a steady-state throughput fact; the accounting is explicit
    (value = k * n_trials / region_wall, k recorded as
    ``sweeps_per_region``), and the median of ``regions`` regions with
    all walls recorded keeps the residual spread visible.
    """
    import math
    import statistics

    k = max(1, math.ceil(min_s / max(warm_wall, 1e-3)))
    walls = []
    for _ in range(regions):
        t0 = time.perf_counter()
        for _ in range(k):
            fn()
        walls.append(time.perf_counter() - t0)
    return statistics.median(walls), walls, k


def _tpu_setup():
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        "/tmp/jax_cache_tpu" if jax.default_backend() != "cpu" else "/tmp/jax_cache_cpu",
    )
    return jax.devices()[0].device_kind


def bench_config1(seed: int):
    """Random search, 16 trials, LogReg on digits, single-process CPU."""
    from mpi_opt_tpu.algorithms import get_algorithm
    from mpi_opt_tpu.backends import get_backend
    from mpi_opt_tpu.driver import run_search
    from mpi_opt_tpu.workloads import get_workload

    wl = get_workload("digits")
    algo = get_algorithm("random")(wl.default_space(), seed=seed, max_trials=16, budget=100)
    be = get_backend("cpu", wl, n_workers=1, seed=seed)
    # warm the worker (process spawn + sklearn import) outside the window
    warm = get_algorithm("random")(wl.default_space(), seed=seed + 1, max_trials=1, budget=100)
    run_search(warm, be)
    res = run_search(algo, be)
    be.close()
    return {
        "config": 1,
        "metric": "random16_digits_logreg_trials_per_sec",
        "value": round(res.trials_per_sec_per_chip, 4),
        "unit": "trials/sec",
        "hardware": "single-process CPU",
        "n_trials": res.n_trials,
        "best_score": round(res.best.score, 4),
        "wall_s": round(res.wall_s, 2),
    }


def bench_config2(seed: int):
    """64-trial successive halving, MLP on Fashion-MNIST, on-chip.

    Two numbers, mirroring config 4: the fused on-device SHA sweep (the
    metric of record) and the generic driver path — the ASYNC ASHA rule
    on the TPU slot-pool backend, which exercises mixed-rung batching,
    warm resumes, and the per-batch host round-trip the fused path
    removes.
    """
    from mpi_opt_tpu.algorithms import get_algorithm
    from mpi_opt_tpu.backends import get_backend
    from mpi_opt_tpu.driver import run_search
    from mpi_opt_tpu.train.fused_asha import fused_sha
    from mpi_opt_tpu.workloads import get_workload

    device = _tpu_setup()
    wl = get_workload("fashion_mlp")
    kw = dict(n_trials=64, min_budget=10, max_budget=270, eta=3, seed=seed)
    t0 = time.perf_counter()
    res = fused_sha(wl, **kw)  # warmup: compile every rung's program pair
    log(f"[config2] warmup {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    fused_sha(wl, **kw)
    warm_wall = time.perf_counter() - t0
    wall, walls, k = timed_region(lambda: fused_sha(wl, **kw), warm_wall)

    # driver path: same-seed warmup search compiles every (steps, pad)
    # group program the timed trajectory will hit; reset() (not reuse —
    # trial ids restart per algorithm and would warm-resume the warmup's
    # states) makes the timed search bit-identical to a fresh backend's.
    # The timed region repeats the whole search (reset + run) to the
    # same >= 5 s floor as the fused number; reset is host bookkeeping
    # only (no device work), so the region measures search throughput.
    asha = lambda: get_algorithm("asha")(
        wl.default_space(), seed=seed, max_trials=64, min_budget=10, max_budget=270, eta=3
    )
    be = get_backend("tpu", wl, population=64, seed=seed)
    run_search(asha(), be)
    t0 = time.perf_counter()
    be.reset()
    dres = run_search(asha(), be)
    d_warm = time.perf_counter() - t0

    def d_once():
        be.reset()
        return run_search(asha(), be)

    d_wall, d_walls, d_k = timed_region(d_once, d_warm, min_s=5.0)
    be.close()
    return {
        "config": 2,
        "metric": "asha64_fashion_mlp_trials_per_sec_per_chip",
        "value": round(k * res["n_trials"] / wall, 4),
        "unit": "trials/sec/chip",
        "hardware": device,
        "rung_budgets": res["rung_budgets"],
        "rung_sizes": res["rung_sizes"],
        "best_score": round(res["best_score"], 4),
        "wall_s": round(wall, 2),
        "wall_s_runs": [round(w, 2) for w in walls],
        "sweeps_per_region": k,
        # completed-trials basis (n_trials / wall), comparable to the
        # fused number; rung re-evaluations are counted separately
        "driver_trials_per_sec_per_chip": round(d_k * dres.n_trials / d_wall, 4),
        "driver_n_evals": dres.n_evals,
        "driver_best_score": round(dres.best.score, 4),
        "driver_wall_s": round(d_wall, 2),
        "driver_wall_s_runs": [round(w, 2) for w in d_walls],
        "driver_sweeps_per_region": d_k,
    }


def bench_config3(seed: int, target_acc: float):
    """PBT pop=32 CNN CIFAR-10: wall-clock to target val-acc.

    Both architectures, completing the fused-vs-driver exhibit across
    all three sweep families (VERDICT r3 item 6): the fused on-device
    sweep (metric of record) and the generic driver path — host PBT
    emitting generation batches onto the TPU slot pool, exploit
    inheritance as ``__inherit_from__`` gathers.
    """
    from mpi_opt_tpu.algorithms import get_algorithm
    from mpi_opt_tpu.backends import get_backend
    from mpi_opt_tpu.driver import run_search
    from mpi_opt_tpu.train.fused_pbt import fused_pbt
    from mpi_opt_tpu.workloads import get_workload

    device = _tpu_setup()
    wl = get_workload("cifar10_cnn")
    pop, gens, steps = 32, 8, 100
    # gen_chunk: the tunneled chip kills single programs over ~60s
    kw = dict(population=pop, generations=gens, steps_per_gen=steps, seed=seed, gen_chunk=2)
    t0 = time.perf_counter()
    fused_pbt(wl, **kw)
    log(f"[config3] warmup {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    res = fused_pbt(wl, **kw)
    wall = time.perf_counter() - t0
    from mpi_opt_tpu.utils.metrics import sweep_wall_to_target as _wtt

    curve = [round(float(v), 4) for v in res["best_curve"]]
    wtt = _wtt(res, wall, target_acc)

    # driver path: same sweep shape through the generic plugin
    # architecture (warmup + reset per the one-search backend contract)
    pbt = lambda s: get_algorithm("pbt")(
        wl.default_space(), seed=s, population=pop, generations=gens,
        steps_per_generation=steps,
    )
    be = get_backend("tpu", wl, population=pop, seed=seed)
    run_search(pbt(seed), be)
    be.reset()
    dres = run_search(pbt(seed), be)
    be.close()
    return {
        "config": 3,
        "metric": "pbt32_cifar10_cnn_wall_to_target",
        "value": round(wtt, 2) if wtt is not None else None,
        "unit": "seconds_to_target_val_acc",
        "hardware": device,
        "target_acc": target_acc,
        "best_val_acc": round(res["best_score"], 4),
        "best_curve": curve,
        "trials_per_sec_per_chip": round(pop * gens / wall, 4),
        "wall_s": round(wall, 2),
        "driver_trials_per_sec_per_chip": round(dres.n_evals / dres.wall_s, 4),
        "driver_n_evals": dres.n_evals,
        "driver_best_score": round(dres.best.score, 4),
        "driver_wall_s": round(dres.wall_s, 2),
    }


def bench_config4(seed: int):
    """Vectorized TPE: 256-suggestion acquisition + end-to-end sweep."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_opt_tpu.algorithms import get_algorithm
    from mpi_opt_tpu.backends import get_backend
    from mpi_opt_tpu.driver import run_search
    from mpi_opt_tpu.ops.tpe import TPEConfig, tpe_suggest
    from mpi_opt_tpu.workloads import get_workload

    device = _tpu_setup()
    wl = get_workload("tabular_mlp")
    space = wl.default_space()
    d = len(space.discrete_mask())

    # (a) the acquisition kernel itself: score 1024 candidates, take the
    # top 256, from a 256-observation buffer — all on device, one jit
    M, n_suggest = 256, 256
    key = jax.random.key(seed)
    k_obs, k_sc, k_run = jax.random.split(key, 3)
    obs = jax.random.uniform(k_obs, (M, d))
    scores = jax.random.normal(k_sc, (M,))
    valid = jnp.ones((M,), bool)
    jitted = jax.jit(tpe_suggest, static_argnames=("n_suggest", "cfg"))
    cfg = TPEConfig()
    np.asarray(jitted(k_run, obs, scores, valid, n_suggest=n_suggest, cfg=cfg)[0])
    iters = 50
    t0 = time.perf_counter()
    for i in range(iters):
        k = jax.random.fold_in(k_run, i)
        out, _ = jitted(k, obs, scores, valid, n_suggest=n_suggest, cfg=cfg)
        # host fetch per batch: what the driver does with suggestions, and
        # the only reliable barrier under this plugin (PERF_NOTES.md)
        np.asarray(out)
    acq_wall = time.perf_counter() - t0
    suggest_per_sec = iters * n_suggest / acq_wall

    # (b) end-to-end: 256-trial TPE search on the tabular MLP, TPU backend.
    # reset() between warmup and timed searches: trial ids restart per
    # algorithm, so reusing the backend as-is would alias the timed run's
    # first 64 trials onto the warmup's ledger entries (rem=0 warm
    # resumes — no training, wrong scores; round-2's driver number had
    # exactly this contamination)
    algo_cls = get_algorithm("tpe")
    be = get_backend("tpu", wl, population=64, seed=seed)
    # warmup must run PAST the n_startup random phase or the surrogate
    # path (and its jitted tpe_suggest variant for this batch size)
    # compiles inside the timed window: a 64-trial warmup is ONE
    # all-random batch and never touches the model (cost round 4 a
    # spurious 120 s "regression" — the timed search was compiling)
    warm = algo_cls(space, seed=seed + 1, max_trials=192, budget=30)
    run_search(warm, be)  # compile train/eval + suggest programs outside the window
    be.reset()
    t0 = time.perf_counter()
    algo = algo_cls(space, seed=seed, max_trials=256, budget=30)
    res = run_search(algo, be)
    d_warm = time.perf_counter() - t0

    def d_once():
        be.reset()
        return run_search(algo_cls(space, seed=seed, max_trials=256, budget=30), be)

    d_wall, d_walls, d_k = timed_region(d_once, d_warm, min_s=5.0)
    be.close()  # release resident population state before config 5

    # (c) the fused path: buffer-resident generational TPE (same sweep)
    from mpi_opt_tpu.train.fused_tpe import fused_tpe

    fres = fused_tpe(wl, n_trials=256, batch=64, budget=30, seed=seed)  # warm
    t0 = time.perf_counter()
    fused_tpe(wl, n_trials=256, batch=64, budget=30, seed=seed)
    f_warm = time.perf_counter() - t0
    fused_wall, fused_walls, f_k = timed_region(
        lambda: fused_tpe(wl, n_trials=256, batch=64, budget=30, seed=seed), f_warm
    )
    return {
        "config": 4,
        "metric": "tpe256_tabular_trials_per_sec_per_chip",
        # metric of record = the fused on-device sweep (as config 2's is
        # the fused SHA path); the generic driver+backend path is the
        # secondary number
        "value": round(f_k * fres["n_trials"] / fused_wall, 4),
        "unit": "trials/sec/chip",
        "hardware": device,
        "best_score": round(fres["best_score"], 4),
        "n_trials": fres["n_trials"],
        "wall_s": round(fused_wall, 2),
        "wall_s_runs": [round(w, 2) for w in fused_walls],
        "sweeps_per_region": f_k,
        "acquisition_suggestions_per_sec": round(suggest_per_sec, 1),
        "acquisition_batch": n_suggest,
        "driver_trials_per_sec_per_chip": round(d_k * res.n_trials / d_wall, 4),
        "driver_best_score": round(res.best.score, 4),
        "driver_wall_s": round(d_wall, 2),
        "driver_wall_s_runs": [round(w, 2) for w in d_walls],
        "driver_sweeps_per_region": d_k,
    }


def bench_config5(
    seed: int,
    population: int,
    member_chunk: int,
    learn_gens: int = 16,
    learn_target: float = 0.5,
):
    """PBT ResNet-18 CIFAR-100 at the single-chip population cap.

    Two phases: (a) steady-state throughput (2 warm generations — the
    trials/sec/chip of record), then (b) a LEARNING sweep: ``learn_gens``
    generations run as one checkpointed, gen-chunked sweep (each launch
    stays under the tunnel's ~60 s program kill; crash-recovery
    machinery makes longer sweeps safe), reporting the best-of-population
    val-acc curve and the launch-granular wall-clock to ``learn_target``
    (chance on 100 classes = 0.01; the dataset's 0.35 label-noise
    ceiling caps reachable val-acc at ~0.6535, so the default 0.5
    target is mid-curve and discriminates hyperparameters). Round-2
    verdict: a throughput demo whose best accuracy sits at chance is
    not a benchmark of record; round-3 verdict: a clean synthetic task
    memorized to 0.999 is not one either.
    """
    import shutil

    from mpi_opt_tpu.train.fused_pbt import fused_pbt
    from mpi_opt_tpu.utils.flops import mfu, population_sweep_flops
    from mpi_opt_tpu.utils.metrics import sweep_wall_to_target
    from mpi_opt_tpu.workloads import get_workload

    import jax

    device = _tpu_setup()
    wl = get_workload("cifar100_resnet18")
    gens, steps = 2, 50
    kw = dict(
        population=population,
        generations=gens,
        steps_per_gen=steps,
        seed=seed,
        member_chunk=member_chunk,
        gen_chunk=1,
    )
    t0 = time.perf_counter()
    fused_pbt(wl, **kw)
    log(f"[config5] warmup {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    res = fused_pbt(wl, **kw)
    wall = time.perf_counter() - t0
    # flops accounting after the timed window (compiles tiny programs)
    flops = population_sweep_flops(wl, population, gens, steps, n_evals=gens)
    util = mfu(flops, wall, jax.devices()[0])

    # release the throughput phase's device state BEFORE the learning
    # sweep initializes its own population: a pop=64 ResNet pool is
    # ~5.7 GB of params+momentum, and holding both is an instant
    # RESOURCE_EXHAUSTED on a 16 GB chip (measured, round 3)
    best_val = round(res["best_score"], 4)
    res = None

    learning = {}
    if learn_gens > 0:
        ckpt = "/tmp/bench_c5_learning_ckpt"
        shutil.rmtree(ckpt, ignore_errors=True)  # fresh sweep, no stale resume
        t0 = time.perf_counter()
        lres = fused_pbt(
            wl,
            population=population,
            generations=learn_gens,
            steps_per_gen=steps,
            seed=seed,
            member_chunk=member_chunk,
            gen_chunk=1,  # one generation per launch: ~21 s << the 60 s kill
            checkpoint_dir=ckpt,
            # each snapshot host-fetches the full pool (~5.7 GB at
            # pop=64) and round-3 measured that at ~5-7 MINUTES through
            # this container's tunnel (~16 MB/s effective) — a platform
            # artifact that makes a save cost MORE than half the sweep's
            # compute (16 x 21 s). Exactly ONE mid-sweep save (at the
            # halfway launch, scaling with learn_gens) bounds a crash's
            # rerun cost at ~half the sweep for roughly that price; the
            # end-of-sweep save is skipped because the bench consumes
            # the result immediately and rmtree's the directory
            snapshot_every=max(1, -(-learn_gens // 2)),  # ceil: ONE mid save
            snapshot_last=False,
        )
        lwall = time.perf_counter() - t0
        shutil.rmtree(ckpt, ignore_errors=True)  # ~3.4 GB/snapshot on /tmp
        wtt = sweep_wall_to_target(lres, lwall, learn_target)
        learning = {
            "learning_generations": learn_gens,
            "learning_steps_per_gen": steps,
            "learning_curve": [round(float(v), 4) for v in lres["best_curve"]],
            "learning_best_val_acc": round(lres["best_score"], 4),
            "learning_target_acc": learn_target,
            "learning_wall_to_target_s": None if wtt is None else round(wtt, 1),
            "learning_wall_s": round(lwall, 1),
        }
        log(f"[config5] learning: best={lres['best_score']:.4f} "
            f"wtt({learn_target})={wtt} curve={learning['learning_curve']}")
    return {
        "config": 5,
        "metric": "pbt_resnet18_cifar100_trials_per_sec_per_chip",
        "value": round(population * gens / wall, 4),
        "unit": "trials/sec/chip",
        "hardware": device,
        "population": population,
        "population_note": (
            f"BASELINE config is pop=1024 on a v4-32 (32 chips); one chip "
            f"holds pop={population} (params+momentum residency, see "
            f"models/resnet.py). 1024/32 = 32 members/chip on the target "
            f"topology — LESS resident state per chip than measured here."
        ),
        "member_chunk": member_chunk,
        "steps_per_gen": steps,
        "mfu": round(util, 4) if util is not None else None,
        "best_val_acc": best_val,
        "wall_s": round(wall, 2),
        **learning,
    }


def bench_config6(seed: int, rounds: int = 8, batch: int = 32):
    """Suggestion-service round trips (ISSUE 14): a REAL server process
    (the `--suggest-serve` flat-CLI tenant, so the measurement pays jax
    bring-up exactly once, outside the timed window) driven through the
    jax-free client over the filesystem spool. suggestions/s is the
    headline; p95 round-trip is the serving-latency number; config 4's
    kernel-only acquisition throughput bounds it from above."""
    import os
    import shutil
    import subprocess
    import sys
    import tempfile

    from mpi_opt_tpu.corpus import client

    sdir = tempfile.mkdtemp(prefix="bench_suggest_")
    spool = os.path.join(sdir, "spool")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "mpi_opt_tpu",
            "--workload", "tabular_mlp",
            "--suggest-serve", spool,
            "--suggest-idle-timeout", "120",
            "--seed", str(seed),
            "--ledger", os.path.join(sdir, "suggest.jsonl"),
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        # readiness probe = the warmup: the first answered suggest means
        # the server imported jax, built the space, and compiled the
        # first acquisition variant — all outside the timed window
        deadline = time.perf_counter() + 300
        ready = False
        while time.perf_counter() < deadline:
            try:
                client.round_trip(spool, {"op": "suggest", "n": batch}, timeout=10)
                ready = True
                break
            except TimeoutError:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"suggestion server died during bring-up "
                        f"(rc {proc.returncode})"
                    )
        if not ready:
            raise RuntimeError("suggestion server never became ready")
        rec = client.bench(spool, rounds=rounds, batch=batch)
        log(
            f"[config6] {rec['suggestions']} suggestions in {rec['wall_s']}s "
            f"-> {rec['suggestions_per_sec']}/s; round-trip "
            f"p50={rec['round_trip_p50_s']}s p95={rec['round_trip_p95_s']}s"
        )
    finally:
        client.request_stop(spool)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        shutil.rmtree(sdir, ignore_errors=True)
    return {
        "config": 6,
        "metric": "suggest_service_suggestions_per_sec",
        "value": rec["suggestions_per_sec"],
        "unit": "suggestions/sec",
        "hardware": "server subprocess (default platform), filesystem spool",
        "rounds": rec["rounds"],
        "batch": rec["batch"],
        "requests": rec["requests"],
        "round_trip_p50_s": rec["round_trip_p50_s"],
        "round_trip_p95_s": rec["round_trip_p95_s"],
        "wall_s": rec["wall_s"],
        "transport_note": (
            "every suggestion was also reported back (one report round "
            "trip per suggestion), so the figure measures the full "
            "suggest→evaluate→report conversation an external sweep "
            "drives, not kernel throughput (config 4 measures that)"
        ),
    }


def bench_config7(seed: int, rounds: int = 12, batch: int = 32, burst: int = 4):
    """HTTP front door under bursty load (ISSUE 16): the same real
    server process as config 6 but behind `--http-port` — ``burst``
    concurrent clients each drive batched suggest→report conversations
    (one HTTP request and ONE journal fsync per report batch), the
    open-loop-ish shape the north star's fleet traffic has. Headline is
    sustained suggestions/s through the batched path (acceptance: ≥10×
    config 6's per-file-round-trip 46.6/s); p95 queue wait is the
    shedding bound's health number."""
    import os
    import shutil
    import subprocess
    import sys
    import tempfile

    from mpi_opt_tpu.corpus import client, transport

    sdir = tempfile.mkdtemp(prefix="bench_http_")
    spool = os.path.join(sdir, "spool")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "mpi_opt_tpu",
            "--workload", "tabular_mlp",
            "--suggest-serve", spool,
            "--suggest-idle-timeout", "120",
            "--http-port", "0",
            "--http-queue", "64",
            "--seed", str(seed),
            "--ledger", os.path.join(sdir, "suggest.jsonl"),
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        # discovery + readiness probe = the warmup (jax bring-up + the
        # first compiled acquisition variant), all outside the timed
        # window; bench_http warms its own batch shape too
        url = client.discover_url(spool, timeout=300)
        deadline = time.perf_counter() + 300
        ready = False
        while time.perf_counter() < deadline:
            try:
                t = transport.HttpTransport(url, timeout=30)
                env = transport.envelope([{"op": "suggest", "n": batch}])
                transport.call_with_retries(t, "/v1/batch", env, retries=2)
                ready = True
                break
            except transport.TransportFault:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"front door died during bring-up (rc {proc.returncode})"
                    )
        if not ready:
            raise RuntimeError("front door never became ready")
        rec = client.bench_http(url, rounds=rounds, batch=batch, burst=burst)
        log(
            f"[config7] {rec['suggestions']} suggestions in {rec['wall_s']}s "
            f"-> {rec['suggestions_per_sec']}/s over {burst} clients; "
            f"round-trip p95={rec['round_trip_p95_s']}s queue-wait "
            f"p95={rec['queue_wait_p95_s']}s"
        )
        stop = transport.HttpTransport(url, timeout=10)
        try:
            stop.call("/v1/stop", {})
        except transport.TransportFault:
            pass
    finally:
        client.request_stop(spool)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        shutil.rmtree(sdir, ignore_errors=True)
    return {
        "config": 7,
        "metric": "http_frontdoor_suggestions_per_sec",
        "value": rec["suggestions_per_sec"],
        "unit": "suggestions/sec",
        "hardware": "server subprocess (default platform), HTTP front door",
        "rounds": rec["rounds"],
        "batch": rec["batch"],
        "burst": rec["burst"],
        "requests": rec["requests"],
        "round_trip_p50_s": rec["round_trip_p50_s"],
        "round_trip_p95_s": rec["round_trip_p95_s"],
        "queue_wait_p50_s": rec["queue_wait_p50_s"],
        "queue_wait_p95_s": rec["queue_wait_p95_s"],
        "wall_s": rec["wall_s"],
        "transport_note": (
            "batched wire protocol: each suggest batch's reports ride "
            "ONE HTTP request sharing one journal fsync, vs config 6's "
            "one file round trip per operation — same full "
            "suggest→evaluate→report conversation, amortized transport"
        ),
    }


def bench_config8(seed: int, population: int = 8, generations: int = 3,
                  steps_per_gen: int = 40):
    """Multi-objective fused PBT (ISSUE 17): accuracy:max,params:min on
    digits_mlp with Pareto-rank + crowding selection INSIDE the compiled
    boundary op. Headline is member-generations/s with the MO exploit in
    the loop (comparable to the scalar fused-PBT families); the record
    also carries the final front's hypervolume at budget under the
    optional ``scores`` object — a throughput win that collapses the
    front is a regression, and the gate can now see it."""
    from mpi_opt_tpu.objectives import ObjectiveSpec
    from mpi_opt_tpu.train.fused_pbt import fused_pbt
    from mpi_opt_tpu.workloads import get_workload

    device = _tpu_setup()
    wl = get_workload("digits_mlp")
    spec = ObjectiveSpec.parse("accuracy:max,params:min")
    kw = dict(
        population=population,
        generations=generations,
        steps_per_gen=steps_per_gen,
        seed=seed,
        gen_chunk=1,
        objectives=spec,
    )
    t0 = time.perf_counter()
    res = fused_pbt(wl, **kw)  # warmup: compile the MO boundary program
    log(f"[config8] warmup {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    fused_pbt(wl, **kw)
    warm_wall = time.perf_counter() - t0
    wall, walls, k = timed_region(lambda: fused_pbt(wl, **kw), warm_wall)
    front = res["pareto"]
    # the selected winner's raw objective vector: under an unconstrained
    # spec "best feasible" is the front member with the best primary
    winner = max(front["front_scores"], key=lambda v: v[0])
    log(
        f"[config8] front_size={front['front_size']} "
        f"hypervolume={front['hypervolume']:.4f} selection={front['selection']}"
    )
    return {
        "config": 8,
        "metric": "mo_pbt8_digits_mlp_member_generations_per_sec_per_chip",
        "value": round(k * population * generations / wall, 4),
        "unit": "trials/sec/chip",
        "hardware": device,
        "objectives": res["objectives"],
        # the optional multi-objective summary the bench schema gate
        # covers: {objective: number} for the selected winner, plus the
        # front's hypervolume at budget (sweep quality, not speed)
        "scores": {
            "accuracy": round(float(winner[0]), 4),
            "params": float(winner[1]),
            "hypervolume_at_budget": round(front["hypervolume"], 6),
        },
        "front_size": front["front_size"],
        "selection": front["selection"],
        "population": population,
        "generations": generations,
        "steps_per_gen": steps_per_gen,
        "sweeps_per_region": k,
        "wall_s": round(wall, 2),
        "wall_s_runs": [round(w, 2) for w in walls],
    }


def bench_config9(seed: int, trials: int = 64, min_budget: int = 10,
                  max_budget: int = 270, eta: int = 3, wave_size: int = 16):
    """Wave-scheduled fused SHA (ISSUE 18): the config-2 sweep with its
    rung cohorts capped at ``wave_size`` resident members, streamed
    through the shared engine's host pool (train/engine.py). Headline
    is trials/s with the stage-in/stage-out traffic in the loop —
    comparable to config 2's resident number, so the trajectory can see
    the price of waves directly. The record also carries the engine's
    staging counters (overlap efficiency is ALSO gated via the embedded
    trace's ``staging`` section when traced)."""
    from mpi_opt_tpu.train.fused_asha import fused_sha
    from mpi_opt_tpu.workloads import get_workload

    device = _tpu_setup()
    wl = get_workload("fashion_mlp")
    kw = dict(n_trials=trials, min_budget=min_budget, max_budget=max_budget,
              eta=eta, seed=seed, wave_size=wave_size)
    t0 = time.perf_counter()
    res = fused_sha(wl, **kw)  # warmup: compile wave + boundary programs
    log(f"[config9] warmup {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    fused_sha(wl, **kw)
    warm_wall = time.perf_counter() - t0
    wall, walls, k = timed_region(lambda: fused_sha(wl, **kw), warm_wall)
    log(
        f"[config9] waves={res.get('waves_run')} "
        f"staged={res.get('staged_bytes', 0) >> 20}MiB "
        f"overlap={res.get('stage_overlap_s', 0.0):.2f}s"
    )
    return {
        "config": 9,
        "metric": "wave_sha64_fashion_mlp_trials_per_sec_per_chip",
        "value": round(k * res["n_trials"] / wall, 4),
        "unit": "trials/sec/chip",
        "hardware": device,
        "rung_budgets": res["rung_budgets"],
        "rung_sizes": res["rung_sizes"],
        "best_score": round(res["best_score"], 4),
        "wave_size": res.get("wave_size", wave_size),
        "waves_run": res.get("waves_run"),
        "staged_bytes": res.get("staged_bytes"),
        "stage_transfer_s": round(res.get("stage_transfer_s", 0.0), 3),
        "stage_wait_s": round(res.get("stage_wait_s", 0.0), 3),
        "stage_overlap_s": round(res.get("stage_overlap_s", 0.0), 3),
        "wall_s": round(wall, 2),
        "wall_s_runs": [round(w, 2) for w in walls],
        "sweeps_per_region": k,
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--configs", default="1,2,3,4,5")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--target-acc", type=float, default=0.70)
    p.add_argument("--c5-population", type=int, default=64)
    p.add_argument("--c5-member-chunk", type=int, default=8)
    p.add_argument("--c5-learn-gens", type=int, default=16,
                   help="generations for config 5's learning sweep (0 disables)")
    p.add_argument("--c5-learn-target", type=float, default=0.5,
                   help="val-acc target for config 5's wall-to-target "
                   "(chance=0.01; label-noise ceiling ~0.65, so 0.5 is "
                   "mid-curve and discriminates hyperparameters)")
    p.add_argument("--out", default="BENCH_ALL.json")
    p.add_argument(
        "--trace-dir",
        default=None,
        help="keep per-config span-trace streams here (default: a temp "
        "dir — only the attribution lands in the record)",
    )
    p.add_argument(
        "--no-trace",
        action="store_true",
        help="measure without span tracing (drops the per-config phase "
        "breakdown from the records)",
    )
    p.add_argument(
        "--gate-base",
        default=None,
        metavar="PRIOR.json",
        help="after measuring, judge the configs measured in THIS run "
        "against a prior record set (a BENCH_ALL.json, or one "
        "BENCH_r0*.json record) with obs/diff.py's bench_gate: "
        "headline-value regressions plus per-phase trace regressions "
        "where both sides embed attributions (stale records merged "
        "from a prior --out are never judged). Prints one benchgate "
        "JSON line and exits 1 on regression — the BENCH-trajectory "
        "CI verdict",
    )
    p.add_argument(
        "--gate-tol",
        default=None,
        metavar="TOL.json",
        help="with --gate-base: tolerance budgets (same file format as "
        "`trace --diff --gate`, plus value_max_rel_regression; default "
        "budgets apply without it)",
    )
    args = p.parse_args()
    if args.gate_tol and not args.gate_base:
        p.error("--gate-tol requires --gate-base")
    gate_tol = None
    if args.gate_tol:
        from mpi_opt_tpu.obs.diff import validate_tolerances

        try:
            with open(args.gate_tol) as f:
                gate_tol = json.load(f)
            validate_tolerances(gate_tol)
        except (OSError, ValueError) as e:
            p.error(f"--gate-tol: {e}")
    gate_base = None
    if args.gate_base:
        # load + shape-check BEFORE measuring: a typo'd prior path must
        # not cost a bench run to discover
        try:
            with open(args.gate_base) as f:
                gate_base = json.load(f)
        except (OSError, ValueError) as e:
            p.error(f"--gate-base: {e}")

    runners = {
        "1": lambda: bench_config1(args.seed),
        "2": lambda: bench_config2(args.seed),
        "3": lambda: bench_config3(args.seed, args.target_acc),
        "4": lambda: bench_config4(args.seed),
        "5": lambda: bench_config5(
            args.seed, args.c5_population, args.c5_member_chunk,
            args.c5_learn_gens, args.c5_learn_target,
        ),
        "6": lambda: bench_config6(args.seed),
        "7": lambda: bench_config7(args.seed),
        "8": lambda: bench_config8(args.seed),
        "9": lambda: bench_config9(args.seed),
    }
    # validate BEFORE measuring: a bad token must not cost a bench run
    wanted = [c.strip() for c in args.configs.split(",") if c.strip()]
    unknown = [c for c in wanted if c not in runners]
    if unknown:
        p.error(f"unknown configs {unknown}; choose from {sorted(runners)}")

    # partial runs merge into the existing record set so measuring one
    # config never discards the others' results; malformed existing
    # content is dropped rather than allowed to crash the run
    import os

    existing = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                loaded = json.load(f)
            if isinstance(loaded, list):
                existing = {
                    r["config"]: r
                    for r in loaded
                    if isinstance(r, dict) and isinstance(r.get("config"), int)
                }
        except (OSError, ValueError):
            pass

    def write_out():
        # called after EVERY config so a crash keeps earlier rounds —
        # which is exactly why the write must be atomic: dying inside
        # json.dump would destroy the very records the incremental
        # write exists to preserve (sweeplint atomic-write)
        records = [existing[k] for k in sorted(existing)]
        tmp = f"{args.out}.tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(records, f, indent=1)
            os.replace(tmp, args.out)
        finally:
            if os.path.exists(tmp):  # failed mid-write: no orphan debris
                os.unlink(tmp)

    import tempfile

    trace_dir = None
    if not args.no_trace:
        trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="bench_trace_")
    for c in wanted:
        log(f"[bench_all] config {c} ...")
        t0 = time.perf_counter()
        try:
            rec = traced_config(runners[c], trace_dir, int(c))
        except Exception as e:  # keep measuring the rest; record the failure
            rec = {"config": int(c), "error": f"{type(e).__name__}: {e}"}
        rec["bench_wall_s"] = round(time.perf_counter() - t0, 1)
        existing[rec["config"]] = rec
        print(json.dumps(rec), flush=True)
        write_out()  # after EVERY config: a later crash loses nothing
    log(f"[bench_all] wrote {args.out}")
    if gate_base is not None:
        # the trajectory verdict: THIS run's measurements vs the prior
        # round, machine-checked (obs/diff.py bench_gate) — rc 1 means a
        # headline value or a gated trace phase regressed past budget.
        # Only configs measured in this invocation are judged: `existing`
        # also holds stale records merged from a prior --out file, and
        # gating those would diff the prior round against itself and
        # report an un-measured config as judged-clean
        from mpi_opt_tpu.obs.diff import bench_gate

        measured = [existing[int(c)] for c in wanted if int(c) in existing]
        verdict = bench_gate(gate_base, measured, gate_tol)
        print(json.dumps(verdict), flush=True)
        if not verdict["ok"]:
            for v in verdict["violations"]:
                log(f"[bench_all] GATE: {v}")
            return 1
        log("[bench_all] gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
