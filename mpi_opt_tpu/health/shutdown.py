"""Graceful-shutdown signal protocol (preemption-safe sweeps).

TPU/cloud platforms preempt workers with SIGTERM and only escalate to
SIGKILL after a grace window. A sweep that treats SIGTERM as death
loses the in-flight batch and makes the supervisor burn a retry on a
non-failure; a sweep that ignores it gets SIGKILLed mid-checkpoint.
The protocol here is the middle path:

1. ``ShutdownGuard`` installs SIGTERM/SIGINT handlers that only SET A
   FLAG — nothing is interrupted, no async-unsafe work happens in the
   handler.
2. Drain points (the driver's batch boundary, the fused trainers'
   launch/rung/generation boundaries) poll ``requested()``: when set,
   they finish the in-flight unit, flush durable state (checkpoint
   snapshot, ledger records are already fsync'd), and raise
   ``SweepInterrupted``.
3. The CLI catches it and exits ``EX_TEMPFAIL`` (75, sysexits.h's
   "temporary failure; retry"), the dedicated code ``launch.py``
   classifies as PREEMPTION: coordinated restart with ``--resume``
   that does NOT consume the ``--retries`` budget.

A second SIGINT escalates to an immediate ``KeyboardInterrupt`` (the
interactive convention: first Ctrl-C drains, second aborts). Repeated
SIGTERM stays graceful on purpose — a supervisor forwarding SIGTERM to
a process group whose members already received the platform's signal
must not turn the drain into an abort.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional

# sysexits.h EX_TEMPFAIL: "temporary failure, user is invited to retry".
# The one exit code in the launch supervisor's contract that means
# "restart me with --resume, and don't bill the retry budget".
EX_TEMPFAIL = 75


class SweepInterrupted(RuntimeError):
    """Raised at a drain point after a graceful-shutdown request.

    By construction the in-flight batch/launch has completed and durable
    state (checkpoint snapshot, ledger journal) is flushed; the catcher
    should summarize and exit ``EX_TEMPFAIL``.
    """

    def __init__(self, signal_name: Optional[str] = None, at: str = ""):
        self.signal = signal_name or "SIGTERM"
        self.at = at
        super().__init__(
            f"graceful shutdown ({self.signal})" + (f" at {at}" if at else "")
        )


_ACTIVE: Optional["ShutdownGuard"] = None


class ShutdownGuard:
    """Context manager owning the process's graceful-shutdown flag.

    Installs the flag-setting handlers on enter (main thread only —
    elsewhere the poll API still works, signal delivery is the host
    application's concern) and restores the previous handlers on exit,
    so in-process callers (tests, library embedders) never leak a
    changed SIGINT disposition.
    """

    def __init__(self):
        self.requested = False
        self.signal_name: Optional[str] = None
        self.installed = False
        self._prev: dict = {}
        self._outer: Optional[ShutdownGuard] = None

    def _handle(self, signum, frame):
        if self.requested and signum == signal.SIGINT:
            # second Ctrl-C: the user wants out NOW, not after the batch
            raise KeyboardInterrupt
        self.requested = True
        if self.signal_name is None:
            self.signal_name = signal.Signals(signum).name

    def __enter__(self) -> "ShutdownGuard":
        global _ACTIVE
        if threading.current_thread() is threading.main_thread():
            for sig in (signal.SIGTERM, signal.SIGINT):
                self._prev[sig] = signal.signal(sig, self._handle)
            self.installed = True
        self._outer = _ACTIVE
        _ACTIVE = self
        return self

    def __exit__(self, *exc):
        global _ACTIVE
        if self.installed:
            for sig, prev in self._prev.items():
                signal.signal(sig, prev)
            self.installed = False
        _ACTIVE = self._outer
        return False


def requested() -> bool:
    """Is a graceful shutdown pending? (False when no guard is active.)"""
    return _ACTIVE is not None and _ACTIVE.requested


def active_signal() -> Optional[str]:
    return None if _ACTIVE is None else _ACTIVE.signal_name
