"""Graceful-shutdown signal protocol (preemption-safe sweeps).

TPU/cloud platforms preempt workers with SIGTERM and only escalate to
SIGKILL after a grace window. A sweep that treats SIGTERM as death
loses the in-flight batch and makes the supervisor burn a retry on a
non-failure; a sweep that ignores it gets SIGKILLed mid-checkpoint.
The protocol here is the middle path:

1. ``ShutdownGuard`` installs SIGTERM/SIGINT handlers that only SET A
   FLAG — nothing is interrupted, no async-unsafe work happens in the
   handler.
2. Drain points (the driver's batch boundary, the fused trainers'
   launch/rung/generation boundaries) poll ``requested()``: when set,
   they finish the in-flight unit, flush durable state (checkpoint
   snapshot, ledger records are already fsync'd), and raise
   ``SweepInterrupted``.
3. The CLI catches it and exits ``EX_TEMPFAIL`` (75, sysexits.h's
   "temporary failure; retry"), the dedicated code ``launch.py``
   classifies as PREEMPTION: coordinated restart with ``--resume``
   that does NOT consume the ``--retries`` budget.

A second SIGINT escalates to an immediate ``KeyboardInterrupt`` (the
interactive convention: first Ctrl-C drains, second aborts). Repeated
SIGTERM stays graceful on purpose — a supervisor forwarding SIGTERM to
a process group whose members already received the platform's signal
must not turn the drain into an abort.
"""

from __future__ import annotations

import signal
import threading
from typing import Callable, Optional

# re-export (utils/exitcodes.py is the one home for the code values;
# the historical import surface `health.shutdown.EX_TEMPFAIL` stays)
from mpi_opt_tpu.utils.exitcodes import EX_TEMPFAIL  # noqa: F401


class SweepInterrupted(RuntimeError):
    """Raised at a drain point after a graceful-shutdown request.

    By construction the in-flight batch/launch has completed and durable
    state (checkpoint snapshot, ledger journal) is flushed; the catcher
    should summarize and exit ``EX_TEMPFAIL``.
    """

    def __init__(self, signal_name: Optional[str] = None, at: str = ""):
        self.signal = signal_name or "SIGTERM"
        self.at = at
        super().__init__(
            f"graceful shutdown ({self.signal})" + (f" at {at}" if at else "")
        )


_ACTIVE: Optional["ShutdownGuard"] = None


class ShutdownGuard:
    """Context manager owning the process's graceful-shutdown flag.

    Installs the flag-setting handlers on enter (main thread only —
    elsewhere the poll API still works, signal delivery is the host
    application's concern) and restores the previous handlers on exit,
    so in-process callers (tests, library embedders) never leak a
    changed SIGINT disposition.
    """

    def __init__(self):
        self.requested = False
        self.signal_name: Optional[str] = None
        self.installed = False
        self._prev: dict = {}
        self._outer: Optional[ShutdownGuard] = None
        self._signal_seen = False

    def _handle(self, signum, frame):
        global _DELIVERED
        name = signal.Signals(signum).name
        # record every REAL signal delivery at module level: nested
        # guards (the sweep service runs each tenant slice under its
        # own guard inside the server's) consume the flag with the
        # inner guard, but the server still needs to know, after the
        # slice returns, whether the drain it observed was its own
        # cooperative time-slice or the platform telling the whole
        # process to die
        _DELIVERED = name
        if self._signal_seen and signum == signal.SIGINT:
            # a REAL signal already arrived and now Ctrl-C: the user
            # wants out NOW, not after the batch. Keyed on delivered
            # signals, NOT self.requested — a programmatic slice/cancel
            # request() must not turn the user's FIRST Ctrl-C into a
            # mid-step KeyboardInterrupt that skips the drain
            raise KeyboardInterrupt
        self._signal_seen = True
        self.requested = True
        # a real signal outranks a programmatic slice request: the
        # supervisor/platform asked the PROCESS to stop, and the exit
        # summary should say so even if a slice fired first
        if self.signal_name is None or self.signal_name == SLICE:
            self.signal_name = name

    def __enter__(self) -> "ShutdownGuard":
        global _ACTIVE
        if threading.current_thread() is threading.main_thread():
            for sig in (signal.SIGTERM, signal.SIGINT):
                self._prev[sig] = signal.signal(sig, self._handle)
            self.installed = True
        self._outer = _ACTIVE
        _ACTIVE = self
        return self

    def __exit__(self, *exc):
        global _ACTIVE
        if self.installed:
            for sig, prev in self._prev.items():
                signal.signal(sig, prev)
            self.installed = False
        _ACTIVE = self._outer
        return False


def requested() -> bool:
    """Is a graceful shutdown pending? (False when no guard is active.)"""
    return _ACTIVE is not None and _ACTIVE.requested


def active_signal() -> Optional[str]:
    return None if _ACTIVE is None else _ACTIVE.signal_name


# -- scoped programmatic drain requests (the sweep service's time-slice) --
#
# The service preempts a running tenant by the SAME mechanism a platform
# SIGTERM uses: set the active guard's drain flag and let the sweep's
# next natural boundary (gen_chunk / rung / TPE batch / wave — the
# launch_boundary call sites; the driver's batch boundary) flush its
# snapshot and raise SweepInterrupted. A time-sliced sweep therefore
# leaves EXACTLY the durable state a preempted one does, which is why a
# parked tenant's ledger is bit-identical to an uninterrupted run's.
# The request is scoped to the active guard: when the slice's guard
# exits, the flag dies with it and nothing leaks to the next tenant.

#: the pseudo-signal name a cooperative time-slice drain reports
SLICE = "SLICE"

#: the most recent REAL signal delivered to a guard's handler in this
#: process (None until one arrives); survives guard exit so a scheduler
#: can distinguish "my slice expired" from "the platform killed us".
#: Written from the signal handler, so it MUST stay a bare GIL-atomic
#: store: a handler that takes a lock can interrupt that lock's own
#: holder on the same thread and self-deadlock (the signal-safety rule)
# sweeplint: disable=guarded-by -- signal handlers may only flag-set; a lock in a handler can self-deadlock against its interrupted holder
_DELIVERED: Optional[str] = None

#: scheduler-installed per-boundary callback (see set_slice_hook)
_SLICE_HOOK: Optional[Callable[[str], None]] = None


def request(source: str = SLICE) -> bool:
    """Programmatically request a graceful drain on the active guard.

    Returns False (no-op) when no guard is active. A real signal name
    already recorded is never overwritten — the platform's SIGTERM
    outranks a slice."""
    if _ACTIVE is None:
        return False
    if _ACTIVE.signal_name is None:
        _ACTIVE.signal_name = source
    _ACTIVE.requested = True
    return True


def delivered_signal() -> Optional[str]:
    """The most recent REAL signal a guard handler received in this
    process, or None. Unlike ``active_signal`` this survives guard
    exit; clear it with ``clear_delivered`` before the window you want
    to observe."""
    return _DELIVERED


def clear_delivered() -> None:
    global _DELIVERED
    _DELIVERED = None


def set_slice_hook(fn: Optional[Callable[[str], None]]) -> None:
    """Install the scheduler's cooperative-slice callback.

    ``fn(stage)`` is invoked from every non-final drain point
    (``train.common.launch_boundary``, the driver's batch boundary)
    BEFORE the drain flag is checked, so a hook that decides the slice
    budget is spent can ``request()`` and have the very same boundary
    honor it. The hook must be cheap and must not raise — it runs on
    the sweep's hot host path — with ONE sanctioned exception:
    ``parallel/coord.py``'s boundary agreement chains onto this hook
    and may raise ``CoordWedged`` when a peer rank never reaches the
    boundary; that is a deliberate process-fatal verdict (exit, let
    the supervisor restart the world), not hot-path work."""
    global _SLICE_HOOK
    _SLICE_HOOK = fn


def get_slice_hook() -> Optional[Callable[[str], None]]:
    """The currently installed slice hook (None without one) — for
    wrappers like the coord plane's drain agreement that chain onto an
    existing scheduler hook instead of displacing it."""
    return _SLICE_HOOK


def clear_slice_hook() -> None:
    set_slice_hook(None)


def poll_slice(stage: str) -> None:
    """Drain points' service call: give an installed slice hook its
    per-boundary look (no-op without one)."""
    if _SLICE_HOOK is not None:
        _SLICE_HOOK(stage)
