"""Rank health: graceful shutdown, progress heartbeats, stall detection.

SURVEY.md §5's failure taxonomy has two classes the trial- and
rank-death layers (PR 1/PR 2) structurally cannot reach:

- PREEMPTION: the platform asks the process to die (SIGTERM) instead of
  killing it. Treating that like a crash wastes the in-flight work and,
  worse, burns the supervisor's ``--retries`` budget on something that
  is not a failure at all. ``shutdown`` turns the signal into a
  cooperative drain: finish the in-flight batch/launch, flush
  checkpoint + ledger, exit ``EX_TEMPFAIL`` (75) — the dedicated
  "restart me with --resume, for free" code the launch supervisor
  understands.
- HANG: a rank that is alive but no longer making progress (wedged
  collective, dead-peer I/O). Exit-code polling never sees it; per-trial
  timeouts can't reach it (the wedge is below the trial layer).
  ``heartbeat`` gives every rank a monotonic progress pulse and
  ``watchdog`` gives the supervisor the reader that turns a frozen
  pulse into a kill + coordinated restart.
"""

from mpi_opt_tpu.health.heartbeat import (  # noqa: F401
    Heartbeat,
    beat,
    configure,
    deconfigure,
    read_beat,
)
from mpi_opt_tpu.health.shutdown import (  # noqa: F401
    EX_TEMPFAIL,
    ShutdownGuard,
    SweepInterrupted,
)
from mpi_opt_tpu.health.watchdog import StallDetector  # noqa: F401
