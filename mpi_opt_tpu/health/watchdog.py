"""Stall detection over rank heartbeat files (the supervisor's side).

``StallDetector`` tracks, per watched path, the last beat counter seen
and WHEN it last advanced (supervisor clock — rank clocks are never
compared across hosts). A rank whose counter has not moved for longer
than ``stall_timeout`` is reported stale.

Engagement rule: a rank is only watched once its heartbeat file EXISTS
— i.e. once it has beaten at least once. Ranks beat at progress
boundaries (first driver batch / first fused launch), which puts the
long, legitimate silence of cold-start compilation BEFORE the first
beat, outside the watchdog's jurisdiction; after the first beat, the
gaps being timed are steady-state launch intervals the operator can
actually bound with ``--stall-timeout``. The cost: a rank that wedges
before its first beat is only caught by whole-rank exit (or the
platform); the alternative — timing compilation — makes every
conservative timeout a false kill.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

from mpi_opt_tpu.health.heartbeat import read_beat


class StallDetector:
    def __init__(self, paths: Sequence[str], stall_timeout: float):
        if stall_timeout <= 0:
            raise ValueError(f"stall_timeout must be > 0, got {stall_timeout}")
        self.paths = list(paths)
        self.timeout = float(stall_timeout)
        # index -> (last beats value, monotonic time it last advanced)
        self._seen: dict[int, tuple[int, float]] = {}

    def poll(self, now: Optional[float] = None) -> list[int]:
        """Indices of watched ranks whose beats are frozen past the
        timeout. ``now`` is injectable for tests; defaults to
        ``time.monotonic()``."""
        if now is None:
            now = time.monotonic()
        stale = []
        for i, path in enumerate(self.paths):
            rec = read_beat(path)
            if rec is None:
                continue  # never beaten (or unreadable): not watched yet
            beats = int(rec.get("beats", 0))
            prev = self._seen.get(i)
            if prev is None or beats != prev[0]:
                self._seen[i] = (beats, now)
            elif now - prev[1] > self.timeout:
                stale.append(i)
        return stale
