"""Per-rank progress heartbeat: the liveness signal exit codes can't give.

A rank wedged in a collective (one peer dead, the rest blocked on ICI)
or in dead I/O is ALIVE to ``waitpid`` — the supervisor's exit-code
poll never fires, and the job runs forever. The heartbeat is the
missing observable: each rank rewrites one small JSON file at every
unit of real progress (driver batch, fused launch/rung/generation)
carrying a MONOTONIC beat counter plus wall timestamp and progress
fields. The supervisor's ``watchdog.StallDetector`` reads the files;
beats frozen past ``--stall-timeout`` while the process lives = hang.

Writes are write-tmp-then-rename so a reader never sees a torn record,
and deliberately NOT fsync'd — the file signals liveness, not history;
losing the last beat in a power cut costs nothing.

Failure isolation: a heartbeat that cannot be written (dir vanished,
disk full) must never kill the sweep it reports on — ``beat`` warns
once and goes quiet.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional


class Heartbeat:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self.beats = 0
        self._warned = False
        # beats arrive from more than one thread (the main loop AND
        # StagingEngine's background transfer thread): the lock keeps
        # the counter monotonic, and the thread-unique tmp name keeps a
        # concurrent beat from truncating a sibling's half-written tmp
        # out from under its rename
        import threading

        self._lock = threading.Lock()

    def beat(self, **progress) -> Optional[dict]:
        """Record one unit of progress; returns the record (None if the
        write failed — warned once, never raised). Thread-safe.

        ``phase`` is the beating thread's active trace span
        (obs/trace.py; best-effort cross-thread fallback) — the field
        that turns a stall report into "stalled during stage_in"
        instead of a bare kill. None outside any span."""
        import threading

        from mpi_opt_tpu.obs import trace

        # blocking ON PURPOSE (racelint beat-path-nonblocking judges
        # this path): the critical section is one integer increment —
        # nanoseconds, no I/O — and a non-blocking skip would lose
        # beats, breaking the counter's monotonic contract the stall
        # watchdog reads. The PR 12 lesson targets locks HELD ACROSS
        # I/O on this path (the Refresher's file round-trip), not this.
        # sweeplint: disable=beat-path-nonblocking -- counter-only critical section (no I/O under the lock); skipping would break beat monotonicity
        with self._lock:
            self.beats += 1
            n = self.beats
        rec = {
            "pid": os.getpid(),
            "beats": n,
            "ts": round(time.time(), 4),
            "phase": trace.current_phase(),
            "progress": progress,
        }
        tmp = f"{self.path}.tmp{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps(rec))
            os.replace(tmp, self.path)
        except OSError as e:
            if not self._warned:
                self._warned = True
                import warnings

                warnings.warn(
                    f"heartbeat write to {self.path} failed ({e}); liveness "
                    "reporting disabled for this process — a stall watchdog "
                    "watching this file will treat the rank as unwatched",
                    stacklevel=2,
                )
            _notify_listener(rec)
            return None
        _notify_listener(rec)
        return rec


_ACTIVE: Optional[Heartbeat] = None

#: process-wide beat listener (see set_beat_listener): piggybacks on
#: every unit of real progress, whichever thread produced it
_LISTENER = None


def set_beat_listener(fn) -> None:
    """Install a callback fired after EVERY beat of every heartbeat in
    this process (the beat record is passed; it may be None when the
    write failed — progress still happened).

    This is the lease-refresh ride-along (service/leases.Refresher):
    beats mark real progress at sub-launch granularity, which is
    exactly the cadence a lease deadline should be re-extended at — no
    new timer thread, no extra clock. The listener must be cheap and
    must never raise (it runs on the sweep's hot host path and inside
    the staging engine's transfer thread); exceptions are contained
    here because a broken listener must not kill the sweep its
    heartbeat reports on."""
    global _LISTENER
    _LISTENER = fn


def clear_beat_listener() -> None:
    set_beat_listener(None)


def _notify_listener(rec) -> None:
    if _LISTENER is None:
        return
    try:
        _LISTENER(rec)
    except Exception:
        pass  # contained: a listener bug must not kill the sweep


def configure(path: str) -> Heartbeat:
    """Install the process-wide heartbeat (the CLI's --heartbeat-file)."""
    global _ACTIVE
    _ACTIVE = Heartbeat(path)
    return _ACTIVE


def deconfigure() -> None:
    """Drop the process-wide heartbeat (end of a CLI run: in-process
    callers must not leave a stale path that later beats crash on)."""
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[Heartbeat]:
    return _ACTIVE


def beat(**progress) -> None:
    """Module-level beat: no-op unless a heartbeat is configured, so
    library code (driver, fused trainers) calls it unconditionally."""
    if _ACTIVE is not None:
        _ACTIVE.beat(**progress)


def read_beat(path: str) -> Optional[dict]:
    """The last complete beat record at ``path``, or None (missing,
    unreadable, or torn — the rename discipline makes torn ~impossible,
    but a reader must still never crash on a file it doesn't own)."""
    try:
        with open(path, "r") as f:
            rec = json.loads(f.read())
    except (OSError, json.JSONDecodeError):
        return None
    return rec if isinstance(rec, dict) else None
