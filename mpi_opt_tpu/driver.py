"""The search driver: suggest → evaluate → report (SURVEY.md §3).

Reference call stack (contract from BASELINE.json; reference
unreadable): CLI → driver loop { algorithm.suggest → backend.evaluate
(Coordinator → MPI → MPIWorker ranks) → collect scores → algorithm
.report }. Here the loop is identical in shape, but the batch size is
pulled from the backend (``capacity``) so a TPU population backend
receives device-shaped batches, and a generational algorithm (PBT) can
hold the loop between generations without extra driver modes.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from mpi_opt_tpu.algorithms.base import Algorithm
from mpi_opt_tpu.backends.base import Backend
from mpi_opt_tpu.trial import Trial
from mpi_opt_tpu.utils.metrics import MetricsLogger, null_logger


@dataclasses.dataclass
class SearchResult:
    best: Optional[Trial]
    n_trials: int
    wall_s: float
    trials_per_sec_per_chip: float
    # evaluations actually run by this call: >= n_trials for multi-rung
    # algorithms (each ASHA promotion re-enters the backend), and the
    # numerator of trials_per_sec_per_chip
    n_evals: int = 0


def run_search(
    algorithm: Algorithm,
    backend: Backend,
    metrics: Optional[MetricsLogger] = None,
    max_batches: Optional[int] = None,
    checkpointer=None,
) -> SearchResult:
    """Drive the suggest→evaluate→report loop to completion.

    ``checkpointer`` (utils.checkpoint.SearchCheckpointer) snapshots
    algorithm + backend state after report_batch on its cadence, so a
    killed process resumes at the last completed batch instead of
    restarting the sweep.
    """
    metrics = metrics or null_logger()
    t0 = time.perf_counter()
    batches = 0
    n_run = 0  # trials evaluated by THIS run (metrics may be shared/reused)
    while not algorithm.finished():
        batch = algorithm.next_batch(backend.capacity)
        if not batch:
            if algorithm.finished():
                break
            raise RuntimeError(
                f"{algorithm.name}: no trials to run but search not finished "
                "(algorithm is waiting on results that were never reported)"
            )
        results = backend.evaluate(batch)
        algorithm.report_batch(results)
        metrics.count_trials(len(results))
        n_run += len(results)
        best = algorithm.best()
        metrics.log(
            "batch",
            algo=algorithm.name,
            backend=backend.name,
            size=len(batch),
            best_score=None if best is None else round(best.score, 6),
        )
        batches += 1
        if checkpointer is not None:
            checkpointer.maybe_save(batches, algorithm, backend)
        if max_batches is not None and batches >= max_batches:
            break
    wall = time.perf_counter() - t0
    return SearchResult(
        best=algorithm.best(),
        n_trials=algorithm.n_trials,
        wall_s=wall,
        trials_per_sec_per_chip=n_run / max(wall, 1e-9) / metrics.n_chips,
        n_evals=n_run,
    )
