"""The search driver: suggest → evaluate → report (SURVEY.md §3).

Reference call stack (contract from BASELINE.json; reference
unreadable): CLI → driver loop { algorithm.suggest → backend.evaluate
(Coordinator → MPI → MPIWorker ranks) → collect scores → algorithm
.report }. Here the loop is identical in shape, but the batch size is
pulled from the backend (``capacity``) so a TPU population backend
receives device-shaped batches, and a generational algorithm (PBT) can
hold the loop between generations without extra driver modes.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Optional, Sequence

from mpi_opt_tpu.algorithms.base import Algorithm
from mpi_opt_tpu.backends.base import Backend
from mpi_opt_tpu.trial import Trial, TrialResult
from mpi_opt_tpu.utils.metrics import MetricsLogger, null_logger


@dataclasses.dataclass
class SearchResult:
    best: Optional[Trial]
    n_trials: int
    wall_s: float
    trials_per_sec_per_chip: float
    # evaluations actually run by this call: >= n_trials for multi-rung
    # algorithms (each ASHA promotion re-enters the backend), and the
    # numerator of trials_per_sec_per_chip
    n_evals: int = 0
    # final per-status failure tallies for this call (post-retry)
    n_failed: int = 0
    n_timeout: int = 0
    n_retried: int = 0


@dataclasses.dataclass(frozen=True)
class FailurePolicy:
    """How the driver treats non-ok trial results.

    Retries re-enter ``backend.evaluate`` for just the failed trials, up
    to ``max_retries`` times per trial, sleeping a jittered exponential
    backoff between rounds (attempt k waits ``backoff_s * 2**(k-1)``,
    scaled by up to ``backoff_jitter`` of random extra — the jitter
    keeps a fleet of retrying drivers from synchronizing against a
    shared resource). Trials still failing after the retries are
    reported to the algorithm as FINAL failures.

    ``max_failure_rate`` is the systemic-bug circuit breaker: when the
    fraction of final failures over all evaluations exceeds it (checked
    only once ``min_evals_for_abort`` evaluations exist, so a tiny
    denominator can't trip it), the sweep raises ``SweepAborted``
    instead of grinding through thousands of doomed trials. 1.0
    disables the breaker (some sweeps legitimately fail a lot).
    """

    max_retries: int = 0
    backoff_s: float = 0.1
    backoff_jitter: float = 0.5
    max_failure_rate: float = 1.0
    min_evals_for_abort: int = 20
    seed: int = 0

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {self.max_retries}")
        if not 0.0 < self.max_failure_rate <= 1.0:
            raise ValueError(
                f"max_failure_rate must be in (0, 1], got {self.max_failure_rate}"
            )

    def backoff(self, attempt: int, rng: random.Random) -> float:
        """Seconds to sleep before retry ``attempt`` (1-based)."""
        return self.backoff_s * (2 ** (attempt - 1)) * (1.0 + self.backoff_jitter * rng.random())


class SweepAborted(RuntimeError):
    """Raised when the failure fraction crosses FailurePolicy.max_failure_rate."""


class _FailureTracker:
    """Per-search retry/abort bookkeeping for one run_search call."""

    def __init__(self, policy: FailurePolicy, metrics: MetricsLogger):
        self.policy = policy
        self.metrics = metrics
        self.rng = random.Random(policy.seed)
        self.evaluated = 0  # final results seen (ok + failed)
        self.failed = 0  # final non-ok results
        self.timeout = 0
        self.retried = 0

    def evaluate(self, backend: Backend, batch: Sequence[Trial]) -> list[TrialResult]:
        """backend.evaluate with per-trial retries; returns FINAL results
        aligned with ``batch`` order."""
        results = backend.evaluate(batch)
        final = {r.trial_id: r for r in results}
        if self.policy.max_retries > 0:
            by_id = {t.trial_id: t for t in batch}
            for attempt in range(1, self.policy.max_retries + 1):
                retry = [by_id[tid] for tid, r in final.items() if not r.ok]
                if not retry:
                    break
                delay = self.policy.backoff(attempt, self.rng)
                if delay > 0:
                    time.sleep(delay)
                self.retried += len(retry)
                self.metrics.count_retries(len(retry))
                self.metrics.log(
                    "trial_retry",
                    attempt=attempt,
                    of=self.policy.max_retries,
                    trials=[t.trial_id for t in retry],
                    backoff_s=round(delay, 3),
                )
                for r in backend.evaluate(retry):
                    final[r.trial_id] = r
        out = [final[t.trial_id] for t in batch]
        self._account(out)
        return out

    def _account(self, results: Sequence[TrialResult]) -> None:
        self.evaluated += len(results)
        # count the batch HERE, before the abort check can raise: an
        # aborting batch's failures must not appear in the summary's
        # failure counters with their evaluations missing from `trials`
        # (operators compute failure fractions from that pair)
        self.metrics.count_trials(len(results))
        for r in results:
            if r.ok:
                continue
            self.failed += 1
            if r.status == "timeout":
                self.timeout += 1
            self.metrics.count_failure(r.status)
            self.metrics.log(
                "trial_failed",
                trial_id=r.trial_id,
                status=r.status,
                error=r.error,
                step=r.step,
            )
        if (
            self.policy.max_failure_rate < 1.0
            and self.evaluated >= self.policy.min_evals_for_abort
            and self.failed / self.evaluated > self.policy.max_failure_rate
        ):
            msg = (
                f"sweep aborted: {self.failed}/{self.evaluated} trial "
                f"evaluations failed ({self.failed / self.evaluated:.0%} > "
                f"max_failure_rate {self.policy.max_failure_rate:.0%}) — "
                "a systemic failure, not unlucky hyperparameters"
            )
            self.metrics.log("sweep_aborted", error=msg)
            raise SweepAborted(msg)


def run_search(
    algorithm: Algorithm,
    backend: Backend,
    metrics: Optional[MetricsLogger] = None,
    max_batches: Optional[int] = None,
    checkpointer=None,
    policy: Optional[FailurePolicy] = None,
) -> SearchResult:
    """Drive the suggest→evaluate→report loop to completion.

    ``checkpointer`` (utils.checkpoint.SearchCheckpointer) snapshots
    algorithm + backend state after report_batch on its cadence, so a
    killed process resumes at the last completed batch instead of
    restarting the sweep.

    ``policy`` (FailurePolicy) governs non-ok trial results: retries
    with jittered backoff first, then the FINAL result — ok or failed —
    is reported to the algorithm, and the failure-rate circuit breaker
    raises ``SweepAborted`` on systemic failure. The default policy is
    no retries and no breaker, so failed trials flow straight through
    as FAILED reports.
    """
    metrics = metrics or null_logger()
    tracker = _FailureTracker(policy or FailurePolicy(), metrics)
    t0 = time.perf_counter()
    batches = 0
    n_run = 0  # trials evaluated by THIS run (metrics may be shared/reused)
    while not algorithm.finished():
        batch = algorithm.next_batch(backend.capacity)
        if not batch:
            if algorithm.finished():
                break
            raise RuntimeError(
                f"{algorithm.name}: no trials to run but search not finished "
                "(algorithm is waiting on results that were never reported)"
            )
        # tracker.evaluate owns metrics.count_trials for the batch (it
        # must tally even a batch whose abort check raises)
        results = tracker.evaluate(backend, batch)
        algorithm.report_batch(results)
        n_run += len(results)
        best = algorithm.best()
        metrics.log(
            "batch",
            algo=algorithm.name,
            backend=backend.name,
            size=len(batch),
            best_score=None if best is None else round(best.score, 6),
        )
        batches += 1
        if checkpointer is not None:
            checkpointer.maybe_save(batches, algorithm, backend)
        if max_batches is not None and batches >= max_batches:
            break
    wall = time.perf_counter() - t0
    return SearchResult(
        best=algorithm.best(),
        n_trials=algorithm.n_trials,
        wall_s=wall,
        trials_per_sec_per_chip=n_run / max(wall, 1e-9) / metrics.n_chips,
        n_evals=n_run,
        n_failed=tracker.failed - tracker.timeout,
        n_timeout=tracker.timeout,
        n_retried=tracker.retried,
    )
