"""The search driver: suggest → evaluate → report (SURVEY.md §3).

Reference call stack (contract from BASELINE.json; reference
unreadable): CLI → driver loop { algorithm.suggest → backend.evaluate
(Coordinator → MPI → MPIWorker ranks) → collect scores → algorithm
.report }. Here the loop is identical in shape, but the batch size is
pulled from the backend (``capacity``) so a TPU population backend
receives device-shaped batches, and a generational algorithm (PBT) can
hold the loop between generations without extra driver modes.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Optional, Sequence

from mpi_opt_tpu.algorithms.base import Algorithm
from mpi_opt_tpu.backends.base import Backend
from mpi_opt_tpu.health import heartbeat, shutdown
from mpi_opt_tpu.health.shutdown import SweepInterrupted
from mpi_opt_tpu.ledger.store import result_from_record
from mpi_opt_tpu.obs import trace
from mpi_opt_tpu.utils import profiling
from mpi_opt_tpu.trial import Trial, TrialResult
from mpi_opt_tpu.utils.metrics import MetricsLogger, null_logger


@dataclasses.dataclass
class SearchResult:
    best: Optional[Trial]
    n_trials: int
    wall_s: float
    trials_per_sec_per_chip: float
    # evaluations actually run by this call: >= n_trials for multi-rung
    # algorithms (each ASHA promotion re-enters the backend), and the
    # numerator of trials_per_sec_per_chip
    n_evals: int = 0
    # final per-status failure tallies for this call (post-retry)
    n_failed: int = 0
    n_timeout: int = 0
    n_retried: int = 0
    # ledger-layer tallies: results served without touching the backend
    # (journal replay on resume / exact-match cache), disjoint from
    # n_evals so throughput never counts un-run work
    n_replayed: int = 0
    n_cache_hits: int = 0


@dataclasses.dataclass(frozen=True)
class FailurePolicy:
    """How the driver treats non-ok trial results.

    Retries re-enter ``backend.evaluate`` for just the failed trials, up
    to ``max_retries`` times per trial, sleeping a jittered exponential
    backoff between rounds (attempt k waits ``backoff_s * 2**(k-1)``,
    scaled by up to ``backoff_jitter`` of random extra — the jitter
    keeps a fleet of retrying drivers from synchronizing against a
    shared resource). Trials still failing after the retries are
    reported to the algorithm as FINAL failures.

    ``max_failure_rate`` is the systemic-bug circuit breaker: when the
    fraction of final failures over all evaluations exceeds it (checked
    only once ``min_evals_for_abort`` evaluations exist, so a tiny
    denominator can't trip it), the sweep raises ``SweepAborted``
    instead of grinding through thousands of doomed trials. 1.0
    disables the breaker (some sweeps legitimately fail a lot).
    """

    max_retries: int = 0
    backoff_s: float = 0.1
    backoff_jitter: float = 0.5
    max_failure_rate: float = 1.0
    min_evals_for_abort: int = 20
    seed: int = 0

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {self.max_retries}")
        if not 0.0 < self.max_failure_rate <= 1.0:
            raise ValueError(
                f"max_failure_rate must be in (0, 1], got {self.max_failure_rate}"
            )

    def backoff(self, attempt: int, rng: random.Random) -> float:
        """Seconds to sleep before retry ``attempt`` (1-based)."""
        return self.backoff_s * (2 ** (attempt - 1)) * (1.0 + self.backoff_jitter * rng.random())


class SweepAborted(RuntimeError):
    """Raised when the failure fraction crosses FailurePolicy.max_failure_rate."""


class _FailureTracker:
    """Per-search retry/abort bookkeeping for one run_search call."""

    def __init__(self, policy: FailurePolicy, metrics: MetricsLogger):
        self.policy = policy
        self.metrics = metrics
        self.rng = random.Random(policy.seed)
        self.evaluated = 0  # final results seen (ok + failed)
        self.failed = 0  # final non-ok results
        self.timeout = 0
        self.retried = 0

    def evaluate(
        self, backend: Backend, batch: Sequence[Trial], on_final=None
    ) -> list[TrialResult]:
        """backend.evaluate with per-trial retries; returns FINAL results
        aligned with ``batch`` order.

        ``on_final(trial, result, attempts)`` fires once per trial with
        its post-retry FINAL result, BEFORE the abort check can raise —
        the ledger's journaling hook: an aborting batch's evaluations
        must be durable even though run_search never returns them.
        ``attempts`` is 1 + the retry rounds the trial re-entered.
        """
        results = backend.evaluate(batch)
        final = {r.trial_id: r for r in results}
        attempts = {t.trial_id: 1 for t in batch}
        if self.policy.max_retries > 0:
            by_id = {t.trial_id: t for t in batch}
            for attempt in range(1, self.policy.max_retries + 1):
                retry = [by_id[tid] for tid, r in final.items() if not r.ok]
                if not retry:
                    break
                delay = self.policy.backoff(attempt, self.rng)
                if delay > 0:
                    time.sleep(delay)
                self.retried += len(retry)
                self.metrics.count_retries(len(retry))
                self.metrics.log(
                    "trial_retry",
                    attempt=attempt,
                    of=self.policy.max_retries,
                    trials=[t.trial_id for t in retry],
                    backoff_s=round(delay, 3),
                )
                for t in retry:
                    attempts[t.trial_id] += 1
                for r in backend.evaluate(retry):
                    final[r.trial_id] = r
        out = [final[t.trial_id] for t in batch]
        if on_final is not None:
            for t, r in zip(batch, out):
                on_final(t, r, attempts[t.trial_id])
        self._account(out)
        return out

    def _account(self, results: Sequence[TrialResult]) -> None:
        self.evaluated += len(results)
        # count the batch HERE, before the abort check can raise: an
        # aborting batch's failures must not appear in the summary's
        # failure counters with their evaluations missing from `trials`
        # (operators compute failure fractions from that pair)
        self.metrics.count_trials(len(results))
        for r in results:
            if r.ok:
                continue
            self.failed += 1
            if r.status == "timeout":
                self.timeout += 1
                # a reaped deadline IS a detected stall: the evaluation
                # wedged (or its worker died) and was killed — the
                # trial-level twin of the supervisor's rank watchdog,
                # and the producer behind the summary's stalls_detected
                self.metrics.count_stalls()
            self.metrics.log(
                "trial_failed",
                trial_id=r.trial_id,
                status=r.status,
                error=r.error,
                step=r.step,
                # the phase the driver was in when the failure was
                # accounted (the stall satellite: "stalled during X",
                # not a bare reap) — None outside any span
                phase=trace.current_phase(),
            )
            self.metrics.count_failure(r.status)
        if (
            self.policy.max_failure_rate < 1.0
            and self.evaluated >= self.policy.min_evals_for_abort
            and self.failed / self.evaluated > self.policy.max_failure_rate
        ):
            msg = (
                f"sweep aborted: {self.failed}/{self.evaluated} trial "
                f"evaluations failed ({self.failed / self.evaluated:.0%} > "
                f"max_failure_rate {self.policy.max_failure_rate:.0%}) — "
                "a systemic failure, not unlucky hyperparameters"
            )
            self.metrics.log("sweep_aborted", error=msg)
            raise SweepAborted(msg)


def run_search(
    algorithm: Algorithm,
    backend: Backend,
    metrics: Optional[MetricsLogger] = None,
    max_batches: Optional[int] = None,
    checkpointer=None,
    policy: Optional[FailurePolicy] = None,
    ledger=None,
    cache=None,
) -> SearchResult:
    """Drive the suggest→evaluate→report loop to completion.

    ``checkpointer`` (utils.checkpoint.SearchCheckpointer) snapshots
    algorithm + backend state after report_batch on its cadence, so a
    killed process resumes at the last completed batch instead of
    restarting the sweep.

    ``policy`` (FailurePolicy) governs non-ok trial results: retries
    with jittered backoff first, then the FINAL result — ok or failed —
    is reported to the algorithm, and the failure-rate circuit breaker
    raises ``SweepAborted`` on systemic failure. The default policy is
    no retries and no breaker, so failed trials flow straight through
    as FAILED reports.

    ``ledger`` (ledger.store.SweepLedger, header already ensured)
    journals every FINAL result fsync-durably before it is reported,
    and REPLAYS the journal on resume: a suggested trial whose id holds
    a final record is served from the journal (params-verified) without
    touching the backend, so a killed driver resumes at the exact last
    completed trial — finer-grained than, and composable with, the
    batch-cadence ``checkpointer``. ``cache`` (ledger.cache.EvalCache)
    is the exact-match params→result memo consulted before
    ``backend.evaluate``; when a ledger is given and no cache, one is
    built from the ledger's ok records automatically. Replay beats
    cache: replay preserves the trial's recorded identity (including a
    FINAL failure), the cache only ever serves ok results to NEW points.
    """
    metrics = metrics or null_logger()
    tracker = _FailureTracker(policy or FailurePolicy(), metrics)
    replay: dict[int, dict] = {} if ledger is None else ledger.completed()
    if cache is None and ledger is not None:
        from mpi_opt_tpu.ledger.cache import EvalCache

        cache = EvalCache(algorithm.space)
        cache.seed_from(ledger.ok_records())
    if replay:
        metrics.log("ledger_replay", completed=len(replay))

    def on_final(trial: Trial, result: TrialResult, attempts: int) -> None:
        # journal BEFORE report/abort so the record can never lag the
        # search state it will be replayed into
        if ledger is not None:
            ledger.record_trial(
                result,
                algorithm.space.canonical_params(trial.params),
                attempts=attempts,
            )
        if cache is not None:
            cache.put(trial.params, result)

    t0 = time.perf_counter()
    batches = 0
    n_run = 0  # trials evaluated by THIS run (metrics may be shared/reused)
    n_replayed = 0
    n_cache_hits = 0
    while not algorithm.finished():
        batch = algorithm.next_batch(backend.capacity)
        if not batch:
            if algorithm.finished():
                break
            raise RuntimeError(
                f"{algorithm.name}: no trials to run but search not finished "
                "(algorithm is waiting on results that were never reported)"
            )
        served: dict[int, TrialResult] = {}
        pending: list[Trial] = []
        for t in batch:
            rec = replay.pop(t.trial_id, None)
            if rec is not None:
                _verify_replay(algorithm.space, t, rec, ledger)
                served[t.trial_id] = result_from_record(rec)
                n_replayed += 1
                metrics.count_replayed()
                continue
            if cache is not None:
                hit = cache.get(t.params, t.budget, t.trial_id)
                if hit is not None:
                    served[t.trial_id] = hit
                    n_cache_hits += 1
                    metrics.count_cache_hits()
                    # the hit is a FINAL ok result of THIS sweep too:
                    # journal it (cached=True, attempts=0) so a later
                    # resume replays it instead of re-consulting fate
                    if ledger is not None:
                        ledger.record_trial(
                            hit,
                            algorithm.space.canonical_params(t.params),
                            attempts=0,
                            cached=True,
                        )
                    continue
            pending.append(t)
        if pending:
            profiling.launch_tick()
            # tracker.evaluate owns metrics.count_trials for the batch
            # (it must tally even a batch whose abort check raises) and
            # fires on_final per trial before that check. The train span
            # is the driver path's launch-equivalent: backend.evaluate
            # blocks until the batch's results exist, so dur_s is real
            # batch wall (retries included); per-trial journal spans
            # nest inside it via on_final
            with trace.span("train", batch=batches + 1, members=len(pending)):
                for r in tracker.evaluate(backend, pending, on_final=on_final):
                    served[r.trial_id] = r
        algorithm.report_batch([served[t.trial_id] for t in batch])
        n_run += len(pending)
        best = algorithm.best()
        metrics.log(
            "batch",
            algo=algorithm.name,
            backend=backend.name,
            size=len(batch),
            evaluated=len(pending),
            best_score=None if best is None else round(best.score, 6),
        )
        batches += 1
        saved = False
        if checkpointer is not None:
            saved = checkpointer.maybe_save(batches, algorithm, backend)
        # the rank's liveness pulse: one beat per completed batch (the
        # launch supervisor's stall watchdog times the gaps between
        # these). No-op unless the process configured --heartbeat-file.
        heartbeat.beat(stage="driver", batches=batches, trials=algorithm.n_trials)
        # cooperative-slice point (the driver-path twin of the fused
        # launch_boundary's): a service slice hook may set the drain
        # flag this very boundary honors. Only batches that EVALUATED
        # something tick the hook — a replay/cache-served batch costs no
        # device time, and counting it would livelock a resumed slice
        # (every slice re-replays the journal, spends its whole budget
        # on free batches, and parks with zero new progress, forever).
        # A finished sweep never drains, matching the fused final=True
        # rule below.
        if pending and not algorithm.finished():
            shutdown.poll_slice(f"batch {batches}")
        if shutdown.requested() and not algorithm.finished():
            # graceful-shutdown drain point: the in-flight batch is done
            # and journaled (the ledger fsyncs per record); force an
            # off-cadence snapshot so --resume loses nothing, then hand
            # the preemption up to the CLI's EX_TEMPFAIL exit. A batch
            # that COMPLETED the sweep exits normally instead — same
            # rule as the fused launch_boundary's final=True: finishing
            # strictly dominates preempting a finished sweep
            if checkpointer is not None and not saved:
                checkpointer.save(batches, algorithm, backend)
            metrics.log(
                "preempt_drain",
                signal=shutdown.active_signal(),
                batches=batches,
                trials=algorithm.n_trials,
            )
            raise SweepInterrupted(
                shutdown.active_signal(), at=f"batch {batches}"
            )
        if max_batches is not None and batches >= max_batches:
            break
    if replay and algorithm.finished():
        # journal records the resumed algorithm never re-suggested: not
        # fatal (the search completed), but operators should know the
        # ledger holds trials this configuration no longer produces
        metrics.log("ledger_replay_unconsumed", trials=sorted(replay))
    wall = time.perf_counter() - t0
    return SearchResult(
        best=algorithm.best(),
        n_trials=algorithm.n_trials,
        wall_s=wall,
        trials_per_sec_per_chip=n_run / max(wall, 1e-9) / metrics.n_chips,
        n_evals=n_run,
        n_failed=tracker.failed - tracker.timeout,
        n_timeout=tracker.timeout,
        n_retried=tracker.retried,
        n_replayed=n_replayed,
        n_cache_hits=n_cache_hits,
    )


def _verify_replay(space, trial: Trial, rec: dict, ledger) -> None:
    """A replayed record must describe the SAME point the resumed
    algorithm re-suggested under that trial id — algorithms re-derive
    their suggestion streams deterministically from (seed, reports), so
    a mismatch means the ledger belongs to a different configuration
    than the header check could see (e.g. a code change shifted the
    stream) and replaying it would corrupt the search."""
    if space.params_key(trial.params) != space.params_key(rec["params"]):
        from mpi_opt_tpu.ledger.store import LedgerError

        raise LedgerError(
            f"ledger replay diverged at trial {trial.trial_id}: journal "
            f"records params {rec['params']} but the resumed search "
            f"suggested {space.canonical_params(trial.params)} — the "
            f"ledger{'' if ledger is None else ' ' + ledger.path} was "
            "written by a different suggestion stream; resume with the "
            "original configuration or start a fresh ledger"
        )
