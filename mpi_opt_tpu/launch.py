"""Coordinated multi-process launch + recovery supervisor.

SURVEY.md §5 (failure detection / elastic recovery) for the one
topology where per-process ``--retries`` is unsound: multi-process
SPMD. One rank restoring a snapshot while its peers sit in a collective
issues mismatched programs and hangs the job — so recovery there must
be a COORDINATED job restart. This module is that coordination, and the
``mpirun``-equivalent front door (the reference's launcher role):

    python -m mpi_opt_tpu.launch --n-proc 4 --retries 2 -- \
        --workload cifar100_resnet18 --algorithm pbt --fused \
        --checkpoint-dir /ckpt/sweep --population 1024 ...

It spawns ``--n-proc`` ranks of ``python -m mpi_opt_tpu`` (appending
``--coordinator/--num-processes/--process-id`` for each, plus
``--coord-dir/--coord-epoch`` wiring the boundary-agreement control
plane — parallel/coord.py — with a fresh epoch per attempt so a
restarted job can never read a killed attempt's stale votes), watches
them, and on ANY rank death kills the survivors and relaunches ALL ranks —
with ``--resume`` appended when the job has durable state
(``--checkpoint-dir`` or ``--ledger``), so the restarted job continues
from the last shared snapshot / journal and (because fused-sweep resume
is bit-identical, tested) finishes with the result the unkilled job
would have produced. Without durable state a restart re-runs the
(deterministic) sweep from scratch.

Three failure classes, three treatments (README: failure-handling
matrix):

- RANK DEATH (nonzero exit, not 75): coordinated restart, consuming one
  unit of the ``--retries`` budget. Transient-vs-program classification
  is deliberately NOT attempted (a supervisor sees exit codes, not
  exception types); a program bug burns its retries in seconds and
  surfaces the rank's stderr, a platform death resumes and completes.
- PREEMPTION (exit 75 = EX_TEMPFAIL, the graceful-shutdown protocol's
  code; or SIGTERM delivered to the supervisor itself): not a failure.
  A rank exiting 75 has drained and flushed; the supervisor restarts
  with ``--resume`` WITHOUT consuming ``--retries`` (bounded by
  ``--max-preemptions`` so a deterministic self-preempting bug cannot
  restart forever). The supervisor being SIGTERMed forwards the signal
  to all ranks, drains them for ``--term-grace`` seconds, then exits 75
  itself — so nested supervision composes.
- HANG (``--stall-timeout``): ranks are alive but their heartbeat files
  (health/heartbeat.py, auto-wired via ``--heartbeat-file``) have
  stopped advancing — a wedged collective or dead I/O that exit-code
  polling can never see. The job is killed and coordinate-restarted,
  consuming one retry.
- COLLECTIVE WEDGE (rank death under SPMD): when a rank dies hard, its
  survivors don't crash — they freeze inside the collective (or the
  coord plane's boundary barrier) waiting for the dead peer, heartbeats
  stuck in a ``train``/``boundary``/staging phase. The exit path
  classifies that shape (dead rank + survivors frozen mid-collective),
  emits ``rank_wedge``, TERM-drains the survivors with the usual
  ``--term-grace`` escalation, and funds ONE coordinated ``--resume``
  restart from the rank-death retry budget — the restarted ledger is
  record-identical to an unkilled run (fused resume is bit-identical).

Two non-retryable classifications cut restart storms short:

- DATA ERROR (exit 65 = EX_DATAERR): the rank's resume found snapshots
  but NONE verified (utils/integrity.py quarantined every retained
  step). Restarting re-reads the same poisoned state — abort with
  diagnostics immediately instead of burning the whole retries/
  preemption budget on a crash loop. (Exit 2, a usage error, is
  refused for the analogous reason — see below.)
- CRASH LOOP (``--crash-loop-threshold``/``--crash-loop-window``): N
  consecutive failure restarts where each attempt died within the
  window are a deterministic bug regardless of exit code — abort even
  while ``--retries`` budget remains, so a large budget sized for rare
  platform deaths can't be burned in seconds.

Escalation is always graceful-first: survivors/stragglers get SIGTERM
(their own drain handlers flush state) and only after ``--term-grace``
seconds SIGKILL.

Per-rank stdout/stderr go to ``--log-dir`` (default: a temp dir,
printed) as ``rank{i}.out``/``rank{i}.err``, truncated per attempt;
rank 0's final summary line is re-printed on the supervisor's stdout so
scripted callers keep the single-JSON-line contract.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import socket
import subprocess
import sys
import tempfile
import time

from mpi_opt_tpu.health.shutdown import ShutdownGuard
from mpi_opt_tpu.health.watchdog import StallDetector
from mpi_opt_tpu.utils.exitcodes import EX_DATAERR, EX_IOERR, EX_TEMPFAIL, EX_USAGE


def _backoff_s(attempt: int, base: float, jitter: float, rng: random.Random) -> float:
    """Seconds to wait before coordinated restart ``attempt`` (1-based):
    jittered exponential, ``base * 2**(attempt-1)`` scaled by up to
    ``jitter`` extra. An immediate relaunch hammers a flapping platform
    (a TPU worker mid-restart rejects the reconnect, burning a retry for
    nothing), and the jitter keeps N supervisors that died together from
    reconnecting in lockstep."""
    if base <= 0:
        return 0.0
    return base * (2 ** (attempt - 1)) * (1.0 + jitter * rng.random())


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _hb_path(log_dir: str, rank: int) -> str:
    return os.path.join(log_dir, f"rank{rank}.hb")


def _stall_phases(log_dir: str, ranks) -> dict:
    """``{rank: phase}`` for stalled ranks, from each rank's LAST beat
    record: the ``phase`` field (the rank's active trace span at beat
    time — obs/trace.py) with the beat's ``stage`` progress label as
    fallback. Turns a bare "ranks [1] stalled" kill into "rank 1
    stalled during stage_in". Unknown phases report None — the beat
    predates the span layer or carried no phase."""
    from mpi_opt_tpu.health.heartbeat import read_beat

    phases = {}
    for i in ranks:
        rec = read_beat(_hb_path(log_dir, i)) or {}
        phases[str(i)] = rec.get("phase") or (rec.get("progress") or {}).get(
            "stage"
        )
    return phases


def _is_collective_phase(phase) -> bool:
    """Is this last-beat phase one a rank holds while inside (or
    waiting to enter) a collective — the shape a survivor freezes in
    when a peer dies mid-job? ``train`` covers fused launches,
    ``boundary*`` the boundary ops AND the coord plane's agreement
    barrier (whose waits deliberately stop advancing beats), the
    staging phases the transfer engine's device-side barriers."""
    return bool(phase) and (
        phase == "train"
        or phase.startswith("boundary")
        or phase.startswith("stage")
        or phase.startswith("staging")
    )


def _spawn_ranks(
    n: int, rest: list[str], log_dir: str, heartbeat: bool = False, coord=None
):
    """One attempt's rank processes; a fresh coordinator port each time
    (the previous attempt's port may linger in TIME_WAIT). With
    ``heartbeat`` each rank gets ``--heartbeat-file`` pointed at its
    per-rank file under ``log_dir`` (the stall watchdog's input).
    ``coord`` is ``(dir, epoch)`` wiring each rank's boundary-agreement
    plane — the epoch is the supervisor's relaunch counter, so every
    attempt votes in a namespace no dead attempt ever touched."""
    port = _free_port()
    # rank env is INHERITED (Popen env=None): MPI_OPT_TPU_CACHE_DIR
    # reaches every restart/resume attempt of every rank, where
    # cli.wire_compile_cache reads it before backend init — a
    # preemption-resume cycle pays a disk read, not the 140–210 s
    # recompile warmup
    procs = []
    # incremental build + cleanup-on-failure: if Popen dies mid-loop
    # (fork EAGAIN, interpreter gone), the already-spawned ranks would
    # otherwise leak as orphans wedged in jax.distributed bring-up
    # waiting for peers that will never start — and their log handles
    # with them. Kill and close everything spawned so far, then re-raise.
    try:
        for i in range(n):
            argv = [
                sys.executable,
                "-m",
                "mpi_opt_tpu",
                *rest,
                "--coordinator",
                f"127.0.0.1:{port}",
                "--num-processes",
                str(n),
                "--process-id",
                str(i),
            ]
            if heartbeat:
                argv += ["--heartbeat-file", _hb_path(log_dir, i)]
            if coord is not None:
                argv += ["--coord-dir", coord[0], "--coord-epoch", str(coord[1])]
            out = open(os.path.join(log_dir, f"rank{i}.out"), "w")
            err = open(os.path.join(log_dir, f"rank{i}.err"), "w")
            try:
                procs.append(
                    (subprocess.Popen(argv, stdout=out, stderr=err, text=True), out, err)
                )
            except BaseException:
                # this rank's handles are not in procs yet
                out.close()
                err.close()
                raise
    except BaseException:
        for p, out, err in procs:
            if p.poll() is None:
                p.kill()
            p.wait()
            out.close()
            err.close()
        raise
    return procs


def _find_summary_line(text: str):
    """The LAST line of a rank's stdout that has the summary-JSON shape:
    a JSON object that is not a metrics event (``stdout_logger`` also
    prints ``{"event": ...}`` records to stdout). Blindly re-printing
    the last line broke the single-JSON-line contract whenever trailing
    non-summary output followed the summary (a stray library print, a
    late metrics flush); scanning for the shape keeps the relay correct
    regardless of what lands after it. Returns None when no line
    qualifies (the caller then falls back to the raw last line so a
    rank whose output format drifted still surfaces SOMETHING)."""
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and "event" not in obj:
            return line
    return None


def _stop_all(procs, grace: float) -> None:
    """Stop every live rank: SIGTERM first (a draining rank flushes its
    checkpoint/ledger and exits 75 on its own), escalate to SIGKILL only
    after ``grace`` seconds — a rank wedged mid-collective never answers
    the TERM, and waiting on it forever recreates the hang this
    supervisor exists to bound."""
    for p, _, _ in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.monotonic() + max(0.0, grace)
    for p, _, _ in procs:
        while p.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
    for p, _, _ in procs:
        if p.poll() is None:
            p.kill()
    for p, out, err in procs:
        p.wait()
        out.close()
        err.close()


def _watch(procs, poll_s: float, grace: float, detector=None, guard=None):
    """Block until the job resolves; returns one of
    ``("done", None)`` — every rank exited 0;
    ``("exit", i)`` — rank i exited nonzero (survivors are stopped: they
    are mid-collective with a dead peer and will never finish alone);
    ``("stall", ranks)`` — ``detector`` saw those ranks' heartbeats
    frozen past the stall timeout while the processes live;
    ``("shutdown", signame)`` — the supervisor itself was asked to die
    (``guard``), so the ranks are drained and the caller exits 75."""
    try:
        while True:
            if guard is not None and guard.requested:
                return ("shutdown", guard.signal_name)
            running = False
            for i, (p, _, _) in enumerate(procs):
                rc = p.poll()
                if rc is None:
                    running = True
                elif rc != 0:
                    return ("exit", i)
            if not running:
                return ("done", None)
            if detector is not None:
                # liveness filter: a rank that EXITED 0 leaves its last
                # heartbeat frozen forever — that is teardown, not a
                # stall, and must not get healthy survivors killed
                stale = [
                    i for i in detector.poll() if procs[i][0].poll() is None
                ]
                if stale:
                    return ("stall", stale)
            time.sleep(poll_s)
    finally:
        _stop_all(procs, grace)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_opt_tpu.launch",
        description="spawn + supervise an N-process SPMD job with "
        "coordinated restart-on-failure recovery",
    )
    parser.add_argument("--n-proc", type=int, required=True)
    parser.add_argument(
        "--retries",
        type=int,
        default=0,
        help="coordinated full-job restarts after any rank death or "
        "stall (resumes from the last snapshot when the job "
        "checkpoints). Preemptions (rank exit 75) do NOT consume this "
        "budget — see --max-preemptions",
    )
    parser.add_argument("--log-dir", default=None, help="per-rank stdout/stderr")
    parser.add_argument(
        "--poll-interval", type=float, default=0.2, help="rank liveness poll (s)"
    )
    parser.add_argument(
        "--restart-backoff",
        type=float,
        default=1.0,
        metavar="SECONDS",
        help="base delay before a coordinated restart; doubles per "
        "attempt with up to 50%% random jitter (0 disables). Preemption "
        "restarts wait only the (jittered) base — they are not failures "
        "and must not back off exponentially",
    )
    parser.add_argument(
        "--stall-timeout",
        type=float,
        default=None,
        metavar="SECONDS",
        help="hang watchdog: kill + coordinated-restart the job when "
        "any rank's heartbeat stops advancing for this long while the "
        "process lives (wedged collective, dead I/O). Ranks are only "
        "watched from their FIRST beat (first completed batch/launch), "
        "so cold-start compilation is never timed; size the timeout "
        "above the longest legitimate gap between launches. Wires "
        "--heartbeat-file into every rank automatically",
    )
    parser.add_argument(
        "--term-grace",
        type=float,
        default=15.0,
        metavar="SECONDS",
        help="how long stopped ranks get to drain after SIGTERM before "
        "SIGKILL (graceful ranks flush checkpoint+ledger and exit 75 "
        "within this window)",
    )
    parser.add_argument(
        "--max-preemptions",
        type=int,
        default=16,
        metavar="N",
        help="bound on free preemption restarts (rank exit 75): a "
        "deterministically self-preempting program must not restart "
        "forever just because preemptions don't bill --retries",
    )
    parser.add_argument(
        "--crash-loop-threshold",
        type=int,
        default=3,
        metavar="N",
        help="abort after N CONSECUTIVE failure restarts whose attempts "
        "each died within --crash-loop-window seconds (0 disables): a "
        "job failing that fast is a deterministic bug, not platform "
        "weather, and must not grind through a large --retries budget",
    )
    parser.add_argument(
        "--crash-loop-window",
        type=float,
        default=30.0,
        metavar="SECONDS",
        help="an attempt shorter than this counts toward the crash-loop "
        "threshold; attempts that lived longer reset the streak",
    )
    parser.add_argument(
        "rest",
        nargs=argparse.REMAINDER,
        help="-- followed by the mpi_opt_tpu CLI arguments for every rank",
    )
    args = parser.parse_args(argv)
    rest = args.rest
    if rest and rest[0] == "--":
        rest = rest[1:]
    if not rest:
        parser.error("pass the per-rank CLI arguments after '--'")
    if args.n_proc < 1:
        parser.error(f"--n-proc must be >= 1, got {args.n_proc}")
    # bad values are usage errors (rc=2 + message), not ValueError
    # tracebacks from the watchdog constructor deep in the launch loop
    if args.stall_timeout is not None and args.stall_timeout <= 0:
        parser.error(f"--stall-timeout must be > 0, got {args.stall_timeout}")
    if args.max_preemptions < 0:
        parser.error(
            f"--max-preemptions must be >= 0, got {args.max_preemptions}"
        )
    if args.term_grace < 0:
        parser.error(f"--term-grace must be >= 0, got {args.term_grace}")
    if args.crash_loop_threshold < 0:
        parser.error(
            f"--crash-loop-threshold must be >= 0, got {args.crash_loop_threshold}"
        )
    if args.crash_loop_window <= 0:
        parser.error(
            f"--crash-loop-window must be > 0, got {args.crash_loop_window}"
        )
    # argparse accepts both '--flag value' and '--flag=value'; match
    # flags by token prefix so the '=' spelling can't slip through the
    # ownership guard (or, below, defeat the --resume recovery append)
    def _has_flag(tokens, flag):
        return any(t == flag or t.startswith(flag + "=") for t in tokens)

    for banned in (
        "--coordinator",
        "--num-processes",
        "--process-id",
        "--retries",
        "--heartbeat-file",
        "--coord-dir",
        "--coord-epoch",
    ):
        if _has_flag(rest, banned):
            parser.error(
                f"{banned} is owned by the supervisor; don't pass it in "
                "the per-rank arguments"
            )
    log_dir = args.log_dir or tempfile.mkdtemp(prefix="mpi_opt_tpu_launch_")
    os.makedirs(log_dir, exist_ok=True)
    coord_root = None
    if args.n_proc > 1:
        # the boundary-agreement control plane (parallel/coord.py)
        # lives under the supervisor's log dir; wipe it via the coord
        # module's own reset (the agreement surface has one writer) so
        # a reused --log-dir cannot leak a previous JOB's epochs —
        # between this job's own attempts the advancing --coord-epoch
        # is the isolation, no wipe needed while ranks may be reading
        coord_root = os.path.join(log_dir, "coord")
        from mpi_opt_tpu.parallel.coord import reset_dir

        reset_dir(coord_root)

    # --resume on restart is valid whenever the job has durable state to
    # continue from: orbax snapshots (--checkpoint-dir) or the trial
    # journal (--ledger); --resume on empty state starts fresh, which is
    # also correct
    has_resumable = _has_flag(rest, "--checkpoint-dir") or _has_flag(rest, "--ledger")
    watch_stalls = args.stall_timeout is not None
    backoff_rng = random.Random(os.getpid())
    attempt = 0  # failure restarts consumed (vs --retries)
    preemptions = 0  # free restarts consumed (vs --max-preemptions)
    stalls = 0
    relaunches = 0
    fast_fails = 0  # consecutive failures quicker than --crash-loop-window

    def _event(name, **fields):
        print(json.dumps({"event": name, **fields}), flush=True)

    def _crash_looping(attempt_wall: float) -> bool:
        """Account one failure outcome; True when the consecutive
        fast-failure streak hits the breaker threshold."""
        nonlocal fast_fails
        if attempt_wall < args.crash_loop_window:
            fast_fails += 1
        else:
            fast_fails = 0
        return 0 < args.crash_loop_threshold <= fast_fails

    def _crash_loop_abort(detail: str, **event_fields) -> int:
        """The breaker's one abort surface (shared by the stall and
        rank-exit paths): failed event + diagnostics, rc 1."""
        _event(
            "failed",
            crash_loop=True,
            consecutive_fast_failures=fast_fails,
            window_s=args.crash_loop_window,
            **event_fields,
        )
        sys.stderr.write(
            f"crash loop: {fast_fails} consecutive failures, each within "
            f"{args.crash_loop_window}s of launch ({detail}); aborting "
            "instead of burning the restart budget.\n"
        )
        return 1

    with ShutdownGuard() as guard:
        while True:
            if guard.requested:
                # preempted between attempts (e.g. during backoff sleep)
                _event("preempted", signal=guard.signal_name)
                return EX_TEMPFAIL
            rank_args = list(rest)
            if relaunches > 0 and has_resumable and "--resume" not in rank_args:
                # the restarted job continues from the last shared
                # snapshot / journal
                rank_args.append("--resume")
            _event(
                "launch",
                attempt=attempt,
                n_proc=args.n_proc,
                log_dir=log_dir,
                resume="--resume" in rank_args,
            )
            detector = None
            if watch_stalls:
                # fresh detector AND fresh heartbeat files per attempt: a
                # stale file from the previous attempt would put the new
                # rank under watch while it is still compiling
                for i in range(args.n_proc):
                    try:
                        os.unlink(_hb_path(log_dir, i))
                    except FileNotFoundError:
                        pass
                detector = StallDetector(
                    [_hb_path(log_dir, i) for i in range(args.n_proc)],
                    args.stall_timeout,
                )
            t_attempt = time.monotonic()
            procs = _spawn_ranks(
                args.n_proc,
                rank_args,
                log_dir,
                heartbeat=watch_stalls,
                coord=None if coord_root is None else (coord_root, relaunches),
            )
            kind, info = _watch(
                procs, args.poll_interval, args.term_grace, detector, guard
            )
            attempt_wall = time.monotonic() - t_attempt
            if kind == "done":
                # success: re-surface rank 0's summary line as our own
                # (scan for the summary-JSON shape — trailing
                # non-summary output must not break the relay)
                with open(os.path.join(log_dir, "rank0.out")) as f:
                    text = f.read()
                line = _find_summary_line(text)
                if line is None:
                    lines = [l for l in text.splitlines() if l.strip()]
                    line = lines[-1] if lines else None
                if line is not None:
                    print(line, flush=True)
                _event(
                    "done",
                    attempts=attempt + 1,
                    preemptions=preemptions,
                    stalls_detected=stalls,
                )
                return 0
            if kind == "shutdown":
                # the supervisor itself was preempted: ranks were
                # TERM-drained by _watch's finally; exit 75 so an OUTER
                # supervisor (or the platform) treats this whole job as
                # gracefully preempted too
                _event("preempted", signal=info, preemptions=preemptions)
                return EX_TEMPFAIL
            if kind == "stall":
                stalls += 1
                # phase-tagged stall diagnostics: what each wedged rank
                # was DOING when its beats froze ("stalled during
                # stage_in"), from the last beat's active-span phase
                phases = _stall_phases(log_dir, info)
                phase_note = ", ".join(
                    f"rank {r} during {p}" for r, p in phases.items() if p
                )
                _event(
                    "stall",
                    ranks=info,
                    phases=phases,
                    stall_timeout=args.stall_timeout,
                    stalls_detected=stalls,
                )
                if attempt >= args.retries:
                    _event(
                        "failed",
                        stalled_ranks=info,
                        phases=phases,
                        attempts=attempt + 1,
                        stalls_detected=stalls,
                    )
                    sys.stderr.write(
                        f"ranks {info} stalled (no heartbeat progress in "
                        f"{args.stall_timeout}s"
                        + (f"; {phase_note}" if phase_note else "")
                        + "); retries exhausted.\n"
                    )
                    return 1
                if _crash_looping(attempt_wall):
                    return _crash_loop_abort(
                        f"last: ranks {info} stalled", stalled_ranks=info
                    )
                attempt += 1
                delay = _backoff_s(attempt, args.restart_backoff, 0.5, backoff_rng)
                relaunches += 1
                _event(
                    "stall_restart",
                    ranks=info,
                    phases=phases,
                    attempt=attempt,
                    of=args.retries,
                    backoff_s=round(delay, 3),
                )
                if delay > 0:
                    time.sleep(delay)
                continue
            # kind == "exit": rank `info` left with a nonzero code
            failed = info
            rc = procs[failed][0].returncode
            with open(os.path.join(log_dir, f"rank{failed}.err")) as f:
                tail = f.read()[-2000:]
            # every rank's LAST heartbeat phase (the files survive
            # _stop_all): the failed rank's phase says WHERE it died;
            # survivors frozen in a collective-holding phase are the
            # wedge signature classified below. Empty without
            # --stall-timeout (no heartbeats wired).
            phases = (
                _stall_phases(log_dir, range(args.n_proc)) if watch_stalls else {}
            )
            failed_phase = phases.get(str(failed))
            at_note = f" during {failed_phase}" if failed_phase else ""
            wedged = [
                i
                for i in range(args.n_proc)
                if i != failed and _is_collective_phase(phases.get(str(i)))
            ]
            if wedged and rc not in (EX_TEMPFAIL, EX_DATAERR, EX_USAGE):
                # collective wedge: the dead rank left its survivors
                # frozen mid-collective (they were TERM-drained, then
                # killed after --term-grace, by _watch's _stop_all).
                # The generic restart below IS the coordinated
                # recovery — this event names the shape so operators
                # (and the SPMD drill) see the classification, not
                # just a bare rank death
                _event(
                    "rank_wedge",
                    rank=failed,
                    returncode=rc,
                    survivors=wedged,
                    phases=phases,
                )
            if rc == EX_TEMPFAIL:
                # the graceful-shutdown protocol: the rank drained and
                # flushed before exiting. A coordinated resume costs the
                # platform nothing it hadn't already decided to spend —
                # so it does NOT consume the failure --retries budget.
                fast_fails = 0  # a drain is progress, not a crash loop
                preemptions += 1
                if preemptions > args.max_preemptions:
                    _event(
                        "failed",
                        rank=failed,
                        returncode=rc,
                        preemptions=preemptions,
                        preemption_budget_exhausted=True,
                    )
                    sys.stderr.write(
                        f"rank {failed} exited 75 (preempted) "
                        f"{preemptions} times, over --max-preemptions "
                        f"{args.max_preemptions}; a program that preempts "
                        "itself deterministically is a bug, not a "
                        f"platform event. Stderr:\n{tail}\n"
                    )
                    return 1
                # flat (jittered) base backoff: this is not a failure
                # and must not walk up the exponential schedule
                delay = _backoff_s(1, args.restart_backoff, 0.5, backoff_rng)
                relaunches += 1
                _event(
                    "preempt_restart",
                    rank=failed,
                    preemptions=preemptions,
                    of=args.max_preemptions,
                    backoff_s=round(delay, 3),
                )
                if delay > 0:
                    time.sleep(delay)
                continue
            if rc == EX_DATAERR:
                # snapshot-corruption dead end (utils/integrity.py): the
                # rank's resume found steps but every one failed
                # verification and was quarantined. A restart's --resume
                # re-reads the same poisoned directory — the exact
                # restart storm this supervisor must NOT fund. Abort
                # with diagnostics, budget untouched.
                _event(
                    "failed",
                    rank=failed,
                    returncode=rc,
                    attempts=attempt + 1,
                    data_error=True,
                )
                sys.stderr.write(
                    f"rank {failed} exited {EX_DATAERR} (EX_DATAERR): no "
                    "verified snapshot remains in its checkpoint "
                    "directory; not retrying a data error — run "
                    "`mpi_opt_tpu fsck` on the checkpoint dir, then "
                    "restart without --resume or point at fresh state. "
                    f"Stderr:\n{tail}\n"
                )
                return 1
            if rc == EX_IOERR:
                # resource exhaustion, classified (utils/resources.py):
                # device OOM with no wave left to halve, or a disk
                # still full after the retention-prune retry. The
                # state is intact — but a restart changes NOTHING
                # until an operator frees the resource, so retrying
                # burns the whole budget re-failing identically.
                # Abort with diagnostics, budget untouched.
                _event(
                    "failed",
                    rank=failed,
                    returncode=rc,
                    attempts=attempt + 1,
                    resource_exhausted=True,
                )
                sys.stderr.write(
                    f"rank {failed} exited {EX_IOERR} (EX_IOERR): device "
                    "or storage exhaustion — not retrying a resource "
                    "error. Free the resource (disk space; or reduce "
                    "residency via --wave-size auto / --population), "
                    "then relaunch with --resume to continue from the "
                    f"intact durable state. Stderr:\n{tail}\n"
                )
                return 1
            if rc == EX_USAGE:
                # argparse usage error: deterministic, and retrying would be
                # actively wrong — e.g. the CLI's stale-checkpoint-dir
                # refusal (exit 2) would be "recovered" by the retry's
                # --resume into silently replaying the old sweep, the exact
                # accident that refusal exists to stop. Surface it instead.
                _event(
                    "failed",
                    rank=failed,
                    returncode=rc,
                    attempts=attempt + 1,
                    usage_error=True,
                )
                sys.stderr.write(
                    f"rank {failed} rejected its arguments (rc=2); not "
                    f"retrying a usage error. Stderr:\n{tail}\n"
                )
                return 1
            if attempt >= args.retries:
                _event(
                    "failed",
                    rank=failed,
                    returncode=rc,
                    phase=failed_phase,
                    attempts=attempt + 1,
                    preemptions=preemptions,
                    stalls_detected=stalls,
                )
                sys.stderr.write(
                    f"rank {failed} died (rc={rc}){at_note}; retries "
                    f"exhausted. Last stderr:\n{tail}\n"
                )
                return 1
            if _crash_looping(attempt_wall):
                sys.stderr.write(f"last rank stderr:\n{tail}\n")
                return _crash_loop_abort(
                    f"last: rank {failed} rc={rc}{at_note}",
                    rank=failed,
                    returncode=rc,
                    phase=failed_phase,
                )
            attempt += 1
            delay = _backoff_s(attempt, args.restart_backoff, 0.5, backoff_rng)
            relaunches += 1
            _event(
                "restart",
                rank=failed,
                returncode=rc,
                phase=failed_phase,
                wedge=bool(wedged),
                attempt=attempt,
                of=args.retries,
                backoff_s=round(delay, 3),
            )
            if delay > 0:
                time.sleep(delay)


if __name__ == "__main__":
    sys.exit(main())
