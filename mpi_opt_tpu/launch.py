"""Coordinated multi-process launch + recovery supervisor.

SURVEY.md §5 (failure detection / elastic recovery) for the one
topology where per-process ``--retries`` is unsound: multi-process
SPMD. One rank restoring a snapshot while its peers sit in a collective
issues mismatched programs and hangs the job — so recovery there must
be a COORDINATED job restart. This module is that coordination, and the
``mpirun``-equivalent front door (the reference's launcher role):

    python -m mpi_opt_tpu.launch --n-proc 4 --retries 2 -- \
        --workload cifar100_resnet18 --algorithm pbt --fused \
        --checkpoint-dir /ckpt/sweep --population 1024 ...

It spawns ``--n-proc`` ranks of ``python -m mpi_opt_tpu`` (appending
``--coordinator/--num-processes/--process-id`` for each), watches them,
and on ANY rank death kills the survivors and relaunches ALL ranks —
with ``--resume`` appended when the job has a ``--checkpoint-dir``, so
the restarted job continues from the last shared snapshot and (because
fused-sweep resume is bit-identical, tested) finishes with the result
the unkilled job would have produced. Without a checkpoint dir a
restart re-runs the (deterministic) sweep from scratch.

Transient-vs-program classification is deliberately NOT attempted here:
a supervisor sees exit codes, not exception types. A program bug burns
its retries quickly (each relaunch fails in seconds at the same point)
and surfaces the rank's stderr; a platform death resumes and completes.

Per-rank stdout/stderr go to ``--log-dir`` (default: a temp dir,
printed) as ``rank{i}.out``/``rank{i}.err``, truncated per attempt;
rank 0's final summary line is re-printed on the supervisor's stdout so
scripted callers keep the single-JSON-line contract.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import socket
import subprocess
import sys
import tempfile
import time


def _backoff_s(attempt: int, base: float, jitter: float, rng: random.Random) -> float:
    """Seconds to wait before coordinated restart ``attempt`` (1-based):
    jittered exponential, ``base * 2**(attempt-1)`` scaled by up to
    ``jitter`` extra. An immediate relaunch hammers a flapping platform
    (a TPU worker mid-restart rejects the reconnect, burning a retry for
    nothing), and the jitter keeps N supervisors that died together from
    reconnecting in lockstep."""
    if base <= 0:
        return 0.0
    return base * (2 ** (attempt - 1)) * (1.0 + jitter * rng.random())


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_ranks(n: int, rest: list[str], log_dir: str):
    """One attempt's rank processes; a fresh coordinator port each time
    (the previous attempt's port may linger in TIME_WAIT)."""
    port = _free_port()
    procs = []
    for i in range(n):
        argv = [
            sys.executable,
            "-m",
            "mpi_opt_tpu",
            *rest,
            "--coordinator",
            f"127.0.0.1:{port}",
            "--num-processes",
            str(n),
            "--process-id",
            str(i),
        ]
        out = open(os.path.join(log_dir, f"rank{i}.out"), "w")
        err = open(os.path.join(log_dir, f"rank{i}.err"), "w")
        procs.append(
            (subprocess.Popen(argv, stdout=out, stderr=err, text=True), out, err)
        )
    return procs


def _kill_all(procs) -> None:
    for p, out, err in procs:
        if p.poll() is None:
            p.kill()
    for p, out, err in procs:
        p.wait()
        out.close()
        err.close()


def _watch(procs, poll_s: float):
    """Block until every rank exits 0 (returns None) or any rank fails
    (returns its index; survivors are killed — they are mid-collective
    with a dead peer and will never finish on their own)."""
    try:
        while True:
            running = False
            for i, (p, _, _) in enumerate(procs):
                rc = p.poll()
                if rc is None:
                    running = True
                elif rc != 0:
                    return i
            if not running:
                return None
            time.sleep(poll_s)
    finally:
        _kill_all(procs)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_opt_tpu.launch",
        description="spawn + supervise an N-process SPMD job with "
        "coordinated restart-on-failure recovery",
    )
    parser.add_argument("--n-proc", type=int, required=True)
    parser.add_argument(
        "--retries",
        type=int,
        default=0,
        help="coordinated full-job restarts after any rank death "
        "(resumes from the last snapshot when the job checkpoints)",
    )
    parser.add_argument("--log-dir", default=None, help="per-rank stdout/stderr")
    parser.add_argument(
        "--poll-interval", type=float, default=0.2, help="rank liveness poll (s)"
    )
    parser.add_argument(
        "--restart-backoff",
        type=float,
        default=1.0,
        metavar="SECONDS",
        help="base delay before a coordinated restart; doubles per "
        "attempt with up to 50%% random jitter (0 disables)",
    )
    parser.add_argument(
        "rest",
        nargs=argparse.REMAINDER,
        help="-- followed by the mpi_opt_tpu CLI arguments for every rank",
    )
    args = parser.parse_args(argv)
    rest = args.rest
    if rest and rest[0] == "--":
        rest = rest[1:]
    if not rest:
        parser.error("pass the per-rank CLI arguments after '--'")
    if args.n_proc < 1:
        parser.error(f"--n-proc must be >= 1, got {args.n_proc}")
    # argparse accepts both '--flag value' and '--flag=value'; match
    # flags by token prefix so the '=' spelling can't slip through the
    # ownership guard (or, below, defeat the --resume recovery append)
    def _has_flag(tokens, flag):
        return any(t == flag or t.startswith(flag + "=") for t in tokens)

    for banned in ("--coordinator", "--num-processes", "--process-id", "--retries"):
        if _has_flag(rest, banned):
            parser.error(
                f"{banned} is owned by the supervisor; don't pass it in "
                "the per-rank arguments"
            )
    log_dir = args.log_dir or tempfile.mkdtemp(prefix="mpi_opt_tpu_launch_")
    os.makedirs(log_dir, exist_ok=True)

    has_ckpt = _has_flag(rest, "--checkpoint-dir")
    backoff_rng = random.Random(os.getpid())
    attempt = 0
    while True:
        rank_args = list(rest)
        if attempt > 0 and has_ckpt and "--resume" not in rank_args:
            # the restarted job continues from the last shared snapshot;
            # --resume on an empty dir (crash before the first save)
            # starts fresh, which is also correct
            rank_args.append("--resume")
        print(
            json.dumps(
                {
                    "event": "launch",
                    "attempt": attempt,
                    "n_proc": args.n_proc,
                    "log_dir": log_dir,
                    "resume": "--resume" in rank_args,
                }
            ),
            flush=True,
        )
        procs = _spawn_ranks(args.n_proc, rank_args, log_dir)
        failed = _watch(procs, args.poll_interval)
        if failed is None:
            # success: re-surface rank 0's summary line as our own
            with open(os.path.join(log_dir, "rank0.out")) as f:
                lines = [l for l in f.read().splitlines() if l.strip()]
            if lines:
                print(lines[-1], flush=True)
            print(
                json.dumps({"event": "done", "attempts": attempt + 1}), flush=True
            )
            return 0
        rc = procs[failed][0].returncode
        with open(os.path.join(log_dir, f"rank{failed}.err")) as f:
            tail = f.read()[-2000:]
        if rc == 2:
            # argparse usage error: deterministic, and retrying would be
            # actively wrong — e.g. the CLI's stale-checkpoint-dir
            # refusal (exit 2) would be "recovered" by the retry's
            # --resume into silently replaying the old sweep, the exact
            # accident that refusal exists to stop. Surface it instead.
            print(
                json.dumps(
                    {"event": "failed", "rank": failed, "returncode": rc,
                     "attempts": attempt + 1, "usage_error": True}
                ),
                flush=True,
            )
            sys.stderr.write(
                f"rank {failed} rejected its arguments (rc=2); not "
                f"retrying a usage error. Stderr:\n{tail}\n"
            )
            return 1
        if attempt >= args.retries:
            print(
                json.dumps(
                    {
                        "event": "failed",
                        "rank": failed,
                        "returncode": rc,
                        "attempts": attempt + 1,
                    }
                ),
                flush=True,
            )
            sys.stderr.write(
                f"rank {failed} died (rc={rc}); retries exhausted. "
                f"Last stderr:\n{tail}\n"
            )
            return 1
        attempt += 1
        delay = _backoff_s(attempt, args.restart_backoff, 0.5, backoff_rng)
        print(
            json.dumps(
                {
                    "event": "restart",
                    "rank": failed,
                    "returncode": rc,
                    "attempt": attempt,
                    "of": args.retries,
                    "backoff_s": round(delay, 3),
                }
            ),
            flush=True,
        )
        if delay > 0:
            time.sleep(delay)


if __name__ == "__main__":
    sys.exit(main())
