"""``mpi_opt_tpu corpus index|resolve`` (dispatched from cli.main).

``index DIR`` builds/refreshes the persistent corpus index (atomic
write, incremental over unchanged ledgers) and renders a one-line-per-
entry summary; ``resolve DIR --workload W`` is the dry run of
``--warm-start auto:DIR`` — it prints exactly which sources a sweep
over that workload's default space would ingest (exact vs fuzzy, with
per-record loss counters) WITHOUT running anything, so an operator can
audit the auto-resolution before trusting a long sweep to it.
``index`` never touches jax; ``resolve`` builds the workload's space.
"""

from __future__ import annotations

import argparse
import json
import os


def corpus_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="mpi_opt_tpu corpus",
        description="the cross-sweep ledger-corpus knowledge layer "
        "(see README: Cross-sweep knowledge corpus)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    ip = sub.add_parser("index", help="build/refresh DIR's corpus index")
    ip.add_argument("dir", metavar="DIR", help="corpus root (ledgers underneath)")
    ip.add_argument("--json", action="store_true", help="machine-readable output")
    rp = sub.add_parser(
        "resolve", help="dry-run what --warm-start auto:DIR would ingest"
    )
    rp.add_argument("dir", metavar="DIR", help="corpus root")
    rp.add_argument(
        "--workload", required=True, help="the sweep's workload (space source)"
    )
    rp.add_argument("--json", action="store_true", help="machine-readable output")
    args = p.parse_args(argv)

    if not os.path.isdir(args.dir):
        p.error(f"{args.dir!r} is not a directory")

    if args.cmd == "index":
        from mpi_opt_tpu.corpus.index import index_corpus, index_path

        doc = index_corpus(args.dir)
        if args.json:
            print(json.dumps(doc))
            return 0
        entries = doc["entries"]
        errored = [e for e in entries if e.get("error")]
        print(
            f"corpus {args.dir}: {len(entries)} ledger(s) indexed -> "
            f"{index_path(args.dir)}"
        )
        for e in entries:
            if e.get("error"):
                print(f"  {e['path']}: UNREADABLE ({e['error']})")
                continue
            best = e.get("best_score")
            print(
                f"  {e['path']}: {e.get('workload')}/{e.get('algorithm')} "
                f"space={str(e.get('space_hash'))[:8]} ok={e.get('ok')}"
                f"/{e.get('records')}"
                + (f" best={best:.6f}" if best is not None else " best=none")
            )
        # unreadable entries are recorded, not fatal: resolution skips
        # them with events — but the INDEXING operator should see red
        return 1 if errored else 0

    # resolve: the auto warm-start dry run
    from mpi_opt_tpu.corpus.resolve import resolve
    from mpi_opt_tpu.workloads import available, get_workload

    if args.workload not in available():
        p.error(f"--workload must be one of {available()}, got {args.workload!r}")
    space = get_workload(args.workload).default_space()
    res = resolve(space, args.dir, workload=args.workload)
    out = {
        "corpus": args.dir,
        "workload": args.workload,
        "space_hash": space.space_hash(),
        "observations": len(res.observations),
        "sources": res.sources,
        "skips": res.skips,
        "skipped_entries": res.skipped,
    }
    if args.json:
        print(json.dumps(out))
        return 0
    print(
        f"corpus {args.dir} -> {args.workload} "
        f"(space {space.space_hash()[:8]}): "
        f"{len(res.observations)} observation(s) from {len(res.sources)} source(s)"
    )
    for s in res.sources:
        print(f"  [{s['match']}] {s['path']}: {s['records']} record(s)")
    if res.skips:
        print(f"  record skips: {res.skips}")
    for sk in res.skipped:
        print(f"  skipped entry: {sk['path']} ({sk['reason']})")
    return 0
