"""The suggestion service: one resident process answering suggest →
report → lookup traffic over a filesystem spool, backed by the batched
TPE acquisition kernel (``ops/tpe.py:tpe_suggest``) warm-started from
the ledger corpus.

Why this exists (ISSUE 14 / ROADMAP "cross-sweep knowledge"): the
acquisition kernel scores thousands of candidates per jitted call
(BENCH config 4: ~2176 suggestions/s), which is orders of magnitude
more suggestion throughput than any single sweep consumes — so one
chip can serve suggestion traffic for MANY external sweeps that bring
their own evaluation capacity. The transport is the same
no-network-needed shape as the sweep service's spool: clients
atomic-write request files, the server atomic-writes responses::

    SDIR/requests/<req>.json    # {"id", "op", ...} (client-owned)
    SDIR/responses/<id>.json    # the answer (server-owned)
    SDIR/control/stop           # flag: finish the queue and exit 0

Ops: ``suggest`` (n unit-cube points + typed params, acquisition-
ranked), ``report`` (a completed external evaluation: enters the
observation ring, the corpus cache, and — when the server journals —
the server's own ledger, so the knowledge COMPOUNDS: a suggestion
tenant's ledger is itself corpus material for the next index), and
``lookup`` (the CorpusCache view: exact hit, near-match ``fidelity:
"prior"`` evidence, or miss).

Tenant integration: ``run_suggest_tenant`` is the flat-CLI entry
(``--suggest-serve DIR``) and is submittable to the sweep service
unchanged — every served request beats the heartbeat and ticks the
cooperative slice hook, so the scheduler time-slices a suggestion
tenant exactly like a sweep (drain parks it with exit 75; its ledger +
``--resume`` rebuild the ring on the next slice); the stop flag or an
idle timeout completes it (exit 0).
"""

from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

from mpi_opt_tpu.service.spool import _read_json, _write_json_atomic

#: response written for a request the server cannot parse — the client
#: gets an answer (not a timeout) and the queue never wedges on garbage
_MALFORMED = {"error": "malformed request (need JSON with id/op)"}


def spool_paths(sdir: str) -> dict:
    return {
        "requests": os.path.join(sdir, "requests"),
        "responses": os.path.join(sdir, "responses"),
        "control": os.path.join(sdir, "control"),
    }


def ensure_spool(sdir: str) -> dict:
    paths = spool_paths(sdir)
    for p in paths.values():
        os.makedirs(p, exist_ok=True)
    return paths


def stop_path(sdir: str) -> str:
    return os.path.join(sdir, "control", "stop")


#: responses a client never consumed (it timed out, or died after
#: writing its request) are expired after this age; swept on idle ticks
_RESPONSE_TTL_S = 600.0
_RESPONSE_GC_EVERY_S = 60.0


def _sweep_responses(resp_dir: str, ttl_s: float = _RESPONSE_TTL_S) -> None:
    """Best-effort expiry of abandoned response files — clients unlink
    the answers they consume, so anything older than the TTL has no
    reader left and is only inode debris."""
    now = time.time()
    try:
        names = os.listdir(resp_dir)
    except OSError:
        return
    for name in names:
        path = os.path.join(resp_dir, name)
        try:
            if now - os.path.getmtime(path) > ttl_s:
                os.unlink(path)
        except OSError:
            pass  # consumed/replaced mid-sweep: exactly the goal


class SuggestServer:
    """The acquisition state: a fixed-shape observation ring (the TPE
    algorithm's layout — one jit for the server's lifetime) plus the
    corpus-backed near-match cache. Transport-free: ``handle`` answers
    one request dict; the serve loop owns the filesystem."""

    def __init__(
        self,
        space,
        seed: int = 0,
        buffer_size: int = 512,
        n_startup: int = 10,
        config=None,
    ):
        import jax

        from mpi_opt_tpu.ledger.cache import CorpusCache
        from mpi_opt_tpu.ops.tpe import TPEConfig, tpe_suggest

        self.space = space
        self.seed = seed
        self.n_startup = n_startup
        self.config = config or TPEConfig()
        self.buffer_size = buffer_size
        self._obs_unit = np.zeros((buffer_size, space.dim), dtype=np.float32)
        self._obs_score = np.zeros(buffer_size, dtype=np.float32)
        self._valid = np.zeros(buffer_size, dtype=bool)
        self._n_obs = 0
        self._suggested = 0  # fold-in counter: every batch draws fresh keys
        self._next_id = 0  # journaled report serial
        self.cache = CorpusCache(space)
        self._suggest_fn = jax.jit(tpe_suggest, static_argnames=("n_suggest", "cfg"))

    # -- state feeds -------------------------------------------------

    def _push(self, unit: np.ndarray, score: float) -> None:
        slot = self._n_obs % self.buffer_size
        self._obs_unit[slot] = np.asarray(unit, dtype=np.float32)
        self._obs_score[slot] = score
        self._valid[slot] = True
        self._n_obs += 1

    def ingest(self, observations) -> int:
        """Corpus warm start: ascending score order so a prior that
        overflows the ring evicts its own worst rows first (the TPE
        algorithm's rule)."""
        finite = [o for o in observations if np.isfinite(o.score)]
        finite.sort(key=lambda o: o.score)
        for o in finite:
            self._push(o.unit, float(o.score))
        return len(finite)

    def seed_from_ledger(self, records) -> int:
        """Resume: rebuild the ring and the exact cache from the
        server's OWN journaled reports (every report below journals one
        trial record), and continue the report serial past them."""
        from mpi_opt_tpu.ledger.warmstart import observations_from_records

        obs, _skips = observations_from_records(records, self.space)
        n = self.ingest(obs)
        self.cache.seed_from(records)
        self.cache.seed_prior(records)
        if records:
            self._next_id = 1 + max(int(r["trial_id"]) for r in records)
        return n

    # -- ops ---------------------------------------------------------

    def suggest(self, n: int) -> dict:
        import jax

        from mpi_opt_tpu.utils.hostdev import host_ops

        n = max(1, min(int(n), self.config.n_candidates))
        with host_ops():  # tiny acquisition: never pay a tunnel round trip
            key = jax.random.fold_in(jax.random.key(self.seed), self._suggested)
            if self._n_obs < self.n_startup:
                unit = np.asarray(self.space.sample_unit(key, n))
            else:
                # power-of-two block rounding: varying client batch
                # sizes hit at most log2(n_candidates) jit variants
                block = 1 << (n - 1).bit_length()
                sugg, _ = self._suggest_fn(
                    key,
                    self._obs_unit,
                    self._obs_score,
                    self._valid,
                    n_suggest=min(block, self.config.n_candidates),
                    cfg=self.config,
                )
                unit = np.asarray(sugg[:n])
        self._suggested += n
        return {
            "units": [[float(v) for v in row] for row in unit],
            "params": [
                self.space.canonical_params(self.space.materialize_row(row))
                for row in unit
            ],
            "n_obs": self._n_obs,
        }

    def report(self, req: dict, ledger=None, meta=None) -> dict:
        """One external evaluation enters the knowledge state (ring +
        cache + optional journal). ``params`` (canonical dict) or
        ``unit`` (row list) identifies the point; non-finite scores
        journal as failed and never touch the ring. ``meta`` rides the
        journal record verbatim (the HTTP front door stamps its
        idempotency key here so a restarted server can rebuild its
        dedup index from the journal)."""
        from mpi_opt_tpu.ledger.warmstart import _decode_params
        from mpi_opt_tpu.trial import TrialResult, failed_result

        score = float(req.get("score", float("nan")))
        budget = int(req.get("budget") or 0)
        if req.get("unit") is not None:
            unit = np.asarray(req["unit"], dtype=np.float32)
            params = self.space.materialize_row(unit)
        elif req.get("params") is not None:
            params = _decode_params(self.space, dict(req["params"]))
            unit = self.space.params_to_unit(params)
        else:
            return {"error": "report needs params or unit"}
        tid = self._next_id
        self._next_id += 1
        if np.isfinite(score):
            result = TrialResult(
                trial_id=tid, score=score, step=budget, wall_time=0.0
            )
            self._push(unit, score)
        else:
            result = failed_result(
                trial_id=tid, step=budget, error="non-finite reported score"
            )
        self.cache.put(params, result)
        if ledger is not None:
            # fsync-durable BEFORE the ack, the same ordering rule as
            # the driver's journal-before-report: a client that saw the
            # ack must find its evidence in the ledger after any crash
            ledger.record_trial(
                result, self.space.canonical_params(params), meta=meta
            )
        return {"ok": result.ok, "trial_id": tid, "n_obs": self._n_obs}

    def lookup(self, req: dict) -> dict:
        """The CorpusCache view: exact → prior → miss, never a result
        substitute (the prior answer says so via ``fidelity``)."""
        from mpi_opt_tpu.ledger.warmstart import _decode_params

        params = _decode_params(self.space, dict(req.get("params") or {}))
        budget = int(req.get("budget") or 0)
        exact = self.cache.get(params, budget, trial_id=-1)
        if exact is not None:
            return {
                "hit": "exact",
                "score": exact.score,
                "step": exact.step,
            }
        prior = self.cache.get_prior(params, trial_id=-1)
        if prior is not None:
            return {
                "hit": "prior",
                "score": prior.score,
                "step": prior.step,
                "fidelity": prior.extra["fidelity"],
                "prior_kind": prior.extra["prior_kind"],
            }
        return {"hit": None}

    def handle(self, req: dict, ledger=None, meta=None) -> dict:
        op = req.get("op")
        try:
            if op == "suggest":
                return self.suggest(int(req.get("n") or 1))
            if op == "report":
                return self.report(req, ledger=ledger, meta=meta)
            if op == "lookup":
                return self.lookup(req)
        except (KeyError, TypeError, ValueError) as e:
            # a bad point/params shape is the CLIENT's error: answer it
            # (the sweep service's tenant_reject moral — one malformed
            # request must not take down the server every other client
            # is riding on), never crash the resident process
            return {"error": f"{type(e).__name__}: {e}"}
        except Exception as e:
            from mpi_opt_tpu.ledger.store import LedgerError

            if isinstance(e, LedgerError):
                return {"error": str(e)}
            raise
        return {"error": f"unknown op {op!r}"}


def serve_loop(
    server: SuggestServer,
    sdir: str,
    metrics,
    ledger=None,
    # 10 ms: the idle poll IS the serving latency floor for a serial
    # client (it writes its next request only after reading the last
    # response, so the server is asleep when every request lands) — at
    # 0.05 the p50 round trip measured 53 ms of which 50 was this nap
    poll_seconds: float = 0.01,
    idle_timeout: Optional[float] = None,
    max_requests: Optional[int] = None,
) -> dict:
    """Answer requests until stop/idle/drain. Returns the summary dict;
    raises SweepInterrupted on a drain request (the caller maps it to
    the EX_TEMPFAIL park, exactly like a sweep)."""
    from mpi_opt_tpu.health import heartbeat, shutdown
    from mpi_opt_tpu.health.shutdown import SweepInterrupted

    paths = ensure_spool(sdir)
    served = suggestions = reports = 0
    last_activity = time.monotonic()
    next_gc = time.monotonic() + _RESPONSE_GC_EVERY_S
    stopped = stop_seen = False
    while True:
        if not stop_seen and os.path.exists(stop_path(sdir)):
            # latch AND consume: the flag means "finish what is queued,
            # then exit 0" — the queue drains below before we break, and
            # unlinking keeps a stale flag from instantly stopping the
            # NEXT server (a --resume'd tenant) on this spool
            stop_seen = True
            try:
                os.unlink(stop_path(sdir))
            except OSError:
                pass
        try:
            pending = sorted(
                f for f in os.listdir(paths["requests"]) if f.endswith(".json")
            )
        except OSError:
            pending = []  # transient listing failure: next poll retries
        if not pending:
            if stop_seen:
                stopped = True
                break
            if shutdown.requested():
                raise SweepInterrupted(shutdown.active_signal(), at=f"request {served}")
            if max_requests is not None and served >= max_requests:
                stopped = True
                break
            if (
                idle_timeout is not None
                and time.monotonic() - last_activity >= idle_timeout
            ):
                stopped = True
                break
            # idle housekeeping: expire abandoned responses (a client
            # that timed out or died never consumes its answer, and a
            # resident server must not grow responses/ without bound)
            if time.monotonic() >= next_gc:
                _sweep_responses(paths["responses"])
                next_gc = time.monotonic() + _RESPONSE_GC_EVERY_S
            time.sleep(poll_seconds)
            continue
        for fname in pending:
            rpath = os.path.join(paths["requests"], fname)
            req = _read_json(rpath)
            if req is None or not req.get("id"):
                # torn client write or garbage: answer under the file's
                # stem so the writer still gets a response, then clear
                rid = fname[: -len(".json")]
                ans = dict(_MALFORMED, id=rid)
            else:
                rid = str(req["id"])
                ans = dict(server.handle(req, ledger=ledger), id=rid)
            # respond-then-unlink: a crash between the two re-serves the
            # request on restart — the response rewrite is atomic and
            # the client takes whichever answer it reads first
            _write_json_atomic(os.path.join(paths["responses"], f"{rid}.json"), ans)
            try:
                os.unlink(rpath)
            except OSError:
                pass
            served += 1
            last_activity = time.monotonic()
            op = (req or {}).get("op")
            if op == "suggest":
                suggestions += len(ans.get("params") or [])
            elif op == "report":
                reports += 1
            metrics.log(
                "suggest_request",
                op=op,
                served=served,
                n_obs=server._n_obs,
                error=ans.get("error"),
            )
            # the tenant's liveness pulse + cooperative slice point:
            # every served request is a natural boundary, so the sweep
            # service can time-slice a suggestion tenant like a sweep
            heartbeat.beat(stage="suggest", served=served, reports=reports)
            shutdown.poll_slice(f"request {served}")
            if shutdown.requested():
                raise SweepInterrupted(
                    shutdown.active_signal(), at=f"request {served}"
                )
            if max_requests is not None and served >= max_requests:
                stopped = True
                break
        if stopped:
            break
    summary = {
        "served": served,
        "suggestions": suggestions,
        "reports": reports,
        "n_obs": server._n_obs,
        "stopped": stopped,
    }
    metrics.log("suggest_stop", **summary)
    return summary
