"""Structural space fingerprints + fuzzy compatibility scoring.

The space HASH answers "is this the exact same search space" — the
right gate for replay and exact warm start, and the wrong one for a
corpus: widen one Uniform bound, add one hyperparameter, and a
thousand-trial ledger's evidence hashes to a stranger. The fingerprint
is the structural view the fuzzy path matches on instead: one row per
domain with its name, a coarse KIND (numeric vs choice), and bounds /
canonicalized options.

Two sources, one shape:

- ``fingerprint_from_spec`` — the authoritative form, from
  ``SearchSpace.spec()`` (headers written since ISSUE 14 carry it as
  the top-level ``space_spec``);
- ``fingerprint_from_records`` — the inference fallback for
  pre-upgrade ledgers: names and value types from the journaled
  canonical params, bounds as the OBSERVED min/max. Honest about what
  it is (``inferred: True``): observed bounds understate the real
  domain, which only makes fuzzy admission more conservative.

Fuzzy admission is per-DIMENSION (``compat_score``: the fraction of
the live space's dims a prior fingerprint covers by name + kind) and
then per-RECORD (``encode_record``: every live dim must hold an
encodable value — a Choice value among the live options, a numeric
inside the live bounds). A prior record that falls outside the live
domain is SKIPPED, never clipped: clipping would fabricate evidence at
a boundary point the prior sweep never evaluated.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from mpi_opt_tpu.algorithms.base import Observation
from mpi_opt_tpu.space import Choice, IntUniform, SearchSpace, _plain

#: minimum fraction of the live space's dims a fuzzy source must cover
#: (name + kind) to be considered at all; per-record encoding then
#: enforces FULL coverage, so the threshold only prunes hopeless
#: sources before their records are read
MIN_COMPAT = 1.0


def fingerprint_from_spec(spec) -> list:
    """``SearchSpace.spec()`` rows -> fingerprint rows."""
    out = []
    for d in spec:
        row = {"name": d["name"], "kind": _kind_of_spec(d)}
        if "options" in d:
            row["options"] = list(d["options"])
        else:
            row["low"], row["high"] = d.get("low"), d.get("high")
        out.append(row)
    return out


def _kind_of_spec(d: dict) -> str:
    return "choice" if d.get("kind") == "Choice" else "numeric"


def fingerprint_from_records(records) -> list:
    """Inferred fingerprint for a pre-``space_spec`` ledger: domain
    names from the canonical params, kind from the value types, bounds
    as observed min/max (numerics) or the observed value set (others).
    Empty for a record-less ledger — nothing to infer from."""
    names: list = []
    values: dict = {}
    for rec in records:
        for name, v in (rec.get("params") or {}).items():
            if name not in values:
                names.append(name)
                values[name] = []
            values[name].append(v)
    out = []
    for name in names:
        vs = values[name]
        # bool is an int in Python, but a bool-valued dim is a Choice
        # in every space this repo builds — judge it non-numeric
        numeric = all(
            isinstance(v, (int, float)) and not isinstance(v, bool) for v in vs
        )
        row: dict = {"name": name, "inferred": True}
        if numeric:
            row["kind"] = "numeric"
            row["low"], row["high"] = min(vs), max(vs)
        else:
            row["kind"] = "choice"
            row["options"] = sorted({repr(v) for v in vs})
        out.append(row)
    return out


def compat_score(live_spec, entry_fp) -> float:
    """Fraction of the LIVE space's dims the entry fingerprint covers
    with a same-name, same-kind domain. 1.0 = every live dim has a
    structurally compatible counterpart (the prior may have EXTRA dims
    — a superset space still informs the live one); 0.0 = disjoint."""
    if not live_spec:
        return 0.0
    live = fingerprint_from_spec(live_spec)
    theirs = {row["name"]: row for row in (entry_fp or [])}
    hit = sum(
        1
        for row in live
        if theirs.get(row["name"], {}).get("kind") == row["kind"]
    )
    return hit / len(live)


def encode_record(space: SearchSpace, rec: dict) -> Optional[np.ndarray]:
    """One fuzzy-source ok record -> a unit row for ``space``, or None.

    Every live dim must be present and in-domain: Choice values must
    canonicalize to a live option, numerics must sit inside the live
    bounds (quantized Int/Choice indices included via the domains' own
    ``to_unit``). Out-of-domain records return None — skipped evidence,
    not clipped fabrication."""
    params = rec.get("params") or {}
    typed = {}
    for name, dom in space.domains.items():
        if name not in params:
            return None
        v = params[name]
        if isinstance(dom, Choice):
            for opt in dom.options:
                if _plain(opt) == v:
                    typed[name] = opt
                    break
            else:
                return None
        else:
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                return None
            lo, hi = dom.low, dom.high
            if isinstance(dom, IntUniform):
                if v != int(v):
                    return None
                v = int(v)
            if not (lo <= v <= hi):
                return None
            typed[name] = v
    return space.params_to_unit(typed)


def fuzzy_observations(
    space: SearchSpace, records, keep_frac: float = 0.5
) -> tuple[list, int]:
    """Down-weighted low-fidelity observations from a fuzzy source:
    ``(observations, n_skipped)``.

    The down-weighting is explicit and two-fold (a different-space
    prior is a HINT, and must never outweigh same-space evidence):
    only the top ``keep_frac`` of the source's encodable finite-scored
    records survive (best-first — the part of a foreign surface most
    likely to transfer), and every survivor enters at ``budget=0`` —
    the lowest fidelity, so budget-aware consumers (BOHB's per-budget
    stores) file it beneath any real evaluation and the exact-match
    EvalCache, whose key includes the budget, can never serve it as a
    result. ``n_skipped`` counts records dropped for being out of the
    live domain or non-finite."""
    encodable = []
    skipped = 0
    for rec in records:
        if rec.get("status") != "ok" or rec.get("score") is None:
            skipped += 1
            continue
        score = float(rec["score"])
        if not np.isfinite(score):
            skipped += 1
            continue
        unit = encode_record(space, rec)
        if unit is None:
            skipped += 1
            continue
        encodable.append((score, unit))
    encodable.sort(key=lambda su: su[0], reverse=True)
    keep = int(np.ceil(len(encodable) * keep_frac)) if encodable else 0
    skipped += len(encodable) - keep
    obs = [
        Observation(unit=unit, score=score, budget=0)
        for score, unit in encodable[:keep]
    ]
    return obs, skipped
