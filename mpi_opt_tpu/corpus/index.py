"""The persistent ledger-corpus index: ``corpus index DIR``.

One JSON document (``corpus-index.json`` at the corpus root) mapping
every ledger under DIR to the facts auto warm-start resolution needs
without re-reading the corpus: sweep identity ``(workload, space_hash,
algorithm)``, record/ok counts, best score, the structural space
fingerprint (corpus/match.py), and a ``(mtime_ns, size)`` freshness
stamp. Discovery reuses ``ledger/report.py:discover_ledgers`` — the
same header-sniffed walk ``report DIR`` audits with, so "what the
report sees" and "what the corpus indexes" can never drift.

Durability: the index is derived state (the ledgers are the truth), so
corruption is cheap — but a TORN index is not: a sweep resolving
``--warm-start auto:`` through half a JSON document would silently see
half a corpus. Every write goes through :func:`write_index` — tmp +
fsync + rename, the same atomic pattern as every spool status write —
and the ``corpus-index-write`` sweeplint checker makes any other write
path a lint error (the lease-checker pattern, ISSUE 14 satellite).
Reads are tolerant: an unreadable/malformed index is reported as None
and callers rebuild from discovery, never crash.

Indexing is incremental: an existing entry whose ledger's
``(mtime_ns, size)`` is unchanged is carried over without re-reading
the file, so re-indexing a thousand-ledger corpus costs one stat per
ledger plus one read per CHANGED ledger.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from mpi_opt_tpu.corpus.match import fingerprint_from_records, fingerprint_from_spec
from mpi_opt_tpu.ledger.report import discover_ledgers
from mpi_opt_tpu.ledger.store import LedgerError, read_ledger

INDEX_VERSION = 1
INDEX_NAME = "corpus-index.json"


def index_path(corpus_dir: str) -> str:
    return os.path.join(corpus_dir, INDEX_NAME)


def write_index(path: str, doc: dict) -> None:
    """THE one legal index write: tmp + fsync + atomic rename (the
    ``corpus-index-write`` checker flags any other). A crash mid-write
    leaves the previous index intact; tmp debris is cleaned up."""
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):  # failed mid-write: no orphan debris
            os.unlink(tmp)


def read_index(corpus_dir: str) -> Optional[dict]:
    """The index document, or None when absent/unreadable/malformed —
    derived state degrades to a rebuild, never to a crash."""
    try:
        with open(index_path(corpus_dir)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or not isinstance(doc.get("entries"), list):
        return None
    try:
        if int(doc.get("version", -1)) > INDEX_VERSION:
            return None  # a newer build's index: rebuild rather than misread
    except (TypeError, ValueError):
        return None  # version: null / "x" — same rebuild-don't-crash rule
    return doc


def _stat_stamp(path: str) -> Optional[tuple]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_mtime_ns, st.st_size)


def summarize_entry(path: str) -> dict:
    """One ledger -> its index entry (``error`` key when unreadable:
    the index records the problem instead of silently shrinking the
    corpus — resolution skips errored entries with an event)."""
    return summarize_entry_with_records(path)[0]


def summarize_entry_with_records(path: str) -> tuple:
    """``(entry, records)`` — the records the summary was built from
    ride along so a caller that needs both (resolve's re-read of a
    grown ledger) pays ONE file parse, not two. ``records`` is empty
    when the entry is errored."""
    stamp = _stat_stamp(path)
    entry: dict = {
        "path": os.path.abspath(path),
        "mtime_ns": None if stamp is None else stamp[0],
        "size": None if stamp is None else stamp[1],
    }
    try:
        header, records, _n_torn = read_ledger(path)
    except (LedgerError, OSError) as e:
        entry["error"] = f"{type(e).__name__}: {e}"
        return entry, []
    if header is None:
        entry["error"] = "empty ledger (no header)"
        return entry, []
    cfg = header.get("config", {})
    ok = [r for r in records if r["status"] == "ok" and r.get("score") is not None]
    best = max((float(r["score"]) for r in ok), default=None)
    spec = header.get("space_spec")
    entry.update(
        workload=cfg.get("workload"),
        algorithm=cfg.get("algorithm"),
        mode=cfg.get("mode", "driver"),
        space_hash=cfg.get("space_hash"),
        sweep_id=header.get("sweep_id"),
        records=len(records),
        ok=len(ok),
        best_score=best,
        fingerprint=(
            fingerprint_from_spec(spec)
            if spec is not None
            else fingerprint_from_records(ok)
        ),
    )
    ospec = header.get("objective_spec")
    if ospec:
        # multi-objective sweeps (ISSUE 17) summarize their final
        # non-dominated front so auto warm-start can rank MO priors
        # (and resolve can seed from the front) without re-reading the
        # ledger; a malformed spec degrades to None, never a crash
        entry["pareto"] = _pareto_entry(ospec, records)
    return entry, records


def _pareto_entry(ospec, records) -> Optional[dict]:
    """Front size/objectives/hypervolume of an MO ledger's final state
    (see ``ledger/report._mo_final_rows`` for the end-state rule)."""
    import numpy as np

    from mpi_opt_tpu.ledger.report import _mo_final_rows
    from mpi_opt_tpu.objectives import (
        ObjectiveSpec,
        hypervolume,
        pareto_front_mask,
    )

    try:
        spec = ObjectiveSpec.from_spec(ospec)
    except (ValueError, TypeError, KeyError):
        return None
    _recs, mat = _mo_final_rows(records, spec)
    norm = np.asarray(spec.normalize(mat), dtype=np.float64)
    mask = pareto_front_mask(norm)
    return {
        "objectives": [o.get("name") for o in ospec],
        "front_size": int(mask.sum()),
        "hypervolume": float(hypervolume(norm[mask])) if mask.any() else 0.0,
    }


def build_index(corpus_dir: str, prior: Optional[dict] = None) -> dict:
    """Scan ``corpus_dir`` and build the index document, reusing
    ``prior``'s entries for ledgers whose freshness stamp is unchanged.
    The document's own ``corpus-index.json`` is never indexed (it is
    not a ledger and the sniff rejects it anyway)."""
    carried = {}
    if prior is not None:
        carried = {
            e.get("path"): e
            for e in prior.get("entries", [])
            if isinstance(e, dict)
        }
    entries = []
    for path in discover_ledgers(corpus_dir):
        path = os.path.abspath(path)
        stamp = _stat_stamp(path)
        old = carried.get(path)
        if (
            old is not None
            and stamp is not None
            and (old.get("mtime_ns"), old.get("size")) == stamp
            and "error" not in old
        ):
            entries.append(old)
            continue
        entries.append(summarize_entry(path))
    return {
        "version": INDEX_VERSION,
        "tool": "corpus-index",
        "root": os.path.abspath(corpus_dir),
        "entries": entries,
    }


def index_corpus(corpus_dir: str) -> dict:
    """Build (incrementally) and persist the index; returns the doc."""
    doc = build_index(corpus_dir, prior=read_index(corpus_dir))
    write_index(index_path(corpus_dir), doc)
    return doc
