"""``--warm-start auto[:DIR]``: resolve priors through the corpus index.

The resolution contract (ISSUE 14):

- **Exact** — every index entry whose ``space_hash`` equals the live
  space's contributes ALL its ok records; the merged set is deduped by
  canonical params key with the NEWEST record (journal ``ts``) winning,
  so a point re-evaluated across sweeps carries its freshest score and
  N overlapping ledgers never multiply one point's weight.
- **Fuzzy** — different-hash entries are admitted only when their
  structural fingerprint covers the live space (corpus/match.py) AND
  they ran the same workload (scores across workloads are not
  comparable evidence); their records enter down-weighted at budget 0
  (``fuzzy_observations``), never as exact-cache material.
- **Degrade, don't die** — a stale index entry (ledger deleted or
  rewritten behind the index), a corrupt entry, or an unreadable
  ledger becomes one ``corpus_skip`` event and the resolution
  continues with the remaining sources. A missing/corrupt index
  rebuilds in memory from discovery (the persistent file is derived
  state; ``corpus index DIR`` re-persists it).

The resolver never writes: a sweep's warm start must not mutate the
corpus it reads (concurrent sweeps share one), so the on-disk index is
refreshed only by the explicit ``corpus index`` command.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from mpi_opt_tpu.corpus import index as cindex
from mpi_opt_tpu.corpus.match import MIN_COMPAT, compat_score, fuzzy_observations
from mpi_opt_tpu.ledger.store import LedgerError, read_ledger, sniff_header
from mpi_opt_tpu.ledger.warmstart import observations_from_records


def _front_only(path: str, records) -> tuple:
    """A multi-objective ledger's records reduced to its final
    non-dominated set: ``(records, n_dominated)``.

    Seeding a new sweep from an MO prior's DOMINATED points would pull
    it toward trade-offs the prior already proved inferior, so only the
    front enters the merge; scalar ledgers (no ``objective_spec`` in
    the header) pass through untouched. A malformed spec also passes
    through — degraded evidence beats refused evidence here, the same
    rule as every other corpus degradation."""
    header = sniff_header(path)
    ospec = None if header is None else header.get("objective_spec")
    if not ospec:
        return records, 0
    import numpy as np

    from mpi_opt_tpu.ledger.report import _mo_final_rows
    from mpi_opt_tpu.objectives import ObjectiveSpec, pareto_front_mask

    try:
        spec = ObjectiveSpec.from_spec(ospec)
    except (ValueError, TypeError, KeyError):
        return records, 0
    recs, mat = _mo_final_rows(records, spec)
    mask = pareto_front_mask(np.asarray(spec.normalize(mat), dtype=np.float64))
    front = [recs[i] for i in np.flatnonzero(mask)]
    return front, len(records) - len(front)


@dataclasses.dataclass
class Resolution:
    """What ``--warm-start auto:`` actually ingested, for the event
    payload and the summary: ``observations`` (exact first, fuzzy
    after), ``sources`` (one row per contributing ledger), ``skips``
    (per-record loss counters merged across sources), ``skipped``
    (whole entries degraded to corpus_skip events)."""

    observations: list
    sources: list
    skips: dict
    skipped: list


def _entry_live(entry: dict) -> Optional[str]:
    """None when the entry's ledger is still the file the index saw,
    else the skip reason ("missing" / "changed")."""
    path = entry.get("path")
    if not path or not os.path.exists(path):
        return "missing"
    stamp = cindex._stat_stamp(path)
    if stamp is None:
        return "missing"
    if (entry.get("mtime_ns"), entry.get("size")) != stamp:
        return "changed"
    return None


def resolve(
    space,
    corpus_dir: str,
    workload: Optional[str] = None,
    exclude: Optional[str] = None,
    metrics=None,
) -> Resolution:
    """Resolve the corpus under ``corpus_dir`` into warm-start
    observations for ``space``. ``workload`` gates fuzzy admission;
    ``exclude`` (realpath'd) drops THIS sweep's own ledger — the
    self-warm-start guard, applied here so every resolution path
    shares it. ``metrics`` (MetricsLogger) receives ``corpus_skip``
    events for degraded entries; None stays silent."""
    skipped: list = []

    def skip(path, reason):
        skipped.append({"path": path, "reason": reason})
        if metrics is not None:
            metrics.log("corpus_skip", path=path, reason=reason)

    doc = cindex.read_index(corpus_dir)
    if doc is None:
        if os.path.exists(cindex.index_path(corpus_dir)):
            # present but unreadable/malformed: degrade loudly, then
            # rebuild from discovery — derived state is replaceable
            skip(cindex.index_path(corpus_dir), "index-unreadable")
        doc = cindex.build_index(corpus_dir)

    live_hash = space.space_hash()
    live_spec = space.spec()
    exclude_real = os.path.realpath(exclude) if exclude else None

    # records already parsed during this resolution (grown-ledger
    # re-summaries), keyed by path — consumed by load_records below
    records_cache: dict = {}
    exact_entries, fuzzy_entries = [], []
    for entry in doc.get("entries", []):
        if not isinstance(entry, dict) or not entry.get("path"):
            skip(str(entry)[:200], "malformed-entry")
            continue
        if entry.get("error"):
            skip(entry["path"], f"unreadable: {entry['error']}")
            continue
        if exclude_real and os.path.realpath(entry["path"]) == exclude_real:
            continue  # this run's own ledger is not a prior sweep
        reason = _entry_live(entry)
        if reason == "missing":
            skip(entry["path"], "stale-entry: ledger deleted")
            continue
        if reason == "changed":
            # the ledger grew/rewrote since indexing: re-summarize it
            # live (fresh evidence is better evidence), degrade to a
            # skip only if the re-read fails; the parsed records are
            # cached so the merge loops don't re-read the file
            entry, records = cindex.summarize_entry_with_records(entry["path"])
            if entry.get("error"):
                skip(entry["path"], f"stale-entry: {entry['error']}")
                continue
            records_cache[entry["path"]] = records
        if entry.get("space_hash") == live_hash:
            exact_entries.append(entry)
        elif (
            workload is not None
            and entry.get("workload") == workload
            and compat_score(live_spec, entry.get("fingerprint")) >= MIN_COMPAT
        ):
            fuzzy_entries.append(entry)

    sources: list = []
    skips: dict = {}
    observations: list = []

    def load_records(entry):
        """One read per ledger per resolution: a grown (``changed``)
        entry was already re-read by ``summarize_entry`` above — the
        cache hands those records straight to the merge loops instead
        of parsing the file a second time."""
        path = entry["path"]
        if path in records_cache:
            return records_cache.pop(path)
        _header, records, _ = read_ledger(path)
        return records

    # exact: merge ALL matching ledgers' ok records, dedup by canonical
    # (params, budget) key — the budget is part of evaluation identity
    # (an ASHA point at step 10 and step 270 is TWO pieces of evidence,
    # the same both-keys-survive rule as EvalCache) — newest journal ts
    # wins within one key
    merged: dict = {}
    exact_order = []  # (path, n contributed) in entry order, for the event
    total_ok = 0
    for entry in exact_entries:
        try:
            records = load_records(entry)
        except (LedgerError, OSError) as e:
            skip(entry["path"], f"unreadable: {type(e).__name__}: {e}")
            continue
        records, n_dom = _front_only(entry["path"], records)
        if n_dom:
            skips["dominated"] = skips.get("dominated", 0) + n_dom
        n = 0
        for rec in records:
            if rec["status"] != "ok" or rec.get("score") is None:
                continue
            try:
                key = (space.params_key(rec["params"]), int(rec["step"]))
            except KeyError:
                continue  # same hash yet missing a dim: hand-edited; skip
            cur = merged.get(key)
            if cur is None or float(rec.get("ts") or 0.0) >= float(
                cur.get("ts") or 0.0
            ):
                merged[key] = rec
            n += 1
        total_ok += n
        exact_order.append((entry["path"], n, entry))
    exact_obs, exact_skips = observations_from_records(
        list(merged.values()), space
    )
    observations.extend(exact_obs)
    for k, v in exact_skips.items():
        skips[k] = skips.get(k, 0) + v
    for path, n, entry in exact_order:
        sources.append(
            {
                "path": path,
                "match": "exact",
                "records": n,
                "space_hash": entry.get("space_hash"),
            }
        )
    # counted unconditionally: one resumed ledger's cached re-journals
    # dedup within a SINGLE source too
    dropped = total_ok - len(merged)
    if dropped:
        skips["duplicate_params"] = skips.get("duplicate_params", 0) + dropped

    # fuzzy: per-source down-weighted low-fidelity observations
    for entry in fuzzy_entries:
        try:
            records = load_records(entry)
        except (LedgerError, OSError) as e:
            skip(entry["path"], f"unreadable: {type(e).__name__}: {e}")
            continue
        records, n_dom = _front_only(entry["path"], records)
        if n_dom:
            skips["dominated"] = skips.get("dominated", 0) + n_dom
        obs, n_skipped = fuzzy_observations(space, records)
        if not obs:
            skip(entry["path"], "fuzzy: no record encodable into the live space")
            continue
        observations.extend(obs)
        if n_skipped:
            skips["fuzzy_dropped"] = skips.get("fuzzy_dropped", 0) + n_skipped
        sources.append(
            {
                "path": entry["path"],
                "match": "fuzzy",
                "records": len(obs),
                "space_hash": entry.get("space_hash"),
            }
        )

    return Resolution(
        observations=observations, sources=sources, skips=skips, skipped=skipped
    )
