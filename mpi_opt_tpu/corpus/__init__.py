"""Cross-sweep knowledge corpus: the ledger archive as a queryable prior.

Every sweep this engine runs journals its trial history durably
(ledger/), every service tenant keeps a per-job ledger (service/), and
since PR 6 the throughput-dominant fused mode journals at member
granularity — so a working deployment accumulates a CORPUS of
evaluated (params, score, budget) facts. Before this package, that
corpus informed a new sweep only when a human pointed ``--warm-start``
at one specific file. This package closes the loop (ISSUE 14 /
ROADMAP "cross-sweep knowledge"):

- ``index``   — ``corpus index DIR``: a persistent, atomically-updated
  index of every ledger under DIR, keyed by (workload, space_hash,
  algorithm), with record counts, best scores, and a structural space
  fingerprint enabling fuzzy matching between different-hash spaces.
- ``match``   — the fingerprint + compatibility layer: exact identity
  stays the hash's business; structurally-overlapping spaces score as
  fuzzy candidates, per-record admission keeps foreign evidence inside
  the live domain.
- ``resolve`` — ``--warm-start auto[:DIR]``: exact-hash sources merge
  (dedup by canonical params, newest wins), fuzzy sources enter
  down-weighted at budget 0, stale/corrupt index entries degrade to
  ``corpus_skip`` events — a deleted ledger never kills a sweep.
- ``serve``   — the suggestion service: a resident (and sweep-service-
  schedulable) tenant answering suggest → report → lookup over a
  filesystem spool at acquisition-kernel speed, warm-started from the
  corpus; its own journal is corpus material for the next index.
- ``client``  — ``suggest-client``: the jax-free protocol client, with
  a ``bench`` mode measuring suggestions/s and round-trip percentiles
  (BENCH config 6).
"""

from __future__ import annotations

from mpi_opt_tpu.corpus.index import (  # noqa: F401
    INDEX_NAME,
    INDEX_VERSION,
    build_index,
    index_corpus,
    index_path,
    read_index,
    write_index,
)
