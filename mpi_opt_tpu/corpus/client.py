"""``mpi_opt_tpu suggest-client``: the suggestion service's thin client.

jax-free (like every service client). TWO transports share one answer
schema:

- the filesystem spool (requests are atomic JSON file drops, responses
  polled reads) — so an external sweep written in ANY language can
  drive the suggestion tenant by copying this ~50-line protocol;
- the HTTP front door (``--url http://HOST:PORT``, service/http.py) —
  batched ops, idempotent retries with capped jittered backoff honoring
  Retry-After, and a typed fault funnel (corpus/transport.py) that
  distinguishes "the server answered" from "the transport failed".

Subcommands::

    suggest-client --dir SDIR suggest -n 8
    suggest-client --url http://127.0.0.1:8713 suggest -n 8
    suggest-client --dir SDIR report --params '{"lr": 0.1}' --score 0.93 [--budget 20]
    suggest-client --dir SDIR lookup --params '{"lr": 0.1}' [--budget 20]
    suggest-client --dir SDIR stop
    suggest-client --dir SDIR bench --rounds 32 --batch 16
    suggest-client --url URL bench --rounds 32 --batch 16 --burst 4

``bench`` is the measured scenario: over the spool it is BENCH config
6 (serial suggest→report round trips); over HTTP it is BENCH config 7
(``--burst`` concurrent conversations of batched suggest + batched
reports — one HTTP request and ONE journal fsync per report batch),
printing suggestions/s, p50/p95 round trips and the p95 server-side
queue wait. :class:`SuggestHttpClient` also memoizes ``lookup``
answers by params key (ROADMAP 3b: repeat lookups never cross the
wire) with explicit invalidation on ``report``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from collections import OrderedDict
from typing import Optional

from mpi_opt_tpu.service.spool import _read_json, _write_json_atomic


def request(sdir: str, payload: dict) -> str:
    """Drop one request; returns its id (nanosecond-stamped like spool
    job ids, so lexicographic order is submission order)."""
    rid = payload.get("id") or f"req-{time.time_ns():020d}-{os.getpid() % 100000:05d}"
    req_dir = os.path.join(sdir, "requests")
    os.makedirs(req_dir, exist_ok=True)
    _write_json_atomic(
        os.path.join(req_dir, f"{rid}.json"), dict(payload, id=rid)
    )
    return rid


def wait_response(
    sdir: str, rid: str, timeout: float = 30.0, poll: float = 0.01
) -> Optional[dict]:
    """Poll for the response; None on timeout (server down or wedged —
    the caller decides whether that is an error)."""
    path = os.path.join(sdir, "responses", f"{rid}.json")
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ans = _read_json(path)
        if ans is not None:
            try:
                os.unlink(path)  # consume: responses are single-reader
            except OSError:
                pass
            return ans
        time.sleep(poll)
    return None


def round_trip(sdir: str, payload: dict, timeout: float = 30.0) -> dict:
    rid = request(sdir, payload)
    ans = wait_response(sdir, rid, timeout=timeout)
    if ans is None:
        raise TimeoutError(
            f"no response to {payload.get('op')!r} within {timeout}s — is a "
            f"suggestion server (--suggest-serve {sdir}) running?"
        )
    return ans


def request_stop(sdir: str) -> None:
    ctrl = os.path.join(sdir, "control")
    os.makedirs(ctrl, exist_ok=True)
    with open(os.path.join(ctrl, "stop"), "w") as f:
        f.write("")


def _synthetic_score(params: dict) -> float:
    """The bench's stand-in objective: a deterministic quadratic bowl
    over the numeric dims (closer to mid-range scores higher), so the
    served acquisition has a real surface to learn during the bench."""
    score = 0.0
    n = 0
    for v in params.values():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        score -= (float(v) - 0.5) ** 2
        n += 1
    return score if n else 0.0


def bench(sdir: str, rounds: int, batch: int, timeout: float = 60.0) -> dict:
    """``rounds`` suggest→report round trips of ``batch`` suggestions,
    every suggestion reported back: suggestions/s over the whole
    conversation plus p50/p95 per-request round-trip seconds."""
    trips: list = []

    def timed(payload):
        t0 = time.perf_counter()
        ans = round_trip(sdir, payload, timeout=timeout)
        trips.append(time.perf_counter() - t0)
        if ans.get("error"):
            raise RuntimeError(f"server refused {payload.get('op')!r}: {ans['error']}")
        return ans

    timed({"op": "suggest", "n": batch})  # warm the jitted acquisition
    t0 = time.perf_counter()
    n_suggestions = 0
    for _ in range(rounds):
        ans = timed({"op": "suggest", "n": batch})
        got = ans.get("params") or []
        n_suggestions += len(got)
        for params in got:
            timed(
                {
                    "op": "report",
                    "params": params,
                    "score": _synthetic_score(params),
                    "budget": 1,
                }
            )
    wall = time.perf_counter() - t0
    trips_sorted = sorted(trips)

    def pct(p):
        return trips_sorted[min(len(trips_sorted) - 1, int(p * len(trips_sorted)))]

    return {
        "rounds": rounds,
        "batch": batch,
        "suggestions": n_suggestions,
        "requests": len(trips),
        "wall_s": round(wall, 3),
        "suggestions_per_sec": round(n_suggestions / max(wall, 1e-9), 2),
        "round_trip_p50_s": round(pct(0.50), 4),
        "round_trip_p95_s": round(pct(0.95), 4),
    }


# -- the HTTP mode --------------------------------------------------------


def discover_url(sdir: str, timeout: float = 10.0, poll: float = 0.05) -> str:
    """Resolve a front door's URL from its spool's endpoint file
    (``SDIR/control/http.json``, written atomically after the bind) —
    how clients find an ``--http-port 0`` ephemeral server without
    racing the bind."""
    from mpi_opt_tpu.service.http import endpoint_path

    path = endpoint_path(sdir)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        doc = _read_json(path)
        if doc and doc.get("url"):
            return str(doc["url"])
        time.sleep(poll)
    raise TimeoutError(
        f"no HTTP endpoint published at {path} within {timeout}s — is a "
        f"front door (--suggest-serve SDIR --http-port N) running?"
    )


class SuggestHttpClient:
    """One client's conversation with the front door: batched envelopes,
    idempotent retries, and a bounded lookup memo.

    Every :meth:`batch` generates ONE idempotency key and reuses it
    verbatim across retries, so a torn response or a server restart
    mid-request can never double-journal a report. ``lookup`` answers
    memoize by canonical params key; ``report`` clears the memo — a
    report shifts the server's near-match priors for OTHER keys too, so
    per-key invalidation would serve stale priors."""

    def __init__(
        self,
        url: str,
        client_id: Optional[str] = None,
        timeout: float = 30.0,
        retries: int = 6,
        backoff_s: float = 0.05,
        cache_size: int = 256,
        sleep=time.sleep,
    ):
        from mpi_opt_tpu.corpus import transport

        self.transport = transport.HttpTransport(url, timeout=timeout)
        self.client_id = client_id or f"pid-{os.getpid()}"
        self.retries = retries
        self.backoff_s = backoff_s
        self.cache_size = cache_size
        self._sleep = sleep
        self._lookup_memo: "OrderedDict" = OrderedDict()
        self.stats = {"batches": 0, "replayed": 0, "lookup_hits": 0}

    def batch(self, ops: list, deadline_s: Optional[float] = None) -> dict:
        from mpi_opt_tpu.corpus import transport

        env = transport.envelope(ops, client=self.client_id, deadline_s=deadline_s)
        ans = transport.call_with_retries(
            self.transport,
            "/v1/batch",
            env,
            retries=self.retries,
            backoff_s=self.backoff_s,
            sleep=self._sleep,
        )
        self.stats["batches"] += 1
        if ans.get("replayed"):
            self.stats["replayed"] += 1
        return ans

    def _one(self, op: dict, deadline_s: Optional[float] = None) -> dict:
        return self.batch([op], deadline_s=deadline_s)["results"][0]

    def suggest(self, n: int = 1, deadline_s: Optional[float] = None) -> dict:
        return self._one({"op": "suggest", "n": int(n)}, deadline_s=deadline_s)

    def report(self, params: dict, score: float, budget: int = 0) -> dict:
        ans = self._one(
            {"op": "report", "params": params, "score": float(score),
             "budget": int(budget)}
        )
        self._lookup_memo.clear()
        return ans

    def lookup(self, params: dict, budget: int = 0) -> dict:
        key = json.dumps(
            {"params": params, "budget": int(budget)},
            sort_keys=True, separators=(",", ":"),
        )
        hit = self._lookup_memo.get(key)
        if hit is not None:
            self._lookup_memo.move_to_end(key)
            self.stats["lookup_hits"] += 1
            return dict(hit)
        ans = self._one({"op": "lookup", "params": params, "budget": int(budget)})
        if not ans.get("error"):
            self._lookup_memo[key] = dict(ans)
            while len(self._lookup_memo) > self.cache_size:
                self._lookup_memo.popitem(last=False)
        return ans

    def stop(self) -> dict:
        return self.transport.call("/v1/stop", {})


def bench_http(
    url: str,
    rounds: int,
    batch: int,
    burst: int = 4,
    timeout: float = 60.0,
    deadline_s: Optional[float] = None,
) -> dict:
    """BENCH config 7's measured scenario: ``burst`` concurrent clients
    each run ``rounds`` conversations of [one suggest batch, then ALL
    its reports in one batched request] — open-loop enough to keep the
    admission queue non-empty, while every report still journals
    exactly once. Reports suggestions/s over the whole conversation,
    client round-trip p50/p95, and the SERVER-side p95 queue wait (from
    each answer's ``queue_wait_s`` — the number the shedding bound is
    judged on)."""
    import threading

    trips: list = []
    waits: list = []
    counts = {"suggestions": 0, "requests": 0, "replayed": 0}
    lock = threading.Lock()
    errors: list = []

    def one_client(idx: int) -> None:
        cli = SuggestHttpClient(
            url, client_id=f"bench-{os.getpid()}-{idx}", timeout=timeout
        )
        try:
            for _ in range(rounds):
                t0 = time.perf_counter()
                ans = cli.batch([{"op": "suggest", "n": batch}],
                                deadline_s=deadline_s)
                dt = time.perf_counter() - t0
                sugg = ans["results"][0]
                if sugg.get("error"):
                    raise RuntimeError(f"suggest refused: {sugg['error']}")
                got = sugg.get("params") or []
                ops = [
                    {"op": "report", "params": p,
                     "score": _synthetic_score(p), "budget": 1}
                    for p in got
                ]
                t1 = time.perf_counter()
                rep = cli.batch(ops, deadline_s=deadline_s) if ops else None
                dt2 = time.perf_counter() - t1
                with lock:
                    trips.append(dt)
                    waits.append(float(ans.get("queue_wait_s") or 0.0))
                    counts["requests"] += 1
                    counts["suggestions"] += len(got)
                    if rep is not None:
                        trips.append(dt2)
                        waits.append(float(rep.get("queue_wait_s") or 0.0))
                        counts["requests"] += 1
                        counts["replayed"] += int(bool(rep.get("replayed")))
        except Exception as e:  # noqa: BLE001 - a bench worker reports, never hangs the join
            with lock:
                errors.append(f"client {idx}: {type(e).__name__}: {e}")

    # Warm the server outside the timed window: the first suggest runs
    # the startup sampler; reporting it pushes n_obs past n_startup so
    # the SECOND suggest compiles the jitted acquisition path — both
    # one-time costs the steady-state number must not absorb.
    warm = SuggestHttpClient(url, client_id="bench-warmup", timeout=timeout)
    got = warm.suggest(batch).get("params") or []
    warm.batch(
        [{"op": "report", "params": p, "score": _synthetic_score(p), "budget": 1}
         for p in got]
    )
    warm.suggest(batch)
    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=one_client, args=(i,), daemon=True)
        for i in range(burst)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=max(timeout * rounds, 120.0))
    wall = time.perf_counter() - t0
    if errors:
        raise RuntimeError("; ".join(errors[:3]))
    trips_sorted = sorted(trips) or [0.0]
    waits_sorted = sorted(waits) or [0.0]

    def pct(xs, p):
        return xs[min(len(xs) - 1, int(p * len(xs)))]

    return {
        "rounds": rounds,
        "batch": batch,
        "burst": burst,
        "suggestions": counts["suggestions"],
        "requests": counts["requests"],
        "replayed": counts["replayed"],
        "wall_s": round(wall, 3),
        "suggestions_per_sec": round(counts["suggestions"] / max(wall, 1e-9), 2),
        "round_trip_p50_s": round(pct(trips_sorted, 0.50), 4),
        "round_trip_p95_s": round(pct(trips_sorted, 0.95), 4),
        "queue_wait_p50_s": round(pct(waits_sorted, 0.50), 4),
        "queue_wait_p95_s": round(pct(waits_sorted, 0.95), 4),
    }


def client_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="mpi_opt_tpu suggest-client",
        description="drive a suggestion server (--suggest-serve) over "
        "its filesystem spool (see README: Cross-sweep knowledge corpus)",
    )
    p.add_argument(
        "--dir",
        metavar="SDIR",
        help="the suggestion spool directory (shared with the server)",
    )
    p.add_argument(
        "--url",
        metavar="URL",
        help="HTTP front door endpoint (http://HOST:PORT); with --dir "
        "and no --url, the spool's control/http.json is NOT consulted — "
        "the filesystem protocol is used",
    )
    p.add_argument("--timeout", type=float, default=30.0, help="response wait")
    sub = p.add_subparsers(dest="op", required=True)
    sp = sub.add_parser("suggest", help="ask for acquisition-ranked points")
    sp.add_argument("-n", type=int, default=1, help="suggestions to fetch")
    rp = sub.add_parser("report", help="report one completed evaluation")
    rp.add_argument("--params", required=True, help="canonical params JSON")
    rp.add_argument("--score", type=float, required=True)
    rp.add_argument("--budget", type=int, default=0)
    lp = sub.add_parser("lookup", help="exact/near-match prior lookup")
    lp.add_argument("--params", required=True, help="canonical params JSON")
    lp.add_argument("--budget", type=int, default=0)
    sub.add_parser("stop", help="flag the server to finish and exit 0")
    bp = sub.add_parser("bench", help="measured suggest→report round trips")
    bp.add_argument("--rounds", type=int, default=16)
    bp.add_argument("--batch", type=int, default=16)
    bp.add_argument(
        "--burst", type=int, default=4,
        help="concurrent clients (HTTP mode only; the spool bench is serial)",
    )
    args = p.parse_args(argv)
    if not args.dir and not args.url:
        p.error("need --dir SDIR (filesystem spool) or --url URL (HTTP)")

    from mpi_opt_tpu.corpus import transport
    from mpi_opt_tpu.utils.exitcodes import EX_PROTOCOL, EX_UNAVAILABLE

    try:
        if args.url:
            return _http_main(args, p)
        if args.op == "stop":
            request_stop(args.dir)
            print(json.dumps({"stop": True}))
            return 0
        if args.op == "bench":
            print(json.dumps(bench(args.dir, args.rounds, args.batch, args.timeout)))
            return 0
        payload: dict = {"op": args.op}
        if args.op == "suggest":
            payload["n"] = args.n
        else:
            try:
                payload["params"] = json.loads(args.params)
            except ValueError as e:
                p.error(f"--params must be JSON: {e}")
            payload["budget"] = args.budget
            if args.op == "report":
                payload["score"] = args.score
        ans = round_trip(args.dir, payload, timeout=args.timeout)
    except transport.RequestRefused as e:
        # the server ANSWERED with a typed protocol refusal (409/400):
        # retrying the same bytes re-refuses — distinct exit code so
        # scripts never blind-retry a client bug
        print(str(e), file=sys.stderr)
        return EX_PROTOCOL
    except transport.TransportFault as e:
        # retries exhausted (or a non-retryable expiry): the service is
        # unavailable from here — sysexits EX_UNAVAILABLE
        print(str(e), file=sys.stderr)
        return EX_UNAVAILABLE
    except (TimeoutError, RuntimeError) as e:
        print(str(e), file=sys.stderr)
        return 1
    print(json.dumps(ans))
    return 0 if not ans.get("error") else 1


def _http_main(args, p) -> int:
    """The --url route of ``client_main`` (same answer schema as the
    spool route; transport faults propagate to client_main's funnel)."""
    if args.op == "bench":
        print(
            json.dumps(
                bench_http(
                    args.url, args.rounds, args.batch,
                    burst=args.burst, timeout=args.timeout,
                )
            )
        )
        return 0
    cli = SuggestHttpClient(args.url, timeout=args.timeout)
    if args.op == "stop":
        print(json.dumps(cli.stop()))
        return 0
    if args.op == "suggest":
        ans = cli.suggest(args.n)
    else:
        try:
            params = json.loads(args.params)
        except ValueError as e:
            p.error(f"--params must be JSON: {e}")
        if args.op == "report":
            ans = cli.report(params, args.score, args.budget)
        else:
            ans = cli.lookup(params, args.budget)
    print(json.dumps(ans))
    return 0 if not ans.get("error") else 1
