"""``mpi_opt_tpu suggest-client``: the suggestion service's thin client.

jax-free (like every service client): requests are atomic JSON file
drops, responses are polled reads, so an external sweep written in ANY
language can drive the suggestion tenant by copying this ~50-line
protocol. Subcommands::

    suggest-client --dir SDIR suggest -n 8
    suggest-client --dir SDIR report --params '{"lr": 0.1}' --score 0.93 [--budget 20]
    suggest-client --dir SDIR lookup --params '{"lr": 0.1}' [--budget 20]
    suggest-client --dir SDIR stop
    suggest-client --dir SDIR bench --rounds 32 --batch 16

``bench`` is the measured scenario (BENCH config 6): ``--rounds``
suggest→report round trips of ``--batch`` suggestions each, every
suggestion reported back with a synthetic quadratic score — printing
suggestions/s and the p50/p95 request round-trip, the two numbers the
ISSUE 14 acceptance names.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional

from mpi_opt_tpu.service.spool import _read_json, _write_json_atomic


def request(sdir: str, payload: dict) -> str:
    """Drop one request; returns its id (nanosecond-stamped like spool
    job ids, so lexicographic order is submission order)."""
    rid = payload.get("id") or f"req-{time.time_ns():020d}-{os.getpid() % 100000:05d}"
    req_dir = os.path.join(sdir, "requests")
    os.makedirs(req_dir, exist_ok=True)
    _write_json_atomic(
        os.path.join(req_dir, f"{rid}.json"), dict(payload, id=rid)
    )
    return rid


def wait_response(
    sdir: str, rid: str, timeout: float = 30.0, poll: float = 0.01
) -> Optional[dict]:
    """Poll for the response; None on timeout (server down or wedged —
    the caller decides whether that is an error)."""
    path = os.path.join(sdir, "responses", f"{rid}.json")
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ans = _read_json(path)
        if ans is not None:
            try:
                os.unlink(path)  # consume: responses are single-reader
            except OSError:
                pass
            return ans
        time.sleep(poll)
    return None


def round_trip(sdir: str, payload: dict, timeout: float = 30.0) -> dict:
    rid = request(sdir, payload)
    ans = wait_response(sdir, rid, timeout=timeout)
    if ans is None:
        raise TimeoutError(
            f"no response to {payload.get('op')!r} within {timeout}s — is a "
            f"suggestion server (--suggest-serve {sdir}) running?"
        )
    return ans


def request_stop(sdir: str) -> None:
    ctrl = os.path.join(sdir, "control")
    os.makedirs(ctrl, exist_ok=True)
    with open(os.path.join(ctrl, "stop"), "w") as f:
        f.write("")


def _synthetic_score(params: dict) -> float:
    """The bench's stand-in objective: a deterministic quadratic bowl
    over the numeric dims (closer to mid-range scores higher), so the
    served acquisition has a real surface to learn during the bench."""
    score = 0.0
    n = 0
    for v in params.values():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        score -= (float(v) - 0.5) ** 2
        n += 1
    return score if n else 0.0


def bench(sdir: str, rounds: int, batch: int, timeout: float = 60.0) -> dict:
    """``rounds`` suggest→report round trips of ``batch`` suggestions,
    every suggestion reported back: suggestions/s over the whole
    conversation plus p50/p95 per-request round-trip seconds."""
    trips: list = []

    def timed(payload):
        t0 = time.perf_counter()
        ans = round_trip(sdir, payload, timeout=timeout)
        trips.append(time.perf_counter() - t0)
        if ans.get("error"):
            raise RuntimeError(f"server refused {payload.get('op')!r}: {ans['error']}")
        return ans

    timed({"op": "suggest", "n": batch})  # warm the jitted acquisition
    t0 = time.perf_counter()
    n_suggestions = 0
    for _ in range(rounds):
        ans = timed({"op": "suggest", "n": batch})
        got = ans.get("params") or []
        n_suggestions += len(got)
        for params in got:
            timed(
                {
                    "op": "report",
                    "params": params,
                    "score": _synthetic_score(params),
                    "budget": 1,
                }
            )
    wall = time.perf_counter() - t0
    trips_sorted = sorted(trips)

    def pct(p):
        return trips_sorted[min(len(trips_sorted) - 1, int(p * len(trips_sorted)))]

    return {
        "rounds": rounds,
        "batch": batch,
        "suggestions": n_suggestions,
        "requests": len(trips),
        "wall_s": round(wall, 3),
        "suggestions_per_sec": round(n_suggestions / max(wall, 1e-9), 2),
        "round_trip_p50_s": round(pct(0.50), 4),
        "round_trip_p95_s": round(pct(0.95), 4),
    }


def client_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="mpi_opt_tpu suggest-client",
        description="drive a suggestion server (--suggest-serve) over "
        "its filesystem spool (see README: Cross-sweep knowledge corpus)",
    )
    p.add_argument(
        "--dir",
        required=True,
        metavar="SDIR",
        help="the suggestion spool directory (shared with the server)",
    )
    p.add_argument("--timeout", type=float, default=30.0, help="response wait")
    sub = p.add_subparsers(dest="op", required=True)
    sp = sub.add_parser("suggest", help="ask for acquisition-ranked points")
    sp.add_argument("-n", type=int, default=1, help="suggestions to fetch")
    rp = sub.add_parser("report", help="report one completed evaluation")
    rp.add_argument("--params", required=True, help="canonical params JSON")
    rp.add_argument("--score", type=float, required=True)
    rp.add_argument("--budget", type=int, default=0)
    lp = sub.add_parser("lookup", help="exact/near-match prior lookup")
    lp.add_argument("--params", required=True, help="canonical params JSON")
    lp.add_argument("--budget", type=int, default=0)
    sub.add_parser("stop", help="flag the server to finish and exit 0")
    bp = sub.add_parser("bench", help="measured suggest→report round trips")
    bp.add_argument("--rounds", type=int, default=16)
    bp.add_argument("--batch", type=int, default=16)
    args = p.parse_args(argv)

    if args.op == "stop":
        request_stop(args.dir)
        print(json.dumps({"stop": True}))
        return 0
    try:
        if args.op == "bench":
            print(json.dumps(bench(args.dir, args.rounds, args.batch, args.timeout)))
            return 0
        payload: dict = {"op": args.op}
        if args.op == "suggest":
            payload["n"] = args.n
        else:
            try:
                payload["params"] = json.loads(args.params)
            except ValueError as e:
                p.error(f"--params must be JSON: {e}")
            payload["budget"] = args.budget
            if args.op == "report":
                payload["score"] = args.score
        ans = round_trip(args.dir, payload, timeout=args.timeout)
    except (TimeoutError, RuntimeError) as e:
        print(str(e), file=sys.stderr)
        return 1
    print(json.dumps(ans))
    return 0 if not ans.get("error") else 1
