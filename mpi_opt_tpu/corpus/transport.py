"""The HTTP front door's wire protocol + typed transport-fault funnel.

jax-free (it rides in every client, like service/spool's primitives).
One batched envelope carries MANY operations per HTTP request::

    POST /v1/batch   {"version": 1, "key": <idempotency key>,
                      "client": <caller id>, "digest": <ops sha256>,
                      "deadline_ts": <abs epoch s | null>,
                      "ops": [{"op": "suggest"|"report"|"lookup"|
                               "submit"|"status"|"cancel", ...}, ...]}
    -> 200           {"key": ..., "replayed": bool, "queue_wait_s": ...,
                      "results": [<one answer dict per op>, ...]}

Batching is the throughput lever the ROADMAP's front-door item names:
PR 14's file spool paid one request round trip per operation (46.6
suggestions/s measured, BENCH config 6) against a ~2176/s acquisition
ceiling; here a batch of reports shares one HTTP round trip AND one
journal fsync (service/http.py wraps the batch in
``SweepLedger.batched()``).

Overload answers are TYPED, mirroring ``utils/resources.py``'s funnel
discipline: a client distinguishes "the server ANSWERED (maybe with a
refusal)" from "the transport FAILED" by exception class, never by
string matching. The HTTP status mapping is fixed wire schema:

- 503 -> :class:`Overloaded` (admission queue full; honors Retry-After)
- 429 -> :class:`BreakerOpen` (per-client circuit breaker; Retry-After)
- 504 -> :class:`DeadlineExpired` (the batch aged past its deadline
  before execution — the server expired it instead of serving it late)
- 409 -> :class:`KeyConflict` (same idempotency key, DIFFERENT body:
  refused, never replayed — a retry must be byte-identical)
- 400 -> :class:`RequestRefused` (malformed envelope)
- connect/read failures, torn bodies -> :class:`Unreachable` /
  :class:`TornResponse`

``is_retryable`` walks the ``__cause__`` chain like
``resources.is_storage_full`` so wrapped faults classify like their
root cause. The chaos seam (``set_net_fault_injector`` /
``net_fault``) sits inside :class:`HttpTransport` exactly where a real
network would fail — workloads/chaos.py ``inject_net`` installs seeded
schedules of refused connections, torn responses and delayed replies.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Callable, Optional

WIRE_VERSION = 1
DEFAULT_TIMEOUT_S = 30.0


# -- typed faults ---------------------------------------------------------


class TransportFault(RuntimeError):
    """Base: the conversation with the server did not produce a usable
    answer. ``retryable`` says whether an idempotent retry can help;
    ``retry_after`` carries the server's Retry-After hint (seconds)
    when one was sent."""

    retryable = True

    def __init__(self, message: str, retry_after: Optional[float] = None):
        super().__init__(message)
        self.retry_after = retry_after


class Unreachable(TransportFault):
    """Connection refused / reset / DNS failure: no server answered.
    Retryable — the drill shape is 'server SIGKILLed, client retries
    to its restart'."""


class TornResponse(TransportFault):
    """The server (or the network) died mid-reply: short read, invalid
    JSON body. The request MAY have executed — which is exactly why
    every envelope carries an idempotency key: the retry is answered
    from the server's dedup window instead of re-executing."""


class Overloaded(TransportFault):
    """HTTP 503: the bounded admission queue shed this request. The
    server is alive and SAYING it is saturated — back off for
    ``retry_after`` and retry."""


class BreakerOpen(TransportFault):
    """HTTP 429: this client tripped the per-client circuit breaker
    (retry storm). Retryable only after the cooldown."""


class DeadlineExpired(TransportFault):
    """HTTP 504: the batch's deadline passed before execution; the
    server expired it instead of serving it late. NOT retryable — the
    answer would be just as late."""

    retryable = False


class RequestRefused(TransportFault):
    """HTTP 400: the envelope itself is malformed. A retry of the same
    bytes re-refuses."""

    retryable = False


class KeyConflict(RequestRefused):
    """HTTP 409: idempotency key reuse with a DIFFERENT body digest.
    The dedup window answers only byte-identical retries; anything else
    is a client bug surfaced loudly, never replayed."""


def is_retryable(e: BaseException) -> bool:
    """Can an idempotent retry of the same envelope help? Walks the
    explicit ``raise X from e`` cause chain (the resources.py
    discipline) so a wrapped fault classifies like its root cause."""
    depth = 0
    while isinstance(e, BaseException) and depth < 8:
        if isinstance(e, TransportFault):
            return e.retryable
        e = e.__cause__
        depth += 1
    return False


# -- envelope helpers -----------------------------------------------------


def make_key() -> str:
    """A client-generated idempotency key: 128 random bits. Generated
    ONCE per logical request and reused verbatim on every retry — the
    key identifies the intent, not the attempt."""
    return os.urandom(16).hex()


def ops_digest(ops: list) -> str:
    """The body fingerprint the server checks on key reuse: canonical
    JSON (sorted keys) so a semantically identical retry hashes
    identically regardless of dict construction order."""
    return hashlib.sha256(
        json.dumps(ops, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()


def envelope(
    ops: list,
    key: Optional[str] = None,
    client: Optional[str] = None,
    deadline_s: Optional[float] = None,
) -> dict:
    """Build one batched request envelope. ``deadline_s`` is relative
    seconds from now; the wire carries the ABSOLUTE ``deadline_ts`` so
    queue wait on the server side counts against it."""
    env = {
        "version": WIRE_VERSION,
        "key": key or make_key(),
        "client": client or f"pid-{os.getpid()}",
        "digest": ops_digest(ops),
        "deadline_ts": None if deadline_s is None else time.time() + deadline_s,
        "ops": list(ops),
    }
    return env


# -- chaos seam -----------------------------------------------------------
#
# Direct-call injector hook in the utils/resources.py style: a seeded
# schedule installed for a drill (workloads/chaos.py inject_net),
# uninstalled in a finally. Stages: "connect" (before the TCP connect),
# "send" (before the request body is written), "read" (before the
# response is read) — the three places a real network fails.

_NET_FAULTS: Optional[Callable[[str, str], None]] = None


def set_net_fault_injector(fn: Optional[Callable[[str, str], None]]) -> None:
    global _NET_FAULTS
    _NET_FAULTS = fn


def net_fault(stage: str, url: str) -> None:
    if _NET_FAULTS is not None:
        _NET_FAULTS(stage, url)


# -- the transport --------------------------------------------------------


class HttpTransport:
    """One server endpoint, stdlib ``http.client`` only. ``call`` POSTs
    a JSON payload and returns the decoded JSON answer or raises a
    typed fault; it never returns a half-answer."""

    def __init__(self, base_url: str, timeout: float = DEFAULT_TIMEOUT_S):
        from urllib.parse import urlparse

        u = urlparse(base_url)
        if u.scheme not in ("http", ""):
            raise ValueError(f"only http:// endpoints are supported, got {base_url!r}")
        if not u.hostname:
            raise ValueError(f"no host in url {base_url!r}")
        self.host = u.hostname
        self.port = u.port or 80
        self.timeout = timeout
        self.base_url = f"http://{self.host}:{self.port}"

    def call(self, path: str, payload: Optional[dict] = None, method: str = "POST") -> dict:
        import http.client

        url = f"{self.base_url}{path}"
        conn = http.client.HTTPConnection(self.host, self.port, timeout=self.timeout)
        try:
            try:
                net_fault("connect", url)
                conn.connect()
                net_fault("send", url)
                body = None if payload is None else json.dumps(payload).encode()
                headers = {"Content-Type": "application/json"} if body else {}
                conn.request(method, path, body=body, headers=headers)
            except TransportFault:
                raise
            except (ConnectionError, OSError) as e:
                raise Unreachable(f"{url}: {e}") from e
            try:
                net_fault("read", url)
                resp = conn.getresponse()
                status = resp.status
                retry_after = _parse_retry_after(resp.getheader("Retry-After"))
                raw = resp.read()
            except TransportFault:
                raise
            except (ConnectionError, OSError, http.client.HTTPException) as e:
                # the reply never arrived whole: the request MAY have
                # executed — the idempotency key makes the retry safe
                raise TornResponse(f"{url}: {e}") from e
        finally:
            conn.close()
        try:
            ans = json.loads(raw) if raw else {}
        except ValueError as e:
            raise TornResponse(f"{url}: invalid JSON body ({e})") from e
        if status == 200:
            return ans
        detail = (ans.get("error") or {}).get("detail") if isinstance(ans, dict) else None
        msg = f"{url}: HTTP {status}" + (f" ({detail})" if detail else "")
        if status == 503:
            raise Overloaded(msg, retry_after=retry_after)
        if status == 429:
            raise BreakerOpen(msg, retry_after=retry_after)
        if status == 504:
            raise DeadlineExpired(msg)
        if status == 409:
            raise KeyConflict(msg)
        if status in (400, 404, 405):
            raise RequestRefused(msg)
        # anything else (500s from a contained handler fault) is
        # transport-shaped: the answer is unusable, a retry may land on
        # a healthy code path or a restarted server
        raise TornResponse(msg)


def _parse_retry_after(value: Optional[str]) -> Optional[float]:
    if value is None:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        return None


def _jitter(key: str, attempt: int) -> float:
    """Deterministic jitter factor in [0.5, 1.5): seeded by (key,
    attempt) so retry storms from N clients decorrelate without any
    wall-clock or RNG dependence (same discipline as spool.retry_io's
    bounded backoff, but reproducible in drills)."""
    h = hashlib.sha256(f"retry:{key}:{attempt}".encode()).digest()
    return 0.5 + int.from_bytes(h[:8], "big") / 2**64


def call_with_retries(
    transport: HttpTransport,
    path: str,
    payload: dict,
    retries: int = 6,
    backoff_s: float = 0.05,
    max_backoff_s: float = 2.0,
    sleep=time.sleep,
) -> dict:
    """POST ``payload`` with capped jittered backoff on RETRYABLE
    transport faults, honoring Retry-After when the server sent one.
    The payload (and with it the idempotency key) is reused verbatim on
    every attempt — that is what makes the retry safe: a replay is
    answered from the server's dedup window, so reports journal exactly
    once no matter how many attempts the network cost. Non-retryable
    faults (DeadlineExpired, KeyConflict, RequestRefused) raise
    immediately."""
    key = str(payload.get("key") or "")
    attempt = 0
    while True:
        try:
            return transport.call(path, payload)
        except TransportFault as e:
            if not e.retryable or attempt >= retries:
                raise
            delay = min(backoff_s * (2**attempt), max_backoff_s) * _jitter(key, attempt)
            if e.retry_after is not None:
                delay = max(delay, e.retry_after)
            sleep(delay)
            attempt += 1
