"""Small CNN — the BASELINE config-3 model (CIFAR-10 scale PBT target)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def max_pool_2x2(x: jnp.ndarray) -> jnp.ndarray:
    """Non-overlapping 2x2/stride-2 max pool as reshape + reduce-max.

    Forward-identical to ``nn.max_pool(x, (2,2), strides=(2,2))`` (the
    windows don't overlap, so both are an exact max over the same
    disjoint 2x2 blocks), but the VJP is an elementwise equality mask
    instead of TPU's ``select-and-scatter`` — which a profiler trace of
    the population sweep measured at 8% of device time (PERF_NOTES.md
    "Trace-level breakdown"). The only numerical difference is tie
    handling in the gradient: reduce-max splits the cotangent evenly
    among tied window elements (common post-relu, where whole windows
    are exactly 0) where select-and-scatter sends it all to the first —
    both are valid subgradients.
    """
    b, h, w, c = x.shape
    if h % 2 or w % 2:
        raise ValueError(
            f"max_pool_2x2 needs even spatial dims, got {h}x{w} "
            "(nn.max_pool floors the window count; this exact-reshape "
            "variant deliberately does not)"
        )
    x = x.reshape(b, h // 2, 2, w // 2, 2, c)
    return x.max(axis=(2, 4))


class SmallCNN(nn.Module):
    """conv32-conv32-pool-conv64-conv64-pool-dense128-dense.

    GroupNorm keeps members stateless (see models package docstring);
    widths are MXU-friendly multiples.
    """

    n_classes: int = 10
    width: int = 32
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        w = self.width
        x = x.astype(self.dtype)
        for i, ch in enumerate((w, w, 2 * w, 2 * w)):
            x = nn.Conv(ch, (3, 3), padding="SAME", dtype=self.dtype, name=f"conv{i}")(x)
            x = nn.GroupNorm(num_groups=8, dtype=self.dtype, name=f"gn{i}")(x)
            x = nn.relu(x)
            if i % 2 == 1:
                x = max_pool_2x2(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(4 * w, dtype=self.dtype, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dense(self.n_classes, dtype=self.dtype, name="fc2")(x)
        return x.astype(jnp.float32)
