"""Small CNN — the BASELINE config-3 model (CIFAR-10 scale PBT target)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class SmallCNN(nn.Module):
    """conv32-conv32-pool-conv64-conv64-pool-dense128-dense.

    GroupNorm keeps members stateless (see models package docstring);
    widths are MXU-friendly multiples.
    """

    n_classes: int = 10
    width: int = 32
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        w = self.width
        x = x.astype(self.dtype)
        for i, ch in enumerate((w, w, 2 * w, 2 * w)):
            x = nn.Conv(ch, (3, 3), padding="SAME", dtype=self.dtype, name=f"conv{i}")(x)
            x = nn.GroupNorm(num_groups=8, dtype=self.dtype, name=f"gn{i}")(x)
            x = nn.relu(x)
            if i % 2 == 1:
                # nn.max_pool (select-and-scatter backward, ~8% of device
                # time) was A/B'd against a reshape+reduce-max variant
                # whose VJP is an elementwise tie-splitting mask: the
                # variant measured SLOWER (17.7 vs 15.6 s, pop=64 x 2
                # gens on the real chip) and learned far worse (best
                # 0.211 vs 0.548 at gen 2, seed 0) — bf16 ties make the
                # split gradient materially different. Refutation probe:
                # probes/probe_pool_ab.py; PERF_NOTES.md "Pooling".
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(4 * w, dtype=self.dtype, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dense(self.n_classes, dtype=self.dtype, name="fc2")(x)
        return x.astype(jnp.float32)
