"""Two-layer MLP — the BASELINE config-2 model (Fashion-MNIST scale)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    hidden: int = 128
    n_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        x = nn.Dense(self.hidden, dtype=self.dtype, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dense(self.n_classes, dtype=self.dtype, name="fc2")(x)
        return x.astype(jnp.float32)
