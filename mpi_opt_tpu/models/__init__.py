"""Model zoo (SURVEY.md §2 row 10): flax modules for the NN workloads.

All models follow TPU conventions: bfloat16 activations with float32
params and float32 logits/loss, channel-last layouts, GroupNorm instead
of BatchNorm (no mutable batch statistics — population members must be
pure pytrees so exploit/explore is a gather, and XLA fuses GN into the
surrounding ops).
"""

from mpi_opt_tpu.models.mlp import MLP
from mpi_opt_tpu.models.cnn import SmallCNN
from mpi_opt_tpu.models.resnet import BasicBlock, ResNet, ResNet18

__all__ = ["MLP", "SmallCNN", "BasicBlock", "ResNet", "ResNet18"]
