"""ResNet-18 — the BASELINE config-5 model (PBT pop=1024, CIFAR-100).

CIFAR-style ResNet (3x3 stem, no max-pool, 4 stages of basic blocks),
following the models-package conventions: GroupNorm (stateless members;
exploit/explore stays a pure gather), bf16 compute with f32 params and
f32 logits, channel-last.

Population memory math (why config 5 is a multi-chip/chunked config):
full ResNet-18 is ~11.2M params. Per member, params + SGD momentum in
f32 = ~90 MB; pop=1024 of those is ~92 GB — an order of magnitude over
one v5e chip's 16 GB HBM, which is why BASELINE.json puts config 5 on a
v4-32 (32 chips). On a mesh the population axis shards it: 1024/32
members per chip = ~2.9 GB resident, comfortable. Single-chip runs cap
the population (~128 members = 11.5 GB resident) and bound *activation*
memory with ``member_chunk`` (the trainer lax.map's members in chunks).
``remat`` rematerializes block activations in the backward pass
(activations drop from every conv output to block boundaries, ~8x, for
~33% more FLOPs). Round-5 measurement: at the measured single-chip
envelope (pop=64, member_chunk=8, batch 128) the stored-backward
activations FIT, and remat=False is 18% faster per segment — so remat
is a knob for heavier per-chip loads, not the default (PERF_NOTES
round 5).

Measured on this container's v5e-class chip (2026-07-29, batch 128,
member_chunk=8, remat on, train_segment donating its input state):
pop=64 trains at ~158 member-steps/s and sweeps end-to-end under fused
PBT; pop>=96 fails at compile time in the axon remote compiler. Without
donation even pop=64 OOMs (old + new population state resident at once
is 2 x 5.75 GB before activations).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class PallasGN(nn.Module):
    """GroupNorm(+optional fused ReLU) through the Pallas kernel
    (ops/pallas_gn.py). Param names/shapes match ``nn.GroupNorm``
    (``scale``/``bias``), so the two variants' population states are
    interchangeable; stats run in f32 either way."""

    num_groups: int
    relu: bool = False
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from mpi_opt_tpu.ops.pallas_gn import group_norm_relu

        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        return group_norm_relu(
            x.astype(self.dtype), scale, bias, self.num_groups, 1e-6, self.relu
        )


class BasicBlock(nn.Module):
    """Two 3x3 convs + identity/projection shortcut."""

    channels: int
    stride: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    pallas_gn: bool = False

    @nn.compact
    def __call__(self, x):
        # 32 groups at full width; small test widths shrink the count
        groups = min(32, self.channels)
        if self.pallas_gn:
            gn = lambda name, relu=False: PallasGN(
                num_groups=groups, relu=relu, dtype=self.dtype, name=name
            )
            gn_relu = lambda name: gn(name, relu=True)
        else:
            gn = lambda name: nn.GroupNorm(
                num_groups=groups, dtype=self.dtype, name=name
            )
            gn_relu = lambda name: (lambda v: nn.relu(gn(name)(v)))
        y = nn.Conv(
            self.channels, (3, 3), strides=(self.stride, self.stride),
            padding="SAME", use_bias=False, dtype=self.dtype, name="conv1",
        )(x)
        y = gn_relu("gn1")(y)
        y = nn.Conv(
            self.channels, (3, 3), padding="SAME", use_bias=False,
            dtype=self.dtype, name="conv2",
        )(y)
        y = gn("gn2")(y)
        if x.shape[-1] != self.channels or self.stride != 1:
            x = nn.Conv(
                self.channels, (1, 1), strides=(self.stride, self.stride),
                use_bias=False, dtype=self.dtype, name="proj",
            )(x)
            x = gn("gn_proj")(x)
        return nn.relu(x + y)


class ResNet(nn.Module):
    """CIFAR-style ResNet; ResNet-18 = stage_sizes (2, 2, 2, 2).

    ``width`` scales all stage channels (64*width at the stem); tests use
    small widths/stages for CPU speed without changing program structure.
    """

    n_classes: int = 100
    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    remat: bool = False
    pallas_gn: bool = False

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.width, (3, 3), padding="SAME", use_bias=False,
            dtype=self.dtype, name="stem",
        )(x)
        if self.pallas_gn:
            x = PallasGN(
                num_groups=min(32, self.width), relu=True, dtype=self.dtype,
                name="gn_stem",
            )(x)
        else:
            x = nn.relu(
                nn.GroupNorm(num_groups=min(32, self.width), dtype=self.dtype, name="gn_stem")(x)
            )
        block_cls = nn.remat(BasicBlock) if self.remat else BasicBlock
        for stage, n_blocks in enumerate(self.stage_sizes):
            channels = self.width * (2**stage)
            for b in range(n_blocks):
                stride = 2 if stage > 0 and b == 0 else 1
                x = block_cls(
                    channels=channels, stride=stride, dtype=self.dtype,
                    pallas_gn=self.pallas_gn, name=f"stage{stage}_block{b}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.n_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


def ResNet18(
    n_classes: int = 100, width: int = 64, remat: bool = False,
    pallas_gn: bool = False,
) -> ResNet:
    return ResNet(
        n_classes=n_classes, stage_sizes=(2, 2, 2, 2), width=width, remat=remat,
        pallas_gn=pallas_gn,
    )
