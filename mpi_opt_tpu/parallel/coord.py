"""Boundary-agreement control plane for multi-process SPMD (ISSUE 20).

The one fault-tolerance problem exit codes, heartbeats, and snapshots
cannot solve alone: under multi-process SPMD every rank runs the same
host program, but the DECISIONS that change that program's trajectory
arrive on ONE rank — the platform's SIGTERM lands on one process, a
device OOM raises in one process's wave loop, a stall verdict forms in
one watchdog. A rank that acts on such a decision alone (drains at its
next boundary, halves its wave cap) issues different collectives than
its peers and wedges the mesh forever; the reference's MPI world has
``MPI_Allreduce`` for exactly this. This module is the filesystem
equivalent: a vote/decide barrier at every launch/rung/generation
boundary, built from the same atomic primitives as the fleet spool
(``service/spool.py``: O_EXCL fsync'd creates, tmp+rename JSON,
transient-I/O retry — the tomb-protocol toolbox), so every
rank-divergent decision becomes unanimous BEFORE the next collective.

Protocol (per agreement kind, per boundary ordinal):

1. every rank atomically creates its vote file
   (``<kind>.<seq>.r<rank>.vote.json``, O_EXCL — a lost race is a
   protocol error, not a retry);
2. rank 0 polls until all ``world`` votes exist, reduces them with the
   call site's pure ``decide(votes)`` function, and publishes the
   decision file (``<kind>.<seq>.decision.json``, O_EXCL — duplicate
   publication after a crash is benign: the first file wins and is
   what everyone reads);
3. every rank polls until the decision exists and returns it.

Because SPMD ranks execute identical host code, the sequence of
``agree`` calls per kind is identical on every rank — the per-kind
ordinal IS the barrier identity, no clocks involved. A rank that dies
between boundaries leaves its peers waiting in step 1/3; the waiters'
heartbeats freeze in the boundary phase, which is precisely the shape
``launch.py``'s supervisor classifies as a collective wedge (dead rank
+ survivors frozen in ``train``/``boundary:*``) and escalates. As a
belt-and-suspenders local verdict, waits are bounded by ``timeout_s``
and raise :class:`CoordWedged` (the in-rank stall verdict) so an
unsupervised job cannot hang forever.

Epoching: one plane instance namespaces all its files under
``<root>/e<epoch>/``. ``launch.py`` passes a fresh ``--coord-epoch``
per attempt (its relaunch counter), so a restarted job can never read
the killed attempt's stale votes. Reusing an epoch directory is
refused at bring-up (rank 0 finds leftover files) — wiping it in place
would race peers reading the previous attempt's READY marker.

The agreement file surface is write-exclusive to this module: the
``coord-write`` sweeplint checker flags vote/decision/coord-path
writes anywhere else, the same way ``lease-write`` fences the lease
protocol.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

from mpi_opt_tpu.service.spool import _read_json, excl_write_json, retry_io

#: marker rank 0 publishes once its epoch directory is ready; peers
#: wait for it before voting so they can never observe a half-created
#: control plane
_READY = "READY.json"


class CoordError(RuntimeError):
    """Control-plane protocol violation (reused epoch dir, duplicate
    vote) — deterministic misuse, not weather."""


class CoordWedged(CoordError):
    """The in-rank stall verdict: an agreement wait exceeded the
    plane's timeout, meaning at least one peer never reached the
    boundary (dead, or wedged in a collective). The caller's process
    should exit and let the supervisor's coordinated restart recover —
    restarting alone would desynchronize the world further."""


def _decide_drain(votes: list) -> dict:
    """Drain iff ANY rank saw a shutdown request; carry the first real
    signal name so every rank's SweepInterrupted reports the same
    cause."""
    drain = any(v.get("drain") for v in votes)
    signal = None
    for v in votes:
        if v.get("drain") and v.get("signal"):
            signal = v["signal"]
            break
    return {"drain": drain, "signal": signal}


def _decide_min_cap(votes: list) -> dict:
    """The most constrained rank wins: min over positive proposed caps
    (0 = "no local constraint" — an OOM-free rank's vote)."""
    caps = [int(v.get("cap", 0)) for v in votes]
    positive = [c for c in caps if c > 0]
    return {"cap": min(positive) if positive else 0}


class CoordPlane:
    """One rank's handle on the shared agreement directory.

    ``root`` is shared by all ranks (under the run/log dir); ``rank``/
    ``world`` come from ``jax.process_index()``/``process_count()``;
    ``epoch`` namespaces one job attempt. ``timeout_s`` bounds every
    wait (the local wedge verdict); ``poll_s`` is the vote/decision
    poll interval — agreement happens at launch/rung/generation
    boundaries (seconds to minutes apart), so a coarse poll costs
    nothing and keeps a shared filesystem calm.
    """

    def __init__(
        self,
        root: str,
        rank: int,
        world: int,
        *,
        epoch: int = 0,
        timeout_s: float = 300.0,
        poll_s: float = 0.01,
    ):
        if not 0 <= rank < world:
            raise CoordError(f"rank {rank} outside world of {world}")
        self.root = os.path.abspath(root)
        self.rank = int(rank)
        self.world = int(world)
        self.epoch = int(epoch)
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s)
        self.dir = os.path.join(self.root, f"e{self.epoch:04d}")
        self._seq: dict = {}
        #: set once a drain decision came back affirmative: the gate
        #: ``train.common.launch_boundary`` consults before honoring a
        #: LOCALLY-seen shutdown request (an unagreed drain must wait
        #: for the next boundary's vote, or ranks drain split)
        self.drain_agreed = False
        self._ready()

    # -- bring-up --------------------------------------------------------

    def _ready(self) -> None:
        ready = os.path.join(self.dir, _READY)
        if self.rank == 0:
            retry_io(lambda: os.makedirs(self.dir, exist_ok=True))
            leftovers = [f for f in os.listdir(self.dir) if f != _READY]
            if leftovers or os.path.exists(ready):
                # wiping in place would race peers still reading the
                # previous attempt's READY — epochs are single-use
                raise CoordError(
                    f"coord epoch dir {self.dir} already holds "
                    f"{len(leftovers) or 1} file(s) from a previous "
                    "attempt; pass a fresh --coord-epoch (launch.py "
                    "does this per relaunch) or a clean --coord-dir"
                )
            excl_write_json(ready, {"world": self.world, "epoch": self.epoch})
            return
        self._wait(
            lambda: os.path.exists(ready),
            what=f"rank 0's {_READY} in {self.dir}",
        )
        rec = _read_json(ready) or {}
        if rec.get("world") not in (None, self.world):
            raise CoordError(
                f"coord world mismatch: rank 0 announced "
                f"{rec.get('world')} ranks, this rank was launched "
                f"into a world of {self.world}"
            )

    # -- the vote/decide barrier ----------------------------------------

    def _wait(self, done: Callable[[], bool], what: str):
        deadline = time.monotonic() + self.timeout_s
        while True:
            if done():
                return
            if time.monotonic() >= deadline:
                from mpi_opt_tpu.utils import resources

                resources.notify(
                    "rank_wedge",
                    rank=self.rank,
                    world=self.world,
                    epoch=self.epoch,
                    waited_s=round(self.timeout_s, 3),
                    waiting_for=what,
                )
                raise CoordWedged(
                    f"rank {self.rank}: no {what} after "
                    f"{self.timeout_s}s — a peer died or wedged before "
                    "this boundary; exiting for a coordinated restart"
                )
            time.sleep(self.poll_s)

    def _vote_path(self, kind: str, seq: int, rank: int) -> str:
        return os.path.join(self.dir, f"{kind}.{seq:06d}.r{rank}.vote.json")

    def _decision_path(self, kind: str, seq: int) -> str:
        return os.path.join(self.dir, f"{kind}.{seq:06d}.decision.json")

    def agree(self, kind: str, vote: dict, decide: Callable[[list], dict]) -> dict:
        """One barrier: publish this rank's ``vote``, have rank 0 reduce
        all ``world`` votes with ``decide`` (a pure function every rank
        links identically — only rank 0 runs it), and return the
        published decision. Blocks until unanimity or ``timeout_s``."""
        seq = self._seq.get(kind, 0)
        self._seq[kind] = seq + 1
        if not excl_write_json(self._vote_path(kind, seq, self.rank), vote):
            raise CoordError(
                f"duplicate vote for {kind}#{seq} by rank {self.rank} — "
                "two planes sharing one (dir, epoch, rank) identity"
            )
        decision_path = self._decision_path(kind, seq)
        if self.rank == 0:
            peer_paths = [
                self._vote_path(kind, seq, r) for r in range(self.world)
            ]
            self._wait(
                lambda: all(os.path.exists(p) for p in peer_paths),
                what=f"all {self.world} votes for {kind}#{seq}",
            )
            votes = []
            for p in peer_paths:
                rec = _read_json(p)
                if rec is None:
                    # exists-but-unparseable: O_EXCL writes are fsync'd
                    # before visibility on a local fs, but a shared one
                    # may expose the name first — re-read briefly
                    self._wait(
                        lambda p=p: _read_json(p) is not None,
                        what=f"readable vote {os.path.basename(p)}",
                    )
                    rec = _read_json(p)
                votes.append(rec or {})
            # duplicate publication (crash between publish and use, or
            # a re-entered epoch) concedes to the first file — what
            # every peer already read
            excl_write_json(decision_path, decide(votes))
        self._wait(
            lambda: _read_json(decision_path) is not None,
            what=f"rank 0's decision for {kind}#{seq}",
        )
        return _read_json(decision_path) or {}

    # -- the three decision kinds ----------------------------------------

    def boundary_tick(self, stage: str) -> None:
        """The per-boundary drain agreement — installed as (chained
        onto) the shutdown slice hook, so every non-final
        ``launch_boundary`` runs one barrier: each rank votes whether
        IT has seen a shutdown request; if any has, every rank raises
        its own drain flag at THIS boundary and all drain together.

        May raise :class:`CoordWedged` (the sanctioned slice-hook
        exception): a peer that never arrives IS the wedge this plane
        exists to bound.
        """
        from mpi_opt_tpu.health import shutdown
        from mpi_opt_tpu.utils import resources

        vote = {
            "drain": bool(shutdown.requested()),
            "signal": shutdown.active_signal(),
            "stage": str(stage),
        }
        decision = self.agree("drain", vote, _decide_drain)
        if not decision.get("drain"):
            return
        if not self.drain_agreed:
            self.drain_agreed = True
            resources.notify(
                "rank_agreed",
                kind="drain",
                rank=self.rank,
                boundary=self._seq["drain"],
                signal=decision.get("signal"),
                stage=str(stage),
            )
        # peers that never saw the signal adopt the agreed cause; the
        # rank that did already holds it (request never overwrites a
        # real signal name)
        shutdown.request(source=decision.get("signal") or "SIGTERM")

    def agree_cap(self, kind: str, cap: int) -> int:
        """Min-reduce a proposed wave cap (``wave_cap`` at sizing time,
        ``oom`` per absorbed backoff). 0 votes "no local constraint";
        returns 0 only when NO rank proposed one."""
        from mpi_opt_tpu.utils import resources

        decision = self.agree(kind, {"cap": int(cap)}, _decide_min_cap)
        agreed = int(decision.get("cap", 0))
        if agreed:
            resources.notify(
                "rank_agreed",
                kind=kind,
                rank=self.rank,
                boundary=self._seq[kind],
                cap=agreed,
            )
        return agreed


# -- process-wide plane + hook wiring ---------------------------------------
#
# The CLI activates ONE plane per run; the engine's sizing door and OOM
# backoff consult it through ``active_plane()`` (they have no argument
# path that every adapter threads), and ``install_hook`` chains the
# drain agreement onto the shutdown slice hook the boundaries already
# poll.

_ACTIVE: Optional[CoordPlane] = None


def activate(plane: Optional[CoordPlane]) -> None:
    global _ACTIVE
    _ACTIVE = plane


def deactivate() -> None:
    activate(None)


def active_plane() -> Optional[CoordPlane]:
    return _ACTIVE


def drain_allowed() -> bool:
    """May a locally-seen shutdown request drain at THIS boundary?
    Always, without a plane (single-process: local IS global); with one,
    only after a drain decision — ``launch_boundary`` consults this so
    a signal that lands mid-boundary on one rank waits for the next
    boundary's vote instead of splitting the world."""
    return _ACTIVE is None or _ACTIVE.drain_agreed


def install_hook(plane: CoordPlane) -> Callable[[], None]:
    """Activate ``plane`` and chain its ``boundary_tick`` onto the
    shutdown slice hook (the service scheduler's hook, when installed,
    keeps running first — its slice request then rides the SAME
    boundary's vote). Returns an uninstall closure that restores the
    prior hook and deactivates the plane — callers pair it in a
    ``finally``."""
    from mpi_opt_tpu.health import shutdown

    prev = shutdown.get_slice_hook()

    def _tick(stage: str) -> None:
        if prev is not None:
            prev(stage)
        plane.boundary_tick(stage)

    activate(plane)
    shutdown.set_slice_hook(_tick)

    def uninstall() -> None:
        shutdown.set_slice_hook(prev)
        deactivate()

    return uninstall


def reset_dir(root: str) -> None:
    """Remove every epoch's agreement files under ``root`` (the
    supervisor's between-JOBS cleanup; between attempts it advances
    ``--coord-epoch`` instead — an in-place wipe would race live
    readers). Lives here so the agreement file surface keeps exactly
    one writer module (the ``coord-write`` fence)."""
    import shutil

    try:
        shutil.rmtree(root)
    except FileNotFoundError:
        pass
