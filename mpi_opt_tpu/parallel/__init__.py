"""Distributed layer: device meshes, shardings, multi-host bring-up."""

from mpi_opt_tpu.parallel.mesh import (
    make_mesh,
    pop_sharding,
    replicate,
    shard_popstate,
    initialize_multihost,
)

__all__ = [
    "make_mesh",
    "pop_sharding",
    "replicate",
    "shard_popstate",
    "initialize_multihost",
]
