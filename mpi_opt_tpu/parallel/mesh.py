"""Device mesh + sharding layer (SURVEY.md §2 row 9, §5).

This is the TPU-native replacement for the reference's communication
backend (MPI collectives over ranks; SURVEY.md attests MPI_Allgather for
PBT/ASHA decisions — reference unreadable, contract from BASELINE.json).

Design:

- Mesh axes ``('pop', 'data')``. Trial/population parallelism shards the
  leading member axis over ``pop``; data parallelism *within* a member
  (config 5, ResNet-scale) shards the batch over ``data``.
- Population training needs **no hand-written collectives at all**: the
  members are independent, so sharding the inputs over ``pop`` lets
  XLA's SPMD partitioner run each shard's members locally — the
  reference's rank-parallel trial evaluation becomes a layout, not a
  protocol. With the batch sharded over ``data`` and params replicated
  across it, the partitioner inserts the gradient ``psum`` over ICI on
  its own — the all-reduce the reference delegates to MPI.
- PBT exploit/explore and ASHA cuts operate on [P]-scores and gather
  along the member axis; over a sharded population XLA lowers these to
  ``all_gather``/``all_to_all`` over ICI (cross-slice traffic rides DCN
  if the mesh spans hosts). No code change versus single-chip.
- Multi-host: ``initialize_multihost`` wraps ``jax.distributed``;
  ``make_mesh`` then spans all processes' devices (the way an mpirun
  world spans ranks).
"""

from __future__ import annotations

import warnings
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _warn_replicated(n: int, n_pop: int) -> None:
    """One runtime signal for the replication fallback: silent
    correctness-preserving replication turns an intended N-device sweep
    into an effectively single-device one, which a user should learn
    from a warning, not from a profile."""
    lo, hi = (n // n_pop) * n_pop, -(-n // n_pop) * n_pop
    hint = f"e.g. {hi}" if lo == 0 else f"e.g. {lo} or {hi}"
    warnings.warn(
        f"population axis of size {n} does not divide the mesh 'pop' axis "
        f"({n_pop}); the array is replicated on every device instead of "
        f"sharded — correct, but not member-parallel. Use a population "
        f"that is a multiple of {n_pop} ({hint}).",
        RuntimeWarning,
        stacklevel=3,
    )


def make_mesh(
    n_pop: Optional[int] = None,
    n_data: int = 1,
    devices: Optional[list] = None,
) -> Mesh:
    """Create a ``('pop', 'data')`` mesh over the available devices.

    ``n_pop`` defaults to ``len(devices) // n_data``. Device order keeps
    the ``data`` axis innermost so its gradient psum rides neighboring
    ICI links (the highest-traffic collective gets the shortest hops).
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_pop is None:
        if len(devices) % n_data:
            raise ValueError(f"{len(devices)} devices not divisible by n_data={n_data}")
        n_pop = len(devices) // n_data
    need = n_pop * n_data
    if need > len(devices):
        raise ValueError(f"mesh {n_pop}x{n_data} needs {need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(n_pop, n_data)
    return Mesh(grid, axis_names=("pop", "data"))


def pop_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for arrays with a leading population/member axis."""
    return NamedSharding(mesh, P("pop"))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_popstate(state: Any, mesh: Mesh) -> Any:
    """Place a PopState (or any pytree with leading member axes) so the
    member axis is sharded over ``pop`` and everything else replicated
    across ``data``.

    Leaves whose member axis does not divide the ``pop`` axis replicate
    instead (XLA's device_put rejects uneven shards): correct, just not
    member-parallel — this happens for e.g. an SHA first cohort of 9
    trials on an 8-way mesh, whose later (rounded) rungs shard fully.
    The fallback WARNS (once per distinct size, via the warnings
    module's dedup) so it can't silently serialize a sweep.
    """
    n_pop = mesh.shape["pop"]
    bad = sorted({l.shape[0] for l in jax.tree.leaves(state) if l.shape[0] % n_pop})
    for n in bad:
        _warn_replicated(n, n_pop)
    return jax.tree.map(lambda x: place_pop(x, mesh, _warn=False), state)


def place_pop(x: jax.Array, mesh: Mesh, _warn: bool = True) -> jax.Array:
    """Place one array's leading axis over ``pop`` (replicates, with a
    warning, when the axis does not divide — see ``shard_popstate``)."""
    if x.shape[0] % mesh.shape["pop"] == 0:
        return jax.device_put(x, pop_sharding(mesh))
    if _warn:
        _warn_replicated(x.shape[0], mesh.shape["pop"])
    return jax.device_put(x, replicate(mesh))


def constrain_pop(tree: Any, mesh: Optional[Mesh]) -> Any:
    """Sharding *constraint* over ``pop`` on every leaf's leading axis.

    The in-jit counterpart of ``shard_popstate`` (device_put is a
    host-side placement; inside a traced computation the layout is
    requested with ``with_sharding_constraint`` and the SPMD partitioner
    obliges). Used where population state is *created inside* a fused
    program — e.g. fused TPE initializes each generation's fresh cohort
    on-device — so the members land sharded instead of wherever
    propagation guesses. No-op without a mesh; non-dividing member axes
    (a TPE tail generation) are left to the partitioner's choice.
    """
    if mesh is None:
        return tree
    n_pop = mesh.shape["pop"]
    sh = pop_sharding(mesh)
    return jax.tree.map(
        lambda x: jax.lax.with_sharding_constraint(x, sh)
        if x.shape[0] % n_pop == 0
        else x,
        tree,
    )


def spans_processes(mesh: Mesh) -> bool:
    """Does this mesh place shards on devices owned by OTHER processes?
    The staging layer branches on this: a host-local mesh stages waves
    with plain ``device_put`` (which rejects non-addressable targets),
    a process-spanning one must assemble global arrays per shard."""
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def shard_popstate_global(state: Any, mesh: Mesh) -> Any:
    """Process-spanning twin of ``shard_popstate``: place a host pytree
    (every process holds the FULL host copy — SPMD ranks derive
    identical pools from identical code) so the member axis shards over
    ``pop`` across ALL processes' devices.

    ``jax.device_put`` cannot target non-addressable devices, so each
    leaf is assembled with ``jax.make_array_from_callback``: every
    process contributes only the index-slices its local devices own,
    read out of its full host copy — no cross-host data movement at
    all, which is exactly the MPI world's "each rank stages its own
    shard". Non-dividing member axes replicate with the standard
    warning, same contract as the host-local path.
    """
    n_pop = mesh.shape["pop"]
    bad = sorted({l.shape[0] for l in jax.tree.leaves(state) if l.shape[0] % n_pop})
    for n in bad:
        _warn_replicated(n, n_pop)

    def _place(x):
        x = np.asarray(x)
        sh = pop_sharding(mesh) if x.shape[0] % n_pop == 0 else replicate(mesh)
        return jax.make_array_from_callback(x.shape, sh, lambda idx: x[idx])

    return jax.tree.map(_place, state)


def fetch_global(x) -> np.ndarray:
    """Host copy of a possibly multi-process-sharded array.

    ``np.asarray`` works only on fully-addressable arrays, so it breaks
    the moment a fused sweep's mesh spans OS processes (config 5's
    v4-32 topology is multi-HOST: every process runs the same host
    ledger code and needs the same global values). Three cases:
    single-process arrays fetch directly; a fully-replicated
    multi-process output is read from any local shard; an
    actually-sharded one is assembled with ``process_allgather`` (a
    collective — every process must reach this call, which holds
    because SPMD processes execute identical host code).
    """
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        if x.sharding.is_fully_replicated:
            return np.asarray(x.addressable_shards[0].data)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def fetch_global_batched(arrays) -> list:
    """Host copies of many arrays with ONE transfer when possible.

    The deferred-barrier pattern (fused SHA's rung ledger, fused TPE's
    curve) accumulates device values and flushes once — but flushing
    with per-array fetches still pays one round trip each, which
    measured no better than not deferring at all. Fully-addressable
    sets batch through a single ``jax.device_get``; process-spanning
    sets fall back to per-array ``fetch_global`` (collective order must
    stay identical across processes).
    """
    arrays = list(arrays)
    if all(not isinstance(x, jax.Array) or x.is_fully_addressable for x in arrays):
        return list(jax.device_get(arrays))
    return [fetch_global(x) for x in arrays]


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    require: bool = False,
) -> int:
    """Bring up the multi-host runtime (config 5: v4-32-scale sweeps).

    Mirrors the role of ``mpirun`` + ``MPI_Init`` in the reference: after
    this, ``jax.devices()`` spans every host's chips and the same mesh
    code scales out. Arguments default to cluster auto-detection (TPU
    pod metadata); returns the process index.

    MUST be called before any other JAX operation — even
    ``jax.process_count()`` initializes the XLA backend, after which
    distributed bring-up is impossible (jax raises). Therefore no
    pre-checks here: we attempt initialization directly and only
    swallow the failure when the caller did not explicitly require a
    multi-process world (single-process runs, this container).
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    try:
        jax.distributed.initialize(**kwargs)
    except (ValueError, RuntimeError):
        # an explicit multi-host request must not silently shrink.
        # ``require`` covers the auto-detect form (CLI --multihost on a
        # box with no pod metadata): the user asked for a multi-process
        # world, so a failed bring-up is an error, not a fallback.
        if require or coordinator_address is not None or num_processes not in (None, 1):
            raise
    return jax.process_index()
