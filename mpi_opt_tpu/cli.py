"""CLI + config system (SURVEY.md §2 row 1).

Reference contract (BASELINE.json north_star): named algorithm
selection, ``--backend=tpu`` opt-in with the CPU path as default,
population/trial counts, workload selection.

Example (config 1, the minimum end-to-end slice):
    python -m mpi_opt_tpu --workload digits --algorithm random \
        --trials 16 --budget 100 --backend cpu --workers 4
"""

from __future__ import annotations

import argparse
import json
import sys

from mpi_opt_tpu.algorithms import ALGORITHMS, get_algorithm
from mpi_opt_tpu.backends import available_backends, get_backend
from mpi_opt_tpu.driver import run_search
from mpi_opt_tpu.health import SweepInterrupted
from mpi_opt_tpu.health import heartbeat as _heartbeat
from mpi_opt_tpu.health import shutdown as _shutdown
from mpi_opt_tpu.obs import trace as _trace
from mpi_opt_tpu.ops.pbt import PBTConfig
from mpi_opt_tpu.utils import integrity, resources
from mpi_opt_tpu.utils.exitcodes import EX_DATAERR, EX_IOERR, EX_TEMPFAIL
from mpi_opt_tpu.utils.integrity import NoVerifiedSnapshotError
from mpi_opt_tpu.utils.metrics import stdout_logger
from mpi_opt_tpu.workloads import available, get_workload


def _wire_integrity_observer(metrics):
    """Route snapshot-corruption events (utils/integrity.py) into this
    run's metrics stream: each ``snapshot_corrupt`` becomes a logged
    event plus one tick of the ``snapshots_quarantined`` counter. The
    observer is process-global (fused trainers build checkpointers deep
    inside the sweep, far from any metrics handle); main() clears it on
    the way out so in-process callers see no residue."""

    def observe(event, **fields):
        metrics.log(event, **fields)
        if event == "snapshot_corrupt":
            metrics.count_quarantined()

    integrity.set_observer(observe)


def _wire_resource_observer(metrics):
    """Route resource-exhaustion events (utils/resources.py) into this
    run's metrics stream: oom_backoff / wave_resized / snapshot_pruned
    become logged events plus their summary counters. Process-global
    like the integrity observer (the wave scheduler and checkpoint
    layer run deep inside fused sweeps, far from any metrics handle);
    main() clears it on the way out."""

    def observe(event, **fields):
        metrics.log(event, **fields)
        if event == "oom_backoff":
            metrics.count_oom_backoffs()
        elif event == "wave_resized":
            metrics.count_wave_resized()
        elif event == "snapshot_pruned":
            metrics.count_pruned()

    resources.set_observer(observe)


def _resource_exit(e, metrics, kind: str, **summary_fields) -> int:
    """The resource-exhaustion park (utils/resources.py): a device OOM
    with no wave left to halve, or a disk still full after the one
    retention-prune retry. Durable state is INTACT (unlike exit 65 —
    the failed write never landed and the newest verified step was
    never touched), but a retry without operator action re-fails
    identically — so exit EX_IOERR (74): launch.py aborts with
    diagnostics, budget untouched; the service scheduler PARKS the
    tenant, and freeing the resource + ``--resume`` recovers."""
    metrics.summary(final=True)
    print(json.dumps({"resource_exhausted": str(e), "kind": kind, **summary_fields}))
    hint = (
        "free disk space, then relaunch with --resume"
        if kind == "storage_full"
        else "reduce residency: --wave-size auto (wave mode backs off "
        "automatically via --oom-backoff), smaller --population, or "
        "--member-chunk"
    )
    print(f"{e}\n({hint}; exit {EX_IOERR})", file=sys.stderr)
    return EX_IOERR


def _data_error_exit(e, metrics, **summary_fields) -> int:
    """The corruption-dead-end exit: no verified snapshot remains, so a
    retry would re-read the same poisoned state. Summarize, print the
    single-JSON-line shape, and exit EX_DATAERR (65) — the code
    launch.py classifies as NON-retryable (abort with diagnostics
    instead of burning the restart budget)."""
    metrics.summary(final=True)
    print(json.dumps({"data_error": str(e), **summary_fields}))
    print(
        f"{e}\n(no retry can help: exit {EX_DATAERR})",
        file=sys.stderr,
    )
    return EX_DATAERR


def wire_compile_cache() -> bool:
    """ROADMAP's "kill warmup" lever: point jax's persistent compilation
    cache at ``$MPI_OPT_TPU_CACHE_DIR`` so repeat sweeps, supervisor
    restarts, and every service tenant whose programs were ever
    compiled on this machine skip XLA compilation entirely (the
    140–210 s warmup measured in BENCH_r01–r05 becomes a disk read).

    Called BEFORE backend init on every sweep path (and inherited by
    launch.py's rank processes via their environment). Opt-in by env
    var because cache artifacts carry machine features: a shared dir
    crossing machines trips mismatch errors (PERF_NOTES round 4) — the
    CPU pool workers' separate ``MPI_OPT_TPU_CPU_CACHE_DIR`` default
    (backends/cpu.py) stays platform-split for the same reason."""
    import os

    cache = os.environ.get("MPI_OPT_TPU_CACHE_DIR")
    if not cache:
        return False
    import jax

    jax.config.update("jax_compilation_cache_dir", cache)
    return True


def pin_platform(platform, local_devices, error) -> None:
    """Validate and apply the pre-backend-init platform pin — the ONE
    implementation for the flat CLI and ``serve`` bring-up (``error`` is
    ``parser.error``-shaped: prints usage and exits 2). Must run before
    anything touches the XLA backend."""
    if platform is None and local_devices is None:
        return
    if local_devices is not None:
        if platform != "cpu":
            error("--local-devices requires --platform cpu")
        if local_devices < 1:
            error(f"--local-devices must be >= 1, got {local_devices}")
    import jax

    try:
        jax.config.update("jax_platforms", platform)
        if local_devices is not None:
            from mpi_opt_tpu.utils.hostdev import request_cpu_devices

            request_cpu_devices(local_devices)
    except RuntimeError as e:
        error(
            f"--platform/--local-devices must be set before any JAX "
            f"use in this process: {e}"
        )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi_opt_tpu",
        description="TPU-native hyperparameter optimization",
    )
    p.add_argument("--workload", required=True, choices=available())
    p.add_argument("--algorithm", default="random", choices=sorted(ALGORITHMS))
    p.add_argument(
        "--backend",
        default="cpu",
        choices=available_backends(),
        help="execution backend (cpu is the default path; tpu is opt-in)",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--trials", type=int, default=16, help="total trials (random/tpe/asha)")
    p.add_argument("--budget", type=int, default=100, help="steps per trial (random/tpe)")
    p.add_argument("--workers", type=int, default=0, help="cpu backend: processes (0=auto)")
    p.add_argument("--metrics-file", default=None, help="JSONL metrics output path")
    # durable sweep ledger (ledger/ package; see README: sweep ledger)
    p.add_argument(
        "--ledger",
        default=None,
        metavar="PATH",
        help="journal every FINAL result to this JSONL file (fsync'd "
        "per record). Driver path: one record per completed trial; "
        "--fused: one record per population member at every natural "
        "boundary (PBT generation, SHA/BOHB rung, TPE batch), written "
        "before the boundary's snapshot. With --resume, completed "
        "records are replayed (driver) or verified against the "
        "re-trained boundaries (fused) so a killed sweep resumes with "
        "an identical journal, and the driver's exact-match params "
        "cache skips re-evaluating recorded-ok points",
    )
    p.add_argument(
        "--warm-start",
        default=None,
        metavar="PATH|auto:DIR",
        help="feed PRIOR sweep evidence into this sweep as observations "
        "before the search starts (TPE/BOHB build surrogate priors — "
        "fused TPE pre-fills its on-device ring; random/asha/pbt seed "
        "with the prior best). A PATH names one prior ledger (CROSS-"
        "MODE: a fused ledger warm-starts a driver sweep and vice "
        "versa; the only gate is the space hash). 'auto:DIR' resolves "
        "through DIR's corpus index instead (`corpus index DIR`): "
        "every exact-space-hash ledger merges in (dedup by canonical "
        "params, newest wins) and fuzzy-matched same-workload ledgers "
        "enter down-weighted at budget 0; stale index entries degrade "
        "to corpus_skip events, never errors",
    )
    # checkpoint/resume (SURVEY.md §2 row 13, §5)
    p.add_argument(
        "--checkpoint-dir",
        default=None,
        help="durable search checkpoints (orbax) written here after each batch",
    )
    p.add_argument(
        "--checkpoint-every", type=int, default=1, help="batches between checkpoints"
    )
    p.add_argument(
        "--resume",
        action="store_true",
        help="resume from the latest checkpoint in --checkpoint-dir "
        "(starts fresh if the directory is empty)",
    )
    p.add_argument(
        "--profile-dir",
        default=None,
        help="capture a jax.profiler trace of the search loop here "
        "(TensorBoard-loadable)",
    )
    p.add_argument(
        "--profile-launches",
        default=None,
        metavar="N|A:B",
        help="with --profile-dir: profile only this launch window "
        "(1-based, inclusive — fused launches/rungs/generations, or "
        "driver batches) instead of the whole run; e.g. 2:3 skips the "
        "cold-compile first launch so the XLA trace shows steady state",
    )
    # span tracing (obs/; see README: Observability)
    p.add_argument(
        "--trace",
        action="store_true",
        help="emit span records (compile/train/staging/boundary/save/"
        "journal phase durations, obs/trace.py) into the metrics stream "
        "— give --metrics-file and render with `mpi_opt_tpu trace FILE`. "
        "Off by default: an untraced sweep does zero tracing work",
    )
    # ASHA
    p.add_argument("--min-budget", type=int, default=10)
    p.add_argument("--max-budget", type=int, default=270)
    p.add_argument("--eta", type=int, default=3)
    # PBT
    p.add_argument("--population", type=int, default=32)
    p.add_argument("--generations", type=int, default=10)
    p.add_argument("--steps-per-generation", type=int, default=200)
    p.add_argument("--truncation", type=float, default=0.25)
    # fused on-device sweeps (train/fused_pbt.py, train/fused_asha.py)
    p.add_argument(
        "--fused",
        action="store_true",
        help="run the whole sweep on-device (random/pbt/asha/hyperband/"
        "bohb/tpe): no driver round-trips, population never leaves the "
        "device; --checkpoint-dir makes it crash-recoverable (pbt: "
        "launch granularity, asha/hyperband/bohb: rung granularity, "
        "tpe: generation granularity)",
    )
    p.add_argument(
        "--member-chunk",
        type=int,
        default=0,
        help="fused: process members in chunks of this size "
        "(activation-memory relief for big populations)",
    )
    p.add_argument(
        "--gen-chunk",
        type=int,
        default=0,
        help="fused pbt: generations per program launch (bit-identical "
        "split; needed where single programs are time-limited)",
    )
    p.add_argument(
        "--step-chunk",
        type=int,
        default=0,
        help="fused pbt: max training steps per launch WITHIN a "
        "generation (for populations whose single-generation program "
        "exceeds the platform's execution window; deterministic, "
        "checkpoint-guarded, not bit-identical to unchunked)",
    )
    p.add_argument(
        "--wave-size",
        default="0",
        metavar="N|auto",
        help="fused sweeps (any algorithm): cohort > device residency — "
        "train resident waves of N members per generation/rung/batch, "
        "staging cold members on host between waves (double-buffered "
        "async transfers overlap wave compute); the boundary op "
        "(exploit, rung cut, re-suggest) still runs over the FULL "
        "cohort. 'auto' sizes the wave from a residency estimate; 0 "
        "disables (fully resident). Bit-identical to resident mode on "
        "the CPU backend (tested); see README 'Wave scheduling'",
    )
    p.add_argument(
        "--oom-backoff",
        type=int,
        default=2,
        metavar="N",
        help="fused wave mode (any algorithm): on a device OOM (XLA "
        "RESOURCE_EXHAUSTED), automatically halve the wave size and "
        "re-run the generation/rung/batch — bit-identical at any wave "
        "size — up to N times (0 disables). Also pre-clamps an "
        "explicit --wave-size against the measured device budget. "
        "Resident-mode and post-budget OOMs exit 74 (classified, "
        "non-retryable)",
    )
    p.add_argument(
        "--objectives",
        default=None,
        metavar="SPEC",
        help="fused pbt/asha: multi-objective search, e.g. "
        '"accuracy:max,params:min<=2e4" — comma-separated '
        "name:direction terms, each optionally constrained (<= for min, "
        ">= for max). Boundary selection runs on the Pareto front "
        "(non-dominated sort + crowding) inside the compiled boundary "
        "op; constrained sweeps pick the best FEASIBLE member, "
        "degrading (typed, never a crash) to the least-violating one "
        "when nothing is feasible. The ledger journals each member's "
        "objective vector beside the scalarized primary score; see "
        "README 'Multi-objective search'",
    )
    # multi-host bring-up (SURVEY.md §2 row 1 + §5): the reference's
    # ``mpirun`` launch WAS its user surface; the CLI owns SPMD bring-up
    # the same way — one OS process per host, each invoking this CLI
    # with its rank, called BEFORE any backend/mesh construction
    # (jax.distributed must initialize before the XLA backend exists)
    p.add_argument(
        "--coordinator",
        default=None,
        metavar="HOST:PORT",
        help="multi-process SPMD: the rank-0 coordinator address. Give "
        "together with --num-processes/--process-id on every rank "
        "(the mpirun-equivalent launch); on TPU pods --multihost alone "
        "auto-detects all three from pod metadata",
    )
    p.add_argument(
        "--num-processes",
        type=int,
        default=None,
        help="multi-process SPMD: total process count (with --coordinator)",
    )
    p.add_argument(
        "--process-id",
        type=int,
        default=None,
        help="multi-process SPMD: this process's rank (with --coordinator)",
    )
    p.add_argument(
        "--multihost",
        action="store_true",
        help="bring up jax.distributed via cluster auto-detection (TPU "
        "pod metadata); fails rather than silently running "
        "single-process. Implied by --coordinator",
    )
    p.add_argument(
        "--platform",
        default=None,
        choices=["cpu", "tpu"],
        help="force the jax platform at config level (env vars are "
        "unreliable under site plugins); cpu + --local-devices N gives "
        "an N-device virtual host for debugging SPMD launches off-pod",
    )
    p.add_argument(
        "--local-devices",
        type=int,
        default=None,
        help="with --platform cpu: virtual device count for this process",
    )
    # mesh / multi-chip (SURVEY.md §2 row 9: the communication layer,
    # reachable from the user surface)
    p.add_argument(
        "--n-data",
        type=int,
        default=1,
        help="mesh 'data' axis size: within-member data parallelism "
        "(gradient all-reduce over ICI). Devices are split as "
        "(devices/n_data) x n_data",
    )
    p.add_argument(
        "--n-pop",
        type=int,
        default=0,
        help="mesh 'pop' axis size (0 = all remaining devices). "
        "Population/trial parallelism axis",
    )
    p.add_argument(
        "--no-mesh",
        action="store_true",
        help="disable the automatic ('pop','data') mesh on multi-device "
        "hosts (run single-device)",
    )
    # failure recovery (SURVEY.md §5): accelerator runtimes demonstrably
    # die mid-sweep (this container's tunneled TPU worker crashes and
    # restarts); fused sweeps are crash-recoverable via --checkpoint-dir,
    # and --retries closes the loop by resuming automatically
    p.add_argument(
        "--retries",
        type=int,
        default=0,
        help="fused: auto-retry the sweep this many times on a TRANSIENT "
        "runtime failure (worker crash/restart, unavailable, deadline). "
        "With --checkpoint-dir each retry resumes at the last snapshot; "
        "without, it restarts the (deterministic) sweep from scratch",
    )
    # per-trial failure policy (driver path; SURVEY.md §5): --retries
    # above recovers whole-SWEEP platform deaths, these recover
    # individual trials — the normal HPO failure mode (extreme
    # hyperparameters are part of the search space)
    p.add_argument(
        "--trial-retries",
        type=int,
        default=0,
        help="driver path: re-evaluate a failed/timed-out trial up to "
        "this many times (jittered exponential backoff between "
        "attempts) before reporting it as failed",
    )
    p.add_argument(
        "--trial-timeout",
        type=float,
        default=None,
        metavar="SECONDS",
        help="cpu backend: per-trial evaluation deadline; a trial still "
        "running past it is reaped as a 'timeout' result and its worker "
        "pool recycled (unset = wait forever)",
    )
    p.add_argument(
        "--max-failure-rate",
        type=float,
        default=1.0,
        metavar="FRAC",
        help="driver path: abort the sweep once more than this fraction "
        "of trial evaluations has failed (checked after 20 evaluations; "
        "1.0 disables). Catches systemic bugs fast instead of grinding "
        "through thousands of doomed trials",
    )
    p.add_argument(
        "--chaos",
        default=None,
        metavar="SPEC",
        help="fault-injection drill (driver path): wrap the workload in "
        "seeded chaos, e.g. 'exc=0.1,nan=0.05,hang=0.02,slow=0.1,seed=7' "
        "(probabilities per fault; preempt= drills the graceful-shutdown "
        "protocol; hang_s=/slow_s= tune durations). Faults are a "
        "deterministic function of (seed, trial params)",
    )
    # rank health (health/): graceful preemption + hang detection
    p.add_argument(
        "--isolate-stateful",
        action="store_true",
        help="cpu backend: evaluate STATEFUL workloads (PBT inheritance, "
        "ASHA warm resume) in a dedicated spawned worker holding the "
        "state store, instead of in-parent — makes --trial-timeout "
        "enforceable there (a hung trial is reaped as status=timeout "
        "and the worker respawned; its state store resets, so "
        "inheritors of lost states retrain from scratch)",
    )
    p.add_argument(
        "--heartbeat-file",
        default=None,
        metavar="PATH",
        help="write a monotonic progress beat (atomic JSON rewrite) to "
        "this file at every completed batch/launch — the liveness "
        "signal launch.py's --stall-timeout watchdog reads. The "
        "supervisor wires this per rank automatically; set manually "
        "for external watchdogs",
    )
    # multi-process SPMD boundary agreement (parallel/coord.py): every
    # rank-divergent decision (drain, wave cap, OOM halving) votes
    # through a filesystem control plane and becomes unanimous before
    # the next collective. launch.py owns these per rank, like
    # --coordinator/--heartbeat-file
    p.add_argument(
        "--coord-dir",
        default=None,
        metavar="DIR",
        help="multi-process SPMD: directory of the boundary-agreement "
        "control plane (per-rank vote files, rank-0 decisions). "
        "launch.py wires this per rank automatically; set manually "
        "only for external supervisors. Single-process runs may set it "
        "too (a world-of-1 plane agrees with itself — useful for "
        "protocol drills)",
    )
    p.add_argument(
        "--coord-epoch",
        type=int,
        default=0,
        metavar="N",
        help="with --coord-dir: the job attempt's vote namespace. Each "
        "coordinated restart must use a FRESH epoch (launch.py passes "
        "its relaunch counter) — a reused epoch is refused, stale "
        "votes from a killed attempt must be unreadable",
    )
    p.add_argument(
        "--coord-timeout",
        type=float,
        default=300.0,
        metavar="SECONDS",
        help="with --coord-dir: how long a rank waits at an agreement "
        "boundary for its peers before declaring the collective wedged "
        "(CoordWedged -> nonzero exit -> the supervisor's coordinated "
        "restart). Size above the longest legitimate gap between "
        "boundaries, like --stall-timeout",
    )
    p.add_argument(
        "--rank-kill",
        default=None,
        metavar="SPEC",
        help="chaos drill: SIGKILL a chosen rank at a chosen boundary "
        "— 'rank=R,at=K[,n=N][,marker=PATH]' dies hard at the K-th "
        "(1-based) launch/rung/generation boundary on the rank whose "
        "process index is R. marker makes the kill one-shot across "
        "coordinated restarts (fire only if PATH does not exist). "
        "Exercises the collective-wedge escalation end to end",
    )
    # the suggestion service (corpus/serve.py): instead of running a
    # sweep, answer suggest/report/lookup traffic for EXTERNAL sweeps
    p.add_argument(
        "--suggest-serve",
        default=None,
        metavar="DIR",
        help="run as a resident suggestion server over this filesystem "
        "spool instead of sweeping: answers suggest/report/lookup "
        "requests (`suggest-client`) from the batched TPE acquisition "
        "kernel over --workload's space, warm-started via --warm-start "
        "(incl. auto:DIR). Submittable to the sweep service unchanged "
        "— every served request is a natural boundary, so `serve` "
        "time-slices it like a sweep; with --ledger every report "
        "journals and --resume rebuilds the ring",
    )
    p.add_argument(
        "--suggest-idle-timeout",
        type=float,
        default=None,
        metavar="S",
        help="with --suggest-serve: exit 0 (done) after S seconds with "
        "no requests (unset = stay resident until `suggest-client stop` "
        "or a drain)",
    )
    # the HTTP front door (service/http.py): put a batched, overload-
    # safe REST endpoint in front of the suggestion server
    p.add_argument(
        "--http-port",
        type=int,
        default=None,
        metavar="PORT",
        help="with --suggest-serve: serve the HTTP front door on this "
        "port instead of the filesystem request spool (0 = ephemeral; "
        "the bound port publishes atomically to DIR/control/http.json). "
        "Batched ops share one journal fsync; overload sheds with typed "
        "503s; idempotency keys make client retries exactly-once",
    )
    p.add_argument(
        "--http-queue",
        type=int,
        default=64,
        metavar="N",
        help="with --http-port: admission-queue bound — requests beyond "
        "it shed with 503 + Retry-After instead of queueing unboundedly",
    )
    p.add_argument(
        "--http-state-dir",
        default=None,
        metavar="DIR",
        help="with --http-port: also expose the sweep service's "
        "submit/status/cancel ops over HTTP against this service state "
        "dir (the spool stays the durability layer; fencing tokens "
        "stay the authority)",
    )
    return p


_TRANSIENT_MARKERS = (
    "crashed",
    "restarted",
    "unavailable",
    "deadline",
    "socket closed",
    "connection reset",
    # NOT "cancelled": when an async op fails, the runtime reports its
    # dependents as CANCELLED — retrying one of those secondary errors
    # would re-run a genuine program bug N times
)


def _is_transient(e: BaseException) -> bool:
    """Platform-failure heuristic: retry-worthy errors name the runtime
    dying, not the program being wrong (a shape error or OOM retried N
    times is N identical failures).

    Two gates, both required: the exception TYPE must be one the
    accelerator runtime actually raises (JaxRuntimeError — the class the
    tunneled worker's crash/unavailable/deadline errors arrive as — or a
    transport-layer OSError), and its message must name the runtime
    dying. Type-first keeps a program error that merely QUOTES a marker
    (a dataset path containing 'unavailable', a user exception citing a
    'deadline') from being retried N times (ADVICE r4)."""
    import jax.errors

    # sweeplint: disable=resource-funnel -- deliberate: this is the TRANSIENT platform-death classifier (crashed/unavailable/deadline), disjoint from the OOM funnel — its markers exclude RESOURCE_EXHAUSTED, and DeviceOOM never reaches here (classified before the retry loop)
    if not isinstance(e, (jax.errors.JaxRuntimeError, OSError)):
        return False
    return any(m in str(e).lower() for m in _TRANSIENT_MARKERS)


def _wire_trace(args, metrics):
    """Install this run's MetricsLogger as the span sink (obs/trace.py)
    when --trace is set; returns the prior trace state (restored by
    main's finally) or None when tracing is off. Rank tags come from
    jax.process_index() under SPMD so multi-rank streams merge
    attributably; the tenant tag comes from the service scheduler's
    ``MPI_OPT_TPU_TRACE_TAG`` env around each slice."""
    if not args.trace:
        return None
    import os

    rank = 0
    if args.multihost or args.coordinator is not None:
        import jax

        rank = jax.process_index()
    return _trace.configure(
        metrics, rank=rank, tenant=os.environ.get("MPI_OPT_TPU_TRACE_TAG")
    )


def _run_with_retries(launch, retries: int, metrics):
    """Run ``launch()``; on a transient runtime failure, retry up to
    ``retries`` times. Callers pass a closure over a fused sweep whose
    checkpoint machinery (if enabled) turns each retry into a resume —
    the automatic form of the kill-and-rerun recovery the snapshot
    tests prove by hand."""
    attempt = 0
    while True:
        try:
            return launch()
        except Exception as e:
            if attempt >= retries or not _is_transient(e):
                if attempt:  # the retries were burned: record what won
                    metrics.log(
                        "retry_exhausted",
                        attempts=attempt,
                        error=f"{type(e).__name__}: {e}"[:1000],
                    )
                raise
            attempt += 1
            metrics.log(
                "retry",
                attempt=attempt,
                of=retries,
                error=f"{type(e).__name__}: {e}"[:300],
            )


def build_mesh(args):
    """The run's device mesh, or None for plain single-device execution.

    Auto-meshes whenever more than one device is visible (a v4-32 user
    typing ``--fused`` gets all 32 chips without extra flags); explicit
    ``--n-data``/``--n-pop`` force a mesh shape, ``--no-mesh`` opts out.
    """
    if args.no_mesh:
        if args.n_data > 1 or args.n_pop > 0:
            raise SystemExit("--no-mesh contradicts --n-data/--n-pop")
        return None
    import jax

    if jax.device_count() > 1 or args.n_data > 1 or args.n_pop > 0:
        from mpi_opt_tpu.parallel.mesh import make_mesh

        return make_mesh(n_pop=args.n_pop or None, n_data=args.n_data)
    return None


def make_algorithm(args, space):
    cls = get_algorithm(args.algorithm)
    if args.algorithm == "random":
        return cls(space, seed=args.seed, max_trials=args.trials, budget=args.budget)
    if args.algorithm == "tpe":
        return cls(space, seed=args.seed, max_trials=args.trials, budget=args.budget)
    if args.algorithm == "asha":
        return cls(
            space,
            seed=args.seed,
            max_trials=args.trials,
            min_budget=args.min_budget,
            max_budget=args.max_budget,
            eta=args.eta,
        )
    if args.algorithm in ("hyperband", "bohb"):
        return cls(space, seed=args.seed, max_budget=args.max_budget, eta=args.eta)
    if args.algorithm == "pbt":
        return cls(
            space,
            seed=args.seed,
            population=args.population,
            generations=args.generations,
            steps_per_generation=args.steps_per_generation,
            config=PBTConfig(truncation_frac=args.truncation),
        )
    raise AssertionError(args.algorithm)


def _finite_or_null(obj):
    """Summary-layer JSON hygiene: ``json.dumps`` emits bare ``NaN`` /
    ``Infinity`` tokens for non-finite floats — invalid JSON per the
    spec, breaking the documented single-JSON-line contract for strict
    (non-Python) parsers. An all-diverged fused sweep produces exactly
    that: best_score NaN, and NaN entries in the curves (a generation
    whose every member diverged has ``scores.max() == NaN``). Replace
    non-finite floats with None recursively HERE, at the serialization
    boundary — the result dicts keep their NaNs so library callers can
    still detect divergence numerically."""
    import math

    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _finite_or_null(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite_or_null(v) for v in obj]
    return obj


def _has_snapshot(directory) -> bool:
    """Does an orbax sweep snapshot already live under ``directory``?

    Orbax lays out one numeric step directory per save (hyperband nests
    them under per-bracket dirs), each holding a ``_CHECKPOINT_METADATA``
    file once the save committed. Requiring BOTH the digit name and the
    metadata marker keeps unrelated numeric directories sharing the tree
    (e.g. profiler output ``plugins/profile/2026_07_30/``) from
    false-positiving a fresh sweep into a hard "pass --resume" error.
    """
    import os

    if not directory or not os.path.isdir(directory):
        return False
    for root, dirs, _files in os.walk(directory):
        for d in dirs:
            if d.isdigit() and os.path.exists(
                os.path.join(root, d, "_CHECKPOINT_METADATA")
            ):
                return True
    return False


def _resolve_warm_start(args, space, metrics, parser):
    """ONE home for ``--warm-start`` resolution (ISSUE 14 satellite:
    the load/validate block used to be written twice — fused and driver
    — and the realpath self-warm-start guard protected only the flat
    main() flow; now every path, the ``auto:`` corpus resolution and
    the suggestion tenant included, flows through here).

    Returns ``(warm_obs, warm_info)``: the observations to ingest and
    the event-payload dict (``sources`` naming every contributing
    ledger with its match kind, ``skips`` counting per-record losses).
    Usage errors (bad path, space-hash mismatch, self-warm-start,
    malformed auto spec) surface as ``parser.error`` — exit 2, before
    any durable state is touched."""
    import os

    from mpi_opt_tpu.ledger import LedgerError

    spec = args.warm_start
    if spec == "auto" or spec.startswith("auto:"):
        corpus_dir = spec[len("auto:"):] if spec.startswith("auto:") else ""
        if not corpus_dir:
            parser.error(
                "--warm-start auto needs a corpus root: --warm-start auto:DIR"
            )
        if not os.path.isdir(corpus_dir):
            parser.error(
                f"--warm-start auto: {corpus_dir!r} is not a directory"
            )
        from mpi_opt_tpu.corpus.resolve import resolve

        # exclude= is the auto-path self-warm-start guard: this run's
        # own --ledger may already live under the corpus root
        res = resolve(
            space,
            corpus_dir,
            workload=args.workload,
            exclude=args.ledger,
            metrics=metrics,
        )
        # degraded whole entries already surfaced as corpus_skip events
        # inside resolve(); the warm_start payload carries the sources
        # that DID contribute plus the per-record loss counters
        return res.observations, {
            "sources": res.sources,
            "skips": res.skips or None,
        }
    # plain path: one PRIOR ledger. realpath: './sweep.jsonl' vs
    # 'sweep.jsonl' (or a symlink) is still self-feeding — this run's
    # journal is not a prior sweep
    if args.ledger and os.path.realpath(spec) == os.path.realpath(args.ledger):
        parser.error(
            "--warm-start must name a PRIOR sweep's ledger, not this "
            "run's --ledger (resuming this sweep is --ledger --resume)"
        )
    from mpi_opt_tpu.ledger.warmstart import load_observations

    try:
        obs, skips = load_observations(spec, space)
    except (LedgerError, OSError) as e:
        parser.error(f"--warm-start: {e}")
    return obs, {
        "sources": [{"path": spec, "match": "exact", "records": len(obs)}],
        "skips": skips or None,
    }


def _log_warm_start(metrics, args, warm_info, observations: int) -> None:
    """The one ``warm_start`` event shape, shared by every path:
    ``observations`` is what actually informed the search (the
    algorithm's own count where one exists), ``sources`` names the
    chosen ledgers, ``skipped`` carries the per-record loss counters
    instead of letting the list silently shrink."""
    metrics.log(
        "warm_start",
        path=args.warm_start,
        observations=observations,
        sources=(warm_info or {}).get("sources"),
        skipped=(warm_info or {}).get("skips"),
    )


def run_fused(args, parser, workload) -> int:
    """--fused: the whole sweep as on-device programs, no driver loop.

    PBT maps to train.fused_pbt (generation scan, exploit/explore and
    winner gathers on-device, optional crash-recovery snapshots); ASHA
    maps to train.fused_asha (synchronous successive halving, rung cuts
    as on-device top_k). Emits the same summary JSON shape as the
    driver path so downstream tooling doesn't care which path ran.
    """
    import time

    from mpi_opt_tpu.utils.profiling import profile_window
    from mpi_opt_tpu.workloads.base import PopulationWorkload

    if not isinstance(workload, PopulationWorkload):
        parser.error(f"--fused requires a population workload, not {args.workload!r}")
    # getattr: main() parsed --objectives; direct in-process callers
    # (tests) may hand a namespace without it
    objectives = getattr(args, "objective_spec", None)
    if objectives is not None:
        supported = tuple(workload.objective_metrics())
        missing = [n for n in objectives.names if n not in supported]
        if missing:
            parser.error(
                f"--objectives: workload {args.workload!r} cannot evaluate "
                f"{missing}; supported metrics: {list(supported)}"
            )
    if args.retries:
        import jax

        if jax.process_count() > 1:
            # a per-process retry under multi-process SPMD is unsound:
            # one process restoring a snapshot while its peers sit in a
            # collective issues mismatched programs and hangs the job.
            # Recovery there is job-level: rerun (snapshots resume it).
            parser.error(
                "--retries requires a single-process run; under "
                "multi-process SPMD recovery is a coordinated job "
                "restart — run under `python -m mpi_opt_tpu.launch "
                "--retries N`, which relaunches ALL ranks with "
                "--resume and a fresh --coord-epoch"
            )
    # resuming is explicit opt-in, matching the driver path: a stale
    # checkpoint dir must not silently replay an old sweep (ADVICE r2)
    if args.checkpoint_dir and not args.resume and _has_snapshot(args.checkpoint_dir):
        parser.error(
            f"--checkpoint-dir {args.checkpoint_dir!r} already holds a sweep "
            "snapshot; pass --resume to continue it, or point at a fresh "
            "directory"
        )

    mesh = build_mesh(args)
    # PBT/TPE keep a standing --population cohort for the whole sweep:
    # a non-dividing population would replicate on every device (see
    # parallel.mesh.shard_popstate) and silently run effectively
    # single-device — fail up front with the fix spelled out. SHA-family
    # sweeps instead round their shrinking cohorts to the mesh
    # (round_to), so only their first cohort may warn.
    if mesh is not None and args.algorithm in ("pbt", "tpe"):
        n_pop = int(mesh.shape["pop"])
        # only the population-exceeds-axis case is refused: sharding was
        # possible and the user plausibly expected it. A population
        # SMALLER than the axis (debug-sized run on a big mesh) can only
        # replicate, and gets the runtime warning instead of a hard stop.
        if args.population % n_pop and args.population > n_pop:
            lo = (args.population // n_pop) * n_pop
            parser.error(
                f"--population {args.population} does not divide the mesh "
                f"'pop' axis ({n_pop}); the population would be replicated "
                "on every device instead of sharded. Use --population "
                f"{lo} or {lo + n_pop}, reshape the mesh with "
                "--n-pop/--n-data, or pass --no-mesh."
            )
    # per-chip accounting divides by the devices the sweep ACTUALLY runs
    # on: the mesh's GLOBAL device count when sharded, exactly 1
    # otherwise (local_device_count would overstate the denominator on a
    # multi-chip host running --no-mesh; ADVICE round 2). Global, not
    # this process's share: under multi-host SPMD every process drives
    # the same global sweep and counts the same global trial total, so a
    # local divisor would overstate per-chip throughput by the host count.
    n_chips = int(mesh.devices.size) if mesh is not None else 1
    metrics = stdout_logger(path=args.metrics_file, n_chips=n_chips)
    _wire_integrity_observer(metrics)
    _wire_resource_observer(metrics)
    _wire_trace(args, metrics)  # restored by main's finally
    # boundary-agreement control plane (multi-process SPMD): activate
    # the plane and chain its drain agreement onto the slice hook
    # BEFORE any boundary runs; torn down in the finally below so no
    # hook/plane leaks into in-process callers' next sweep
    from mpi_opt_tpu.parallel import coord as _coord

    coord_uninstall = None
    if getattr(args, "coord_dir", None):
        import jax

        plane = _coord.CoordPlane(
            args.coord_dir,
            jax.process_index(),
            jax.process_count(),
            epoch=getattr(args, "coord_epoch", 0) or 0,
            timeout_s=getattr(args, "coord_timeout", None) or 300.0,
        )
        coord_uninstall = _coord.install_hook(plane)
    rank_kill_uninstall = None
    if getattr(args, "rank_kill", None):
        from mpi_opt_tpu.workloads.chaos import inject_rank_kill, parse_rank_kill_spec

        _, rank_kill_uninstall = inject_rank_kill(
            **parse_rank_kill_spec(args.rank_kill)
        )
    from mpi_opt_tpu.ledger import LedgerError

    space = workload.default_space()
    # the prior ledger validates BEFORE this run's own ledger header
    # commits, same rule as the driver path: a typo'd --warm-start must
    # not be journaled into a fresh ledger's identity
    warm_obs = None
    if args.warm_start:
        warm_obs, warm_info = _resolve_warm_start(args, space, metrics, parser)
        _log_warm_start(metrics, args, warm_info, len(warm_obs))
    ledger = _open_fused_ledger(args, parser, space, metrics)
    t0 = time.perf_counter()
    try:
        # the fused launch path's device-OOM classification boundary:
        # any driver's XLA RESOURCE_EXHAUSTED arrives here as ONE type
        with resources.oom_funnel():
            return _run_fused_dispatch(
                args,
                parser,
                workload,
                mesh,
                n_chips,
                metrics,
                t0,
                ledger,
                warm_obs,
                objectives=objectives,
            )
    except resources.DeviceOOM as e:
        # deterministic for this program+population: retrying the same
        # shape re-OOMs (wave mode already spent its --oom-backoff
        # budget before this propagates) — park classified, exit 74
        return _resource_exit(
            e,
            metrics,
            "device_oom",
            workload=args.workload,
            algorithm=args.algorithm,
            backend="fused",
        )
    except resources.StorageFull as e:
        # the disk filled mid-snapshot/journal after the one
        # retention-prune retry: durable state intact, free disk +
        # --resume recovers — park classified, exit 74
        return _resource_exit(
            e,
            metrics,
            "storage_full",
            workload=args.workload,
            algorithm=args.algorithm,
            backend="fused",
        )
    except (NoVerifiedSnapshotError, LedgerError) as e:
        # both are data dead-ends: an unverifiable snapshot tree, or a
        # journal that diverges from / lags the sweep it claims to
        # record — no restart re-reads either into health, so exit 65
        # (launch.py classifies it as non-retryable)
        return _data_error_exit(
            e,
            metrics,
            workload=args.workload,
            algorithm=args.algorithm,
            backend="fused",
        )
    except SweepInterrupted as e:
        # graceful preemption: the drained launch's snapshot is flushed
        # (fused trainers force an off-cadence save before raising);
        # exit EX_TEMPFAIL so a supervisor restarts with --resume
        # without billing its --retries budget
        metrics.count_preempted()
        metrics.summary(final=True)
        print(
            json.dumps(
                {
                    "preempted": True,
                    "signal": e.signal,
                    "at": e.at,
                    "workload": args.workload,
                    "algorithm": args.algorithm,
                    "backend": "fused",
                }
            )
        )
        print(
            f"graceful shutdown ({e.signal}) at {e.at}: snapshot flushed; "
            f"relaunch with --resume to continue (exit {EX_TEMPFAIL})",
            file=sys.stderr,
        )
        return EX_TEMPFAIL
    except _coord.CoordWedged as e:
        # a peer never reached this rank's agreement boundary — the
        # collective is wedged, and only a COORDINATED restart (the
        # launch.py supervisor relaunching every rank with --resume and
        # a fresh epoch) can recover. Exit nonzero-generic so the
        # supervisor funds exactly that from its retry budget.
        metrics.summary(final=True)
        print(f"collective wedge: {e}", file=sys.stderr)
        return 1
    finally:
        if rank_kill_uninstall is not None:
            rank_kill_uninstall()
        if coord_uninstall is not None:
            coord_uninstall()
        if ledger is not None:
            ledger.close()


def _open_fused_ledger(args, parser, space, metrics):
    """Open + identity-check the fused sweep's ledger (None without
    --ledger). Mirrors the driver path's rules — rank-0-only journaling
    under multi-process SPMD, stale journals need explicit --resume —
    and commits a FUSED header: ``mode``/``granularity`` mark the
    boundary-granular record stream, and the config carries everything
    that shapes the deterministic trajectory the journal will be
    verified against on resume."""
    if not args.ledger:
        return None
    from mpi_opt_tpu.ledger import LedgerError, SweepLedger

    ledger_rank = 0
    if args.multihost or args.coordinator is not None:
        import jax

        ledger_rank = jax.process_index()
    try:
        ledger = SweepLedger(args.ledger, read_only=ledger_rank != 0)
    except LedgerError as e:
        parser.error(f"--ledger: {e}")
    if ledger.read_only:
        metrics.log("ledger_rank_gated", rank=ledger_rank)
    if ledger.records and not args.resume:
        parser.error(
            f"--ledger {args.ledger!r} already holds "
            f"{len(ledger.records)} member records; pass --resume to "
            "verify and continue them, or point at a fresh path"
        )
    config = {
        "mode": "fused",
        "granularity": {"pbt": "generation", "tpe": "batch"}.get(
            args.algorithm, "rung"
        ),
        "algorithm": args.algorithm,
        "workload": args.workload,
        "backend": "fused",
        "seed": args.seed,
        "space_hash": space.space_hash(),
        "warm_start": args.warm_start,
    }
    objectives = getattr(args, "objective_spec", None)
    if objectives is not None:
        # objective identity (names + directions + bounds) IS config:
        # resuming a ledger under different objectives would journal a
        # different selection trajectory. Scalar sweeps never write the
        # key, so every pre-existing ledger keeps resuming byte-for-byte
        config["objectives"] = args.objectives
    # the knobs that shape each algorithm's boundary/member structure
    if args.algorithm == "pbt":
        # wave_size is deliberately NOT ledger identity: wave scheduling
        # is bit-identical to resident mode, so the journal records the
        # same trajectory either way (snapshots still refuse the
        # cross-resume — that's state shape, not history)
        config.update(
            population=args.population,
            generations=args.generations,
            steps_per_generation=args.steps_per_generation,
        )
    elif args.algorithm == "tpe":
        config.update(
            trials=args.trials, batch=args.population, budget=args.budget
        )
    elif args.algorithm == "random":
        config.update(trials=args.trials, budget=args.budget)
    elif args.algorithm == "asha":
        config.update(
            trials=args.trials,
            min_budget=args.min_budget,
            max_budget=args.max_budget,
            eta=args.eta,
        )
    else:  # hyperband / bohb
        config.update(max_budget=args.max_budget, eta=args.eta)
    try:
        # space_spec rides the header top-level (not identity): the
        # corpus index fuzzy-fingerprints ledgers from it, and
        # objective_spec (ISSUE 17) rides the same way so report/corpus
        # consumers render fronts without re-parsing the config string
        ledger.ensure_header(
            config,
            space_spec=space.spec(),
            objective_spec=None if objectives is None else objectives.spec(),
        )
    except LedgerError as e:
        parser.error(f"--ledger: {e}")
    if ledger.n_torn:
        metrics.log("ledger_torn_tail_dropped", path=args.ledger)
    if ledger.n_torn_boundary:
        metrics.log(
            "ledger_torn_boundary_dropped",
            path=args.ledger,
            records=ledger.n_torn_boundary,
        )
    return ledger


def _wave_extras(res: dict) -> dict:
    """Wave-scheduling observability fields for the fused summary —
    the staging traffic and how much of it the double buffer hid
    behind compute. Empty when the sweep ran resident; shared across
    all wave-capable algorithms so the summary shape cannot drift."""
    if not res.get("wave_size"):
        return {}
    return dict(
        wave_size=res["wave_size"],
        n_waves=res["n_waves"],
        staged_bytes=res["staged_bytes"],
        stage_overlap_s=round(res["stage_overlap_s"], 3),
        stage_wait_s=round(res["stage_wait_s"], 3),
        oom_backoffs=res.get("oom_backoffs", 0),
    )


def _run_fused_dispatch(
    args,
    parser,
    workload,
    mesh,
    n_chips,
    metrics,
    t0,
    ledger=None,
    warm_obs=None,
    objectives=None,
) -> int:
    """The fused algorithm dispatch + summary (run_fused's tail, split
    out so the graceful-shutdown catch wraps every fused path)."""
    import time

    from mpi_opt_tpu.utils.profiling import profile_window

    # getattr: main() parses the window; direct in-process callers of
    # run_fused (tests) may hand an argparse namespace without it
    with profile_window(args.profile_dir, launches=getattr(args, "profile_window", None)):
        if args.algorithm == "pbt":
            from mpi_opt_tpu.train.fused_pbt import fused_pbt

            res = _run_with_retries(lambda: fused_pbt(
                workload,
                population=args.population,
                generations=args.generations,
                steps_per_gen=args.steps_per_generation,
                seed=args.seed,
                cfg=PBTConfig(truncation_frac=args.truncation),
                mesh=mesh,
                member_chunk=args.member_chunk,
                gen_chunk=args.gen_chunk,
                step_chunk=args.step_chunk,
                wave_size=args.wave_size,
                checkpoint_dir=args.checkpoint_dir,
                snapshot_every=args.checkpoint_every,
                ledger=ledger,
                warm_obs=warm_obs,
                oom_backoff=args.oom_backoff,
                objectives=objectives,
            ), args.retries, metrics)
            n_trials = args.population * args.generations
            extra = {"best_curve": [round(float(v), 4) for v in res["best_curve"]]}
            extra.update(_wave_extras(res))
        elif args.algorithm in ("asha", "random"):
            from mpi_opt_tpu.train.fused_asha import fused_sha

            # fused random search IS the single-rung case of fused SHA:
            # one cohort of --trials members trains to --budget in
            # lockstep, no cuts — so one code path serves both
            if args.algorithm == "random":
                lo = hi = args.budget
            else:
                lo, hi = args.min_budget, args.max_budget
            res = _run_with_retries(lambda: fused_sha(
                workload,
                n_trials=args.trials,
                min_budget=lo,
                max_budget=hi,
                eta=args.eta,
                seed=args.seed,
                member_chunk=args.member_chunk,
                mesh=mesh,
                wave_size=args.wave_size,
                oom_backoff=args.oom_backoff,
                checkpoint_dir=args.checkpoint_dir,
                ledger=ledger,
                warm_obs=warm_obs,
                objectives=objectives,
            ), args.retries, metrics)
            n_trials = res["n_trials"]
            extra = {"rung_sizes": res["rung_sizes"], "rung_budgets": res["rung_budgets"]}
            extra.update(_wave_extras(res))
        elif args.algorithm == "tpe":
            from mpi_opt_tpu.train.fused_tpe import fused_tpe

            res = _run_with_retries(lambda: fused_tpe(
                workload,
                n_trials=args.trials,
                batch=args.population,
                budget=args.budget,
                seed=args.seed,
                member_chunk=args.member_chunk,
                mesh=mesh,
                wave_size=args.wave_size,
                oom_backoff=args.oom_backoff,
                checkpoint_dir=args.checkpoint_dir,
                ledger=ledger,
                warm_obs=warm_obs,
            ), args.retries, metrics)
            n_trials = res["n_trials"]
            extra = {"best_curve": [round(float(v), 4) for v in res["best_curve"]]}
            extra.update(_wave_extras(res))
        elif args.algorithm == "hyperband":
            from mpi_opt_tpu.train.fused_asha import fused_hyperband

            res = _run_with_retries(lambda: fused_hyperband(
                workload,
                max_budget=args.max_budget,
                eta=args.eta,
                seed=args.seed,
                member_chunk=args.member_chunk,
                mesh=mesh,
                wave_size=args.wave_size,
                oom_backoff=args.oom_backoff,
                checkpoint_dir=args.checkpoint_dir,
                ledger=ledger,
                warm_obs=warm_obs,
            ), args.retries, metrics)
            n_trials = res["n_trials"]
            extra = {"brackets": res["brackets"]}
            extra.update(_wave_extras(res))
        elif args.algorithm == "bohb":
            from mpi_opt_tpu.train.fused_bohb import fused_bohb

            res = _run_with_retries(lambda: fused_bohb(
                workload,
                max_budget=args.max_budget,
                eta=args.eta,
                seed=args.seed,
                member_chunk=args.member_chunk,
                mesh=mesh,
                wave_size=args.wave_size,
                oom_backoff=args.oom_backoff,
                checkpoint_dir=args.checkpoint_dir,
                ledger=ledger,
                warm_obs=warm_obs,
            ), args.retries, metrics)
            n_trials = res["n_trials"]
            extra = {"brackets": res["brackets"]}
            extra.update(_wave_extras(res))
        else:
            # registry-drift guard: unreachable while every registered
            # algorithm has a fused branch above (argparse's choices
            # rejects unknown names first); a NEW algorithm added to the
            # registry without fused support lands here with a clear
            # error instead of an UnboundLocalError
            parser.error(
                f"--fused supports random/pbt/asha/hyperband/bohb/tpe, "
                f"not {args.algorithm!r}"
            )
    wall = time.perf_counter() - t0
    metrics.count_trials(n_trials)
    # per-member failure visibility (ROADMAP open item): every fused
    # sweep reports how many member evaluations came back non-finite
    # per generation/rung — the divergence its isfinite winner picks
    # mask. None only when a pre-upgrade snapshot hid the counts
    member_failures = res.get("member_failures")
    summary = {
        "workload": args.workload,
        "algorithm": args.algorithm,
        "backend": "fused",
        "mesh": None if mesh is None else dict(mesh.shape),
        "n_chips": n_chips,
        "n_trials": n_trials,
        "member_failures": member_failures,
        "wall_s": round(wall, 3),
        "trials_per_sec_per_chip": round(n_trials / max(wall, 1e-9) / n_chips, 4),
        # best_params is None when the whole sweep diverged (all scores
        # non-finite) — mirror the driver path's no-best summary shape,
        # including best_score: null (json.dumps would otherwise emit
        # the non-standard NaN token and break strict parsers)
        "best_score": None
        if res["best_params"] is None
        else round(res["best_score"], 6),
        "best_params": None
        if res["best_params"] is None
        else {k: v for k, v in res["best_params"].items() if not k.startswith("__")},
        **extra,
    }
    # staging traffic (wave-scheduled sweeps): feed the counters BEFORE
    # the summary so staged_bytes/stage_overlap_s appear in it
    if res.get("staged_bytes") is not None:
        metrics.count_staging(res["staged_bytes"], res.get("stage_overlap_s", 0.0))
    # fused ledger observability: member records appended this run vs
    # re-verified on resume (parity with the driver path's replayed)
    if res.get("journal") is not None:
        metrics.count_journaled(res["journal"]["written"])
        summary["journal"] = dict(res["journal"])
    # multi-objective extras (ISSUE 17): the final front + how the
    # winner was picked. A constrained sweep that found nothing feasible
    # reports selection="least_violation" AND emits the typed
    # objective_degraded event — degradation is an outcome operators
    # page on, never a silent argmax
    if objectives is not None:
        summary["objectives"] = res.get("objectives")
        pareto = res.get("pareto")
        summary["pareto"] = pareto
        if pareto is not None:
            metrics.log(
                "pareto_front",
                front_size=pareto["front_size"],
                hypervolume=pareto["hypervolume"],
                selection=pareto["selection"],
                objectives=",".join(objectives.names),
            )
            if pareto["selection"] != "feasible":
                metrics.log(
                    "objective_degraded",
                    selection=pareto["selection"],
                    violation=pareto["violation"],
                    objectives=",".join(objectives.names),
                )
    metrics.summary(
        final=True,
        member_failures=(
            None if member_failures is None else int(sum(member_failures))
        ),
    )
    print(json.dumps(_finite_or_null(summary)))
    return 0


def run_suggest_serve(args, parser, workload) -> int:
    """--suggest-serve DIR: the suggestion-service tenant (corpus/serve).

    Instead of sweeping, this process answers suggest/report/lookup
    traffic over DIR at acquisition-kernel speed. Lifecycle mirrors a
    sweep's exactly so the sweep service can own it: a drain request
    (slice budget, SIGTERM, cancel) parks it with EX_TEMPFAIL — every
    report is already fsync-journaled, so nothing is lost — and
    ``--ledger --resume`` rebuilds the observation ring on the next
    slice; the stop flag / idle timeout completes it (exit 0)."""
    from mpi_opt_tpu.corpus.serve import SuggestServer, serve_loop
    from mpi_opt_tpu.ledger import LedgerError, SweepLedger

    space = workload.default_space()
    metrics = stdout_logger(path=args.metrics_file, n_chips=1)
    _wire_trace(args, metrics)  # restored by main's finally
    server = SuggestServer(space, seed=args.seed)
    # corpus warm start resolves BEFORE the ledger header commits, the
    # same ordering rule as the sweep paths
    warm_obs = warm_info = None
    if args.warm_start:
        warm_obs, warm_info = _resolve_warm_start(args, space, metrics, parser)
    ledger = None
    if args.ledger:
        try:
            # the suggestion server is single-process by construction
            # (it owns its spool dir; SPMD bring-up never reaches this
            # branch), so the rank gate is constantly writable
            ledger = SweepLedger(args.ledger, read_only=False)
        except LedgerError as e:
            parser.error(f"--ledger: {e}")
        if ledger.records and not args.resume:
            parser.error(
                f"--ledger {args.ledger!r} already holds "
                f"{len(ledger.records)} report records; pass --resume to "
                "rebuild the ring from them, or point at a fresh path"
            )
        try:
            ledger.ensure_header(
                {
                    "mode": "suggest",
                    "algorithm": "tpe",
                    "workload": args.workload,
                    "backend": "suggest",
                    "seed": args.seed,
                    "space_hash": space.space_hash(),
                    "warm_start": args.warm_start,
                },
                space_spec=space.spec(),
            )
        except LedgerError as e:
            parser.error(f"--ledger: {e}")
        if ledger.n_torn:
            metrics.log("ledger_torn_tail_dropped", path=args.ledger)
        if ledger.records:
            # resume: the server's own journaled reports rebuild the
            # ring + exact cache (and the report serial continues past
            # them, so records never alias across slices)
            server.seed_from_ledger(ledger.records)
            metrics.log("ledger_replay", completed=len(ledger.records))
    if warm_obs is not None:
        n_warm = server.ingest(warm_obs)
        _log_warm_start(metrics, args, warm_info, n_warm)
    metrics.log(
        "suggest_serve",
        workload=args.workload,
        n_obs=server._n_obs,
    )
    try:
        if args.http_port is not None:
            # the HTTP front door: handler threads admit, THIS thread
            # executes (so drain/heartbeat semantics stay identical to
            # serve_loop's); the spool dir still hosts the stop flag,
            # the heartbeat and the endpoint file
            from mpi_opt_tpu.service.http import FrontDoor, serve_http

            spool = None
            if args.http_state_dir:
                from mpi_opt_tpu.service.spool import Spool

                spool = Spool(args.http_state_dir)
            front = FrontDoor(
                suggest=server,
                ledger=ledger,
                spool=spool,
                metrics=metrics,
                queue_depth=args.http_queue,
            )
            summary = serve_http(
                front,
                args.suggest_serve,
                metrics,
                port=args.http_port,
                idle_timeout=args.suggest_idle_timeout,
            )
        else:
            summary = serve_loop(
                server,
                args.suggest_serve,
                metrics,
                ledger=ledger,
                idle_timeout=args.suggest_idle_timeout,
            )
    except SweepInterrupted as e:
        # the drain park: every report the clients saw acked is already
        # fsync-journaled, so the park is free — EX_TEMPFAIL tells the
        # scheduler/supervisor "resume me" exactly like a sweep
        metrics.count_preempted()
        metrics.summary(final=True)
        print(
            json.dumps(
                {
                    "preempted": True,
                    "signal": e.signal,
                    "at": e.at,
                    "workload": args.workload,
                    "backend": "suggest",
                }
            )
        )
        print(
            f"graceful shutdown ({e.signal}) at {e.at}: reports journaled; "
            f"relaunch with --resume to continue (exit {EX_TEMPFAIL})",
            file=sys.stderr,
        )
        return EX_TEMPFAIL
    finally:
        if ledger is not None:
            ledger.close()
    metrics.summary(final=True)
    print(
        json.dumps(
            _finite_or_null(
                {
                    "workload": args.workload,
                    "algorithm": "suggest",
                    "backend": "suggest",
                    **summary,
                }
            )
        )
    )
    return 0


def main(argv=None, *, _workload=None) -> int:
    """CLI entrypoint. ``_workload`` is the sweep service's injection
    seam (service/programs.py): a resident server passes its cached
    workload instance so back-to-back tenants share trainers — and with
    them jax's in-process jit cache, making a shape-matching tenant's
    marginal cost dispatch instead of compile. None (every normal
    invocation) resolves the workload from the registry as always."""
    if argv is None:
        argv = sys.argv[1:]
    # subcommand dispatch: `mpi_opt_tpu report ...` renders/validates
    # ledgers and never touches jax; the flat sweep interface (the
    # reference's mpirun-style surface) stays exactly as it was
    if argv and argv[0] == "report":
        from mpi_opt_tpu.ledger.report import report_main

        return report_main(argv[1:])
    # `mpi_opt_tpu fsck DIR` audits a sweep's durable snapshot state
    # (verify manifests, surface torn saves, --repair quarantines) —
    # same subcommand surface as report, see utils/integrity.py
    if argv and argv[0] == "fsck":
        from mpi_opt_tpu.utils.integrity import fsck_main

        return fsck_main(argv[1:])
    # `mpi_opt_tpu lint [PATHS]` machine-checks the engine's invariants
    # (analysis/ sweeplint suite); never touches jax
    if argv and argv[0] == "lint":
        from mpi_opt_tpu.analysis.cli import lint_main

        return lint_main(argv[1:])
    # `mpi_opt_tpu trace FILE|DIR` renders phase-time attribution over
    # JSONL metrics streams (obs/report.py); `trace --diff BASE NEW
    # [--gate TOL.json]` compares two attributions and gates perf
    # regressions (obs/diff.py). Never touches jax
    if argv and argv[0] == "trace":
        from mpi_opt_tpu.obs.report import trace_main

        return trace_main(argv[1:])
    # the resident multi-tenant sweep service (service/): `serve` is the
    # long-lived device-owning server, `submit`/`status`/`cancel`/`drain`
    # are the thin filesystem-spool clients (no network dependency)
    if argv and argv[0] in ("serve", "submit", "status", "cancel", "drain"):
        from mpi_opt_tpu.service import service_main

        return service_main(argv)
    # `mpi_opt_tpu corpus index|resolve` maintains/audits the ledger-
    # corpus knowledge layer (corpus/); `index` never touches jax
    if argv and argv[0] == "corpus":
        from mpi_opt_tpu.corpus.cli import corpus_main

        return corpus_main(argv[1:])
    # `mpi_opt_tpu suggest-client` drives a --suggest-serve server over
    # its filesystem spool; jax-free like every service client
    if argv and argv[0] == "suggest-client":
        from mpi_opt_tpu.corpus.client import client_main

        return client_main(argv[1:])
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.resume and not (args.checkpoint_dir or args.ledger):
        parser.error("--resume requires --checkpoint-dir or --ledger")
    # validate the failure-policy flags HERE so a bad value is a usage
    # error (exit 2), not a ValueError traceback from FailurePolicy or
    # the backend constructor deep in the run
    if args.trial_retries < 0:
        parser.error(f"--trial-retries must be >= 0, got {args.trial_retries}")
    if not 0.0 < args.max_failure_rate <= 1.0:
        parser.error(
            f"--max-failure-rate must be in (0, 1], got {args.max_failure_rate}"
        )
    if args.trial_timeout is not None and args.trial_timeout <= 0:
        parser.error(f"--trial-timeout must be > 0, got {args.trial_timeout}")
    # --wave-size: parse + validate as a usage error (exit 2), not a
    # ValueError traceback from fused_pbt deep in the run
    if args.wave_size != "auto":
        try:
            args.wave_size = int(args.wave_size)
        except ValueError:
            parser.error(
                f"--wave-size must be an integer or 'auto', got {args.wave_size!r}"
            )
        if args.wave_size < 0:
            parser.error(f"--wave-size must be >= 0, got {args.wave_size}")
    if args.oom_backoff < 0:
        parser.error(f"--oom-backoff must be >= 0, got {args.oom_backoff}")
    if args.wave_size:
        if not args.fused:
            parser.error(
                "--wave-size schedules a fused cohort through host-staged "
                "waves (engine); it requires --fused (any algorithm: "
                "pbt/asha/random/tpe/hyperband/bohb)"
            )
        if args.gen_chunk > 1 or args.step_chunk > 0:
            parser.error(
                "--wave-size schedules whole generations as resident "
                "waves; combining it with --gen-chunk/--step-chunk "
                "launch splitting is ambiguous"
            )
    # --objectives: parse + cross-validate as a usage error (exit 2),
    # not a ValueError deep in the fused driver. The parsed spec rides
    # args.objective_spec for run_fused's ledger/dispatch wiring.
    args.objective_spec = None
    if args.objectives:
        if not args.fused or args.algorithm not in ("pbt", "asha"):
            parser.error(
                "--objectives runs multi-objective selection inside the "
                "fused boundary ops; it requires --fused --algorithm "
                "pbt|asha"
            )
        if args.wave_size:
            parser.error(
                "--objectives is not supported with --wave-size yet; run "
                "resident (--wave-size 0) or shard over a mesh"
            )
        if args.step_chunk > 0:
            parser.error(
                "--objectives is not supported with --step-chunk (the "
                "sub-segment boundary program is scalar); use --gen-chunk"
            )
        from mpi_opt_tpu.objectives import ObjectiveSpec

        try:
            args.objective_spec = ObjectiveSpec.parse(args.objectives)
        except ValueError as e:
            parser.error(f"--objectives: {e}")
    # --profile-launches: parse + validate as a usage error, and carry
    # the parsed window on args for the profile_window call sites
    args.profile_window = None
    if args.profile_launches is not None:
        if not args.profile_dir:
            parser.error("--profile-launches requires --profile-dir")
        from mpi_opt_tpu.utils.profiling import parse_launch_window

        try:
            args.profile_window = parse_launch_window(args.profile_launches)
        except ValueError as e:
            parser.error(f"--profile-launches: {e}")
    if args.isolate_stateful and (args.fused or args.backend != "cpu"):
        parser.error(
            "--isolate-stateful moves the cpu backend's in-parent "
            "stateful path into a worker process; fused/TPU sweeps "
            "have no such path"
        )
    # --ledger/--warm-start work on BOTH paths: the driver journals per
    # trial, fused sweeps journal per population member at every
    # launch/rung/generation boundary (ledger/fused.py) — and warm-start
    # is cross-mode (the records share space_hash/canonical params).
    # Resolution — including the realpath self-warm-start guard and the
    # auto: corpus path — lives in _resolve_warm_start, ONE helper every
    # execution path (driver, fused, suggestion tenant) flows through.
    if args.suggest_serve:
        if args.fused:
            parser.error(
                "--suggest-serve answers suggestion traffic instead of "
                "sweeping; it cannot combine with --fused"
            )
        if args.chaos is not None:
            parser.error(
                "--chaos injects faults into trial evaluation; a "
                "--suggest-serve server evaluates nothing"
            )
    if args.suggest_idle_timeout is not None:
        if not args.suggest_serve:
            parser.error("--suggest-idle-timeout requires --suggest-serve")
        if args.suggest_idle_timeout <= 0:
            parser.error(
                f"--suggest-idle-timeout must be > 0, got "
                f"{args.suggest_idle_timeout}"
            )
    if args.http_port is not None:
        if not args.suggest_serve:
            parser.error("--http-port requires --suggest-serve DIR")
        if not 0 <= args.http_port <= 65535:
            parser.error(f"--http-port must be in [0, 65535], got {args.http_port}")
        if args.http_queue < 1:
            parser.error(f"--http-queue must be >= 1, got {args.http_queue}")
    elif args.http_state_dir is not None:
        parser.error("--http-state-dir requires --http-port")
    # persistent compile cache (env-gated), then platform pinning, then
    # multi-host bring-up, BEFORE anything touches the XLA backend
    # (build_mesh, workload data, backend construction all do)
    wire_compile_cache()
    pin_platform(args.platform, args.local_devices, parser.error)
    explicit = (args.coordinator, args.num_processes, args.process_id)
    if any(v is not None for v in explicit) and not all(
        v is not None for v in explicit
    ):
        parser.error(
            "--coordinator, --num-processes and --process-id must be "
            "given together (or use --multihost alone for TPU-pod "
            "auto-detection)"
        )
    if args.multihost or args.coordinator is not None:
        from mpi_opt_tpu.parallel.mesh import initialize_multihost

        try:
            initialize_multihost(
                coordinator_address=args.coordinator,
                num_processes=args.num_processes,
                process_id=args.process_id,
                require=True,
            )
        except (ValueError, RuntimeError) as e:
            # loud but actionable, matching every other user-input
            # failure's parser.error surface — not a raw jax traceback
            parser.error(
                f"multi-host bring-up failed: {e}\n(--multihost needs "
                "TPU-pod metadata; off-pod, pass --coordinator "
                "HOST:PORT --num-processes N --process-id RANK on every "
                "rank, and note bring-up must happen before any other "
                "JAX use in the process)"
            )
    # everything from here RUNS the sweep: arm the graceful-shutdown
    # protocol (SIGTERM/SIGINT set a drain flag; batch/launch boundaries
    # flush and exit EX_TEMPFAIL) and the optional progress heartbeat.
    # All three are scoped: handlers restored, heartbeat dropped, and
    # the trace sink RESTORED to its entry state on the way out — a
    # service tenant slice (in-process cli.main under serve --trace)
    # must hand the server back its own sink, not a cleared one.
    trace_entry = _trace.save()
    try:
        with _shutdown.ShutdownGuard():
            if args.heartbeat_file:
                _heartbeat.configure(args.heartbeat_file)
            return _run_sweep(args, parser, _workload=_workload)
    finally:
        _heartbeat.deconfigure()
        integrity.clear_observer()
        resources.clear_observer()
        _trace.deconfigure(trace_entry)


def _run_sweep(args, parser, _workload=None) -> int:
    """The sweep body of ``main`` (split out so the shutdown guard and
    heartbeat lifecycle wrap every path)."""
    # the service's shared instance when injected; --chaos still wraps
    # below (the wrapper is built fresh by name, so injection never
    # leaks one tenant's fault schedule into another)
    workload = _workload if _workload is not None else get_workload(args.workload)
    chaos_kwargs = None
    if args.chaos is not None:
        if args.fused or args.backend != "cpu":
            parser.error(
                "--chaos exercises the host driver's trial-level failure "
                "policy through the cpu backend; fused/TPU sweeps have no "
                "per-trial injection point (their divergence masking is "
                "always on)"
            )
        from mpi_opt_tpu.workloads.chaos import parse_chaos_spec

        try:
            chaos_kwargs = {"inner": args.workload, **parse_chaos_spec(args.chaos)}
            workload = get_workload("chaos", **chaos_kwargs)
        except ValueError as e:
            parser.error(f"--chaos: {e}")
    if args.suggest_serve:
        return run_suggest_serve(args, parser, workload)
    if args.fused:
        return run_fused(args, parser, workload)
    space = workload.default_space()
    algorithm = make_algorithm(args, space)
    mesh = None
    backend_kwargs = {}
    if args.backend == "cpu":
        backend_kwargs = {
            "n_workers": args.workers,
            "seed": args.seed,
            "trial_timeout": args.trial_timeout,
            "isolate_stateful": args.isolate_stateful,
        }
        if chaos_kwargs is not None:
            # pool workers rebuild the workload from (name, kwargs);
            # without this they would reconstruct a default (fault-free)
            # chaos wrapper and the drill would silently inject nothing
            backend_kwargs["workload_kwargs"] = chaos_kwargs
    elif args.backend == "tpu":
        mesh = build_mesh(args)
        backend_kwargs = {"population": args.population, "seed": args.seed, "mesh": mesh}
    # the metric of record is trials/sec/CHIP; normalizing by 1 on a
    # multi-chip TPU run would overstate it by the chip count, and by
    # the device count on a --no-mesh run that only uses one device —
    # so count the devices the slot pool is actually sharded over: the
    # mesh's GLOBAL size (every SPMD process drives and counts the same
    # global batches, so a per-process share would overstate per-chip
    # throughput by the host count).
    n_chips = 1
    if args.backend == "tpu" and mesh is not None:
        n_chips = int(mesh.devices.size)
    # metrics + tracing wire BEFORE backend construction so the pool
    # bring-up (dataset load, worker spawn, device upload) lands in a
    # setup span — it is most of a driver sweep's time-to-first-trial
    metrics = stdout_logger(path=args.metrics_file, n_chips=n_chips)
    _wire_integrity_observer(metrics)
    _wire_resource_observer(metrics)
    _wire_trace(args, metrics)  # restored by main's finally
    with _trace.span("setup", backend=args.backend) as _setup_sp:
        # device kind keys the roofline's platform-cap calibration
        _trace.note_device(_setup_sp)
        backend = get_backend(args.backend, workload, **backend_kwargs)
    checkpointer = None
    restored_step = None
    if args.checkpoint_dir:
        from mpi_opt_tpu.utils.checkpoint import SearchCheckpointer

        checkpointer = SearchCheckpointer(args.checkpoint_dir, every=args.checkpoint_every)
        if args.resume:
            try:
                restored_step = checkpointer.restore_into(algorithm, backend)
            except NoVerifiedSnapshotError as e:
                # every retained step failed verification: a retry (or a
                # supervisor's --resume restart) would re-read the same
                # poisoned state — abort with the distinct data-error code
                checkpointer.close()
                backend.close()
                return _data_error_exit(
                    e,
                    metrics,
                    workload=args.workload,
                    algorithm=args.algorithm,
                    backend=args.backend,
                )
            metrics.log("resume", step=restored_step)
    from mpi_opt_tpu.driver import FailurePolicy, SweepAborted
    from mpi_opt_tpu.utils.profiling import profile_window

    # the prior ledger is VALIDATED (loaded, space-hash checked) before
    # this run's own ledger header commits: a typo'd --warm-start path
    # must fail before it is journaled into a fresh ledger's identity,
    # which would refuse the corrected re-run
    warm_obs = warm_info = None
    if args.warm_start:
        warm_obs, warm_info = _resolve_warm_start(args, space, metrics, parser)
    ledger = None
    if args.ledger:
        from mpi_opt_tpu.ledger import LedgerError, SweepLedger

        # rank-0-only journaling under multi-process SPMD: every rank
        # runs the same deterministic driver loop and must replay the
        # SHARED journal identically, but N ranks fsync-appending one
        # file would interleave records and corrupt it — non-zero ranks
        # open read-only (in-memory bookkeeping only)
        ledger_rank = 0
        if args.multihost or args.coordinator is not None:
            import jax

            ledger_rank = jax.process_index()
        try:
            ledger = SweepLedger(args.ledger, read_only=ledger_rank != 0)
        except LedgerError as e:
            parser.error(f"--ledger: {e}")
        if ledger.read_only:
            metrics.log("ledger_rank_gated", rank=ledger_rank)
        if ledger.records and not args.resume:
            # explicit opt-in, same rule as --checkpoint-dir (ADVICE r2):
            # a stale journal must not silently replay an old sweep
            parser.error(
                f"--ledger {args.ledger!r} already holds "
                f"{len(ledger.records)} trial records; pass --resume to "
                "replay them, or point at a fresh path"
            )
        try:
            # the sweep's identity: everything that shapes the
            # deterministic suggestion stream the replay relies on
            # (space_spec rides top-level — corpus metadata, not identity)
            ledger.ensure_header(
                {
                    "algorithm": args.algorithm,
                    "workload": args.workload,
                    "backend": args.backend,
                    "seed": args.seed,
                    "space_hash": space.space_hash(),
                    "capacity": backend.capacity,
                    "trials": args.trials,
                    "budget": args.budget,
                    "chaos": args.chaos,
                    "warm_start": args.warm_start,
                },
                space_spec=space.spec(),
            )
        except LedgerError as e:
            parser.error(f"--ledger: {e}")
        if ledger.n_torn:
            metrics.log("ledger_torn_tail_dropped", path=args.ledger)
    if warm_obs is not None:
        if restored_step is not None:
            # the priors were ingested before that checkpoint was taken
            # and live inside the restored state (TPE/BOHB ring buffers
            # are checkpointed) — re-ingesting would double-weight them
            # in the model and re-queue already-consumed seed points
            metrics.log(
                "warm_start_skipped",
                reason="checkpoint restored (priors already in state)",
                step=restored_step,
            )
        else:
            n_warm = algorithm.ingest_observations(warm_obs)
            _log_warm_start(metrics, args, warm_info, n_warm)
    policy = FailurePolicy(
        max_retries=args.trial_retries,
        max_failure_rate=args.max_failure_rate,
        seed=args.seed,
    )
    try:
        with profile_window(
            args.profile_dir, launches=getattr(args, "profile_window", None)
        ):
            result = run_search(
                algorithm,
                backend,
                metrics=metrics,
                checkpointer=checkpointer,
                policy=policy,
                ledger=ledger,
            )
    except resources.StorageFull as e:
        # classified disk-full during a ledger fsync or checkpoint save
        # (after its one retention-prune retry): durable state intact,
        # exit 74 — free disk + --resume recovers
        return _resource_exit(
            e,
            metrics,
            "storage_full",
            workload=args.workload,
            algorithm=args.algorithm,
            backend=args.backend,
        )
    except SweepAborted as e:
        # the circuit breaker tripping is an OPERATOR outcome, not a
        # crash: summarize the counters that tripped it and exit nonzero
        # (launch.py supervisors see a retryable rc=1, not a usage error)
        metrics.summary(**{"final": True, "aborted": True})
        print(json.dumps({"aborted": str(e)}))
        print(str(e), file=sys.stderr)
        return 1
    except SweepInterrupted as e:
        # graceful preemption: run_search drained at a batch boundary —
        # every completed trial is journaled (ledger fsyncs per record)
        # and an off-cadence checkpoint was forced. EX_TEMPFAIL tells
        # the launch supervisor "restart me with --resume, for free"
        metrics.count_preempted()
        metrics.summary(final=True)
        print(
            json.dumps(
                {
                    "preempted": True,
                    "signal": e.signal,
                    "at": e.at,
                    "trials_done": metrics.trials_done,
                }
            )
        )
        print(
            f"graceful shutdown ({e.signal}): checkpoint + ledger "
            f"flushed; relaunch with --resume to continue "
            f"(exit {EX_TEMPFAIL})",
            file=sys.stderr,
        )
        return EX_TEMPFAIL
    finally:
        backend.close()
        if checkpointer is not None:
            checkpointer.close()
        if ledger is not None:
            ledger.close()
    best = result.best
    summary = {
        "workload": args.workload,
        "algorithm": args.algorithm,
        "backend": args.backend,
        "n_trials": result.n_trials,
        "wall_s": round(result.wall_s, 3),
        "trials_per_sec_per_chip": round(result.trials_per_sec_per_chip, 4),
        "trials_failed": metrics.trials_failed,
        "trials_retried": metrics.trials_retried,
        "trials_timeout": metrics.trials_timeout,
        "cache_hits": metrics.cache_hits,
        "replayed": metrics.replayed,
        "best_score": None if best is None else round(best.score, 6),
        "best_params": None
        if best is None
        else {k: v for k, v in best.params.items() if not k.startswith("__")},
    }
    metrics.summary(**{"final": True})
    print(json.dumps(_finite_or_null(summary)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
