"""CLI + config system (SURVEY.md §2 row 1).

Reference contract (BASELINE.json north_star): named algorithm
selection, ``--backend=tpu`` opt-in with the CPU path as default,
population/trial counts, workload selection.

Example (config 1, the minimum end-to-end slice):
    python -m mpi_opt_tpu --workload digits --algorithm random \
        --trials 16 --budget 100 --backend cpu --workers 4
"""

from __future__ import annotations

import argparse
import json
import sys

from mpi_opt_tpu.algorithms import ALGORITHMS, get_algorithm
from mpi_opt_tpu.backends import available_backends, get_backend
from mpi_opt_tpu.driver import run_search
from mpi_opt_tpu.ops.pbt import PBTConfig
from mpi_opt_tpu.utils.metrics import stdout_logger
from mpi_opt_tpu.workloads import available, get_workload


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi_opt_tpu",
        description="TPU-native hyperparameter optimization",
    )
    p.add_argument("--workload", required=True, choices=available())
    p.add_argument("--algorithm", default="random", choices=sorted(ALGORITHMS))
    p.add_argument(
        "--backend",
        default="cpu",
        choices=available_backends(),
        help="execution backend (cpu is the default path; tpu is opt-in)",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--trials", type=int, default=16, help="total trials (random/tpe/asha)")
    p.add_argument("--budget", type=int, default=100, help="steps per trial (random/tpe)")
    p.add_argument("--workers", type=int, default=0, help="cpu backend: processes (0=auto)")
    p.add_argument("--metrics-file", default=None, help="JSONL metrics output path")
    # checkpoint/resume (SURVEY.md §2 row 13, §5)
    p.add_argument(
        "--checkpoint-dir",
        default=None,
        help="durable search checkpoints (orbax) written here after each batch",
    )
    p.add_argument(
        "--checkpoint-every", type=int, default=1, help="batches between checkpoints"
    )
    p.add_argument(
        "--resume",
        action="store_true",
        help="resume from the latest checkpoint in --checkpoint-dir "
        "(starts fresh if the directory is empty)",
    )
    p.add_argument(
        "--profile-dir",
        default=None,
        help="capture a jax.profiler trace of the search loop here "
        "(TensorBoard-loadable)",
    )
    # ASHA
    p.add_argument("--min-budget", type=int, default=10)
    p.add_argument("--max-budget", type=int, default=270)
    p.add_argument("--eta", type=int, default=3)
    # PBT
    p.add_argument("--population", type=int, default=32)
    p.add_argument("--generations", type=int, default=10)
    p.add_argument("--steps-per-generation", type=int, default=200)
    p.add_argument("--truncation", type=float, default=0.25)
    return p


def make_algorithm(args, space):
    cls = get_algorithm(args.algorithm)
    if args.algorithm == "random":
        return cls(space, seed=args.seed, max_trials=args.trials, budget=args.budget)
    if args.algorithm == "tpe":
        return cls(space, seed=args.seed, max_trials=args.trials, budget=args.budget)
    if args.algorithm == "asha":
        return cls(
            space,
            seed=args.seed,
            max_trials=args.trials,
            min_budget=args.min_budget,
            max_budget=args.max_budget,
            eta=args.eta,
        )
    if args.algorithm == "pbt":
        return cls(
            space,
            seed=args.seed,
            population=args.population,
            generations=args.generations,
            steps_per_generation=args.steps_per_generation,
            config=PBTConfig(truncation_frac=args.truncation),
        )
    raise AssertionError(args.algorithm)


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.resume and not args.checkpoint_dir:
        parser.error("--resume requires --checkpoint-dir")
    workload = get_workload(args.workload)
    space = workload.default_space()
    algorithm = make_algorithm(args, space)
    backend_kwargs = {}
    if args.backend == "cpu":
        backend_kwargs = {"n_workers": args.workers, "seed": args.seed}
    elif args.backend == "tpu":
        backend_kwargs = {"population": args.population, "seed": args.seed}
    backend = get_backend(args.backend, workload, **backend_kwargs)
    # the metric of record is trials/sec/CHIP; normalizing by 1 on a
    # multi-chip TPU run would overstate it by the chip count. Local
    # devices, not global: each host's driver counts only its own
    # trials, so dividing by the global count would understate per-chip
    # throughput by the host count. (On 2-core-per-chip generations this
    # is per-core, the conservative direction.)
    n_chips = 1
    if args.backend == "tpu":
        import jax

        n_chips = jax.local_device_count()
    metrics = stdout_logger(path=args.metrics_file, n_chips=n_chips)
    checkpointer = None
    if args.checkpoint_dir:
        from mpi_opt_tpu.utils.checkpoint import SearchCheckpointer

        checkpointer = SearchCheckpointer(args.checkpoint_dir, every=args.checkpoint_every)
        if args.resume:
            step = checkpointer.restore_into(algorithm, backend)
            metrics.log("resume", step=step)
    from mpi_opt_tpu.utils.profiling import profile_window

    try:
        with profile_window(args.profile_dir):
            result = run_search(
                algorithm, backend, metrics=metrics, checkpointer=checkpointer
            )
    finally:
        backend.close()
        if checkpointer is not None:
            checkpointer.close()
    best = result.best
    summary = {
        "workload": args.workload,
        "algorithm": args.algorithm,
        "backend": args.backend,
        "n_trials": result.n_trials,
        "wall_s": round(result.wall_s, 3),
        "trials_per_sec_per_chip": round(result.trials_per_sec_per_chip, 4),
        "best_score": None if best is None else round(best.score, 6),
        "best_params": None
        if best is None
        else {k: v for k, v in best.params.items() if not k.startswith("__")},
    }
    metrics.summary(**{"final": True})
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
