"""Dataset registry. See package docstring for the no-network policy."""

from __future__ import annotations

import numpy as np

from mpi_opt_tpu.data.synthetic import make_image_classification

_CACHE: dict = {}


def _sklearn_tabular(loader_name: str, seed: int = 0, val_frac: float = 0.25):
    from sklearn import datasets as skd
    from sklearn.model_selection import train_test_split

    d = getattr(skd, loader_name)()
    x = np.asarray(d.data, dtype=np.float32)
    x = (x - x.mean(0)) / (x.std(0) + 1e-8)
    y = np.asarray(d.target)
    classification = y.dtype.kind in "iu"
    y = y.astype(np.int32) if classification else y.astype(np.float32)
    xtr, xva, ytr, yva = train_test_split(
        x, y, test_size=val_frac, random_state=seed,
        stratify=y if classification else None,
    )
    return {
        "train_x": xtr,
        "train_y": ytr,
        "val_x": xva,
        "val_y": yva,
        "n_classes": int(y.max()) + 1 if classification else 0,
    }


def _digits_images(seed: int = 0):
    """sklearn digits reshaped to [n, 8, 8, 1] images."""
    d = _sklearn_tabular("load_digits", seed)
    for k in ("train_x", "val_x"):
        d[k] = d[k].reshape(-1, 8, 8, 1)
    return d


DATASETS = {
    # real offline data
    "digits": lambda seed=0: _sklearn_tabular("load_digits", seed),
    "digits_image": _digits_images,
    "wine": lambda seed=0: _sklearn_tabular("load_wine", seed),
    "breast_cancer": lambda seed=0: _sklearn_tabular("load_breast_cancer", seed),
    "diabetes": lambda seed=0: _sklearn_tabular("load_diabetes", seed),  # regression
    # synthetic stand-ins, original shapes (no network in this container)
    "fashion_mnist": lambda seed=0, n_train=16384, n_val=2048, **kw: make_image_classification(
        n_train, n_val, 28, 28, 1, 10, seed=seed, **kw
    ),
    # cifar10 difficulty calibrated AT BENCH SCALE on the real chip
    # (2026-07-29: pop=32, batch 256, 8x100 steps, random hparams):
    # best-of-pop climbs 0.17 -> 0.69 across generations and keeps
    # rising — so config 3's metric of record (wall-clock to target
    # val-acc) discriminates instead of saturating at 1.0 in one
    # generation, which is what the old defaults (delta=0.2, noise=1.5,
    # protos=4, coarse=4) did.
    "cifar10": lambda seed=0, n_train=16384, n_val=2048, **kw: make_image_classification(
        n_train, n_val, 32, 32, 3, 10, seed=seed,
        **{"delta": 0.1, "noise": 2.0, "protos": 16, "coarse": 8, **kw}
    ),
    # label_noise=0.35: irreducible-error ceiling 1 - p + p/K = 0.6535,
    # so config-5's val-acc curve plateaus ~0.65 instead of memorizing
    # to 0.999 (round-3 verdict weak #3) and a 0.5 target sits mid-curve
    "cifar100": lambda seed=0, n_train=16384, n_val=2048, **kw: make_image_classification(
        n_train, n_val, 32, 32, 3, 100, seed=seed,
        **{"coarse": 6, "noise": 1.2, "delta": 0.3, "label_noise": 0.35, **kw}
    ),
}


def load_dataset(name: str, **kwargs):
    key = (name, tuple(sorted(kwargs.items())))
    if key not in _CACHE:
        try:
            fn = DATASETS[name]
        except KeyError:
            raise ValueError(f"unknown dataset {name!r}; available: {sorted(DATASETS)}") from None
        _CACHE[key] = fn(**kwargs)
    return _CACHE[key]
