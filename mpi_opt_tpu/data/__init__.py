"""Data loading (SURVEY.md §2 row 11).

The reference's workloads are Fashion-MNIST, CIFAR-10, CIFAR-100, UCI
tabular and sklearn digits (BASELINE.json configs). This container has
**no network**, so the torchvision/keras downloads those imply are
impossible; datasets resolve as:

- ``digits``, ``wine``, ``breast_cancer``, ``diabetes``: real data, from
  sklearn's offline bundles (UCI-derived tabular + image data).
- ``fashion_mnist``, ``cifar10``, ``cifar100``: deterministic synthetic
  stand-ins with the exact shapes/dtypes/class counts of the originals
  (see synthetic.py for the generative recipe). Benchmarks measure
  throughput, which depends on shapes, not pixels; accuracy-style tests
  assert learnability of the synthetic task instead of absolute numbers.

All loaders return host numpy; device placement is the backend's job
(one transfer per search, not per trial — that is the point of the
TPU-native design).
"""

from mpi_opt_tpu.data.loaders import DATASETS, load_dataset

__all__ = ["load_dataset", "DATASETS"]
