"""Deterministic synthetic image-classification data.

Recipe: each class gets a smooth spatial template (coarse Gaussian noise
bilinearly upsampled — low-frequency, so convolutions have structure to
find), plus per-sample Gaussian noise and a random brightness jitter.
The SNR is chosen so a small CNN separates classes quickly but not
instantly (useful for early-stopping/PBT dynamics), and a linear model
underperforms a conv net (architecture matters, as with the real sets).

Generated with numpy's Philox counter RNG from a fixed seed: stable
across processes and platforms, no files, ~100 MB/s generation rate.
"""

from __future__ import annotations

import numpy as np


def _upsample_bilinear(x: np.ndarray, h: int, w: int) -> np.ndarray:
    """[n, ch, cw, c] coarse -> [n, h, w, c] smooth (separable linear)."""
    n, ch, cw, c = x.shape
    ys = np.linspace(0, ch - 1, h)
    xs = np.linspace(0, cw - 1, w)
    y0 = np.clip(np.floor(ys).astype(int), 0, ch - 2)
    x0 = np.clip(np.floor(xs).astype(int), 0, cw - 2)
    wy = (ys - y0)[None, :, None, None]
    wx = (xs - x0)[None, None, :, None]
    a = x[:, y0][:, :, x0]
    b = x[:, y0 + 1][:, :, x0]
    cc = x[:, y0][:, :, x0 + 1]
    d = x[:, y0 + 1][:, :, x0 + 1]
    return (
        a * (1 - wy) * (1 - wx)
        + b * wy * (1 - wx)
        + cc * (1 - wy) * wx
        + d * wy * wx
    ).astype(np.float32)


def make_image_classification(
    n_train: int,
    n_val: int,
    h: int,
    w: int,
    c: int,
    n_classes: int,
    seed: int = 0,
    noise: float = 1.5,
    coarse: int = 4,
    delta: float = 0.2,
    protos: int = 4,
    label_noise: float = 0.0,
):
    """Returns dict(train_x, train_y, val_x, val_y); float32 images.

    Difficulty comes from class *overlap*, not pixel noise alone: each
    image is drawn from one of ``protos`` per-class prototypes, and a
    prototype = shared background + ``delta`` * class signal + prototype
    variation. With small ``delta`` the class signal is a minor part of
    every image, so accuracy grows with training budget instead of
    saturating immediately (pure per-class templates are linearly
    separable almost instantly at any noise level).

    ``label_noise``: fraction of labels (train AND val, independently)
    re-drawn uniformly over the classes AFTER the image is built from
    the true class — an IRREDUCIBLE error ceiling. The Bayes classifier
    predicts the true class, so the best reachable val accuracy is
    ``1 - p + p/K``: a benchmark curve plateaus there instead of at
    ~1.0, which is what makes mid-curve wall-to-target figures
    discriminate hyperparameters (an 11M-param net memorizing a clean
    synthetic task to 0.999 measures memorization speed, not search
    quality — round-3 verdict weak #3).
    """
    rng = np.random.Generator(np.random.Philox(seed))
    up = lambda z: _upsample_bilinear(z.astype(np.float32), h, w)
    common = up(rng.normal(size=(1, coarse, coarse, c)))  # shared background
    class_sig = up(rng.normal(size=(n_classes, coarse, coarse, c)))
    proto_var = up(rng.normal(size=(n_classes * protos, coarse, coarse, c))).reshape(
        n_classes, protos, h, w, c
    )
    # [K, P, h, w, c]
    templates = common[:, None] + delta * class_sig[:, None] + 0.5 * proto_var

    def split(n, salt):
        r = np.random.Generator(np.random.Philox([seed, salt]))
        y = r.integers(0, n_classes, size=n)
        p = r.integers(0, protos, size=n)
        x = templates[y, p]
        x = x + r.normal(scale=noise, size=x.shape).astype(np.float32)
        x = x * (1.0 + 0.1 * r.normal(size=(n, 1, 1, 1)).astype(np.float32))
        # normalize to a stable range
        x = (x - x.mean()) / (x.std() + 1e-8)
        if label_noise > 0.0:
            # AFTER x: the image carries the true class signal, the
            # recorded label lies with probability p*(1-1/K)
            flip = r.random(n) < label_noise
            y = np.where(flip, r.integers(0, n_classes, size=n), y)
        return x.astype(np.float32), y.astype(np.int32)

    train_x, train_y = split(n_train, 1)
    val_x, val_y = split(n_val, 2)
    return {
        "train_x": train_x,
        "train_y": train_y,
        "val_x": val_x,
        "val_y": val_y,
        "n_classes": n_classes,
    }
