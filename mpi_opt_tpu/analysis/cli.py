"""``mpi_opt_tpu lint [PATHS] [--json] [--baseline FILE]``.

Dispatched from cli.py like ``report``/``fsck``/``trace``; never
touches jax. Exit 0 = no non-baselined findings (and no unparseable
files), 1 = findings (or scan errors), 2 = usage.

The JSON schema mirrors the ``fsck``/``report --validate`` pattern —
one stable top-level object a CI gate can parse::

    {"ok": bool, "tool": "sweeplint", "files_scanned": N,
     "findings": [{"check", "file", "line", "severity", "message",
                   "hint"}, ...],
     "baselined": [...same shape...], "errors": [str, ...],
     "checks": [{"id", "severity", "hint", "wall_s"}, ...],
     "project": {"locks": [...], "thread_entries": [...],
                 "signal_handlers": [...], "beat_entries": [...],
                 "lock_order": {"edges": [...], "cycles": [...]}}}

``wall_s`` is each checker's attributed wall time (the full-repo
self-lint budgets <15 s total; per-checker attribution makes a future
slow checker a number instead of a mystery) — the synthetic
``project-table`` entry carries the pass-1 symbol-table build + link
time, which belongs to no single checker — and ``project`` is the
racelint pass-1 digest (ISSUE 15), null when the run carried no
project checkers.

``--write-baseline FILE`` records the CURRENT findings as accepted —
the adoption workflow: run it once on a legacy tree, commit the file,
and the gate only fails on NEW findings from then on. (This repo's
committed ``sweeplint-baseline.json`` is empty by policy: ISSUE 9 fixed
every true positive and marked deliberate cases inline with
``# sweeplint: disable`` — the baseline exists so the NEXT big refactor
can stage fixes without turning the gate off.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from mpi_opt_tpu.analysis import all_checkers
from mpi_opt_tpu.analysis.core import (
    load_baseline,
    run_paths_ex,
    split_baselined,
    write_baseline,
)
from mpi_opt_tpu.utils.exitcodes import EX_FAILURE, EX_OK


def repo_root() -> str:
    """Default scan root: the directory HOLDING the mpi_opt_tpu package
    (the repo checkout in every supported layout), so bare
    ``mpi_opt_tpu lint`` covers package + top-level scripts (bench.py,
    launch entry) exactly like the tier-1 self-lint."""
    import mpi_opt_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(mpi_opt_tpu.__file__)))


def lint_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="mpi_opt_tpu lint",
        description="AST invariant checks for the sweep engine's "
        "contracts (see README: Static analysis)",
    )
    p.add_argument(
        "paths",
        nargs="*",
        metavar="PATH",
        help="files/directories to lint (default: the repo root; "
        "tests/ and probes/ are always excluded)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable report on stdout"
    )
    p.add_argument(
        "--baseline",
        default=None,
        metavar="FILE",
        help="accepted-legacy-findings file: findings fingerprinted "
        "there are reported separately and never fail the run",
    )
    p.add_argument(
        "--write-baseline",
        default=None,
        metavar="FILE",
        help="record the current findings as the accepted baseline "
        "and exit 0 (the adoption workflow)",
    )
    args = p.parse_args(argv)

    root = repo_root()
    paths = args.paths or [root]
    for path in paths:
        if not os.path.exists(path):
            p.error(f"{path!r} does not exist")
    baseline = []
    if args.baseline is not None:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            p.error(f"--baseline: {e}")

    checkers = all_checkers()
    findings, n_files, errors, table = run_paths_ex(paths, checkers)
    fresh, accepted = split_baselined(findings, baseline, root)

    if args.write_baseline is not None:
        if errors:
            # a baseline recorded while files are unparseable is a lie:
            # every finding in those files would later surface as "new"
            # (or ship unrecorded) — refuse, same no-silent-skips rule
            # as the lint itself
            for e in errors:
                print(f"scan error: {e}", file=sys.stderr)
            print(
                f"refusing to write a baseline over {len(errors)} "
                "unparseable file(s) — fix them and re-run",
                file=sys.stderr,
            )
            return EX_FAILURE
        write_baseline(args.write_baseline, findings, root)
        print(
            f"wrote {len(findings)} accepted finding(s) to "
            f"{args.write_baseline}",
            file=sys.stderr,
        )
        return EX_OK

    ok = not fresh and not errors
    if args.json:
        from mpi_opt_tpu.analysis import project as project_mod

        print(
            json.dumps(
                {
                    "ok": ok,
                    "tool": "sweeplint",
                    "files_scanned": n_files,
                    "findings": [f.as_dict(root) for f in fresh],
                    "baselined": [f.as_dict(root) for f in accepted],
                    "errors": errors,
                    "checks": [
                        {
                            "id": c.id,
                            "severity": c.severity,
                            "hint": c.hint,
                            "wall_s": round(c.wall_s, 4),
                        }
                        for c in checkers
                    ]
                    + (
                        # the symbol-table build is the project pass's
                        # dominant cost and belongs to no one checker;
                        # a synthetic entry keeps wall attribution
                        # honest (a slow build must be a number too)
                        [
                            {
                                "id": "project-table",
                                "severity": "info",
                                "hint": "racelint pass-1 symbol-table "
                                "build + call-graph link (shared by "
                                "all project checkers)",
                                "wall_s": round(table.build_wall_s, 4),
                            }
                        ]
                        if table is not None
                        else []
                    ),
                    "project": (
                        None if table is None else project_mod.summary(table, root)
                    ),
                }
            )
        )
    else:
        for f in fresh:
            print(f.render(root))
        for f in accepted:
            print(f"{f.render(root)} [baselined]")
        for e in errors:
            print(f"scan error: {e}", file=sys.stderr)
        tail = f"{n_files} file(s), {len(fresh)} finding(s)"
        if accepted:
            tail += f", {len(accepted)} baselined"
        if errors:
            tail += f", {len(errors)} unparseable"
        print(("OK: " if ok else "FAIL: ") + tail, file=sys.stderr)
    return EX_OK if ok else EX_FAILURE
