"""sweeplint: AST invariant checkers for the sweep engine's contracts.

Eight PRs of review rounds accreted cross-cutting invariants — fsync-
before-report / journal-before-snapshot ordering, rank-0-gated ledger
writes, exit codes only from ``utils/exitcodes``, atomic tmp+rename
status writes, drain exceptions that must propagate, PRNG-key split
discipline, no host syncs in the fused hot path, the event/span name
registry — that previously lived only in review memory and CHANGES.md
prose. This package machine-checks them, so the multi-file refactors
the ROADMAP plans next cannot silently regress them.

Surface:

- ``mpi_opt_tpu lint [PATHS] [--json] [--baseline FILE]`` (cli.py
  dispatch -> :mod:`mpi_opt_tpu.analysis.cli`), exit 0/1;
- inline suppressions: ``# sweeplint: disable=<id>[,<id>] -- reason``
  on the finding line or the line above;
- barrier annotations for the host-sync checker:
  ``# sweeplint: barrier(reason)`` on a ``def`` line exempts that
  function's DIRECT body (nested defs are judged on their own);
- a committed baseline (``sweeplint-baseline.json``) for accepted
  legacy findings, fingerprinted by (check, file, line content) so
  line-number drift never invalidates it;
- the tier-1 self-lint (tests/test_analysis.py) runs the whole suite
  over the repo.
"""

from __future__ import annotations

from mpi_opt_tpu.analysis.core import (  # noqa: F401
    Checker,
    FileContext,
    Finding,
    check_source,
    iter_python_files,
    run_paths,
)


def all_checkers():
    """One fresh instance of every registered checker (stateless between
    files by contract; a fresh set per run keeps that honest)."""
    from mpi_opt_tpu.analysis.checkers_concurrency import (
        BeatPathChecker,
        FsyncBeforeRenameChecker,
        GuardedByChecker,
        LockOrderChecker,
        SignalSafetyChecker,
    )
    from mpi_opt_tpu.analysis.checkers_coord import CoordWriteChecker
    from mpi_opt_tpu.analysis.checkers_corpus import CorpusIndexWriteChecker
    from mpi_opt_tpu.analysis.checkers_drain import DrainSwallowChecker
    from mpi_opt_tpu.analysis.checkers_durability import (
        AtomicWriteChecker,
        JournalOrderChecker,
        LedgerFsyncChecker,
        LedgerGateChecker,
    )
    from mpi_opt_tpu.analysis.checkers_exit import ExitCodeChecker
    from mpi_opt_tpu.analysis.checkers_http import HttpHandlerChecker
    from mpi_opt_tpu.analysis.checkers_jax import HostSyncChecker, KeyReuseChecker
    from mpi_opt_tpu.analysis.checkers_lease import LeaseWriteChecker
    from mpi_opt_tpu.analysis.checkers_registry import EventRegistryChecker
    from mpi_opt_tpu.analysis.checkers_resources import ResourceFunnelChecker

    return [
        ExitCodeChecker(),
        JournalOrderChecker(),
        LedgerGateChecker(),
        AtomicWriteChecker(),
        LedgerFsyncChecker(),
        DrainSwallowChecker(),
        KeyReuseChecker(),
        HostSyncChecker(),
        EventRegistryChecker(),
        LeaseWriteChecker(),
        CoordWriteChecker(),
        CorpusIndexWriteChecker(),
        ResourceFunnelChecker(),
        FsyncBeforeRenameChecker(),
        HttpHandlerChecker(),
        # project-pass checkers (racelint, ISSUE 15): run over the
        # repo-wide symbol table after every file is parsed
        GuardedByChecker(),
        BeatPathChecker(),
        SignalSafetyChecker(),
        LockOrderChecker(),
    ]
