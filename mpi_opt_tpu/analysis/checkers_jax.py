"""JAX-discipline checkers: PRNG key reuse, host syncs in the hot path.

- **key-reuse** — the same key NAME fed to two ``jax.random`` consumers
  with no intervening rebind. PR 4's wave/resident bit-identity proof
  hinged on exact key-split discipline (``split(k_init, P)`` windows,
  per-step split streams); a copy-pasted ``normal(key, ...)`` pair
  correlates draws that every algorithm assumes independent, and
  nothing crashes — the search just quietly degrades. The checker is
  deliberately linear and local: each straight-line statement list is
  judged on its own (no cross-branch joins), so an if/else that each
  consume a key once never false-positives.
- **host-sync** — ``.item()`` / ``np.asarray`` / ``.block_until_ready``
  / ``jax.device_get`` inside the fused hot-path modules
  (``train/fused_*.py``, ``train/staging.py``) force a device
  round-trip and serialize the dispatch pipeline; PERF_NOTES attributes
  real plateau time to exactly such accidental syncs. Boundary code
  MUST sync (scores must reach the host to exploit/journal) — those
  functions carry an explicit ``# sweeplint: barrier(reason)`` on their
  ``def`` line, which exempts the function's DIRECT body; nested defs
  are judged on their own, so a traced program builder nested inside an
  annotated host loop stays protected.
"""

from __future__ import annotations

import ast

from mpi_opt_tpu.analysis.core import Checker, FileContext

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# -- key-reuse -----------------------------------------------------------

#: jax.random callables whose FIRST argument is consumed key material.
#: fold_in/key_data/wrap_key_data/PRNGKey/key are deliberately absent:
#: fold_in DERIVES (reusing the base key with different data is the
#: idiom), the others convert representations.
_KEY_CONSUMERS = frozenset(
    {
        "split",
        "normal",
        "uniform",
        "bernoulli",
        "randint",
        "permutation",
        "categorical",
        "choice",
        "gumbel",
        "exponential",
        "truncated_normal",
        "dirichlet",
        "beta",
        "gamma",
        "poisson",
        "laplace",
        "cauchy",
        "logistic",
        "rademacher",
        "shuffle",
        "bits",
    }
)

#: module spellings that mean jax.random at a call site
_RANDOM_BASES = frozenset({"random", "jrandom", "jr"})
#: bases whose `.random` is NOT jax's (numpy's legacy global RNG)
_NOT_JAX = frozenset({"np", "numpy"})


def _consumed_key(call: ast.Call):
    """The key variable name a jax.random consumer call consumes, else
    None. Conservative: only ``<random-module>.<consumer>(<Name>, ...)``
    shapes count — a computed or attribute key expression is skipped
    rather than guessed about."""
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr in _KEY_CONSUMERS):
        return None
    base = fn.value
    if isinstance(base, ast.Name):
        if base.id not in _RANDOM_BASES:
            return None
    elif isinstance(base, ast.Attribute):
        if base.attr != "random":
            return None
        if isinstance(base.value, ast.Name) and base.value.id in _NOT_JAX:
            return None
    else:
        return None
    if call.args and isinstance(call.args[0], ast.Name):
        return call.args[0].id
    return None


def _bound_names(stmt):
    """Names (re)bound by one statement: assignment targets, for/with
    bindings, walrus — anything that makes a key name FRESH again."""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign, ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in stmt.items if i.optional_vars]
    out = set()
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name):
                out.add(sub.id)
    # walrus anywhere in the statement's expressions
    for sub in ast.walk(stmt):
        if isinstance(sub, ast.NamedExpr) and isinstance(sub.target, ast.Name):
            out.add(sub.target.id)
    return out


def _stmt_consumers(stmt):
    """Consumer calls in ``stmt``'s OWN expressions, in source order —
    not in nested statements (an if/else's arms are separate regions;
    counting both arms here would false-positive a perfectly balanced
    branch pair) and not in nested function defs."""
    found = []
    stack = list(ast.iter_child_nodes(stmt))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.stmt, ast.Lambda)):
            continue  # nested statements are their own region
        if isinstance(node, ast.Call):
            name = _consumed_key(node)
            if name is not None:
                found.append((node.lineno, node.col_offset, name, node))
        stack.extend(ast.iter_child_nodes(node))
    return sorted(found, key=lambda t: (t[0], t[1]))


class KeyReuseChecker(Checker):
    id = "key-reuse"
    hint = (
        "split the key first (k1, k2 = jax.random.split(key)) and give "
        "each consumer its own stream"
    )
    interests = _FUNC_NODES + (ast.Module,)

    def visit(self, node, ctx: FileContext) -> None:
        # every statement LIST in this scope is one straight-line region
        # (if/else arms, loop bodies, try blocks each stand alone);
        # nested function defs are their own visit.
        for region in self._regions(node):
            consumed: dict = {}
            for stmt in region:
                for lineno, _col, name, _call in _stmt_consumers(stmt):
                    if name in consumed:
                        self.report(
                            ctx,
                            lineno,
                            f"PRNG key {name!r} consumed again (first use "
                            f"line {consumed[name]}) with no intervening "
                            "split/rebind — correlated draws",
                        )
                    else:
                        consumed[name] = lineno
                for name in _bound_names(stmt):
                    consumed.pop(name, None)

    def _regions(self, scope):
        stack = [scope]
        while stack:
            node = stack.pop()
            for fieldname in ("body", "orelse", "finalbody"):
                body = getattr(node, fieldname, None)
                if isinstance(body, list) and body:
                    yield body
                    for ch in body:
                        if not isinstance(ch, (*_FUNC_NODES, ast.Lambda)):
                            stack.append(ch)
            for handler in getattr(node, "handlers", ()) or ():
                yield handler.body
                for ch in handler.body:
                    if not isinstance(ch, (*_FUNC_NODES, ast.Lambda)):
                        stack.append(ch)


# -- host-sync -----------------------------------------------------------

_SYNC_ATTRS = frozenset({"item", "block_until_ready"})


def _sync_kind(call: ast.Call):
    fn = call.func
    if isinstance(fn, ast.Attribute):
        if fn.attr in _SYNC_ATTRS and not call.args:
            return f".{fn.attr}()"
        if fn.attr == "asarray" and isinstance(fn.value, ast.Name):
            if fn.value.id in ("np", "numpy"):
                return "np.asarray"
        if fn.attr == "device_get":
            return "jax.device_get"
    elif isinstance(fn, ast.Name) and fn.id == "device_get":
        return "device_get"
    return None


class HostSyncChecker(Checker):
    id = "host-sync"
    severity = "error"
    hint = (
        "hot-path code must stay async; if this IS boundary/host code, "
        "annotate the def line: # sweeplint: barrier(reason)"
    )
    interests = _FUNC_NODES + (ast.Module,)

    def interested(self, ctx: FileContext) -> bool:
        p = ctx.path.replace("\\", "/")
        name = p.rsplit("/", 1)[-1]
        return "train/" in p and (
            name.startswith("fused_") or name == "staging.py"
        )

    def _annotated_barrier(self, fn, ctx: FileContext) -> bool:
        start = fn.lineno
        for dec in getattr(fn, "decorator_list", ()):
            start = min(start, dec.lineno)
        end = fn.body[0].lineno if fn.body else fn.lineno
        return any(ln in ctx.barriers for ln in range(start, end + 1))

    def visit(self, node, ctx: FileContext) -> None:
        if isinstance(node, _FUNC_NODES) and self._annotated_barrier(node, ctx):
            return
        for call in self._direct_calls(node):
            kind = _sync_kind(call)
            if kind is None:
                continue
            # line-level barrier: one-off sync lines can be annotated
            # without exempting the whole function
            if call.lineno in ctx.barriers or call.lineno - 1 in ctx.barriers:
                continue
            self.report(
                ctx,
                call,
                f"{kind} forces a host sync in a fused hot-path module — "
                "only annotated barrier functions may block on the device",
            )

    @staticmethod
    def _direct_calls(scope):
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if isinstance(node, (*_FUNC_NODES, ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))
