"""corpus-index-write: index files are written ONLY by corpus/index.py.

The corpus index is derived state read by ``--warm-start auto:``
resolution while sweeps run concurrently — a TORN index (half a JSON
document behind an ``open(path, "w")``) would make a sweep silently
resolve against half a corpus, the exact quiet-failure class the
atomic ``write_index`` helper (tmp + fsync + rename) exists to close.
This checker is the lease-write pattern (ISSUE 12 / checkers_lease.py)
applied to the corpus: any index write outside the helper's home
module is a lint error, so a future refactor cannot re-open the
read-a-partial-document window and have nothing fail until a sweep
races an indexer.

What is flagged, outside ``corpus/index.py``:

- ``open(<index-ish>, "w"/"a"/...)`` — any write/append/update mode;
- ``os.open(<index-ish>, ...)`` — flag-driven writes included;
- ``os.replace``/``os.rename`` whose either operand is index-ish (a
  rename ONTO the index is an index write; renaming it away would be a
  tomb protocol this file does not have — both are helper-only);
- ``os.unlink``/``os.remove`` of an index-ish path (deleting the index
  out from under a resolving sweep is also a write to its state).

"Index-ish" is judged lexically and conservatively, mirroring the
lease checker: a string constant containing ``corpus-index`` (the
on-disk name) or an identifier whose underscore-split words contain
the ``corpus_index`` pair — so ``corpus-index.json``,
``corpus_index_path`` match while ``index``, ``reindex`` and every
ordinary use of the word never do. Reads stay free: resolution and
the report surfaces may inspect the index at will.
"""

from __future__ import annotations

import ast
import re

from mpi_opt_tpu.analysis.core import Checker, FileContext

#: `corpus_index` as adjacent whole words inside an identifier's
#: underscore-split: `corpus_index`, `corpus_index_path` yes;
#: `index`, `corpus`, `corpus_reindex` no
_INDEX_WORD = re.compile(r"(?:^|_)corpus_index(?:_|$)")


def _index_ident(name: str) -> bool:
    return bool(_INDEX_WORD.search(name))


def _mentions_index(node) -> bool:
    """Does this expression lexically name a corpus-index path?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            if "corpus-index" in sub.value or _index_ident(sub.value):
                return True
        elif isinstance(sub, ast.Name) and _index_ident(sub.id):
            return True
        elif isinstance(sub, ast.Attribute) and _index_ident(sub.attr):
            return True
    return False


def _callee(fn):
    if isinstance(fn, ast.Attribute):
        base = fn.value.id if isinstance(fn.value, ast.Name) else ""
        return base, fn.attr
    if isinstance(fn, ast.Name):
        return "", fn.id
    return "", ""


_WRITE_MODES = re.compile(r"[wax+]")


class CorpusIndexWriteChecker(Checker):
    id = "corpus-index-write"
    hint = (
        "go through corpus/index.py (write_index: tmp + fsync + atomic "
        "rename) — a torn index makes --warm-start auto: resolve half "
        "a corpus"
    )
    interests = (ast.Call,)

    def interested(self, ctx: FileContext) -> bool:
        # the atomic helper's own home is the one legal writer
        return not ctx.path.replace("\\", "/").endswith("corpus/index.py")

    def visit(self, node, ctx: FileContext) -> None:
        base, name = _callee(node.func)
        if name == "open":
            if not node.args or not _mentions_index(node.args[0]):
                return
            if base == "os":
                self.report(
                    ctx,
                    node,
                    "os.open of a corpus-index path outside corpus/index.py",
                )
                return
            mode = None
            if len(node.args) >= 2:
                mode = node.args[1]
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if (
                isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and _WRITE_MODES.search(mode.value)
            ):
                self.report(
                    ctx,
                    node,
                    f"open(..., {mode.value!r}) on a corpus-index path "
                    "outside corpus/index.py",
                )
            return
        if base != "os":
            return
        if name in ("replace", "rename"):
            if any(_mentions_index(a) for a in node.args[:2]):
                self.report(
                    ctx,
                    node,
                    f"os.{name} involving a corpus-index path outside "
                    "corpus/index.py (atomic updates are helper-only)",
                )
        elif name in ("unlink", "remove"):
            if node.args and _mentions_index(node.args[0]):
                self.report(
                    ctx,
                    node,
                    f"os.{name} of a corpus-index path outside "
                    "corpus/index.py (deleting the index under a "
                    "resolving sweep is a write to its state)",
                )
