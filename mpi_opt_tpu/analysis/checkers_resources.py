"""resource-funnel: resource-exhaustion handling outside the classifier.

ISSUE 13 funneled the two scale-out failure classes into
``utils/resources.py``: XLA ``RESOURCE_EXHAUSTED`` becomes typed
``DeviceOOM`` (wave backoff / classified exit 74) and ENOSPC/EDQUOT
becomes ``StorageFull`` (prune-then-park). The funnel only holds if
nothing ELSE quietly grows its own handling — an ad-hoc
``except XlaRuntimeError`` swallow or a ``"RESOURCE_EXHAUSTED" in
str(e)`` probe in a driver would bypass the backoff and the exit-code
contract, and a bare ``errno.ENOSPC`` comparison would re-inline the
storage classification the spool/checkpoint layers now ask
``is_storage_full`` about. Flagged shapes (outside utils/resources.py):

- an ``except`` clause or ``isinstance`` check naming
  ``XlaRuntimeError`` / ``JaxRuntimeError`` (catch/ask the classified
  ``DeviceOOM`` instead; the one deliberate keep — cli.py's transient-
  platform-death classifier — carries an inline disable with reason);
- a ``"RESOURCE_EXHAUSTED"`` string literal used in a COMPARISON
  (``in`` / ``==`` probes — the ad-hoc swallow shape; docstrings and
  messages merely mentioning the token are not handling and pass);
- ``errno.ENOSPC`` / ``errno.EDQUOT`` references (attribute or
  from-import): the storage-exhaustion predicate is
  ``resources.is_storage_full``.
"""

from __future__ import annotations

import ast

from mpi_opt_tpu.analysis.core import Checker, FileContext

_XLA_ERROR_NAMES = frozenset({"XlaRuntimeError", "JaxRuntimeError"})
_STORAGE_ERRNO_NAMES = frozenset({"ENOSPC", "EDQUOT"})
#: held in a constant (not inline) so this checker's own source does
#: not carry the literal-in-a-Compare shape it flags
_OOM_TOKEN = "RESOURCE_EXHAUSTED"


def _names_xla_error(expr) -> bool:
    """Does this type expression (possibly a tuple) name the raw XLA
    runtime error class?"""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in _XLA_ERROR_NAMES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _XLA_ERROR_NAMES:
            return True
    return False


class ResourceFunnelChecker(Checker):
    id = "resource-funnel"
    hint = (
        "classify through mpi_opt_tpu.utils.resources "
        "(is_device_oom/is_storage_full, DeviceOOM/StorageFull, oom_funnel)"
    )
    interests = (ast.ExceptHandler, ast.Call, ast.Compare, ast.Attribute, ast.ImportFrom)

    def interested(self, ctx: FileContext) -> bool:
        # the one home for the raw markers; the classifier itself must
        # hold them
        return not ctx.path.endswith("utils/resources.py")

    def visit(self, node, ctx: FileContext) -> None:
        if isinstance(node, ast.ExceptHandler):
            if node.type is not None and _names_xla_error(node.type):
                self.report(
                    ctx,
                    node,
                    "except clause names the raw XLA runtime error — "
                    "catch the classified DeviceOOM (utils/resources) "
                    "so the OOM funnel/backoff is not bypassed",
                )
            return
        if isinstance(node, ast.Call):
            fn = node.func
            is_isinstance = (
                isinstance(fn, ast.Name) and fn.id == "isinstance"
            )
            if is_isinstance and any(_names_xla_error(a) for a in node.args[1:]):
                self.report(
                    ctx,
                    node,
                    "isinstance check against the raw XLA runtime error — "
                    "ask utils.resources.is_device_oom (type gate "
                    "included) instead of re-deriving the classification",
                )
            return
        if isinstance(node, ast.Compare):
            for operand in (node.left, *node.comparators):
                if (
                    isinstance(operand, ast.Constant)
                    and isinstance(operand.value, str)
                    and _OOM_TOKEN in operand.value.upper()
                ):
                    self.report(
                        ctx,
                        node,
                        f"{_OOM_TOKEN} message probe — ad-hoc OOM "
                        "classification belongs in utils/resources "
                        "(is_device_oom)",
                    )
                    return
            return
        if isinstance(node, ast.Attribute):
            if node.attr in _STORAGE_ERRNO_NAMES and isinstance(
                node.value, ast.Name
            ) and node.value.id == "errno":
                self.report(
                    ctx,
                    node,
                    f"errno.{node.attr} literal — the storage-exhaustion "
                    "predicate is utils.resources.is_storage_full",
                )
            return
        if isinstance(node, ast.ImportFrom):
            if node.module == "errno":
                for alias in node.names:
                    if alias.name in _STORAGE_ERRNO_NAMES:
                        self.report(
                            ctx,
                            node,
                            f"imports errno.{alias.name} — the storage-"
                            "exhaustion predicate is "
                            "utils.resources.is_storage_full",
                        )
