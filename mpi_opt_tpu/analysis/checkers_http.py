"""http-handler-contained: HTTP handler methods answer, never raise.

The front door's serving contract (service/http.py): a bug in a
``do_*`` handler must cost ONE typed 500 answer, never the serving
thread — stdlib ``ThreadingHTTPServer`` logs an uncaught handler
exception to stderr and drops the connection, which from the client
side is indistinguishable from a torn network and from the operator
side is a silent capacity leak. So the contract is structural, and this
checker makes it machine-checked the way drain-swallow does the drain
contract:

every ``do_*`` method of a class whose base names end in
``RequestHandler`` must have a body that is exactly one
``try`` statement (after the docstring) whose handlers include an
``except Exception`` (or bare ``except``) — the shape that guarantees
the typed-error answer path sees every failure. Code before the try,
code after it, or a try that can only catch narrower types all leave a
raise path straight into the server plumbing and are flagged.
"""

from __future__ import annotations

import ast

from mpi_opt_tpu.analysis.core import Checker, FileContext


def _is_handler_class(node: ast.ClassDef) -> bool:
    """A class serving HTTP requests: any base whose dotted name ends
    in "RequestHandler" (BaseHTTPRequestHandler and kin; a project
    subclass-of-a-subclass must keep the suffix in its base's name for
    this textual test to see it — the repo convention)."""
    for base in node.bases:
        name = ""
        if isinstance(base, ast.Name):
            name = base.id
        elif isinstance(base, ast.Attribute):
            name = base.attr
        if name.endswith("RequestHandler"):
            return True
    return False


def _catches_exception(try_node: ast.Try) -> bool:
    """Does any handler of this try catch Exception (or everything)?"""
    for h in try_node.handlers:
        if h.type is None:  # bare except
            return True
        types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        for t in types:
            name = ""
            if isinstance(t, ast.Name):
                name = t.id
            elif isinstance(t, ast.Attribute):
                name = t.attr
            if name in ("Exception", "BaseException"):
                return True
    return False


class HttpHandlerChecker(Checker):
    id = "http-handler-contained"
    hint = (
        "wrap the whole do_* body in one try/except Exception that "
        "answers a typed error (service/http.py contract: a handler "
        "raise must answer, never kill the serving thread)"
    )
    interests = (ast.ClassDef,)

    def visit(self, node, ctx: FileContext) -> None:
        if not _is_handler_class(node):
            return
        for item in node.body:
            if not isinstance(item, ast.FunctionDef):
                continue
            if not item.name.startswith("do_"):
                continue
            body = list(item.body)
            # a leading docstring is fine; it can't raise
            if body and isinstance(body[0], ast.Expr) and isinstance(
                body[0].value, ast.Constant
            ) and isinstance(body[0].value.value, str):
                body = body[1:]
            if len(body) != 1 or not isinstance(body[0], ast.Try):
                self.report(
                    ctx,
                    item,
                    f"handler {node.name}.{item.name} has statements "
                    "outside its containment try — the body must be "
                    "exactly one try/except Exception",
                )
                continue
            if not _catches_exception(body[0]):
                self.report(
                    ctx,
                    item,
                    f"handler {node.name}.{item.name}'s try never "
                    "catches Exception — a handler bug would escape "
                    "into the server plumbing instead of answering a "
                    "typed 500",
                )
