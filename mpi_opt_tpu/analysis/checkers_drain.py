"""drain-swallow: except clauses that eat the graceful-drain signal.

The preemption protocol (PR 3) only works if ``SweepInterrupted`` —
raised at a drain point AFTER durable state flushed — propagates all
the way to the CLI's catch, which maps it to exit 75. The same goes for
``KeyboardInterrupt`` (the interactive escalation). A handler that
catches either (explicitly, or via bare ``except:`` /
``except BaseException:``) and does not re-raise turns a platform
preemption into a silent continue: the sweep keeps running, the
supervisor SIGKILLs it mid-checkpoint, and the whole drain machinery is
bypassed. Review rounds caught this class twice; this checker makes it
a lint failure.

A handler passes when its body contains a ``raise`` (bare or explicit)
anywhere — containment-then-reraise is the launch supervisor's cleanup
idiom and is exactly right. Deliberate terminal swallows (a scheduler
containing a tenant slice, a transfer thread surfacing errors through
``drain()``) carry a ``# sweeplint: disable=drain-swallow`` with the
one-line reason.
"""

from __future__ import annotations

import ast

from mpi_opt_tpu.analysis.core import Checker, FileContext

#: exception names whose capture-without-reraise kills the protocol.
#: Exception is NOT here: SweepInterrupted is a RuntimeError, so
#: `except Exception` does technically catch it, but flagging every
#: generic handler would drown the suite in noise — the CLI's own
#: retry/containment handlers are `except Exception` by design and
#: re-raise non-transient errors.
_DRAIN_NAMES = frozenset({"SweepInterrupted", "KeyboardInterrupt", "BaseException"})


def _caught_names(handler: ast.ExceptHandler):
    t = handler.type
    if t is None:
        yield "<bare>"
        return
    parts = t.elts if isinstance(t, ast.Tuple) else [t]
    for p in parts:
        if isinstance(p, ast.Name):
            yield p.id
        elif isinstance(p, ast.Attribute):
            yield p.attr


def _reraises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
    return False


def _is_protocol_endpoint(handler: ast.ExceptHandler) -> bool:
    """The ONE legitimate terminal catch: the CLI's mapper that turns
    the drain into exit EX_TEMPFAIL. Recognized by the handler body
    referencing the constant — anything that maps the drain to the
    protocol's own exit code has, by definition, not swallowed it."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Name) and node.id == "EX_TEMPFAIL":
            return True
        if isinstance(node, ast.Attribute) and node.attr == "EX_TEMPFAIL":
            return True
    return False


class DrainSwallowChecker(Checker):
    id = "drain-swallow"
    hint = (
        "re-raise (the drain must reach the CLI's exit-75 catch), or "
        "mark the deliberate swallow with a disable + reason"
    )
    interests = (ast.ExceptHandler,)

    def visit(self, node, ctx: FileContext) -> None:
        caught = set(_caught_names(node))
        hit = caught & _DRAIN_NAMES or ("<bare>" in caught and {"<bare>"})
        if not hit or _reraises(node) or _is_protocol_endpoint(node):
            return
        what = sorted(hit)[0]
        label = "bare except:" if what == "<bare>" else f"except {what}"
        self.report(
            ctx,
            node,
            f"{label} swallows the graceful-drain signal "
            "(SweepInterrupted/KeyboardInterrupt) without re-raising",
        )
