"""coord-write: agreement files are written ONLY by parallel/coord.py.

The multi-process SPMD argument (ISSUE 20) that every rank-divergent
decision is unanimous before the next collective rests on the vote/
decide protocol's atomicity: ``O_EXCL`` vote creates (a duplicate vote
is a protocol error, not a race winner), ``O_EXCL`` decision publishes
(the first file is what every peer read), single-use epochs. An
agreement file touched any other way — a supervisor "helpfully"
unlinking stale votes while ranks are mid-barrier, a test scribbling a
decision with ``json.dump`` — silently reintroduces exactly the split
decisions the plane exists to prevent, and nothing would fail until
two ranks actually diverged at a boundary. This checker makes that a
lint error instead, the same fence ``lease-write`` puts around the
lease protocol.

What is flagged, outside ``parallel/coord.py``:

- ``open(<coord-ish>, "w"/"a"/...)`` — any write/append/update mode;
- ``os.open(<coord-ish>, ...)`` — the O_EXCL path is plane-only too;
- ``os.replace``/``os.rename`` with a coord-ish operand (votes and
  decisions are never renamed by anyone but the plane's primitives);
- ``os.unlink``/``os.remove`` of a coord-ish path (cleanup is
  ``coord.reset_dir``; a bare unlink under live readers is the
  stale-READY race the epoch protocol closes).

"Coord-ish" is judged lexically and conservatively: a string constant
containing ``vote.json`` / ``decision.json``, or an identifier (name,
attribute, string path segment) whose ``coord``/``coords`` appears as
a whole ``_``-delimited word — so ``coord_dir``, ``args.coord_dir``,
``"run/coord"`` all match while ``coordinator`` (the jax.distributed
address plumbing) and ``coordinates`` never do. Reads stay free:
status surfaces may inspect votes at will.
"""

from __future__ import annotations

import ast
import re

from mpi_opt_tpu.analysis.core import Checker, FileContext

#: `coord` / `coords` as a whole word inside an identifier's
#: underscore-split (or at a dotted/word boundary): `coord_dir` yes,
#: `args.coord` yes (attr == "coord"), `coordinator`/`coordinates` no
_COORD_WORD = re.compile(r"(?:^|_)coords?(?:_|$)")

#: the plane's file-name suffixes; a constant carrying one IS an
#: agreement path regardless of what the variable around it is called
_COORD_FILES = ("vote.json", "decision.json")


def _coord_ident(name: str) -> bool:
    return bool(_COORD_WORD.search(name))


def _mentions_coord(node) -> bool:
    """Does this expression lexically name an agreement path?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            if any(f in sub.value for f in _COORD_FILES) or _coord_ident(sub.value):
                return True
        elif isinstance(sub, ast.Name) and _coord_ident(sub.id):
            return True
        elif isinstance(sub, ast.Attribute) and _coord_ident(sub.attr):
            return True
    return False


def _callee(fn):
    """(module-ish, name) for a call target: os.replace -> ("os",
    "replace"); bare open -> ("", "open")."""
    if isinstance(fn, ast.Attribute):
        base = fn.value.id if isinstance(fn.value, ast.Name) else ""
        return base, fn.attr
    if isinstance(fn, ast.Name):
        return "", fn.id
    return "", ""


_WRITE_MODES = re.compile(r"[wax+]")


class CoordWriteChecker(Checker):
    id = "coord-write"
    hint = (
        "go through parallel/coord.py (agree/reset_dir) — the O_EXCL "
        "vote/decision primitives and single-use epochs are what makes "
        "boundary decisions unanimous"
    )
    interests = (ast.Call,)

    def interested(self, ctx: FileContext) -> bool:
        # the plane's own home is the one legal writer
        return not ctx.path.replace("\\", "/").endswith("parallel/coord.py")

    def visit(self, node, ctx: FileContext) -> None:
        base, name = _callee(node.func)
        if name == "open":
            # open(path, "w"/"a"/"r+"/...) or os.open(path, flags):
            # os.open is always suspicious on an agreement file (its
            # only legitimate coord use IS the plane's O_EXCL create);
            # builtin open only in an explicit write-ish mode
            if not node.args or not _mentions_coord(node.args[0]):
                return
            if base == "os":
                self.report(
                    ctx, node, "os.open of a coord path outside parallel/coord.py"
                )
                return
            mode = None
            if len(node.args) >= 2:
                mode = node.args[1]
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if (
                isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and _WRITE_MODES.search(mode.value)
            ):
                self.report(
                    ctx,
                    node,
                    f"open(..., {mode.value!r}) on a coord path outside "
                    "parallel/coord.py",
                )
            return
        if base != "os":
            return
        if name in ("replace", "rename"):
            if any(_mentions_coord(a) for a in node.args[:2]):
                self.report(
                    ctx,
                    node,
                    f"os.{name} involving a coord path outside "
                    "parallel/coord.py (votes/decisions move only "
                    "through the plane's primitives)",
                )
        elif name in ("unlink", "remove"):
            if node.args and _mentions_coord(node.args[0]):
                self.report(
                    ctx,
                    node,
                    f"os.{name} of a coord path outside parallel/coord.py "
                    "(cleanup is coord.reset_dir; a bare unlink under "
                    "live readers races the READY protocol)",
                )
