"""racelint pass 2: the concurrency-contract checkers (ISSUE 15).

Three PRs of thread/signal/file-protocol code (the staging engine's
transfer thread, the lease Refresher riding the heartbeat, the
ShutdownGuard flag handlers, the fleet claim protocol) each learned an
invariant the hard way in review rounds, and until now those invariants
lived only in prose. The ROADMAP's next item — collapsing the four
fused drivers into one wave-capable engine with per-host
StagingEngines — churns exactly this code, so the contracts become
machine checks first:

- **guarded-by** — a module global written from both a thread-entry
  call graph and main-line code is a data race unless every shared
  write holds a named lock. The lock is declared on the global's
  declaration line: ``# sweeplint: guarded-by(<lock>)``; writes
  lexically inside ``with <that lock>:`` pass, writes outside it are
  findings, and an UNANNOTATED shared global whose writes aren't all
  lock-covered is a finding at its declaration. Deliberate GIL-atomic
  flag stores carry ``# sweeplint: disable=guarded-by -- reason``.
- **beat-path-nonblocking** — the PR 12 Refresher lesson: code
  reachable from the heartbeat / beat-listener / slice-hook surfaces
  runs on the sweep's hot host path AND inside the staging transfer
  thread, so a blocking lock acquisition there stalls the very loop
  the heartbeat reports on. ``acquire(blocking=False)`` or a timeout
  pass; bare ``with lock:`` / ``acquire()`` are findings.
- **signal-safety** — code reachable from a registered signal handler
  may only set flags, read state, and raise: lock acquisition (the
  handler may interrupt the holder — instant self-deadlock), I/O,
  allocation-heavy formatting/logging and thread operations are
  findings.
- **lock-order** — the static partial order of nested lock scopes
  across files must be acyclic; a cycle is a deadlock two threads can
  reach. Non-blocking acquires contribute no edge (a trylock cannot
  deadlock).
- **fsync-before-rename** — extends atomic-write for the DURABLE
  layers (``ledger/``, ``corpus/``, ``service/``): a tmp-write whose
  scope renames it into place must fsync the fd first, or the rename
  can publish an empty/partial file after a crash (the contract
  ``corpus/index.write_index`` and ``spool._write_json_atomic`` follow
  but nothing checked). The heartbeat's deliberately-unfsynced beat
  files live in ``health/`` — out of scope by design: liveness, not
  history.
"""

from __future__ import annotations

import ast

from mpi_opt_tpu.analysis.core import Checker, FileContext, ProjectChecker
from mpi_opt_tpu.analysis.project import (
    ProjectTable,
    find_cycles,
    lock_order_edges,
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


# -- guarded-by ------------------------------------------------------------


class GuardedByChecker(ProjectChecker):
    id = "guarded-by"
    hint = (
        "declare the guard on the global's declaration line "
        "(# sweeplint: guarded-by(<lock>)) and take that lock around "
        "every shared write; a deliberate GIL-atomic flag store gets "
        "# sweeplint: disable=guarded-by -- reason"
    )

    def check_project(self, table: ProjectTable) -> None:
        thread = table.thread_side()
        main = table.main_side()
        for (path, name), g in sorted(table.globals.items()):
            if not g.writes:
                continue
            thread_writes = [w for w in g.writes if w[0] in thread]
            main_writes = [
                w for w in g.writes if w[0] is None or w[0] in main
            ]
            if not thread_writes or not main_writes:
                continue  # single-context global: not shared
            ctx = table.ctxs.get(path)
            if ctx is None:
                continue
            declared = ctx.guard_for(g.line)
            if declared is None:
                # no annotation: pass only when ONE common lock covers
                # every shared write (two writers under two different
                # locks exclude nothing)
                common = None
                for _fk, _ln, held in g.writes:
                    resolved = {table.resolve_lock(h) for h in held}
                    common = resolved if common is None else common & resolved
                if common:
                    continue
                writers = sorted(
                    {
                        table.functions[w[0]].qualname
                        for w in g.writes
                        if w[0] in table.functions
                    }
                )
                self.report(
                    ctx,
                    g.line,
                    f"module global {name!r} is written from a thread-entry "
                    f"call graph AND main-line code ({', '.join(writers)}) "
                    "with no declared guard — unsynchronized shared write",
                )
                continue
            # annotation present: resolve the lock and hold writers to it
            lock_key = self._resolve_guard(table, path, declared)
            if lock_key is None:
                self.report(
                    ctx, g.line,
                    f"guarded-by({declared}) names no lock the symbol table "
                    "knows in this module",
                )
                continue
            for funckey, line, held in g.writes:
                held_resolved = {table.resolve_lock(h) for h in held}
                if table.resolve_lock(lock_key) not in held_resolved:
                    self.report(
                        ctx, line,
                        f"write to {name!r} outside its declared guard "
                        f"{declared!r} (guarded-by on line {g.line})",
                    )

    @staticmethod
    def _resolve_guard(table: ProjectTable, path: str, declared: str):
        """``guarded-by(<lock>)`` names: a module-level lock name, or
        ``Class._attr`` / ``self._attr``-style dotted name."""
        tail = declared.split(".")[-1]
        for key, d in table.locks.items():
            if d.file != path:
                continue
            if key.endswith(f"::{declared}") or key.endswith(f".{tail}"):
                return key
            if key == f"{path}::{declared}":
                return key
        return None


# -- beat-path-nonblocking -------------------------------------------------


class BeatPathChecker(ProjectChecker):
    id = "beat-path-nonblocking"
    hint = (
        "use lock.acquire(blocking=False) (skip and let the next beat "
        "retry) or a timeout — the beat path runs on the sweep's hot "
        "host path and inside the staging transfer thread"
    )

    def check_project(self, table: ProjectTable) -> None:
        roots = [k for k, _r in table.beat_entries]
        if not roots:
            return
        for key in sorted(table.reachable(roots)):
            fn = table.functions.get(key)
            if fn is None:
                continue
            for lock_key, line, mode in fn.lock_events:
                if mode in ("nonblocking", "timeout"):
                    continue
                ctx = table.ctxs.get(fn.file)
                if ctx is None:
                    continue
                self.report(
                    ctx, line,
                    f"blocking acquisition of {table.lock_display(lock_key)} "
                    f"in beat-path-reachable {fn.qualname} — a contended "
                    "lock here stalls the hot path the heartbeat reports on",
                )


# -- signal-safety ---------------------------------------------------------

#: calls a signal handler's reachable code must not make: file/IO and
#: process ops, serialization, sleeping, thread lifecycle — anything
#: beyond set-a-flag/read/raise. (Matched by callee NAME — conservative
#: lexical judgement, same spirit as the rest of sweeplint.)
_SIGNAL_UNSAFE = frozenset(
    {
        "open", "print", "sleep", "dump", "dumps", "load", "loads",
        "warn", "warning", "error", "info", "debug", "exception",
        "makedirs", "unlink", "remove", "replace", "rename", "fsync",
        "fdopen", "system", "popen", "kill", "write", "flush", "read",
        "readline", "start", "join", "format",
    }
)


class SignalSafetyChecker(ProjectChecker):
    id = "signal-safety"
    hint = (
        "a handler may only set flags, read state, and raise — do the "
        "real work at a drain point that polls the flag (the "
        "ShutdownGuard protocol)"
    )

    def check_project(self, table: ProjectTable) -> None:
        roots = [k for k, _r in table.signal_entries]
        if not roots:
            return
        for key in sorted(table.reachable(roots)):
            fn = table.functions.get(key)
            ctx = table.ctxs.get(fn.file) if fn else None
            if fn is None or ctx is None:
                continue
            for lock_key, line, _mode in fn.lock_events:
                self.report(
                    ctx, line,
                    f"lock acquisition ({table.lock_display(lock_key)}) in "
                    f"signal-handler-reachable {fn.qualname} — the handler "
                    "can interrupt the lock's holder on the same thread: "
                    "self-deadlock",
                )
            for shape, base, attr, line in fn.raw_calls:
                name = attr if shape != "direct" else ""
                if name in _SIGNAL_UNSAFE:
                    self.report(
                        ctx, line,
                        f"{name}() call in signal-handler-reachable "
                        f"{fn.qualname} — handlers may only set flags/read "
                        "(no I/O, no allocation-heavy work)",
                    )


# -- lock-order ------------------------------------------------------------


class LockOrderChecker(ProjectChecker):
    id = "lock-order"
    hint = (
        "pick one global acquisition order for these locks and make "
        "every nesting follow it (or make the inner acquisition "
        "non-blocking)"
    )

    def check_project(self, table: ProjectTable) -> None:
        edges = lock_order_edges(table)
        cycles = find_cycles(edges)
        if not cycles:
            return
        # anchor each cycle at one concrete site of its first edge so
        # the finding is clickable (and suppressible) at real code
        site_of = {}
        for o, i, f, l in edges:
            site_of.setdefault((o, i), (f, l))
        for cyc in cycles:
            pairs = list(zip(cyc, cyc[1:] + cyc[:1]))
            f, l = site_of.get(pairs[0], (None, 0))
            ctx = table.ctxs.get(f)
            if ctx is None:
                continue
            order = " -> ".join(table.lock_display(k) for k in cyc + [cyc[0]])
            self.report(
                ctx, l,
                f"lock-order cycle: {order} — two threads entering this "
                "cycle from different edges deadlock",
            )


# -- fsync-before-rename ---------------------------------------------------

# one home for the scope-walk helpers (checkers_durability defines
# them for the same per-scope judgement shape; a third drifting copy
# is how subtle nested-lambda bugs get fixed in one checker only)
from mpi_opt_tpu.analysis.checkers_durability import (  # noqa: E402
    _callee_name,
    _direct_calls,
)


def _is_write_open(call: ast.Call) -> bool:
    """``open(path, "w"/"a"/...)`` or ``os.fdopen(fd, "w")``."""
    name = _callee_name(call.func)
    if name not in ("open", "fdopen"):
        return False
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    return (
        isinstance(mode, ast.Constant)
        and isinstance(mode.value, str)
        and any(c in mode.value for c in "wa+")
    )


class FsyncBeforeRenameChecker(Checker):
    id = "fsync-before-rename"
    hint = (
        "f.flush(); os.fsync(f.fileno()) before the os.replace — "
        "rename orders METADATA, not data; see spool._write_json_atomic"
    )
    interests = _FUNC_NODES

    def interested(self, ctx: FileContext) -> bool:
        p = ctx.path.replace("\\", "/")
        return any(seg in p for seg in ("ledger/", "corpus/", "service/"))

    def visit(self, node, ctx: FileContext) -> None:
        replaces, opens, has_fsync = [], [], False
        for c in _direct_calls(node):
            name = _callee_name(c.func)
            if (
                name in ("replace", "rename")
                and isinstance(c.func, ast.Attribute)
                and isinstance(c.func.value, ast.Name)
                and c.func.value.id == "os"
            ):
                replaces.append(c.lineno)
            elif name == "fsync":
                has_fsync = True
            elif _is_write_open(c):
                opens.append(c.lineno)
        if replaces and opens and not has_fsync:
            # the defect is the publish: a rename that can promote
            # unsynced bytes into the durable name
            self.report(
                ctx,
                min(replaces),
                "tmp written and renamed into place without an os.fsync in "
                "the same scope — after a crash the durable name can hold "
                "an empty/partial file (rename orders metadata, not data)",
            )
