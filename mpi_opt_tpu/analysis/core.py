"""The checker framework: parse once per file, visitors share the tree.

A ``Checker`` declares the AST node types it wants (``interests``) plus
optional ``begin_file``/``finish_file`` hooks; the framework parses each
file ONCE, walks the tree ONCE, and dispatches nodes to every
interested checker — adding a tenth checker costs one dict lookup per
node, not another parse+walk of the repo. Checkers that reason about
whole function bodies (ordering, key flow) register interest in
``ast.FunctionDef``/``ast.Module`` and scan locally from there.

Findings carry ``file:line``, the check id, severity, message, and a
fix hint. Suppression and the barrier annotation are comment-driven and
parsed once per file into :class:`FileContext`:

- ``# sweeplint: disable=<id>[,<id>] -- reason`` on the finding line or
  the line directly above suppresses those checks there;
- ``# sweeplint: barrier(reason)`` on a ``def`` line marks the function
  as an explicit host-sync barrier (checkers_jax.HostSyncChecker);
- ``# sweeplint: guarded-by(<lock>)`` on a module global's declaration
  line declares which lock its shared writers must hold
  (checkers_concurrency.GuardedByChecker).

Two checker shapes share the framework (ISSUE 15): per-file
:class:`Checker` subclasses ride the single walk above, and
:class:`ProjectChecker` subclasses run a SECOND pass over the repo-wide
symbol table (mpi_opt_tpu/analysis/project.py) after every file has
been parsed — cross-file properties (thread-entry reachability, the
lock partial order) cannot be judged one file at a time. Both report
:class:`Finding` through the same suppression/baseline machinery, and
the framework charges wall time per checker (``Checker.wall_s``) so a
future slow checker is diagnosable from ``lint --json``.

The baseline is a committed JSON file of accepted legacy findings,
keyed by (check, relpath, stripped line content) — content, not line
number, so unrelated edits above a baselined finding never un-baseline
it, while any edit TO the flagged line surfaces it again.
"""

from __future__ import annotations

import ast
import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Iterable, Optional

#: directories never scanned (mirrors obs/events.py's historical walk:
#: tests fabricate names/patterns on purpose; probes are shell-driven
#: drill scripts with deliberate kill shapes)
EXCLUDED_DIRS = ("__pycache__", ".git", "tests", "probes", "node_modules")

_DIRECTIVE = re.compile(r"#\s*sweeplint:\s*(disable|barrier|guarded-by)\b([^#\n]*)")
_DISABLE_IDS = re.compile(r"disable\s*=\s*([\w,\-]+)")
_GUARDED_BY = re.compile(r"guarded-by\s*\(\s*([\w.]+)\s*\)")


@dataclass
class Finding:
    """One invariant violation at a concrete source location."""

    check: str  # check id, e.g. "exit-code"
    file: str  # path as given to the runner
    line: int  # 1-based
    message: str
    hint: str = ""
    severity: str = "error"

    def as_dict(self, root: Optional[str] = None) -> dict:
        return {
            "check": self.check,
            "file": relpath_under(self.file, root),
            "line": self.line,
            "severity": self.severity,
            "message": self.message,
            "hint": self.hint,
        }

    def render(self, root: Optional[str] = None) -> str:
        loc = f"{relpath_under(self.file, root)}:{self.line}"
        tail = f" (fix: {self.hint})" if self.hint else ""
        return f"{loc}: [{self.check}] {self.message}{tail}"


def relpath_under(path: str, root: Optional[str]) -> str:
    """``path`` relative to ``root`` when it lives under it, else as
    given — findings and baseline fingerprints must not bake in an
    absolute checkout location."""
    if not root:
        return path
    try:
        rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    except ValueError:  # pragma: no cover - windows cross-drive
        return path
    return path if rel.startswith("..") else rel


@dataclass
class FileContext:
    """Everything checkers share about one parsed file."""

    path: str
    source: str
    tree: ast.Module
    lines: list = field(default_factory=list)
    #: lineno -> set of check ids disabled there (the line itself; the
    #: runner also honors a directive on the line above a finding)
    disabled: dict = field(default_factory=dict)
    #: linenos carrying a `# sweeplint: barrier` annotation
    barriers: set = field(default_factory=set)
    #: lineno -> lock name from a `# sweeplint: guarded-by(<lock>)`
    #: annotation (the guarded-by checker honors the declaration line
    #: or the line directly above, like suppression)
    guards: dict = field(default_factory=dict)

    @classmethod
    def parse(cls, path: str, source: str) -> "FileContext":
        tree = ast.parse(source)
        ctx = cls(path=path, source=source, tree=tree, lines=source.splitlines())
        for i, line in enumerate(ctx.lines, start=1):
            m = _DIRECTIVE.search(line)
            if not m:
                continue
            if m.group(1) == "barrier":
                ctx.barriers.add(i)
            elif m.group(1) == "guarded-by":
                g = _GUARDED_BY.search(m.group(0))
                if g:
                    ctx.guards[i] = g.group(1)
            else:
                ids = _DISABLE_IDS.search(m.group(0))
                if ids:
                    ctx.disabled.setdefault(i, set()).update(
                        s for s in ids.group(1).split(",") if s
                    )
        return ctx

    def guard_for(self, lineno: int) -> Optional[str]:
        """The guarded-by lock declared on ``lineno`` or the line above."""
        for ln in (lineno, lineno - 1):
            if ln in self.guards:
                return self.guards[ln]
        return None

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, finding: Finding) -> bool:
        """Directive on the finding's line or the line directly above."""
        for ln in (finding.line, finding.line - 1):
            if finding.check in self.disabled.get(ln, ()):
                return True
        return False


class Checker:
    """Base: subclasses set ``id``/``severity``/``hint``, declare the
    node types they want in ``interests``, and append to
    ``self.findings`` from ``visit``/``begin_file``/``finish_file``.
    Checkers must be stateless ACROSS files — per-file state is reset by
    ``begin_file`` (the framework calls it before any visit)."""

    id: str = "checker"
    severity: str = "error"
    hint: str = ""
    #: node classes this checker's visit() receives (empty = no dispatch;
    #: the checker works entirely from begin_file/finish_file)
    interests: tuple = ()

    def __init__(self):
        self.findings: list = []
        #: cumulative seconds this checker spent across the run (begin/
        #: visit/finish for per-file checkers, check_project for project
        #: ones) — surfaced in `lint --json` so a slow checker is a
        #: number, not a mystery
        self.wall_s: float = 0.0

    # -- hooks ------------------------------------------------------------

    def interested(self, ctx: FileContext) -> bool:
        """File-scope gate (path-scoped checkers override); uninterested
        checkers skip the whole file for free."""
        return True

    def begin_file(self, ctx: FileContext) -> None:
        pass

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        pass

    def finish_file(self, ctx: FileContext) -> None:
        pass

    # -- helpers ----------------------------------------------------------

    def report(self, ctx: FileContext, node_or_line, message: str) -> None:
        line = (
            node_or_line
            if isinstance(node_or_line, int)
            else getattr(node_or_line, "lineno", 0)
        )
        self.findings.append(
            Finding(
                check=self.id,
                file=ctx.path,
                line=int(line),
                message=message,
                hint=self.hint,
                severity=self.severity,
            )
        )


class ProjectChecker(Checker):
    """Base for two-pass checkers: ``check_project`` runs once over the
    repo-wide symbol table (analysis/project.py ProjectTable) after
    every file has been parsed. Project checkers take no part in the
    per-file walk (``interested`` is False); their findings flow through
    the same suppression and baseline machinery via the table's parsed
    FileContexts."""

    def interested(self, ctx: FileContext) -> bool:
        return False

    def check_project(self, table) -> None:
        raise NotImplementedError


def check_file_context(ctx: FileContext, checkers: Iterable[Checker]) -> list:
    """Run ``checkers`` over one parsed file: single walk, type-dispatched,
    suppression applied. Returns surviving findings."""
    active = [c for c in checkers if c.interested(ctx)]
    if not active:
        return []
    clock = time.perf_counter
    for c in active:
        c.findings = []
        t0 = clock()
        c.begin_file(ctx)
        c.wall_s += clock() - t0
    dispatch: dict = {}
    for c in active:
        for t in c.interests:
            dispatch.setdefault(t, []).append(c)
    if dispatch:
        for node in ast.walk(ctx.tree):
            for c in dispatch.get(type(node), ()):
                t0 = clock()
                c.visit(node, ctx)
                c.wall_s += clock() - t0
    out: list = []
    for c in active:
        t0 = clock()
        c.finish_file(ctx)
        c.wall_s += clock() - t0
        out.extend(f for f in c.findings if not ctx.suppressed(f))
    return out


def run_project_checkers(ctxs: dict, checkers: Iterable["ProjectChecker"]) -> tuple:
    """The second pass: build the symbol table over every parsed file
    and run the project checkers against it. Returns
    ``(findings, table)`` — findings suppressed through each file's own
    directives, exactly like the per-file pass."""
    from mpi_opt_tpu.analysis.project import build_table

    checkers = list(checkers)
    if not checkers:
        return [], None
    t0 = time.perf_counter()
    table = build_table(list(ctxs.values()))
    table.build_wall_s = time.perf_counter() - t0
    out: list = []
    for c in checkers:
        c.findings = []
        t0 = time.perf_counter()
        c.check_project(table)
        c.wall_s += time.perf_counter() - t0
        for f in c.findings:
            ctx = ctxs.get(f.file)
            if ctx is not None and ctx.suppressed(f):
                continue
            out.append(f)
    return out, table


def check_source(
    source: str, path: str = "snippet.py", checkers: Optional[Iterable[Checker]] = None
) -> list:
    """String-source entry point (the per-checker fixture tests' door:
    no temp repos, just parse and judge). ``path`` matters — several
    checkers are path-scoped (host-sync: train/fused_*; ledger-fsync:
    ledger/)."""
    if checkers is None:
        from mpi_opt_tpu.analysis import all_checkers

        checkers = all_checkers()
    checkers = list(checkers)
    ctx = FileContext.parse(path, source)
    findings = check_file_context(
        ctx, [c for c in checkers if not isinstance(c, ProjectChecker)]
    )
    project = [c for c in checkers if isinstance(c, ProjectChecker)]
    if project:
        pf, _table = run_project_checkers({path: ctx}, project)
        findings = sorted(findings + pf, key=lambda f: (f.file, f.line, f.check))
    return findings


def iter_python_files(root: str):
    """Walk ``root`` for .py files with the standard exclusions; a
    single .py file path yields itself."""
    if os.path.isfile(root):
        if root.endswith(".py"):
            yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in EXCLUDED_DIRS]
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


def run_paths(
    paths: Iterable[str], checkers: Optional[Iterable[Checker]] = None
) -> tuple:
    """Lint every python file under ``paths``. Returns
    ``(findings, n_files, errors)`` — see :func:`run_paths_ex` for the
    variant that also returns the project symbol table."""
    findings, n_files, errors, _table = run_paths_ex(paths, checkers)
    return findings, n_files, errors


def run_paths_ex(
    paths: Iterable[str], checkers: Optional[Iterable[Checker]] = None
) -> tuple:
    """Two-pass lint over every python file under ``paths``: per-file
    checkers ride one walk per file; project checkers then run over the
    repo-wide symbol table built from the same parse. Returns
    ``(findings, n_files, errors, table)`` where ``errors`` are files
    that could not be read/parsed (reported, never silently skipped — a
    syntax-broken file would otherwise make the lint vacuously green
    exactly when the tree is at its sickest) and ``table`` is the
    ProjectTable (None when no project checkers ran)."""
    if checkers is None:
        from mpi_opt_tpu.analysis import all_checkers

        checkers = all_checkers()
    checkers = list(checkers)
    file_checkers = [c for c in checkers if not isinstance(c, ProjectChecker)]
    project_checkers = [c for c in checkers if isinstance(c, ProjectChecker)]
    findings: list = []
    errors: list = []
    ctxs: dict = {}
    n_files = 0
    for root in paths:
        for path in iter_python_files(root):
            n_files += 1
            try:
                with open(path, "r") as f:
                    source = f.read()
                ctx = FileContext.parse(path, source)
            except (OSError, SyntaxError, ValueError) as e:
                errors.append(f"{path}: {type(e).__name__}: {e}")
                continue
            ctxs[path] = ctx
            findings.extend(check_file_context(ctx, file_checkers))
    table = None
    if project_checkers:
        pf, table = run_project_checkers(ctxs, project_checkers)
        findings.extend(pf)
    findings.sort(key=lambda f: (f.file, f.line, f.check))
    return findings, n_files, errors, table


# -- baseline ------------------------------------------------------------

BASELINE_VERSION = 1


def fingerprint(finding: Finding, ctx_line: str, root: Optional[str]) -> dict:
    """The baseline identity of a finding: check id + repo-relative path
    + the flagged line's stripped content. No line numbers — edits
    elsewhere in the file must not churn the baseline."""
    return {
        "check": finding.check,
        "file": relpath_under(finding.file, root),
        "content": ctx_line.strip(),
    }


def _line_of(finding: Finding) -> str:
    try:
        with open(finding.file, "r") as f:
            lines = f.read().splitlines()
        return lines[finding.line - 1] if 1 <= finding.line <= len(lines) else ""
    except OSError:
        return ""


def load_baseline(path: str) -> list:
    """The accepted-finding fingerprints in a baseline file (ValueError
    on malformed content — a truncated baseline silently accepting
    nothing would fail CI confusingly, accepting everything would be
    worse)."""
    with open(path, "r") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"{path}: not a sweeplint baseline (no 'findings')")
    if int(data.get("version", -1)) > BASELINE_VERSION:
        raise ValueError(
            f"{path}: baseline version {data['version']} is newer than "
            f"this build's {BASELINE_VERSION}"
        )
    return list(data["findings"])


def split_baselined(findings: list, baseline: list, root: Optional[str]) -> tuple:
    """(fresh, accepted): findings whose fingerprint is in the baseline
    are accepted (reported separately, never failing the run)."""
    keyset = {(b.get("check"), b.get("file"), b.get("content")) for b in baseline}
    fresh, accepted = [], []
    for f in findings:
        fp = fingerprint(f, _line_of(f), root)
        (accepted if (fp["check"], fp["file"], fp["content"]) in keyset else fresh).append(f)
    return fresh, accepted


def write_baseline(path: str, findings: list, root: Optional[str]) -> None:
    data = {
        "version": BASELINE_VERSION,
        "tool": "sweeplint",
        "findings": [fingerprint(f, _line_of(f), root) for f in findings],
    }
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):  # failed mid-write: no orphan debris
            os.unlink(tmp)
