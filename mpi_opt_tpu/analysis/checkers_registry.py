"""event-registry: every literal event/span name is registered.

This is PR 8's ``obs/events.py`` call-site scanner migrated into the
framework (satellite: the registry TABLES stay in obs/events.py — they
are the metrics-stream schema's home and what a schema change must
diff — while the AST mechanics live here with the other checkers;
``obs.events.scan_call_sites``/``lint`` remain as thin shims so the
historical tier-1 registry-lint surface keeps working).

Emitter shapes gated (same rules as the original scanner):

- kind "event": ``*.log("name", ...)`` (attribute call only — bench.py's
  bare ``log(msg)`` stderr helper is not an emitter),
  ``notify("name", ...)`` in both spellings, ``_event("name", ...)``;
- kind "span": ``span("name", ...)`` / ``trace.span(...)`` /
  ``@traced("name")``.

Non-literal first arguments are skipped (re-emission helpers forward a
variable on purpose).
"""

from __future__ import annotations

import ast

from mpi_opt_tpu.analysis.core import Checker, FileContext


def callee_kind(fn) -> str:
    """"event"/"span"/"" for a call's func node (the one home for the
    emitter-shape convention; obs.events re-exports it)."""
    if isinstance(fn, ast.Attribute):
        name, is_attr = fn.attr, True
    elif isinstance(fn, ast.Name):
        name, is_attr = fn.id, False
    else:
        return ""
    if name == "log" and is_attr:
        return "event"
    if name in ("notify", "_event"):
        return "event"
    if name in ("span", "traced"):
        return "span"
    return ""


def call_site(node: ast.Call):
    """(kind, name) when ``node`` is a registered-emitter call with a
    literal first argument, else None."""
    if not node.args:
        return None
    first = node.args[0]
    if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
        return None
    kind = callee_kind(node.func)
    return (kind, first.value) if kind else None


class EventRegistryChecker(Checker):
    id = "event-registry"
    hint = "register the name in obs/events.py (EVENTS, SPANS or SPAN_ATTRS)"
    interests = (ast.Call,)

    def __init__(self):
        super().__init__()
        # imported lazily-late so the checker module stays importable
        # even while obs/ is being refactored under it
        from mpi_opt_tpu.obs.events import EVENTS, SPAN_ATTRS, SPANS

        self._tables = {"event": EVENTS, "span": SPANS}
        self._span_attrs = SPAN_ATTRS

    def visit(self, node, ctx: FileContext) -> None:
        site = call_site(node)
        if site is None:
            return
        kind, name = site
        if name not in self._tables[kind]:
            table = "EVENTS" if kind == "event" else "SPANS"
            self.report(
                ctx,
                node,
                f"unregistered {kind} name {name!r} — add it to "
                f"obs/events.py {table}",
            )
        if kind != "span":
            return
        # span ATTR keys are schema too (the trace/diff CLIs key on
        # them): every literal keyword at a span call site must be in
        # SPAN_ATTRS. **attrs forwarding (kw.arg is None) is a
        # re-emission helper and is skipped, same rule as non-literal
        # names above. (ISSUE 10 satellite: the registry's scanned
        # surface now covers the attr namespace.)
        for kw in node.keywords:
            if kw.arg is not None and kw.arg not in self._span_attrs:
                self.report(
                    ctx,
                    node,
                    f"unregistered span attr {kw.arg!r} on span {name!r} — "
                    "add it to obs/events.py SPAN_ATTRS",
                )
