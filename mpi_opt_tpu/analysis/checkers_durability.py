"""Durability-contract checkers: journal ordering, rank gating, atomic
status writes, ledger fsync.

These four encode the crash-safety contracts PRs 2/3/5/6 bought with
review rounds:

- **journal-order** — a fused boundary's ledger records journal BEFORE
  that boundary's snapshot saves (``ledger/store.py`` docstring: the
  only append-kill shape is then a torn FINAL boundary, which resume
  self-heals; a snapshot covering an unjournaled boundary is
  unrecoverable divergence).
- **ledger-gate** — ``SweepLedger`` is constructed with an explicit
  ``read_only=`` decision outside the ledger package itself. Under
  multi-process SPMD every rank runs the same loop; N ranks
  fsync-appending one journal interleave records and corrupt it, so
  construction must always state which side of the rank-0 gate it is on
  (the CLI's gate sites pass ``read_only=rank != 0``).
- **atomic-write** — durable JSON state (status, heartbeat, spool,
  results) is written tmp+``os.replace``, never ``open(path, "w")``
  directly: a reader (watchdog, scheduler, report) must never see a
  torn record, and a crash mid-write must not destroy the previous one.
- **ledger-fsync** — every append to a ledger's file handle fsyncs in
  the same function (the fsync-before-report invariant: a journal that
  can lag its snapshot is not a journal).
"""

from __future__ import annotations

import ast

from mpi_opt_tpu.analysis.core import Checker, FileContext

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While)


def _callee_name(fn) -> str:
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _direct_calls(scope):
    """Call nodes lexically in ``scope``'s body, NOT descending into
    nested function/lambda definitions — a nested ``def save_now()``
    deferred to a boundary callback is its own scope, and attributing
    its calls to the parent would misjudge both."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        if isinstance(node, (*_FUNC_NODES, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


# -- journal-order -------------------------------------------------------

#: snapshot-save callee names at the fused drivers' layer (the
#: checkpointer surface: utils/checkpoint.py SweepCheckpointer +
#: population-sweep/wave variants)
_SAVE_NAMES = frozenset(
    {"save", "save_sweep", "save_population_sweep", "save_wave_sweep"}
)


class JournalOrderChecker(Checker):
    id = "journal-order"
    hint = (
        "journal the boundary's member records (journal_boundary) "
        "before its snapshot save in the same region"
    )
    interests = _FUNC_NODES

    def visit(self, node, ctx: FileContext) -> None:
        # judge each straight-line region independently: the nearest
        # enclosing loop body (one region per loop — the per-boundary
        # iteration is what the ordering contract is ABOUT), else the
        # function body itself. Cross-region pairs (a mid-generation
        # drain snapshot before a later loop's journal) are not
        # boundary-ordering violations.
        regions: dict = {}

        def region_of(path):
            for anc in reversed(path):
                if isinstance(anc, _LOOP_NODES):
                    return anc
            return node

        stack = [(node, [])]
        while stack:
            cur, path = stack.pop()
            for ch in ast.iter_child_nodes(cur):
                if isinstance(ch, (*_FUNC_NODES, ast.Lambda)) and ch is not cur:
                    continue
                if isinstance(ch, ast.Call):
                    name = _callee_name(ch.func)
                    if name == "journal_boundary":
                        regions.setdefault(region_of(path), [[], []])[0].append(
                            ch.lineno
                        )
                    elif name in _SAVE_NAMES:
                        regions.setdefault(region_of(path), [[], []])[1].append(
                            ch.lineno
                        )
                stack.append((ch, path + [ch]))
        for region, (journals, saves) in regions.items():
            if journals and saves and min(saves) < min(journals):
                self.report(
                    ctx,
                    min(saves),
                    "snapshot save precedes the boundary's journal_boundary "
                    "call — a crash between them leaves a snapshot covering "
                    "an unjournaled boundary (unrecoverable; the torn-final-"
                    "boundary self-heal relies on journal-before-snapshot)",
                )


# -- ledger-gate ---------------------------------------------------------


class LedgerGateChecker(Checker):
    id = "ledger-gate"
    hint = (
        "pass read_only=<rank != 0 decision> (rank-0-only journaling); "
        "see cli.py's gate sites"
    )
    interests = (ast.Call,)

    def interested(self, ctx: FileContext) -> bool:
        # the ledger package constructs its own stores (load/repair
        # internals); everyone else must take the gate decision
        return "ledger/" not in ctx.path.replace("\\", "/")

    def visit(self, node, ctx: FileContext) -> None:
        if _callee_name(node.func) != "SweepLedger":
            return
        if any(kw.arg == "read_only" for kw in node.keywords):
            return
        self.report(
            ctx,
            node,
            "SweepLedger constructed without an explicit read_only= rank "
            "gate — under multi-process SPMD, N ranks appending one "
            "journal corrupt it",
        )


# -- atomic-write --------------------------------------------------------


def _is_plain_open(call: ast.Call) -> bool:
    """``open(path, "w")`` / ``open(path, mode="w")`` — bare builtin
    only. ``os.fdopen`` wraps descriptors whose atomicity contract
    (O_CREAT|O_EXCL claim files) is made at ``os.open`` time."""
    if not (isinstance(call.func, ast.Name) and call.func.id == "open"):
        return False
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    return (
        isinstance(mode, ast.Constant)
        and isinstance(mode.value, str)
        and "w" in mode.value
        and "b" not in mode.value
    )


def _mentions_json(node) -> bool:
    """Does the open target read as a .json/.jsonl destination? Checks
    string-literal fragments anywhere in the expression (f-strings
    included) and attribute/variable names."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            if ".json" in sub.value:
                return True
        elif isinstance(sub, ast.Attribute) and "json" in sub.attr.lower():
            return True
        elif isinstance(sub, ast.Name) and "json" in sub.id.lower():
            return True
    return False


class AtomicWriteChecker(Checker):
    """Two signatures, one idiom:

    1. ``open(<something .json>, "w")`` in a scope with no
       ``os.replace``/``os.rename``;
    2. ``with open(x, "w") as f: json.dump(_, f)`` (or
       ``f.write(json.dumps(...))``) in such a scope — the destination
       doesn't have to NAME json to hold it.

    The tmp+replace idiom passes because the scope that writes the tmp
    also calls ``os.replace``.
    """

    id = "atomic-write"
    hint = (
        "write to a tmp path and os.replace() it over the destination "
        "(see service/spool._write_json_atomic)"
    )
    interests = _FUNC_NODES + (ast.Module,)

    def visit(self, node, ctx: FileContext) -> None:
        # source order: deterministic findings, and the dedup below
        # relies on an open call being judged before (or guarded
        # against) the dump/write that flows through it
        calls = sorted(
            _direct_calls(node), key=lambda c: (c.lineno, c.col_offset)
        )
        for c in calls:
            name = _callee_name(c.func)
            # os.replace/os.rename SPECIFICALLY: a bare attribute match
            # would let any str.replace() in the scope disarm the check
            if (
                name in ("replace", "rename")
                and isinstance(c.func, ast.Attribute)
                and isinstance(c.func.value, ast.Name)
                and c.func.value.id == "os"
            ):
                return  # the idiom is present in this scope
        # handle names bound by `with open(...) as f`
        json_handles: dict = {}
        for sub in ast.walk(node):
            if not isinstance(sub, (ast.With, ast.AsyncWith)):
                continue
            for item in sub.items:
                cexpr = item.context_expr
                if (
                    isinstance(cexpr, ast.Call)
                    and _is_plain_open(cexpr)
                    and isinstance(item.optional_vars, ast.Name)
                ):
                    json_handles[item.optional_vars.id] = cexpr
        reported: set = set()  # open nodes already flagged (one finding
        # per defective write, even when it matches several signatures)
        for c in calls:
            if (
                _is_plain_open(c)
                and _mentions_json(c.args[0] if c.args else c)
                and id(c) not in reported
            ):
                reported.add(id(c))
                self.report(
                    ctx,
                    c,
                    "non-atomic write to a .json destination — a reader can "
                    "see a torn record, and a crash mid-write destroys the "
                    "previous one",
                )
            elif _callee_name(c.func) == "dump" and len(c.args) >= 2:
                target = c.args[1]
                if (
                    isinstance(target, ast.Name)
                    and target.id in json_handles
                    and id(json_handles[target.id]) not in reported
                ):
                    reported.add(id(json_handles[target.id]))
                    self.report(
                        ctx,
                        json_handles[target.id],
                        "json.dump into a handle opened with open(path, 'w') "
                        "and no os.replace in scope — non-atomic JSON write",
                    )
            elif (
                _callee_name(c.func) == "write"
                and isinstance(c.func, ast.Attribute)
                and isinstance(c.func.value, ast.Name)
                and c.func.value.id in json_handles
                and id(json_handles[c.func.value.id]) not in reported
                and c.args
                and any(
                    isinstance(s, ast.Call) and _callee_name(s.func) == "dumps"
                    for s in ast.walk(c.args[0])
                )
            ):
                reported.add(id(json_handles[c.func.value.id]))
                self.report(
                    ctx,
                    json_handles[c.func.value.id],
                    "json.dumps written through open(path, 'w') with no "
                    "os.replace in scope — non-atomic JSON write",
                )


# -- ledger-fsync --------------------------------------------------------


class LedgerFsyncChecker(Checker):
    id = "ledger-fsync"
    hint = "flush + os.fsync the ledger handle before returning"
    interests = _FUNC_NODES

    def interested(self, ctx: FileContext) -> bool:
        return "ledger/" in ctx.path.replace("\\", "/")

    def visit(self, node, ctx: FileContext) -> None:
        writes = []
        has_fsync = False
        for c in _direct_calls(node):
            name = _callee_name(c.func)
            if name == "fsync":
                has_fsync = True
            elif (
                name == "write"
                and isinstance(c.func, ast.Attribute)
                and isinstance(c.func.value, ast.Attribute)
                and c.func.value.attr == "_file"
            ):
                writes.append(c.lineno)
        if writes and not has_fsync:
            self.report(
                ctx,
                min(writes),
                "ledger handle written without os.fsync in the same "
                "function — the journal may lag the snapshot/report it "
                "must precede (fsync-before-report invariant)",
            )
