"""lease-write: lease files are written ONLY by service/leases.py.

The fleet-federation argument (ISSUE 12) that double execution is
structurally impossible rests on every lease mutation going through
the atomic helpers — ``O_EXCL`` create, rename-tomb steal/restore,
token-checked tmp+rename refresh. A lease written any other way (a
convenient ``json.dump(lease, open(path, "w"))`` in a future scheduler
refactor) silently re-opens the read-modify-write window the helpers
exist to close, and nothing would fail until two servers actually
raced. This checker makes that a lint error instead.

What is flagged, outside ``service/leases.py``:

- ``open(<lease-ish>, "w"/"a"/...)`` — any write/append/update mode;
- ``os.open(<lease-ish>, ...)`` — the O_EXCL path is helper-only too;
- ``os.replace``/``os.rename`` whose DESTINATION is lease-ish (a
  rename onto a lease file is a lease write; renaming a lease away is
  the tomb protocol, also helper-only — so either operand trips it);
- ``os.unlink``/``os.remove`` of a lease-ish path (release is
  token-checked in the helper; a bare unlink is a fencing bypass).

"Lease-ish" is judged lexically and conservatively: a string constant
containing ``lease.json``, or an identifier (name, attribute, keyword
path segment) whose ``lease``/``leases`` appears as a whole ``_``-
delimited word — so ``t.lease``, ``lease_path``, ``"lease.json"`` all
match while ``release``/``released_jobs`` never do. Reads (plain
``open(path)`` in the default mode, ``_read_json``) stay free: status
and report surfaces may inspect leases at will.
"""

from __future__ import annotations

import ast
import re

from mpi_opt_tpu.analysis.core import Checker, FileContext

#: `lease` / `leases` as a whole word inside an identifier's
#: underscore-split (or at a dotted/word boundary): `lease_path` yes,
#: `t.lease` yes (attr == "lease"), `release`/`released` no
_LEASE_WORD = re.compile(r"(?:^|_)leases?(?:_|$)")


def _lease_ident(name: str) -> bool:
    return bool(_LEASE_WORD.search(name))


def _mentions_lease(node) -> bool:
    """Does this expression lexically name a lease path?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            if "lease.json" in sub.value or _lease_ident(sub.value):
                return True
        elif isinstance(sub, ast.Name) and _lease_ident(sub.id):
            return True
        elif isinstance(sub, ast.Attribute) and _lease_ident(sub.attr):
            return True
    return False


def _callee(fn):
    """(module-ish, name) for a call target: os.replace -> ("os",
    "replace"); bare open -> ("", "open")."""
    if isinstance(fn, ast.Attribute):
        base = fn.value.id if isinstance(fn.value, ast.Name) else ""
        return base, fn.attr
    if isinstance(fn, ast.Name):
        return "", fn.id
    return "", ""


_WRITE_MODES = re.compile(r"[wax+]")


class LeaseWriteChecker(Checker):
    id = "lease-write"
    hint = (
        "go through service/leases.py (acquire/refresh/release) — the "
        "atomic, token-checked helpers are what makes exactly-one-"
        "claimant true"
    )
    interests = (ast.Call,)

    def interested(self, ctx: FileContext) -> bool:
        # the helpers' own home is the one legal writer
        return not ctx.path.replace("\\", "/").endswith("service/leases.py")

    def visit(self, node, ctx: FileContext) -> None:
        base, name = _callee(node.func)
        if name == "open":
            # open(path, "w"/"a"/"r+"/...) or os.open(path, flags):
            # os.open is always suspicious on a lease (its only
            # legitimate lease use IS the helper's O_EXCL create);
            # builtin open only in an explicit write-ish mode
            if not node.args or not _mentions_lease(node.args[0]):
                return
            if base == "os":
                self.report(
                    ctx, node, "os.open of a lease path outside service/leases.py"
                )
                return
            mode = None
            if len(node.args) >= 2:
                mode = node.args[1]
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if (
                isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and _WRITE_MODES.search(mode.value)
            ):
                self.report(
                    ctx,
                    node,
                    f"open(..., {mode.value!r}) on a lease path outside "
                    "service/leases.py",
                )
            return
        if base != "os":
            return
        if name in ("replace", "rename"):
            if any(_mentions_lease(a) for a in node.args[:2]):
                self.report(
                    ctx,
                    node,
                    f"os.{name} involving a lease path outside "
                    "service/leases.py (the tomb protocol is helper-only)",
                )
        elif name in ("unlink", "remove"):
            if node.args and _mentions_lease(node.args[0]):
                self.report(
                    ctx,
                    node,
                    f"os.{name} of a lease path outside service/leases.py "
                    "(release is token-checked; bare unlink bypasses the fence)",
                )
