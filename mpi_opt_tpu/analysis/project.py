"""racelint pass 1: the repo-wide concurrency symbol table.

Per-file lexical checks cannot judge the engine's concurrency
contracts: whether a module global is written from BOTH the staging
thread and the main loop is a property of the whole call graph, and a
lock-order cycle is by definition cross-file. This module builds the
one table those judgements need, from the SAME parse the per-file pass
already did (FileContext trees are reused; no second parse):

- **locks** — ``threading.Lock()``/``RLock()``/``Condition(...)``
  assigned to a module-level name, an instance attribute
  (``self._lock = ...``), or a function local. A ``Condition`` wrapping
  a known lock is an alias of it (acquiring the condition acquires the
  lock).
- **thread entries** — ``threading.Thread(target=...)`` targets.
- **signal entries** — handlers installed via ``signal.signal``.
- **beat entries** — callables registered through
  ``set_beat_listener``/``set_slice_hook`` (they run on whatever thread
  beats — including the staging transfer thread), plus the structural
  roots of the beat path itself: ``beat``/``_notify_listener`` defined
  in a ``heartbeat.py`` and ``poll_slice`` in a ``shutdown.py``.
- **module globals** — declarations plus every write site (``global X``
  rebinds, and subscript/augmented mutations of a module-level name),
  each tagged with the ``with``-locks lexically held. Attribute stores
  are skipped on purpose: ``_LOCAL.stack = []`` on a
  ``threading.local`` is the per-thread idiom, not a shared write.
- **call graph** — resolved conservatively: bare names to same-file
  defs (nested defs included — the scheduler's ``hook``/``on_beat``
  closures are exactly the functions that matter) or
  ``from m import f`` imports; ``mod.f`` through the file's import
  aliases (matched by module stem); ``self.m`` to the enclosing class;
  locals whose constructor was seen
  (``r = leases.Refresher(...)`` then ``r()``) to that class's
  ``__call__``/method; anything else by project-wide name match EXCEPT
  a deny list of generic method names (``get``/``put``/``close``/...)
  whose matches would connect unrelated subsystems. Dynamic dispatch
  through stored callables is out of scope — the registration APIs
  above are modeled explicitly because they ARE the dynamic edges that
  matter here.

Pass 2 (checkers_concurrency.py) runs the guarded-by /
beat-path-nonblocking / signal-safety / lock-order judgements over this
table; ``summary()`` is the ``lint --json`` "project" section.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from mpi_opt_tpu.analysis.core import FileContext, relpath_under

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

#: attribute-call names too generic for cross-file name-fallback
#: resolution — an edge guessed from ``.get()`` or ``.close()`` would
#: connect unrelated subsystems and poison every reachability set
_GENERIC_NAMES = frozenset(
    {
        "get", "put", "set", "add", "pop", "update", "append", "extend",
        "remove", "insert", "items", "keys", "values", "close", "open",
        "read", "write", "flush", "join", "start", "run", "send", "recv",
        "acquire", "release", "wait", "notify", "notify_all", "clear",
        "copy", "index", "count", "sort", "split", "strip", "encode",
        "decode", "format", "log", "exists", "mkdir", "load", "loads",
        "dump", "dumps", "save",
    }
)

_REGISTRARS = {
    "set_beat_listener": "beat listener",
    "set_slice_hook": "slice hook",
}


@dataclass
class LockDef:
    key: str  # "path::Class._lock" / "path::_TOKEN_LOCK" / "path::fn.v"
    name: str  # display name, e.g. "heartbeat.Heartbeat._lock"
    file: str
    line: int
    kind: str  # Lock | RLock | Condition
    alias_of: Optional[str] = None  # Condition wrapping a known lock

    def resolve(self, table: "ProjectTable") -> str:
        """The underlying lock key (Condition aliases collapse)."""
        if self.alias_of and self.alias_of in table.locks:
            return self.alias_of
        return self.key


@dataclass
class FuncInfo:
    key: str  # "path::qualname"
    name: str
    qualname: str
    file: str
    line: int
    cls: Optional[str]  # enclosing class name, if a method
    #: raw call records: (shape, base, attr, line); shape "direct" has
    #: the resolved funckey in base, "instance" a (path, Class) tuple
    raw_calls: list = field(default_factory=list)
    #: lock events: (lock_key, line, mode) — mode "with" | "blocking" |
    #: "nonblocking" | "timeout"
    lock_events: list = field(default_factory=list)
    #: lexical nesting: (outer_key, inner_key, line, inner_mode)
    nested_locks: list = field(default_factory=list)
    #: calls made while holding locks: (held tuple, rawcall, line)
    calls_under_lock: list = field(default_factory=list)


@dataclass
class GlobalDef:
    file: str
    name: str
    line: int  # declaration line (first module-level binding)
    #: write sites: (funckey_or_None, line, with-locks held tuple)
    writes: list = field(default_factory=list)


@dataclass
class ProjectTable:
    ctxs: dict = field(default_factory=dict)  # path -> FileContext
    locks: dict = field(default_factory=dict)  # key -> LockDef
    functions: dict = field(default_factory=dict)  # key -> FuncInfo
    classes: dict = field(default_factory=dict)  # path -> {cls: {meth: key}}
    globals: dict = field(default_factory=dict)  # (path, name) -> GlobalDef
    thread_entries: list = field(default_factory=list)  # (funckey, reason)
    signal_entries: list = field(default_factory=list)
    beat_entries: list = field(default_factory=list)
    calls: dict = field(default_factory=dict)  # funckey -> set(funckey)
    callers: dict = field(default_factory=dict)  # reverse edges
    # resolution indexes
    by_stem: dict = field(default_factory=dict)  # module stem -> [paths]
    by_name: dict = field(default_factory=dict)  # func name -> [funckeys]
    imports: dict = field(default_factory=dict)  # path -> alias map
    #: memoized lock_order_edges result — the checker and the cli's
    #: project summary both need it; computing the call-resolution
    #: pass twice per lint run would double the project pass's cost
    edge_cache: Optional[list] = None
    #: seconds spent in build_table (scans + linking) — the dominant
    #: cost of the project pass, charged to the synthetic
    #: "project-table" entry in `lint --json` checks so per-checker
    #: wall_s attribution stays honest
    build_wall_s: float = 0.0

    # -- queries ----------------------------------------------------------

    def reachable(self, roots) -> set:
        seen = set(roots)
        stack = list(roots)
        while stack:
            cur = stack.pop()
            for nxt in self.calls.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return seen

    def thread_side(self) -> set:
        """Functions reachable from ANY asynchronous entry: thread
        targets, signal handlers, registered beat listeners/slice hooks
        and the beat-path roots (listeners run on whichever thread
        beats, so the beat path is thread-side by construction)."""
        roots = [k for k, _r in self.thread_entries]
        roots += [k for k, _r in self.signal_entries]
        roots += [k for k, _r in self.beat_entries]
        return self.reachable(roots)

    def main_side(self) -> set:
        """Functions reachable from main-line code: BFS from every
        function that is NOT itself thread-side. A helper called both
        from the staging thread and from the driver lands in BOTH
        sets — which is exactly the shared-write shape guarded-by
        exists to judge."""
        t = self.thread_side()
        return self.reachable([k for k in self.functions if k not in t])

    def lock_display(self, key: str) -> str:
        d = self.locks.get(key)
        return d.name if d else key

    def resolve_lock(self, key: str) -> str:
        d = self.locks.get(key)
        return d.resolve(self) if d else key


# -- pass 1: per-file scan -------------------------------------------------


def _stem(path: str) -> str:
    name = path.replace("\\", "/").rsplit("/", 1)[-1]
    return name[:-3] if name.endswith(".py") else name


def _call_shape(call: ast.Call):
    """(shape, base, attr) for a call target expression."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return ("name", None, fn.id)
    if isinstance(fn, ast.Attribute):
        base = fn.value
        if isinstance(base, ast.Name):
            if base.id == "self":
                return ("self", None, fn.attr)
            return ("attr", base.id, fn.attr)
        return ("chain", None, fn.attr)
    return ("dynamic", None, "")


def _acquire_mode(call: ast.Call) -> str:
    """"nonblocking" (blocking=False / positional False), "timeout", or
    "blocking" for a bare ``acquire()``."""
    for kw in call.keywords:
        if kw.arg == "blocking":
            if isinstance(kw.value, ast.Constant) and kw.value.value is False:
                return "nonblocking"
        if kw.arg == "timeout":
            return "timeout"
    if call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and a0.value is False:
            return "nonblocking"
        if len(call.args) >= 2:
            return "timeout"
    return "blocking"


class _FileScan:
    """One file's contribution to the table."""

    def __init__(self, ctx: FileContext, table: ProjectTable):
        self.ctx = ctx
        self.path = ctx.path
        self.table = table
        #: alias -> ("module", stem) | ("symbol", modstem, symbol)
        self.aliases: dict = {}
        self.module_globals: set = set()
        self.module_locks: dict = {}  # name -> lock key

    def key(self, qualname: str) -> str:
        return f"{self.path}::{qualname}"

    # -- scan -------------------------------------------------------------

    def scan(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    self.aliases[alias] = ("module", a.name.split(".")[-1])
            elif isinstance(node, ast.ImportFrom):
                modstem = (node.module or "").split(".")[-1]
                for a in node.names:
                    # `from pkg import mod` and `from mod import sym`
                    # are lexically identical; the linker tries both
                    self.aliases[a.asname or a.name] = ("symbol", modstem, a.name)
        for stmt in self.ctx.tree.body:
            self._module_stmt(stmt)
        for stmt in self.ctx.tree.body:
            if isinstance(stmt, _FUNC_NODES):
                self._scan_func(stmt, qual=stmt.name, cls=None, env={})
            elif isinstance(stmt, ast.ClassDef):
                self._scan_class(stmt)
        # module-level calls (import-time registration is rare but
        # legal) ride a pseudo-function "<module>"
        mod_fn = self._ensure_fn("<module>", line=1)
        body = [
            s
            for s in self.ctx.tree.body
            if not isinstance(s, (*_FUNC_NODES, ast.ClassDef))
        ]
        self._scan_body(mod_fn, body, env={}, declared=set(), cls=None)

    def _module_stmt(self, stmt) -> None:
        targets, value = [], None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign):
            targets, value = [stmt.target], stmt.value
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            name = t.id
            kind = (
                _lock_factory_kind(value, self.aliases)
                if isinstance(value, ast.Call)
                else None
            )
            if kind:
                key = self.key(name)
                self.table.locks[key] = LockDef(
                    key, f"{_stem(self.path)}.{name}", self.path, stmt.lineno,
                    kind, self._condition_alias(value, cls=None),
                )
                self.module_locks[name] = key
            elif self._is_threading_local(value):
                pass  # per-thread containers are not shared state
            elif name not in self.module_globals:
                self.module_globals.add(name)
                self.table.globals[(self.path, name)] = GlobalDef(
                    self.path, name, stmt.lineno
                )
            else:
                g = self.table.globals.get((self.path, name))
                if g is not None:  # later module-level rebind: main-line
                    g.writes.append((None, stmt.lineno, ()))

    def _is_threading_local(self, value) -> bool:
        if not isinstance(value, ast.Call):
            return False
        _shape, base, attr = _call_shape(value)
        return attr == "local" and base in ("threading", None)

    def _condition_alias(self, call: ast.Call, cls: Optional[str]) -> Optional[str]:
        """``Condition(<known lock>)`` aliases that lock."""
        _shape, _b, attr = _call_shape(call)
        if attr != "Condition" or not call.args:
            return None
        arg = call.args[0]
        if isinstance(arg, ast.Name) and arg.id in self.module_locks:
            return self.module_locks[arg.id]
        if (
            isinstance(arg, ast.Attribute)
            and isinstance(arg.value, ast.Name)
            and arg.value.id == "self"
            and cls
        ):
            return self.key(f"{cls}.{arg.attr}")
        return None

    def _scan_class(self, cls: ast.ClassDef) -> None:
        methods = self.table.classes.setdefault(self.path, {}).setdefault(
            cls.name, {}
        )
        for stmt in cls.body:
            if isinstance(stmt, _FUNC_NODES):
                qual = f"{cls.name}.{stmt.name}"
                methods[stmt.name] = self.key(qual)
                self._scan_func(stmt, qual=qual, cls=cls.name, env={})

    def _ensure_fn(self, qual: str, line: int) -> FuncInfo:
        key = self.key(qual)
        fn = self.table.functions.get(key)
        if fn is None:
            fn = FuncInfo(
                key=key, name=qual.rsplit(".", 1)[-1], qualname=qual,
                file=self.path, line=line, cls=None,
            )
            self.table.functions[key] = fn
            self.table.by_name.setdefault(fn.name, []).append(key)
        return fn

    def _scan_func(self, node, qual: str, cls: Optional[str], env: dict) -> None:
        fn = self._ensure_fn(qual, node.lineno)
        fn.cls = cls
        # `global` declarations of THIS function only — a nested def's
        # `global X` must not leak here, or the enclosing function's
        # LOCAL X (ordinary Python scoping) would be misread as a
        # module-global write
        declared: set = set()
        stack = list(ast.iter_child_nodes(node))
        while stack:
            sub = stack.pop()
            if isinstance(sub, (*_FUNC_NODES, ast.Lambda)):
                continue
            if isinstance(sub, ast.Global):
                declared.update(sub.names)
            stack.extend(ast.iter_child_nodes(sub))
        local_env = dict(env)  # nested defs see the enclosing
        # function's constructor-typed locals and sibling defs
        self._collect_local_bindings(node, qual, cls, local_env)
        self._scan_body(fn, node.body, local_env, declared, cls)
        for stmt in self._direct_nested_defs(node):
            self._scan_func(
                stmt, qual=f"{qual}.{stmt.name}", cls=cls, env=local_env
            )

    @staticmethod
    def _direct_nested_defs(parent):
        stack = list(ast.iter_child_nodes(parent))
        while stack:
            n = stack.pop()
            if isinstance(n, _FUNC_NODES):
                yield n
                continue
            stack.extend(ast.iter_child_nodes(n))

    def _collect_local_bindings(self, node, qual, cls, env) -> None:
        """Lexical sweep: local lock constructions, instance-attr lock
        constructions (``self._lock = threading.Lock()`` — how instance
        locks enter the table), constructor-typed locals, nested-def
        names."""
        stack = list(node.body)
        while stack:
            stmt = stack.pop(0)
            if isinstance(stmt, _FUNC_NODES):
                env[stmt.name] = ("func", self.key(f"{qual}.{stmt.name}"))
                continue
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
                kind = _lock_factory_kind(stmt.value, self.aliases)
                tgt = stmt.targets[0] if len(stmt.targets) == 1 else None
                if kind and isinstance(tgt, ast.Name):
                    key = self.key(f"{qual}.{tgt.id}")
                    self.table.locks[key] = LockDef(
                        key, f"{_stem(self.path)}.{qual}.{tgt.id}", self.path,
                        stmt.lineno, kind, self._condition_alias(stmt.value, cls),
                    )
                    env[tgt.id] = ("lock", key)
                elif (
                    kind
                    and isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and cls
                ):
                    key = self.key(f"{cls}.{tgt.attr}")
                    self.table.locks[key] = LockDef(
                        key, f"{_stem(self.path)}.{cls}.{tgt.attr}", self.path,
                        stmt.lineno, kind, self._condition_alias(stmt.value, cls),
                    )
                elif isinstance(tgt, ast.Name):
                    ckey = self._class_of_call(stmt.value)
                    if ckey:
                        env[tgt.id] = ("instance", ckey)
            for ch in ast.iter_child_nodes(stmt):
                if isinstance(ch, ast.stmt):
                    stack.append(ch)
                elif isinstance(ch, ast.excepthandler):
                    stack.extend(ch.body)

    def _class_of_call(self, call: ast.Call):
        """(path, ClassName) when the call constructs a project class
        (CamelCase heuristic gates the lookup)."""
        shape, base, attr = _call_shape(call)
        if not attr or not attr[0].isupper():
            return None
        candidates = []
        if shape == "name":
            candidates.append((self.path, attr))
            tgt = self.aliases.get(attr)
            if tgt and tgt[0] == "symbol":
                for p in self.table.by_stem.get(tgt[1], ()):
                    candidates.append((p, attr))
        elif shape == "attr":
            stems = [base]
            tgt = self.aliases.get(base)
            if tgt:
                stems.append(tgt[1])
                if tgt[0] == "symbol":
                    stems.append(tgt[2])
            for s in stems:
                for p in self.table.by_stem.get(s, ()):
                    candidates.append((p, attr))
        for p, c in candidates:
            if c in self.table.classes.get(p, {}):
                return (p, c)
        return None

    # -- body scan: calls, lock events, global writes ---------------------

    def _lock_of_expr(self, expr, cls: Optional[str], env: dict):
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks:
                return self.module_locks[expr.id]
            hit = env.get(expr.id)
            if hit and hit[0] == "lock":
                return hit[1]
        elif (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and cls
        ):
            key = self.key(f"{cls}.{expr.attr}")
            if key in self.table.locks:
                return key
        return None

    def _scan_body(
        self, fn: FuncInfo, body, env: dict, declared: set,
        cls: Optional[str], held: tuple = (),
    ) -> None:
        for stmt in body:
            if isinstance(stmt, _FUNC_NODES):
                continue  # nested defs are their own functions
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in stmt.items:
                    lk = self._lock_of_expr(item.context_expr, cls, env)
                    if lk is not None:
                        fn.lock_events.append((lk, stmt.lineno, "with"))
                        for outer in new_held:
                            fn.nested_locks.append(
                                (outer, lk, stmt.lineno, "with")
                            )
                        new_held = new_held + (lk,)
                    else:
                        self._scan_exprs(fn, item.context_expr, env, cls, held)
                self._scan_body(fn, stmt.body, env, declared, cls, new_held)
                continue
            self._global_writes(fn, stmt, declared, held)
            self._scan_exprs(fn, stmt, env, cls, held, own_exprs_only=True)
            for ch in ast.iter_child_nodes(stmt):
                if isinstance(ch, ast.stmt):
                    self._scan_body(fn, [ch], env, declared, cls, held)
                elif isinstance(ch, ast.excepthandler):
                    self._scan_body(fn, ch.body, env, declared, cls, held)

    def _global_writes(self, fn, stmt, declared, held) -> None:
        if fn.qualname == "<module>":
            return  # module-level statements are import-time init
        names = []
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                names.extend(self._write_names(t, declared))
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            names.extend(self._write_names(stmt.target, declared))
        for name in names:
            g = self.table.globals.get((self.path, name))
            if g is not None:
                g.writes.append((fn.key, stmt.lineno, held))

    def _write_names(self, target, declared) -> list:
        out = []
        if isinstance(target, ast.Name):
            if target.id in declared and target.id in self.module_globals:
                out.append(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                out.extend(self._write_names(el, declared))
        elif isinstance(target, ast.Subscript):
            base = target.value
            # mutation of a module-level container needs no `global`
            if isinstance(base, ast.Name) and base.id in self.module_globals:
                out.append(base.id)
        return out

    def _scan_exprs(
        self, fn, node, env, cls, held, own_exprs_only: bool = False
    ) -> None:
        stack = list(ast.iter_child_nodes(node)) if own_exprs_only else [node]
        while stack:
            cur = stack.pop()
            if isinstance(cur, (*_FUNC_NODES, ast.Lambda)):
                continue
            if own_exprs_only and isinstance(cur, ast.stmt):
                continue  # nested statements handled by _scan_body
            if isinstance(cur, ast.Call):
                self._record_call(fn, cur, env, cls, held)
            stack.extend(ast.iter_child_nodes(cur))

    def _record_call(self, fn, call: ast.Call, env, cls, held) -> None:
        shape, base, attr = _call_shape(call)
        # lock.acquire(...) events (any base form the lock resolver knows)
        if attr == "acquire" and isinstance(call.func, ast.Attribute):
            lk = self._lock_of_expr(call.func.value, cls, env)
            if lk is not None:
                mode = _acquire_mode(call)
                fn.lock_events.append((lk, call.lineno, mode))
                for outer in held:
                    fn.nested_locks.append((outer, lk, call.lineno, mode))
                return
        # registrations: thread targets, signal handlers, beat listeners
        if attr == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    self.table.thread_entries.append(
                        (self._ref(kw.value, env, cls), "Thread target")
                    )
        elif attr == "signal" and len(call.args) >= 2:
            self.table.signal_entries.append(
                (self._ref(call.args[1], env, cls), "signal handler")
            )
        elif attr in _REGISTRARS and call.args:
            self.table.beat_entries.append(
                (self._ref(call.args[0], env, cls), _REGISTRARS[attr])
            )
        raw = self._classify_call(shape, base, attr, env)
        if raw is None:
            return
        fn.raw_calls.append((*raw, call.lineno))
        if held:
            fn.calls_under_lock.append((held, raw, call.lineno))

    def _classify_call(self, shape, base, attr, env):
        """Rewrite a call shape against the local env: constructor-typed
        locals become ("instance", (path, Class), method); known nested
        defs become ("direct", funckey, None)."""
        if shape == "name":
            hit = env.get(attr)
            if hit:
                if hit[0] == "func":
                    return ("direct", hit[1], None)
                if hit[0] == "instance":
                    return ("instance", hit[1], "__call__")
                if hit[0] == "lock":
                    return None
            return ("name", None, attr)
        if shape == "attr":
            hit = env.get(base)
            if hit and hit[0] == "instance":
                return ("instance", hit[1], attr)
            return (shape, base, attr)
        return (shape, base, attr)

    def _ref(self, expr, env, cls):
        """A callable REFERENCE passed to Thread/signal/registrar APIs:
        a funckey, a deferred marker resolved by the linker, or None."""
        if isinstance(expr, ast.Name):
            hit = env.get(expr.id)
            if hit and hit[0] == "func":
                return hit[1]
            if hit and hit[0] == "instance":
                return ("instance_ref", hit[1])
            return ("name_ref", self.path, expr.id)
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            if expr.value.id == "self" and cls:
                return ("method_ref", self.path, cls, expr.attr)
            return ("mod_ref", self.path, expr.value.id, expr.attr)
        return None


def _lock_factory_kind(call: ast.Call, aliases: dict) -> Optional[str]:
    """"Lock"/"RLock"/"Condition" when ``call`` constructs a threading
    primitive (``threading.Lock()``, a bare ``Lock()`` from-import, or
    through a module alias)."""
    shape, base, attr = _call_shape(call)
    if attr not in ("Lock", "RLock", "Condition"):
        return None
    if shape == "name":
        tgt = aliases.get(attr)
        return attr if tgt and tgt[1] == "threading" else None
    if shape == "attr":
        tgt = aliases.get(base)
        if base == "threading" or (tgt and "threading" in (tgt[1],) + tgt[2:]):
            return attr
    return None


# -- linking ---------------------------------------------------------------


class _Linker:
    def __init__(self, table: ProjectTable):
        self.table = table

    def resolve_call(self, path: str, raw) -> list:
        shape, base, attr = raw
        t = self.table
        if shape == "direct":
            return [base] if base in t.functions else []
        if shape == "instance":
            p, c = base
            key = t.classes.get(p, {}).get(c, {}).get(attr)
            return [key] if key else []
        aliases = t.imports.get(path, {})
        if shape == "name":
            key = f"{path}::{attr}"
            if key in t.functions:
                return [key]
            tgt = aliases.get(attr)
            out = []
            if tgt and tgt[0] == "symbol":
                for p in t.by_stem.get(tgt[1], ()):
                    key = f"{p}::{attr}"
                    if key in t.functions:
                        out.append(key)
            return out
        if shape == "attr":
            stems = [base]
            tgt = aliases.get(base)
            if tgt:
                stems.append(tgt[1])
                if tgt[0] == "symbol" and len(tgt) > 2:
                    stems.append(tgt[2])
            out = []
            for s in stems:
                for p in t.by_stem.get(s, ()):
                    key = f"{p}::{attr}"
                    if key in t.functions:
                        out.append(key)
            if out:
                return out
        # fallback: project-wide by (non-generic) name; dunders never
        # fallback — `ann.__enter__()` matching every context manager
        # in the repo would weld unrelated subsystems together
        if attr and attr not in _GENERIC_NAMES and not attr.startswith("__"):
            return list(t.by_name.get(attr, ()))
        return []

    def resolve_with_class(self, fn: FuncInfo, raw) -> list:
        """``resolve_call`` plus the enclosing-class context a "self"
        call needs — the ONE resolution rule for both the call graph
        and the lock-order call edges (a self-method call through a
        generic name like ``.put()`` resolves here where the bare name
        fallback would conservatively drop it)."""
        shape, _base, attr = raw
        if shape == "self":
            methods = self.table.classes.get(fn.file, {}).get(fn.cls or "", {})
            if attr in methods:
                return [methods[attr]]
            return self.resolve_call(fn.file, ("chain", None, attr))
        return self.resolve_call(fn.file, raw)

    def link(self) -> None:
        t = self.table
        for key, fn in t.functions.items():
            targets: set = set()
            for shape, base, attr, _line in fn.raw_calls:
                targets.update(self.resolve_with_class(fn, (shape, base, attr)))
            t.calls[key] = {k for k in targets if k in t.functions and k != key}
        for key, callees in t.calls.items():
            for callee in callees:
                t.callers.setdefault(callee, set()).add(key)

    def resolve_entry(self, ref):
        t = self.table
        if isinstance(ref, str):
            return [ref] if ref in t.functions else []
        if not isinstance(ref, tuple):
            return []
        if ref[0] == "name_ref":
            _tag, path, name = ref
            return self.resolve_call(path, ("name", None, name))
        if ref[0] == "method_ref":
            _tag, path, cls, attr = ref
            key = t.classes.get(path, {}).get(cls, {}).get(attr)
            return [key] if key else []
        if ref[0] == "mod_ref":
            _tag, path, base, attr = ref
            return self.resolve_call(path, ("attr", base, attr))
        if ref[0] == "instance_ref":
            _tag, (path, cls) = ref
            key = t.classes.get(path, {}).get(cls, {}).get("__call__")
            return [key] if key else []
        return []


def build_table(ctxs) -> ProjectTable:
    """Pass 1 over already-parsed files: register class names first
    (constructor typing needs them project-wide), scan every file, link
    the call graph, resolve entry references, seed the beat roots."""
    table = ProjectTable()
    scans = []
    for ctx in ctxs:
        table.ctxs[ctx.path] = ctx
        table.by_stem.setdefault(_stem(ctx.path), []).append(ctx.path)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                table.classes.setdefault(ctx.path, {}).setdefault(node.name, {})
        scans.append(_FileScan(ctx, table))
    for s in scans:
        s.scan()
        table.imports[s.path] = s.aliases
    linker = _Linker(table)
    linker.link()
    for attr in ("thread_entries", "signal_entries", "beat_entries"):
        resolved = []
        for ref, reason in getattr(table, attr):
            for key in linker.resolve_entry(ref):
                resolved.append((key, reason))
        setattr(table, attr, resolved)
    for path in table.by_stem.get("heartbeat", ()):
        for key, fn in table.functions.items():
            if fn.file == path and fn.name in ("beat", "_notify_listener"):
                table.beat_entries.append((key, "beat-path root"))
    for path in table.by_stem.get("shutdown", ()):
        for key, fn in table.functions.items():
            if fn.file == path and fn.name == "poll_slice":
                table.beat_entries.append((key, "beat-path root"))
    return table


# -- lock-order edges ------------------------------------------------------


def lock_order_edges(table: ProjectTable) -> list:
    """The static partial order: ``(outer_key, inner_key, file, line)``
    for every lexical nesting plus one-hop call edges (a with-lock body
    calling a function that acquires another lock). Non-blocking
    acquires contribute no edge — a trylock cannot deadlock. Memoized
    per table (the checker and the summary share one computation)."""
    if table.edge_cache is not None:
        return table.edge_cache
    edges = []
    linker = _Linker(table)
    for fn in table.functions.values():
        for outer, inner, line, mode in fn.nested_locks:
            if mode == "nonblocking":
                continue
            o, i = table.resolve_lock(outer), table.resolve_lock(inner)
            if o != i:
                edges.append((o, i, fn.file, line))
        for held, raw, line in fn.calls_under_lock:
            for callee_key in linker.resolve_with_class(fn, raw):
                callee = table.functions.get(callee_key)
                if callee is None:
                    continue
                for lk, _ln, mode in callee.lock_events:
                    if mode == "nonblocking":
                        continue
                    i = table.resolve_lock(lk)
                    for outer in held:
                        o = table.resolve_lock(outer)
                        if o != i:
                            edges.append((o, i, fn.file, line))
    table.edge_cache = edges
    return edges


def find_cycles(edges) -> list:
    """Cycles in the lock-order graph, each reported once (rotated
    smallest-first for determinism)."""
    graph: dict = {}
    for o, i, _f, _l in edges:
        graph.setdefault(o, set()).add(i)
    cycles, seen = [], set()
    WHITE, GRAY, BLACK = 0, 1, 2
    color: dict = {}

    def dfs(node, stack):
        color[node] = GRAY
        stack.append(node)
        for nxt in sorted(graph.get(node, ())):
            if color.get(nxt, WHITE) == GRAY:
                cyc = stack[stack.index(nxt):]
                lo = min(range(len(cyc)), key=lambda j: cyc[j])
                norm = tuple(cyc[lo:] + cyc[:lo])
                if norm not in seen:
                    seen.add(norm)
                    cycles.append(list(norm))
            elif color.get(nxt, WHITE) == WHITE:
                dfs(nxt, stack)
        stack.pop()
        color[node] = BLACK

    for n in sorted(graph):
        if color.get(n, WHITE) == WHITE:
            dfs(n, [])
    return cycles


# -- the `lint --json` project section -------------------------------------


def summary(table: ProjectTable, root: Optional[str] = None) -> dict:
    """The machine-readable project-pass digest: locks discovered,
    thread/signal/beat entries, and the lock-order graph."""
    edges = lock_order_edges(table)
    uniq_edges = sorted(
        {(table.lock_display(o), table.lock_display(i)) for o, i, _f, _l in edges}
    )

    def fq(key):
        fn = table.functions.get(key)
        if fn is None:
            return key
        return f"{relpath_under(fn.file, root)}::{fn.qualname}"

    return {
        "locks": sorted(
            (
                {
                    "name": d.name,
                    "file": relpath_under(d.file, root),
                    "line": d.line,
                    "kind": d.kind,
                }
                for d in table.locks.values()
            ),
            key=lambda x: (x["file"], x["line"]),
        ),
        "thread_entries": sorted({fq(k) for k, _r in table.thread_entries}),
        "signal_handlers": sorted({fq(k) for k, _r in table.signal_entries}),
        "beat_entries": sorted({fq(k) for k, _r in table.beat_entries}),
        "lock_order": {
            "edges": [list(e) for e in uniq_edges],
            "cycles": [
                [table.lock_display(k) for k in cyc]
                for cyc in find_cycles(edges)
            ],
        },
    }
