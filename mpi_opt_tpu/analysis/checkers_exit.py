"""exit-code: exit-code literals outside ``utils/exitcodes``.

Three layers classify the sweep's exit codes (CLI producing them,
launch supervisor restart policy, service tenant state machine); PR 7
consolidated the literals into ``utils/exitcodes.py`` precisely because
keeping bare 75s/65s in sync across them failed twice in review. The
invariant: a REGISTERED code (0/1/2/65/69/75) appears as an integer
literal only in ``utils/exitcodes.py`` — everywhere else it must be the
named constant, both in exit calls (``sys.exit(75)``,
``SystemExit(65)``, ``os._exit(75)``) and in classification comparisons
(``rc == 75``). Unregistered codes (a chaos drill's ``os._exit(13)``)
are not this contract's business and pass.
"""

from __future__ import annotations

import ast

import re

from mpi_opt_tpu.analysis.core import Checker, FileContext

#: the registered contract codes (utils/exitcodes.py). 0 and 1 are
#: deliberately NOT flagged: `return 0`/`exit(1)` literals are the
#: universal unix idiom and carry no cross-layer protocol meaning the
#: named constants exist to protect (65/75/2 do).
CONTRACT_CODES = frozenset({2, 65, 69, 75})

_EXIT_CALLEES = frozenset({"exit", "_exit", "SystemExit"})

#: variable shapes that mean "this integer is an exit code" in a
#: comparison (returncode covers subprocess handles; `.code` covers
#: SystemExit instances)
_RC_NAME = re.compile(r"\b(rc|ret|returncode|exit_?code|code|status)\b", re.I)


def _exit_callee(fn) -> bool:
    if isinstance(fn, ast.Attribute):
        return fn.attr in _EXIT_CALLEES
    if isinstance(fn, ast.Name):
        return fn.id in _EXIT_CALLEES
    return False


class ExitCodeChecker(Checker):
    id = "exit-code"
    hint = "import the named constant from mpi_opt_tpu.utils.exitcodes"
    interests = (ast.Call, ast.Compare)

    def interested(self, ctx: FileContext) -> bool:
        # the one home for the literals; the table itself must hold them
        return not ctx.path.endswith("utils/exitcodes.py")

    def visit(self, node, ctx: FileContext) -> None:
        if isinstance(node, ast.Call):
            if not (_exit_callee(node.func) and node.args):
                return
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and arg.value in CONTRACT_CODES:
                self.report(
                    ctx,
                    node,
                    f"exit-code literal {arg.value} in an exit call — the "
                    "contract codes live in utils/exitcodes",
                )
            return
        # rc == 75 / rc != 65 classification comparisons: the exact
        # drift utils/exitcodes.classify() exists to end. Gated on the
        # OTHER operand naming an exit code (`rc`, `returncode`,
        # `exit_code`, `e.code`) — a bare `len(x) == 2` is not this
        # contract's business
        operands = [node.left, *node.comparators]
        literal = None
        for comparand in operands:
            if (
                isinstance(comparand, ast.Constant)
                and type(comparand.value) is int
                and comparand.value in CONTRACT_CODES
            ):
                literal = comparand.value
        if literal is None:
            return
        others = " ".join(
            ast.unparse(c) for c in operands if not isinstance(c, ast.Constant)
        )
        if _RC_NAME.search(others):
            self.report(
                ctx,
                node,
                f"exit-code literal {literal} compared against an exit "
                "code — use utils/exitcodes constants (or classify())",
            )
