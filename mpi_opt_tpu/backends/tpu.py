"""TPU population backend: trials are rows of one vmapped population.

This replaces the reference's Coordinator/MPIWorker runtime (SURVEY.md
§2 rows 7-9; reference unreadable — contract from BASELINE.json
north_star: "the per-rank trial-evaluation loop becomes a single vmapped
population kernel running on-device ... registered under the existing
``backend=`` plugin hook ... opt-in via ``--backend=tpu``").

Architecture:

- A device-resident **slot pool**: ``PopState`` with ``pool_size``
  member slots (params + momentum), initialized once. Trials map to
  slots; the mapping lives on the host (tiny), the states never leave
  the device.
- ``evaluate(trials)`` runs the WHOLE batch — even one mixing ASHA
  rungs — as one program chain, padded to a power of two (bounded
  recompile surface): gather source states → overwrite fresh members
  with new inits → ``train_segment_masked`` (the jitted
  scan-of-vmapped-steps, each member frozen past its own remaining
  budget) → eval → scatter back into the pool. One blocking score
  fetch per batch: on a tunneled TPU the per-rung-group fetches of the
  naive plan, not FLOPs, dominate the driver path's wall.
- PBT inheritance (``__inherit_from__``) and ASHA warm resume are both
  just gathers from the pool — the reference's MPI weight transfers and
  re-dispatches collapse into device-side index ops.
- Eviction: slots are LRU-recycled. Losing a slot is safe — budgets are
  cumulative, so an evicted trial retrains from scratch to its budget.

The per-search costs that remain on the host: one dataset upload, one
tiny score download per batch, and the trial ledger.
"""

from __future__ import annotations

import functools
import time
from collections import OrderedDict
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mpi_opt_tpu.backends.base import Backend, register_backend
from mpi_opt_tpu.trial import Trial, TrialResult, failed_result
from mpi_opt_tpu.workloads.base import Workload


@register_backend
class TPUPopulationBackend(Backend):
    name = "tpu"

    def __init__(
        self,
        workload: Workload,
        population: int = 32,
        seed: int = 0,
        member_chunk: int = 0,
        slot_slack: int = 2,
        eval_chunk: int = 1024,
        mesh=None,
    ):
        if not hasattr(workload, "make_trainer"):
            raise ValueError(
                f"workload {workload.name!r} has no population protocol "
                "(make_trainer/make_hparams/data); use --backend cpu"
            )
        super().__init__(workload)
        self.population = population
        self.seed = seed
        self.member_chunk = member_chunk
        self.eval_chunk = eval_chunk
        # optional ('pop','data') mesh: the slot pool shards its member
        # axis over 'pop' and batches constrain over 'data', so the
        # driver path reaches the same mesh layer the fused sweeps use
        self.mesh = mesh
        # slack >= 2 guarantees every batch can pin its sources (<= pop)
        # AND allocate its outputs (<= pop) without evicting a pinned
        # slot; +1 scratch slot absorbs padding writes
        self.pool_size = population * max(2, slot_slack) + 1
        if mesh is not None:
            # the pool only shards if its slot axis divides the 'pop'
            # axis (shard_popstate falls back to replication otherwise,
            # which would silently defeat the mesh); round up — extra
            # slots just enlarge the free list
            n_pop = mesh.shape["pop"]
            self.pool_size = -(-self.pool_size // n_pop) * n_pop
        self._scratch = self.pool_size - 1
        self._setup_done = False
        self._step_counter = 0
        # host-side ledger
        self._slot_of: "OrderedDict[int, int]" = OrderedDict()  # trial_id -> slot (LRU order)
        self._trained: dict[int, int] = {}  # trial_id -> steps completed

    @property
    def capacity(self) -> int:
        return self.population

    # -- lazy device setup ------------------------------------------------

    def _setup(self):
        if self._setup_done:
            return
        # single placement point shared with the fused sweeps: trainer
        # built for (member_chunk, mesh), datasets device-resident and
        # mesh-replicated (train/common.py)
        from mpi_opt_tpu.train.common import workload_arrays

        (
            self._trainer,
            self._space,
            self._train_x,
            self._train_y,
            self._val_x,
            self._val_y,
        ) = workload_arrays(self.workload, self.member_chunk, self.mesh)
        key = jax.random.fold_in(jax.random.key(self.seed), 7001)
        self._pool = self._trainer.init_population(
            key, self._train_x[:2], self.pool_size
        )
        self._pool = self._place_pool(self._pool)
        self._free = [s for s in range(self.pool_size) if s != self._scratch]
        self._setup_done = True

    def _place_pool(self, pool):
        """Shard the slot pool's member axis over the mesh 'pop' axis
        (no-op without a mesh, and zero-copy when already placed)."""
        if self.mesh is None:
            return pool
        from mpi_opt_tpu.parallel.mesh import shard_popstate

        return shard_popstate(pool, self.mesh)

    # -- slot management --------------------------------------------------

    def _alloc_slot(self, trial_id: int, pinned: set[int]) -> int:
        if self._free:
            slot = self._free.pop()
        else:
            # evict the least-recently-used *unpinned* trial; retraining
            # from scratch is always correct because budgets are
            # cumulative. Slots referenced by the in-flight batch are
            # pinned — evicting one mid-plan would silently turn a warm
            # resume into an under-trained fresh init.
            for old_id, cand in self._slot_of.items():  # LRU order
                if cand not in pinned:
                    slot = cand
                    del self._slot_of[old_id]
                    self._trained.pop(old_id, None)
                    break
            else:
                raise RuntimeError(
                    "slot pool exhausted by a single batch; raise slot_slack"
                )
        self._slot_of[trial_id] = slot
        return slot

    def _touch(self, trial_id: int):
        self._slot_of.move_to_end(trial_id)

    # -- evaluation --------------------------------------------------------

    def evaluate(self, trials: Sequence[Trial]) -> list[TrialResult]:
        self._setup()
        # -- atomic plan over the whole batch -----------------------------
        # Phase A: resolve every trial's state source against the CURRENT
        # ledger and pin those slots, so phase-B allocations can never
        # evict a source this batch still needs.
        pinned: set[int] = set()
        resolved = []
        for t in trials:
            src = t.params.get("__inherit_from__")
            if t.trial_id in self._slot_of:  # warm resume
                src_slot = self._slot_of[t.trial_id]
                done = self._trained.get(t.trial_id, 0)
                fresh = False
                self._touch(t.trial_id)
            elif src is not None and src in self._slot_of:  # PBT exploit copy
                src_slot = self._slot_of[src]
                done = self._trained.get(src, 0)
                fresh = False
            else:  # fresh member (or evicted lineage: full retrain)
                src_slot = self._scratch
                done = 0
                fresh = True
            pinned.add(src_slot)
            resolved.append((t, src_slot, fresh, done))
        # Phase B: allocate output slots (own slot for resumes). The
        # whole batch — even one mixing ASHA rungs — runs as ONE device
        # program with per-member remaining-step masks
        # (train_segment_masked): round 3 ran one program per rung group,
        # and the per-group blocking score fetches through the 20-90 ms
        # tunnel RTT were the driver path's dominant cost (VERDICT r3
        # item 2). Frozen members burn discarded-update FLOPs instead;
        # on this platform launches cost more than MLP/CNN step FLOPs.
        entries = []
        for t, src_slot, fresh, done in resolved:
            if t.trial_id in self._slot_of:
                out_slot = self._slot_of[t.trial_id]
            else:
                out_slot = self._alloc_slot(t.trial_id, pinned)
            pinned.add(out_slot)
            rem = max(0, t.budget - done)
            entries.append((t, src_slot, fresh, out_slot, rem))
        results = self._run_batch(entries)
        return [results[t.trial_id] for t in trials]

    def _run_batch(self, entries: list) -> dict[int, TrialResult]:
        """entries: (trial, src_slot, fresh, out_slot, rem) plan rows —
        one device program chain and ONE blocking score fetch for the
        whole batch."""
        if not entries:
            # empty batches must stay free AND not tick _step_counter:
            # reset()'s bit-identical-replay guarantee depends on the
            # RNG stream position being a pure function of the evaluated
            # batches
            return {}
        t0 = time.perf_counter()
        n = len(entries)
        n_pad = 1 << (n - 1).bit_length()  # pow2-pad: bounded recompiles

        gather_idx = np.full(n_pad, self._scratch, dtype=np.int32)
        out_slots = np.full(n_pad, self._scratch, dtype=np.int32)
        fresh = np.zeros(n_pad, dtype=bool)
        unit = np.zeros((n_pad, self._space.dim), dtype=np.float32)
        rem = np.zeros(n_pad, dtype=np.int32)  # padding rows never train

        for i, (t, src_slot, is_fresh, out_slot, t_rem) in enumerate(entries):
            unit[i] = t.unit
            gather_idx[i] = src_slot
            fresh[i] = is_fresh
            out_slots[i] = out_slot
            rem[i] = t_rem

        key = jax.random.fold_in(
            jax.random.key(self.seed), 9000 + self._step_counter
        )
        self._step_counter += 1
        k_init, k_train = jax.random.split(key)

        # device program: gather -> fresh-overwrite -> masked-train ->
        # eval -> scatter (async dispatches; the score fetch below is
        # the only host sync)
        sub = self._trainer.gather_members(self._pool, jnp.asarray(gather_idx))
        if self.mesh is not None and n_pad % self.mesh.shape["pop"] == 0:
            # the gather's output layout follows XLA's guess; re-place so
            # the group trains sharded over 'pop' (skipped for groups
            # smaller than the axis — they run replicated, which is
            # correct, just not parallel)
            from mpi_opt_tpu.parallel.mesh import shard_popstate

            sub = shard_popstate(sub, self.mesh)
        if fresh[:n].any():  # steady-state resume/inherit batches skip init
            fresh_states = self._trainer.init_population(k_init, self._train_x[:2], n_pad)
            sub = self._trainer.select_members(jnp.asarray(fresh), fresh_states, sub)
        hp = self.workload.make_hparams(self._space.from_unit(jnp.asarray(unit)))
        max_steps = int(rem.max())
        if max_steps > 0:
            sub, _ = self._trainer.train_segment_masked(
                sub, hp, self._train_x, self._train_y, k_train, max_steps,
                jnp.asarray(rem),
            )
        scores = self._trainer.eval_population(
            sub, self._val_x, self._val_y, eval_chunk=self.eval_chunk
        )
        self._pool = self._place_pool(_scatter(self._pool, sub, jnp.asarray(out_slots)))

        # fetch_global: on a process-spanning mesh (config-5 multi-host)
        # eval_population's output is not fully addressable and a plain
        # np.asarray raises
        from mpi_opt_tpu.parallel.mesh import fetch_global

        scores = fetch_global(scores)
        wall = time.perf_counter() - t0
        out: dict[int, TrialResult] = {}
        for i, (t, _, _, _, _) in enumerate(entries):
            s = float(scores[i])
            if np.isfinite(s):
                self._trained[t.trial_id] = t.budget
                out[t.trial_id] = TrialResult(
                    trial_id=t.trial_id,
                    score=s,
                    step=t.budget,
                    wall_time=wall / n,
                )
            else:
                # same per-trial failure contract as the CPU backend: a
                # diverged member (NaN/inf eval) reports as failed, not
                # as an "ok" result whose poison score every consumer
                # must remember to isfinite-gate. The diverged state is
                # EVICTED from the ledger (slot back on the free list),
                # mirroring the CPU stateful path's store-nothing rule:
                # a driver retry then resolves the trial as fresh and
                # retrains from scratch instead of re-evaluating the
                # wreck for zero steps, and a PBT successor can never
                # inherit it
                slot = self._slot_of.pop(t.trial_id, None)
                if slot is not None:
                    self._free.append(slot)
                self._trained.pop(t.trial_id, None)
                out[t.trial_id] = failed_result(
                    t.trial_id,
                    t.budget,
                    f"non-finite score {s!r} (member diverged)",
                    score=s,
                    wall_time=wall / n,
                )
        return out

    def close(self):
        pass

    def reset(self):
        """Per-search state back to construction time, pool buffers kept.

        Every post-reset trial resolves as fresh (the ledger is empty),
        so stale pool contents are unreachable except through the
        scratch slot, which is never read as a real member; resetting
        ``_step_counter`` restores the RNG stream, so a reset backend
        produces BIT-IDENTICAL results to a newly constructed one
        (tested) while keeping the device pool and compiled programs.
        """
        if not self._setup_done:
            return
        self._slot_of.clear()
        self._trained.clear()
        self._free = [s for s in range(self.pool_size) if s != self._scratch]
        self._step_counter = 0

    # -- checkpoint/resume ------------------------------------------------
    #
    # The slot pool is the expensive thing to lose: every live trial's
    # params + momentum. host_state_dict carries the ledger that gives
    # the pool meaning (trial -> slot, steps trained, RNG counter);
    # device_state is the pool pytree itself.

    def host_state_dict(self) -> dict:
        if not self._setup_done:
            return {"setup": False}
        return {
            "setup": True,
            "slot_of": list(self._slot_of.items()),  # preserves LRU order
            "trained": list(self._trained.items()),
            "free": list(self._free),
            "step_counter": self._step_counter,
        }

    def load_host_state_dict(self, state: dict) -> None:
        if not state.get("setup", False):
            return
        self._setup()
        self._slot_of = OrderedDict((int(k), int(v)) for k, v in state["slot_of"])
        self._trained = {int(k): int(v) for k, v in state["trained"]}
        self._free = [int(s) for s in state["free"]]
        self._step_counter = int(state["step_counter"])

    def device_state(self):
        return self._pool if self._setup_done else None

    def load_device_state(self, pool) -> None:
        """Install a restored pool (numpy pytree from orbax) on-device."""
        from mpi_opt_tpu.train import PopState

        self._setup()
        if not isinstance(pool, PopState):
            # orbax round-trips the flax.struct dataclass as a plain dict
            pool = PopState(
                params=pool["params"], momentum=pool["momentum"], step=pool["step"]
            )
        got = jax.tree.structure(pool)
        want = jax.tree.structure(self._pool)
        if got != want:
            raise ValueError(
                f"restored pool structure {got} does not match this "
                f"backend's pool {want} (different workload/population?)"
            )
        # treedefs ignore leaf shapes: a pool checkpointed under a
        # different mesh/pool_size (pool_size rounds to the 'pop' axis)
        # has the same structure but different slot counts — installing
        # it would let the scratch slot collide with a live slot and
        # silently corrupt members on every padded scatter
        got_shapes = [tuple(x.shape) for x in jax.tree.leaves(pool)]
        want_shapes = [tuple(x.shape) for x in jax.tree.leaves(self._pool)]
        if got_shapes != want_shapes:
            raise ValueError(
                "restored pool leaf shapes do not match this backend's "
                f"pool (saved slot count {got_shapes[0][0]}, this backend "
                f"{want_shapes[0][0]} — resumed under a different mesh or "
                "population?)"
            )
        got_dtypes = [x.dtype for x in jax.tree.leaves(pool)]
        want_dtypes = [x.dtype for x in jax.tree.leaves(self._pool)]
        if got_dtypes != want_dtypes:
            raise ValueError(
                "restored pool leaf dtypes do not match this backend's pool "
                "(saved under a different momentum storage dtype? see "
                "MPI_OPT_TPU_MOMENTUM_DTYPE) — refusing rather than feeding "
                "mismatched state into the compiled programs"
            )
        # free the freshly-initialized pool BEFORE uploading the restored
        # one: a ResNet-scale pool cannot afford 2x residency
        self._pool = None
        self._pool = self._place_pool(jax.tree.map(jnp.asarray, pool))


@functools.partial(jax.jit, donate_argnames=("pool",))
def _scatter(pool, sub, slots):
    """Write member states back into their pool slots.

    Padding entries all target the scratch slot; duplicate-index writes
    there are benign (scratch content is never read as a real member).
    The old pool is donated: a scatter-update aliases in place, so the
    slot pool costs 1x its size in HBM instead of 2x at update time.
    """
    return jax.tree.map(lambda p, s: p.at[slots].set(s), pool, sub)
