"""Default CPU backend: process-parallel trial evaluation.

Reference parity (SURVEY.md §1-§3; reference unreadable): the
reference's default path evaluates trials on MPI ranks — a Coordinator
sends hyperparameters to MPIWorker processes, which train and report a
score. This container has no MPI, so rank-parallelism is rebuilt on
``multiprocessing`` (same process-per-trial execution model, same
role as the 8-rank MPI baseline in BASELINE.json's north star — and the
measured baseline that bench.py compares the TPU backend against).

Two paths:
- stateless (random/TPE/ASHA from-scratch): trials fan out to a process
  pool; the workload is reconstructed in each worker by registry name so
  nothing unpicklable crosses the fork.
- stateful (PBT inheritance / ASHA warm resume): training states must
  persist between evaluations. By default they live in the parent and
  training runs in-process — correct but sequential, and structurally
  UNINTERRUPTIBLE (no ``trial_timeout`` can reap an in-parent hang).
  ``isolate_stateful=True`` moves the whole stateful path (state store
  included) into ONE dedicated spawned worker process: same sequential
  semantics, same inheritance behavior, but the process boundary makes
  the deadline enforceable — a hung trial is reaped as status=timeout
  and the worker killed + respawned (its state store resets, so
  successors inheriting from lost trials retrain from scratch — the
  same fallback as inheriting from an unknown id).
"""

from __future__ import annotations

import math
import multiprocessing as mp
import os
import time
from collections import OrderedDict
from typing import Any, Sequence

from mpi_opt_tpu.backends.base import Backend, register_backend
from mpi_opt_tpu.trial import Trial, TrialResult, failed_result
from mpi_opt_tpu.workloads.base import Workload

_WORKER_WORKLOAD: Workload | None = None


def _init_worker(workload_name: str, workload_kwargs: dict):
    global _WORKER_WORKLOAD
    from mpi_opt_tpu.workloads import get_workload

    _WORKER_WORKLOAD = get_workload(workload_name, **workload_kwargs)


def _init_pool_worker(workload_name: str, workload_kwargs: dict):
    """Pool-process initializer (never runs in the parent).

    CPU workers must never grab the TPU: the parent may hold it, and N
    spawned children racing to initialize the TPU platform would hang.
    The env var alone is not enough (a site plugin may pin
    JAX_PLATFORMS), so also force the platform through jax.config.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax
    except ImportError:
        jax = None  # workload may not need jax at all
    if jax is not None:
        # No blanket swallow: if the pin fails (backend already up in
        # this child), continuing would let N workers race the real TPU
        # and hang — fail loudly instead.
        jax.config.update("jax_platforms", "cpu")
        # Persistent compile cache: XLA:CPU takes minutes-to-tens-of-
        # minutes to compile conv training programs (measured: >12 min
        # for the 100-step SmallCNN segment on this container), and a
        # fresh pool otherwise pays that on every process start. The
        # dir is platform-specific on purpose — mixing CPU and TPU
        # artifacts in one cache trips machine-feature mismatches.
        cache = os.environ.get("MPI_OPT_TPU_CPU_CACHE_DIR", "/tmp/jax_cache_cpu")
        if cache:  # set env var to "" to disable
            jax.config.update("jax_compilation_cache_dir", cache)
    _init_worker(workload_name, workload_kwargs)


def _eval_one(args):
    """Evaluate one job, NEVER letting a trial's exception escape the
    worker: a raising trial poisons pool.map's whole batch (every other
    job's result is discarded with it), so the failure is materialized
    as a failed TrialResult right where it happens. Non-finite scores
    are mapped onto the same contract — the host driver path's
    equivalent of the fused sweeps' isfinite masking."""
    trial_id, params, budget, seed = args
    t0 = time.perf_counter()
    try:
        score = float(_WORKER_WORKLOAD.evaluate(params, budget, seed))
    except Exception as e:
        return failed_result(
            trial_id,
            budget,
            f"{type(e).__name__}: {e}",
            wall_time=time.perf_counter() - t0,
        )
    if not math.isfinite(score):
        return failed_result(
            trial_id,
            budget,
            f"non-finite score {score!r}",
            score=score,
            wall_time=time.perf_counter() - t0,
        )
    return TrialResult(
        trial_id=trial_id,
        score=score,
        step=budget,
        wall_time=time.perf_counter() - t0,
    )


def _stateful_eval(
    workload: Workload,
    states: "OrderedDict[int, Any]",
    trained: dict,
    max_states: int,
    trial_id: int,
    raw_params: dict,
    budget: int,
    seed: int,
) -> TrialResult:
    """One stateful evaluation against a (states, trained) store — the
    SINGLE implementation behind both the in-parent path and the
    ``isolate_stateful`` worker process, so warm-resume/inheritance
    semantics cannot drift between them."""
    t0 = time.perf_counter()
    params = _clean(raw_params)
    src = raw_params.get("__inherit_from__")
    if src is not None and src in states:
        state = states[src]
        done = trained.get(src, 0)
    elif trial_id in states:
        state = states[trial_id]
        done = trained[trial_id]
    else:
        state = workload.init_state(params, seed)
        done = 0
    remaining = max(0, budget - done)
    try:
        state, score = workload.train(state, params, remaining, seed)
    except Exception as e:
        # the failed member's state is NOT stored: a PBT successor
        # inheriting from it would resume a half-trained wreck
        return failed_result(
            trial_id,
            budget,
            f"{type(e).__name__}: {e}",
            wall_time=time.perf_counter() - t0,
        )
    if not math.isfinite(float(score)):
        return failed_result(
            trial_id,
            budget,
            f"non-finite score {float(score)!r}",
            score=float(score),
            wall_time=time.perf_counter() - t0,
        )
    states[trial_id] = state
    states.move_to_end(trial_id)
    trained[trial_id] = budget
    while len(states) > max_states:
        old, _ = states.popitem(last=False)
        trained.pop(old, None)
    return TrialResult(
        trial_id=trial_id,
        score=float(score),
        step=budget,
        wall_time=time.perf_counter() - t0,
    )


def _stateful_worker_main(conn, workload_name, workload_kwargs, seed, max_states):
    """Entry point of the ``isolate_stateful`` worker (spawned child).

    Owns the (states, trained) store for its lifetime; jobs arrive as
    ``(trial_id, raw_params, budget)`` tuples and leave as TrialResults.
    ``"reset"`` clears the store (Backend.reset), ``None`` exits. The
    initial ``("ready", pid)`` handshake lets the parent exclude child
    cold-start (spawn + jax import + platform pin) from any trial's
    deadline."""
    try:
        _init_pool_worker(workload_name, workload_kwargs)
    # sweeplint: disable=drain-swallow -- spawned worker: no drain protocol here; init failure is reported to the parent over the pipe and the worker exits
    except BaseException as e:
        try:
            conn.send(("init_failed", f"{type(e).__name__}: {e}"))
        finally:
            return
    states: "OrderedDict[int, Any]" = OrderedDict()
    trained: dict = {}
    conn.send(("ready", os.getpid()))
    while True:
        try:
            msg = conn.recv()
        except EOFError:
            return
        if msg is None:
            return
        if msg == "reset":
            states.clear()
            trained.clear()
            conn.send("reset_ok")
            continue
        trial_id, raw_params, budget = msg
        conn.send(
            _stateful_eval(
                _WORKER_WORKLOAD, states, trained, max_states,
                trial_id, raw_params, budget, seed,
            )
        )


@register_backend
class CPUBackend(Backend):
    name = "cpu"

    def __init__(
        self,
        workload: Workload,
        n_workers: int = 0,  # 0 -> os.cpu_count()
        seed: int = 0,
        workload_kwargs: dict | None = None,
        max_states: int = 256,
        trial_timeout: float | None = None,  # seconds per trial, None = unbounded
        isolate_stateful: bool = False,  # stateful path in a spawned worker
    ):
        super().__init__(workload)
        self.n_workers = n_workers or (os.cpu_count() or 1)
        self.seed = seed
        if trial_timeout is not None and trial_timeout <= 0:
            raise ValueError(f"trial_timeout must be > 0, got {trial_timeout}")
        self.trial_timeout = trial_timeout
        self.isolate_stateful = bool(isolate_stateful)
        self._workload_kwargs = workload_kwargs or {}
        self._pool = None
        self._stateful_proc = None
        self._stateful_conn = None
        self._warned_stateful_platform = False
        self._warned_stateful_timeout = False
        # trial_id -> training state, FIFO-bounded: PBT mints fresh trial
        # ids every generation and would otherwise accumulate every
        # generation's model states until OOM (inheritance only ever
        # reaches one generation back; ASHA resumes are also recent)
        self.max_states = max_states
        self._states: "OrderedDict[int, Any]" = OrderedDict()
        self._trained: dict[int, int] = {}  # trial_id -> steps completed

    @property
    def capacity(self) -> int:
        return self.n_workers

    def _get_pool(self):
        if self._pool is None:
            # spawn, not fork: the parent has live JAX threads and forking
            # a multithreaded process risks deadlock in the children
            ctx = mp.get_context("spawn")
            self._pool = ctx.Pool(
                self.n_workers,
                initializer=_init_pool_worker,
                initargs=(self.workload.name, self._workload_kwargs),
            )
        return self._pool

    def evaluate(self, trials: Sequence[Trial]) -> list[TrialResult]:
        if self.workload.stateful:
            if self.isolate_stateful:
                # the state store lives in a dedicated spawned worker:
                # same sequential semantics as in-parent, but the
                # process boundary makes trial_timeout enforceable
                return [self._evaluate_stateful_isolated(t) for t in trials]
            # stateful path: warm resumes + PBT inheritance need the
            # state store, which lives in this process
            if self.trial_timeout is not None and not self._warned_stateful_timeout:
                # in-parent execution cannot be interrupted, so the
                # deadline the user asked for is unenforceable here —
                # say so instead of silently pretending it's active
                self._warned_stateful_timeout = True
                import warnings

                warnings.warn(
                    "cpu backend: trial_timeout cannot be enforced for "
                    "stateful workloads evaluating in-parent (an "
                    "in-process call can't be interrupted) — exceptions "
                    "and non-finite scores are still caught, hangs are "
                    "not reaped. Pass isolate_stateful=True "
                    "(--isolate-stateful) to run the stateful path in a "
                    "killable worker process",
                    stacklevel=3,
                )
            return [self._evaluate_stateful(t) for t in trials]
        jobs = [
            (t.trial_id, _clean(t.params), t.budget, self.seed) for t in trials
        ]
        # a timeout can only be enforced across a process boundary (a
        # hung in-parent call can't be interrupted), so it forces the
        # pool path even for single-trial batches
        if (
            self.trial_timeout is None
            and (self.n_workers == 1 or len(jobs) == 1)
            and self._inline_ok()
        ):
            self._ensure_inline_worker()
            return [_eval_one(j) for j in jobs]
        return self._evaluate_pool(jobs)

    def _evaluate_pool(self, jobs) -> list[TrialResult]:
        """Per-job async dispatch: one trial raising (caught in-worker)
        never takes the rest of the batch with it — pool.map would
        discard every result on the first exception. Hangs and hard
        worker crashes are additionally reaped, but ONLY under a
        configured ``trial_timeout``: a crashed worker's job simply
        never completes (mp.Pool repopulates workers without completing
        lost jobs), so without a deadline its ``get`` blocks forever —
        same exposure as before this layer, and the reason --trial-
        timeout is the recommended production setting."""
        pool = self._get_pool()
        t0 = time.monotonic()
        asyncs = [pool.apply_async(_eval_one, (j,)) for j in jobs]
        out: list[TrialResult] = []
        broken = False
        for i, (job, a) in enumerate(zip(jobs, asyncs)):
            if self.trial_timeout is None:
                wait = None
            else:
                # job i starts no later than wave i // n_workers, so its
                # deadline is (wave+1) whole timeouts from batch start
                # (plus dispatch grace): a job queued behind a hung
                # worker still gets its own full window, while the whole
                # batch is bounded by ~timeout * n_jobs / n_workers
                allowance = self.trial_timeout * (i // self.n_workers + 1) + 1.0
                wait = max(0.05, t0 + allowance - time.monotonic())
            try:
                out.append(a.get(wait))
            except mp.TimeoutError:
                broken = True
                out.append(
                    failed_result(
                        job[0],
                        job[2],
                        f"no result within {self.trial_timeout}s "
                        "(trial hung, or its worker crashed)",
                        status="timeout",
                        wall_time=time.monotonic() - t0,
                    )
                )
            except Exception as e:
                # pool-level failure (worker killed hard enough that the
                # result machinery raised instead of hanging)
                broken = True
                out.append(
                    failed_result(
                        job[0],
                        job[2],
                        f"worker failure: {type(e).__name__}: {e}",
                        wall_time=time.monotonic() - t0,
                    )
                )
        if broken:
            # a reaped job's worker is still wedged (or gone): recycle
            # the whole pool so the next batch starts with clean workers
            self._rebuild_pool()
        return out

    def _rebuild_pool(self):
        if self._pool is not None:
            self._pool.terminate()
            self._pool.join()
            self._pool = None

    def _inline_ok(self) -> bool:
        """Inline (in-parent) evaluation is only allowed when the parent
        is a CPU-platform process: a single-trial batch under
        ``--backend cpu`` must never train on the TPU just because the
        parent process defaults to it. Otherwise route through the
        pinned pool. Side-effect free: never initializes a JAX backend
        just to ask which one is default (that would acquire the very
        accelerator this guard exists to avoid touching)."""
        try:
            import jax
        except ImportError:
            return True
        try:
            from jax._src import xla_bridge

            if xla_bridge.backends_are_initialized():
                return jax.default_backend() == "cpu"
            # uninitialized: the first entry of jax_platforms is the
            # platform the parent WOULD initialize; only a cpu-first pin
            # is safe
            platforms = (jax.config.jax_platforms or "").split(",")
            return platforms[0] == "cpu"
        except Exception:
            # private-API probe (no stability guarantee): if it breaks,
            # conservatively route through the pinned pool
            return False

    def _ensure_inline_worker(self):
        """Install the parent-side workload once and reuse it across
        evaluate() calls (a fresh instance per call would discard
        PopulationWorkload's _eval_cache: recompile + dataset
        regeneration every batch)."""
        global _WORKER_WORKLOAD
        _WORKER_WORKLOAD = self.workload

    def _evaluate_stateful(self, t: Trial) -> TrialResult:
        # stateful training is inherently in-parent (the state store
        # lives here); on a TPU-default parent that means the "cpu"
        # backend actually trains on the accelerator — surface it rather
        # than silently violating the placement the user asked for
        if not self._warned_stateful_platform and not self._inline_ok():
            self._warned_stateful_platform = True
            import warnings

            warnings.warn(
                "cpu backend: stateful workload trains in the parent process, "
                "whose JAX platform is not cpu — use --backend tpu for "
                "on-device population training, or pin the parent to cpu",
                stacklevel=3,
            )
        return _stateful_eval(
            self.workload, self._states, self._trained, self.max_states,
            t.trial_id, t.params, t.budget, self.seed,
        )

    # -- process-isolated stateful evaluation (--isolate-stateful) ---------

    def _ensure_stateful_worker(self) -> None:
        """Spawn (or respawn) the dedicated stateful worker and wait for
        its readiness handshake, so child cold-start (spawn + jax import
        + platform pin, seconds of wall) is never billed to a trial's
        deadline."""
        if self._stateful_proc is not None and self._stateful_proc.is_alive():
            return
        self._kill_stateful_worker()
        ctx = mp.get_context("spawn")
        parent, child = ctx.Pipe()
        proc = ctx.Process(
            target=_stateful_worker_main,
            args=(
                child,
                self.workload.name,
                self._workload_kwargs,
                self.seed,
                self.max_states,
            ),
            daemon=True,
        )
        proc.start()
        child.close()
        self._stateful_proc, self._stateful_conn = proc, parent
        # generous fixed window: this is process bring-up, not a trial
        if not parent.poll(120.0):
            self._kill_stateful_worker()
            raise RuntimeError("stateful worker did not come up within 120s")
        try:
            msg = parent.recv()
        except (EOFError, OSError) as e:
            self._kill_stateful_worker()
            raise RuntimeError(
                f"stateful worker died during startup ({type(e).__name__})"
            ) from None
        if not (isinstance(msg, tuple) and msg[0] == "ready"):
            self._kill_stateful_worker()
            raise RuntimeError(f"stateful worker failed to initialize: {msg!r}")

    def _kill_stateful_worker(self) -> None:
        if self._stateful_proc is None:
            return
        proc, conn = self._stateful_proc, self._stateful_conn
        self._stateful_proc = self._stateful_conn = None
        if proc.is_alive():
            proc.terminate()
            proc.join(timeout=2.0)
            if proc.is_alive():  # ignored the TERM: it is truly wedged
                proc.kill()
        proc.join()
        if conn is not None:
            conn.close()

    def _evaluate_stateful_isolated(self, t: Trial) -> TrialResult:
        self._ensure_stateful_worker()
        t0 = time.monotonic()
        try:
            self._stateful_conn.send((t.trial_id, t.params, t.budget))
        except (BrokenPipeError, OSError):
            # worker died between trials: one respawn, then evaluate
            self._kill_stateful_worker()
            self._ensure_stateful_worker()
            self._stateful_conn.send((t.trial_id, t.params, t.budget))
        if self._stateful_conn.poll(self.trial_timeout):
            try:
                return self._stateful_conn.recv()
            except (EOFError, OSError):
                # the worker died MID-trial (segfault/OOM-kill/os._exit):
                # no result will ever arrive, and the state store died
                # with it — successors inheriting lost states retrain
                # from scratch (the standard unknown-id fallback)
                self._kill_stateful_worker()
                return failed_result(
                    t.trial_id,
                    t.budget,
                    "stateful worker died mid-trial (state store reset; "
                    "inheritors retrain from scratch)",
                    wall_time=time.monotonic() - t0,
                )
        # deadline passed with the worker alive: the trial hung — the
        # reap the in-parent path structurally cannot do (ROADMAP open
        # item closed by process isolation)
        self._kill_stateful_worker()
        return failed_result(
            t.trial_id,
            t.budget,
            f"no result within {self.trial_timeout}s (stateful trial "
            "hung; worker killed, state store reset)",
            status="timeout",
            wall_time=time.monotonic() - t0,
        )

    def reset(self):
        """Drop the stateful-path state store (see Backend.reset): a new
        search's trial ids must not warm-resume the previous search's
        states. The worker pool (process spawns) is kept — and so is the
        isolated stateful worker, whose store is cleared via message
        (falling back to a kill if it doesn't answer)."""
        self._states.clear()
        self._trained.clear()
        if self._stateful_proc is not None and self._stateful_proc.is_alive():
            try:
                self._stateful_conn.send("reset")
                if self._stateful_conn.poll(10.0) and self._stateful_conn.recv() == "reset_ok":
                    return
            except (BrokenPipeError, EOFError, OSError):
                pass
            self._kill_stateful_worker()

    def close(self):
        if self._pool is not None:
            self._pool.terminate()
            self._pool.join()
            self._pool = None
        if self._stateful_proc is not None:
            try:
                self._stateful_conn.send(None)  # clean exit request
                self._stateful_proc.join(timeout=2.0)
            except (BrokenPipeError, OSError):
                pass
            self._kill_stateful_worker()


def _clean(params: dict) -> dict:
    """Strip framework-internal keys before handing params to workloads."""
    return {k: v for k, v in params.items() if not k.startswith("__")}
