"""Default CPU backend: process-parallel trial evaluation.

Reference parity (SURVEY.md §1-§3; reference unreadable): the
reference's default path evaluates trials on MPI ranks — a Coordinator
sends hyperparameters to MPIWorker processes, which train and report a
score. This container has no MPI, so rank-parallelism is rebuilt on
``multiprocessing`` (same process-per-trial execution model, same
role as the 8-rank MPI baseline in BASELINE.json's north star — and the
measured baseline that bench.py compares the TPU backend against).

Two paths:
- stateless (random/TPE/ASHA from-scratch): trials fan out to a process
  pool; the workload is reconstructed in each worker by registry name so
  nothing unpicklable crosses the fork.
- stateful (PBT inheritance / ASHA warm resume): states are kept in the
  parent and training runs in-process — correct but sequential;
  the TPU population backend is the fast path for these.
"""

from __future__ import annotations

import math
import multiprocessing as mp
import os
import time
from collections import OrderedDict
from typing import Any, Sequence

from mpi_opt_tpu.backends.base import Backend, register_backend
from mpi_opt_tpu.trial import Trial, TrialResult, failed_result
from mpi_opt_tpu.workloads.base import Workload

_WORKER_WORKLOAD: Workload | None = None


def _init_worker(workload_name: str, workload_kwargs: dict):
    global _WORKER_WORKLOAD
    from mpi_opt_tpu.workloads import get_workload

    _WORKER_WORKLOAD = get_workload(workload_name, **workload_kwargs)


def _init_pool_worker(workload_name: str, workload_kwargs: dict):
    """Pool-process initializer (never runs in the parent).

    CPU workers must never grab the TPU: the parent may hold it, and N
    spawned children racing to initialize the TPU platform would hang.
    The env var alone is not enough (a site plugin may pin
    JAX_PLATFORMS), so also force the platform through jax.config.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax
    except ImportError:
        jax = None  # workload may not need jax at all
    if jax is not None:
        # No blanket swallow: if the pin fails (backend already up in
        # this child), continuing would let N workers race the real TPU
        # and hang — fail loudly instead.
        jax.config.update("jax_platforms", "cpu")
        # Persistent compile cache: XLA:CPU takes minutes-to-tens-of-
        # minutes to compile conv training programs (measured: >12 min
        # for the 100-step SmallCNN segment on this container), and a
        # fresh pool otherwise pays that on every process start. The
        # dir is platform-specific on purpose — mixing CPU and TPU
        # artifacts in one cache trips machine-feature mismatches.
        cache = os.environ.get("MPI_OPT_TPU_CPU_CACHE_DIR", "/tmp/jax_cache_cpu")
        if cache:  # set env var to "" to disable
            jax.config.update("jax_compilation_cache_dir", cache)
    _init_worker(workload_name, workload_kwargs)


def _eval_one(args):
    """Evaluate one job, NEVER letting a trial's exception escape the
    worker: a raising trial poisons pool.map's whole batch (every other
    job's result is discarded with it), so the failure is materialized
    as a failed TrialResult right where it happens. Non-finite scores
    are mapped onto the same contract — the host driver path's
    equivalent of the fused sweeps' isfinite masking."""
    trial_id, params, budget, seed = args
    t0 = time.perf_counter()
    try:
        score = float(_WORKER_WORKLOAD.evaluate(params, budget, seed))
    except Exception as e:
        return failed_result(
            trial_id,
            budget,
            f"{type(e).__name__}: {e}",
            wall_time=time.perf_counter() - t0,
        )
    if not math.isfinite(score):
        return failed_result(
            trial_id,
            budget,
            f"non-finite score {score!r}",
            score=score,
            wall_time=time.perf_counter() - t0,
        )
    return TrialResult(
        trial_id=trial_id,
        score=score,
        step=budget,
        wall_time=time.perf_counter() - t0,
    )


@register_backend
class CPUBackend(Backend):
    name = "cpu"

    def __init__(
        self,
        workload: Workload,
        n_workers: int = 0,  # 0 -> os.cpu_count()
        seed: int = 0,
        workload_kwargs: dict | None = None,
        max_states: int = 256,
        trial_timeout: float | None = None,  # seconds per trial, None = unbounded
    ):
        super().__init__(workload)
        self.n_workers = n_workers or (os.cpu_count() or 1)
        self.seed = seed
        if trial_timeout is not None and trial_timeout <= 0:
            raise ValueError(f"trial_timeout must be > 0, got {trial_timeout}")
        self.trial_timeout = trial_timeout
        self._workload_kwargs = workload_kwargs or {}
        self._pool = None
        self._warned_stateful_platform = False
        self._warned_stateful_timeout = False
        # trial_id -> training state, FIFO-bounded: PBT mints fresh trial
        # ids every generation and would otherwise accumulate every
        # generation's model states until OOM (inheritance only ever
        # reaches one generation back; ASHA resumes are also recent)
        self.max_states = max_states
        self._states: "OrderedDict[int, Any]" = OrderedDict()
        self._trained: dict[int, int] = {}  # trial_id -> steps completed

    @property
    def capacity(self) -> int:
        return self.n_workers

    def _get_pool(self):
        if self._pool is None:
            # spawn, not fork: the parent has live JAX threads and forking
            # a multithreaded process risks deadlock in the children
            ctx = mp.get_context("spawn")
            self._pool = ctx.Pool(
                self.n_workers,
                initializer=_init_pool_worker,
                initargs=(self.workload.name, self._workload_kwargs),
            )
        return self._pool

    def evaluate(self, trials: Sequence[Trial]) -> list[TrialResult]:
        if self.workload.stateful:
            # stateful path: warm resumes + PBT inheritance need the
            # state store, which lives in this process
            if self.trial_timeout is not None and not self._warned_stateful_timeout:
                # in-parent execution cannot be interrupted, so the
                # deadline the user asked for is unenforceable here —
                # say so instead of silently pretending it's active
                self._warned_stateful_timeout = True
                import warnings

                warnings.warn(
                    "cpu backend: trial_timeout cannot be enforced for "
                    "stateful workloads (they evaluate in-parent, and an "
                    "in-process call can't be interrupted) — exceptions "
                    "and non-finite scores are still caught, hangs are "
                    "not reaped",
                    stacklevel=3,
                )
            return [self._evaluate_stateful(t) for t in trials]
        jobs = [
            (t.trial_id, _clean(t.params), t.budget, self.seed) for t in trials
        ]
        # a timeout can only be enforced across a process boundary (a
        # hung in-parent call can't be interrupted), so it forces the
        # pool path even for single-trial batches
        if (
            self.trial_timeout is None
            and (self.n_workers == 1 or len(jobs) == 1)
            and self._inline_ok()
        ):
            self._ensure_inline_worker()
            return [_eval_one(j) for j in jobs]
        return self._evaluate_pool(jobs)

    def _evaluate_pool(self, jobs) -> list[TrialResult]:
        """Per-job async dispatch: one trial raising (caught in-worker)
        never takes the rest of the batch with it — pool.map would
        discard every result on the first exception. Hangs and hard
        worker crashes are additionally reaped, but ONLY under a
        configured ``trial_timeout``: a crashed worker's job simply
        never completes (mp.Pool repopulates workers without completing
        lost jobs), so without a deadline its ``get`` blocks forever —
        same exposure as before this layer, and the reason --trial-
        timeout is the recommended production setting."""
        pool = self._get_pool()
        t0 = time.monotonic()
        asyncs = [pool.apply_async(_eval_one, (j,)) for j in jobs]
        out: list[TrialResult] = []
        broken = False
        for i, (job, a) in enumerate(zip(jobs, asyncs)):
            if self.trial_timeout is None:
                wait = None
            else:
                # job i starts no later than wave i // n_workers, so its
                # deadline is (wave+1) whole timeouts from batch start
                # (plus dispatch grace): a job queued behind a hung
                # worker still gets its own full window, while the whole
                # batch is bounded by ~timeout * n_jobs / n_workers
                allowance = self.trial_timeout * (i // self.n_workers + 1) + 1.0
                wait = max(0.05, t0 + allowance - time.monotonic())
            try:
                out.append(a.get(wait))
            except mp.TimeoutError:
                broken = True
                out.append(
                    failed_result(
                        job[0],
                        job[2],
                        f"no result within {self.trial_timeout}s "
                        "(trial hung, or its worker crashed)",
                        status="timeout",
                        wall_time=time.monotonic() - t0,
                    )
                )
            except Exception as e:
                # pool-level failure (worker killed hard enough that the
                # result machinery raised instead of hanging)
                broken = True
                out.append(
                    failed_result(
                        job[0],
                        job[2],
                        f"worker failure: {type(e).__name__}: {e}",
                        wall_time=time.monotonic() - t0,
                    )
                )
        if broken:
            # a reaped job's worker is still wedged (or gone): recycle
            # the whole pool so the next batch starts with clean workers
            self._rebuild_pool()
        return out

    def _rebuild_pool(self):
        if self._pool is not None:
            self._pool.terminate()
            self._pool.join()
            self._pool = None

    def _inline_ok(self) -> bool:
        """Inline (in-parent) evaluation is only allowed when the parent
        is a CPU-platform process: a single-trial batch under
        ``--backend cpu`` must never train on the TPU just because the
        parent process defaults to it. Otherwise route through the
        pinned pool. Side-effect free: never initializes a JAX backend
        just to ask which one is default (that would acquire the very
        accelerator this guard exists to avoid touching)."""
        try:
            import jax
        except ImportError:
            return True
        try:
            from jax._src import xla_bridge

            if xla_bridge.backends_are_initialized():
                return jax.default_backend() == "cpu"
            # uninitialized: the first entry of jax_platforms is the
            # platform the parent WOULD initialize; only a cpu-first pin
            # is safe
            platforms = (jax.config.jax_platforms or "").split(",")
            return platforms[0] == "cpu"
        except Exception:
            # private-API probe (no stability guarantee): if it breaks,
            # conservatively route through the pinned pool
            return False

    def _ensure_inline_worker(self):
        """Install the parent-side workload once and reuse it across
        evaluate() calls (a fresh instance per call would discard
        PopulationWorkload's _eval_cache: recompile + dataset
        regeneration every batch)."""
        global _WORKER_WORKLOAD
        _WORKER_WORKLOAD = self.workload

    def _evaluate_stateful(self, t: Trial) -> TrialResult:
        # stateful training is inherently in-parent (the state store
        # lives here); on a TPU-default parent that means the "cpu"
        # backend actually trains on the accelerator — surface it rather
        # than silently violating the placement the user asked for
        if not self._warned_stateful_platform and not self._inline_ok():
            self._warned_stateful_platform = True
            import warnings

            warnings.warn(
                "cpu backend: stateful workload trains in the parent process, "
                "whose JAX platform is not cpu — use --backend tpu for "
                "on-device population training, or pin the parent to cpu",
                stacklevel=3,
            )
        t0 = time.perf_counter()
        params = _clean(t.params)
        src = t.params.get("__inherit_from__")
        if src is not None and src in self._states:
            state = self._states[src]
            done = self._trained.get(src, 0)
        elif t.trial_id in self._states:
            state = self._states[t.trial_id]
            done = self._trained[t.trial_id]
        else:
            state = self.workload.init_state(params, self.seed)
            done = 0
        remaining = max(0, t.budget - done)
        try:
            state, score = self.workload.train(state, params, remaining, self.seed)
        except Exception as e:
            # the failed member's state is NOT stored: a PBT successor
            # inheriting from it would resume a half-trained wreck. No
            # timeout is possible here (in-parent execution can't be
            # interrupted) — that's the documented stateful-path limit.
            return failed_result(
                t.trial_id,
                t.budget,
                f"{type(e).__name__}: {e}",
                wall_time=time.perf_counter() - t0,
            )
        if not math.isfinite(float(score)):
            return failed_result(
                t.trial_id,
                t.budget,
                f"non-finite score {float(score)!r}",
                score=float(score),
                wall_time=time.perf_counter() - t0,
            )
        self._states[t.trial_id] = state
        self._states.move_to_end(t.trial_id)
        self._trained[t.trial_id] = t.budget
        while len(self._states) > self.max_states:
            old, _ = self._states.popitem(last=False)
            self._trained.pop(old, None)
        return TrialResult(
            trial_id=t.trial_id,
            score=float(score),
            step=t.budget,
            wall_time=time.perf_counter() - t0,
        )

    def reset(self):
        """Drop the stateful-path state store (see Backend.reset): a new
        search's trial ids must not warm-resume the previous search's
        states. The worker pool (process spawns) is kept."""
        self._states.clear()
        self._trained.clear()

    def close(self):
        if self._pool is not None:
            self._pool.terminate()
            self._pool.join()
            self._pool = None


def _clean(params: dict) -> dict:
    """Strip framework-internal keys before handing params to workloads."""
    return {k: v for k, v in params.items() if not k.startswith("__")}
