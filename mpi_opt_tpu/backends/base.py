"""Execution-backend plugin layer (SURVEY.md §2 rows 1, 7-9).

Reference contract (from BASELINE.json north_star): a ``backend=``
plugin hook through which the search driver evaluates suggested trials;
the CPU path is the default, TPU opt-in via ``--backend=tpu``.

A backend owns the mapping from host-side Trial records to actual
training work. ``capacity`` tells the driver how many trials to request
per batch — the TPU backend reports its whole population size so the
driver naturally feeds it device-shaped batches.
"""

from __future__ import annotations

import abc
from typing import Sequence

from mpi_opt_tpu.trial import Trial, TrialResult
from mpi_opt_tpu.workloads.base import Workload

_BACKENDS: dict[str, type] = {}


def register_backend(cls):
    _BACKENDS[cls.name] = cls
    return cls


def get_backend(name: str, workload: Workload, **kwargs) -> "Backend":
    try:
        cls = _BACKENDS[name]
    except KeyError:
        raise ValueError(f"unknown backend {name!r}; available: {sorted(_BACKENDS)}") from None
    return cls(workload, **kwargs)


def available_backends() -> list[str]:
    return sorted(_BACKENDS)


class Backend(abc.ABC):
    name: str = "base"

    def __init__(self, workload: Workload):
        self.workload = workload

    @property
    @abc.abstractmethod
    def capacity(self) -> int:
        """Preferred evaluation batch size."""

    @abc.abstractmethod
    def evaluate(self, trials: Sequence[Trial]) -> list[TrialResult]:
        """Run each trial to its budget; return scores.

        Trials may carry ``params['__inherit_from__']`` (PBT weight
        inheritance) and cumulative budgets (ASHA promotions); stateful
        backends honor both, stateless backends retrain from scratch.

        Failure contract: one trial failing must not poison the batch —
        a raising/hanging/diverging trial comes back as a non-ok
        TrialResult (``status`` failed/timeout, NaN-family score,
        ``error`` set; see trial.failed_result), never as a raised
        exception, so the driver's FailurePolicy can retry or report it
        while the rest of the batch's results stand.
        """

    def close(self) -> None:
        pass

    def reset(self) -> None:
        """Forget all per-search state, making the backend equivalent to a
        freshly constructed one (minus re-paying device setup/compiles).

        A backend serves ONE search at a time: trial ids are allocated
        per-algorithm starting at 0, so running a second search against a
        used backend makes the new ids collide with the old ledger — a
        stateful backend would silently treat fresh trials as warm
        resumes of the previous search's state. Call ``reset()`` between
        independent searches that share a backend (e.g. a warmup search
        before a timed one). Stateless backends need nothing.
        """

    # -- checkpoint/resume (utils/checkpoint.py) -------------------------
    #
    # Backends without device-resident state use the defaults: losing a
    # worker's in-progress training is always CORRECT here because
    # budgets are cumulative — a resumed trial whose state is gone
    # retrains from scratch to its budget (slower, never wrong).

    def host_state_dict(self) -> dict:
        """JSON-able host-side state (ledgers, counters)."""
        return {}

    def load_host_state_dict(self, state: dict) -> None:
        pass

    def device_state(self):
        """Device-resident pytree worth persisting (None if stateless)."""
        return None

    def load_device_state(self, pool) -> None:
        raise NotImplementedError(f"{self.name} backend has no device state")
