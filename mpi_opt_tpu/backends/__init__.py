"""Backend registry — ``backend=`` plugin hook (BASELINE.json north_star)."""

from mpi_opt_tpu.backends.base import (
    Backend,
    available_backends,
    get_backend,
    register_backend,
)

# registration side effects
from mpi_opt_tpu.backends import cpu  # noqa: E402,F401

# The TPU backend imports lazily from get_backend to keep CPU-only usage
# light; importing mpi_opt_tpu.backends.tpu pulls in flax.


def _register_lazy():
    try:
        from mpi_opt_tpu.backends import tpu  # noqa: F401
    except ImportError:
        pass


_register_lazy()

__all__ = ["Backend", "get_backend", "register_backend", "available_backends"]
