"""Feed a prior sweep's ledger into a new algorithm as observations.

A finished (or even half-finished) sweep's journal is evidence about
the objective surface; a NEW sweep over the SAME space should not start
blind. ``warm_start`` converts a ledger's ok records into
``Observation``s — unit-cube rows via the space's canonical params
round trip — and hands them to ``Algorithm.ingest_observations``: TPE
and BOHB build surrogate priors, random/ASHA seed their first
suggestions with the prior best (see each algorithm's override).

Warm start is CROSS-MODE: fused member records (ledger/fused.py) carry
the same canonical params / score / step fields as driver trial
records, so a fused sweep's ledger seeds a driver TPE/BOHB search and
a driver ledger seeds a fused one (the fused drivers pre-fill their
observation buffers / seed their cohorts — see each driver's
``warm_obs``). The ONLY compatibility gate is the space hash; the mode
that produced the observations is irrelevant to their evidence value.

Space compatibility is checked by HASH, not by hope: a ledger written
for a different space would decode its params into the wrong unit
coordinates and silently poison the new search, so a mismatch raises.
WITHIN a hash-matched ledger, individual records that cannot inform
the search — non-ok status, a missing score, a Choice value no live
option canonicalizes to — are SKIPPED and COUNTED (``skips``), not
silently dropped and not fatal: one bit-rotted record must not refuse
the other thousand, but the ``warm_start`` event must say how many
records the prior lost on the way in (ISSUE 14 satellite).
"""

from __future__ import annotations

from mpi_opt_tpu.algorithms.base import Algorithm, Observation
from mpi_opt_tpu.ledger.store import LedgerError, read_ledger
from mpi_opt_tpu.space import Choice, _plain

#: the per-record skip reasons ``observations_from_records`` counts —
#: one shared shape so every ``warm_start`` event payload agrees
SKIP_REASONS = ("not_ok", "bad_choice")


def _decode_params(space, params: dict) -> dict:
    """Journaled canonical params -> live typed params for ``space``.

    Scalars round-trip as-is; Choice options were canonicalized through
    ``_plain`` (exotic objects became their repr), so decoding matches
    each journaled value against the canonical form of the live options
    instead of feeding a repr STRING to ``value_to_index``.
    """
    out = dict(params)
    for name, dom in space.domains.items():
        if not isinstance(dom, Choice):
            continue
        v = params[name]
        for opt in dom.options:
            if _plain(opt) == v:
                out[name] = opt
                break
        else:
            raise LedgerError(
                f"params[{name!r}] = {v!r} matches no option of {dom.options} "
                "(same space hash but un-decodable Choice value)"
            )
    return out


def observations_from_records(records, space) -> tuple[list, dict]:
    """ok trial records (ledger JSON shape) -> ``(observations, skips)``.

    ``skips`` counts the records that could NOT become observations,
    by reason: ``not_ok`` (failed/timeout status or a missing score —
    nothing to learn from) and ``bad_choice`` (a Choice value no live
    option canonicalizes to: the hash matched but the record predates
    an option's repr change, or was hand-edited). Counting instead of
    raising keeps one damaged record from refusing a thousand good
    ones, while the caller's ``warm_start`` event carries the honest
    loss tally instead of a silently shorter observation list.
    """
    obs: list[Observation] = []
    skips = {k: 0 for k in SKIP_REASONS}
    for rec in records:
        if rec["status"] != "ok" or rec.get("score") is None:
            skips["not_ok"] += 1
            continue
        try:
            decoded = _decode_params(space, rec["params"])
        except LedgerError:
            skips["bad_choice"] += 1
            continue
        vec = rec.get("scores")
        obs.append(
            Observation(
                unit=space.params_to_unit(decoded),
                score=float(rec["score"]),
                budget=int(rec["step"]),
                # the optional objective vector (ISSUE 17) rides along so
                # Pareto-aware consumers (corpus front seeding, the
                # all-finite guard below) can see it; None entries stay
                # None — the guard treats them as non-finite
                scores=None
                if vec is None
                else tuple(None if v is None else float(v) for v in vec),
            )
        )
    return obs, {k: v for k, v in skips.items() if v}


def load_observations(path: str, space) -> tuple[list, dict]:
    """A ledger's ok records as Observations for ``space``:
    ``(observations, skips)`` (see ``observations_from_records``).

    Raises LedgerError when the ledger has no header or was written for
    a space whose hash differs from ``space``'s.
    """
    header, records, _ = read_ledger(path)
    if header is None:
        raise LedgerError(f"{path}: empty ledger, nothing to warm-start from")
    theirs = header.get("config", {}).get("space_hash")
    ours = space.space_hash()
    if theirs != ours:
        raise LedgerError(
            f"{path}: ledger space hash {theirs!r} != this search's {ours!r} "
            "— the prior sweep ran over a different search space, and its "
            "params would decode into the wrong unit coordinates"
        )
    return observations_from_records(records, space)


def observation_fully_finite(o) -> bool:
    """True when every numeric fact of the observation is finite: the
    scalar score AND — for multi-objective priors — every entry of its
    ``scores`` vector. A NaN in ANY objective disqualifies the record
    from seeding (ISSUE 17 satellite): the scalarized score of a
    partially-diverged trial can look healthy while the trial itself is
    exactly what a new sweep must not start at."""
    import numpy as np

    if not np.isfinite(o.score):
        return False
    if getattr(o, "scores", None) is not None:
        return all(
            v is not None and np.isfinite(v) for v in o.scores
        )
    return True


def best_observation(observations) -> "Observation | None":
    """The highest FINITE-scored prior observation, or None — the point
    the sampler-family consumers (driver random/ASHA, fused cohort
    seeding) start from. Non-finite priors never seed (see
    ``observation_fully_finite`` for the vector-score generalization):
    a diverged prior point is exactly what a new sweep must not start
    at."""
    finite = [o for o in observations if observation_fully_finite(o)]
    return max(finite, key=lambda o: o.score) if finite else None


def warm_start(algorithm: Algorithm, path: str) -> int:
    """Ingest a prior ledger into ``algorithm``; returns how many
    observations actually informed it (the algorithm's own count)."""
    obs, _skips = load_observations(path, algorithm.space)
    return algorithm.ingest_observations(obs)
