"""Post-hoc sweep reporting: render one-or-many ledgers for operators.

``python -m mpi_opt_tpu report TARGET [TARGET ...]`` — best trial,
score trajectory, failure/timeout/retry/cache breakdown, throughput;
``--json`` for machines, ``--validate`` as the CI schema gate (exit 1
on any malformed record, torn tail included — format drift should be
caught by the suite, not by a resume failure in production).

A TARGET may be a DIRECTORY: every ledger underneath is discovered
(header-sniffed ``*.jsonl``) and rendered grouped by sweep identity —
pointed at a service ``--state-dir``, one command audits every
tenant's best/status/throughput, with each tenant's service state
(done/parked/cancelled, slices) read from the sibling ``status.json``
the scheduler maintains.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Optional

from mpi_opt_tpu.ledger.store import (
    LedgerError,
    read_ledger,
    scan_boundaries,
    sniff_header,
    validate_ledger,
)

# score trajectory rendered as a coarse unicode sparkline: enough to see
# "when did the sweep stop improving" in a terminal without plotting
_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values: list[float], width: int = 32) -> str:
    finite = [v for v in values if v == v]  # NaN-free
    if not finite:
        return ""
    if len(values) > width:  # downsample evenly to terminal width
        step = len(values) / width
        values = [values[int(i * step)] for i in range(width)]
    lo, hi = min(finite), max(finite)
    span = (hi - lo) or 1.0
    out = []
    for v in values:
        if v != v:
            out.append(" ")
        else:
            out.append(_SPARK[int((v - lo) / span * (len(_SPARK) - 1))])
    return "".join(out)


def discover_ledgers(directory: str) -> list[str]:
    """Every ledger file under ``directory``: ``*.jsonl`` whose first
    line is a ledger header record (``store.sniff_header``). Metrics
    streams (JSONL of ``{"event": ...}``) and other JSON files are
    skipped by the sniff, so pointing this at a service state-dir finds
    exactly the per-tenant journals."""
    found = []
    for root, _dirs, files in os.walk(directory):
        for f in files:
            if not f.endswith(".jsonl"):
                continue
            path = os.path.join(root, f)
            if sniff_header(path) is not None:
                found.append(path)
    return sorted(found)


def _service_status(path: str) -> Optional[dict]:
    """The scheduler-maintained tenant status next to a service ledger
    (None for plain CLI ledgers): operators reading a state-dir report
    want done/parked/cancelled and slice counts beside the scores."""
    status_path = os.path.join(os.path.dirname(path), "status.json")
    try:
        with open(status_path) as f:
            s = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(s, dict) or "state" not in s:
        return None
    out = {
        "job": s.get("id"),
        "tenant": s.get("tenant"),
        "state": s.get("state"),
        "priority": int(s.get("priority") or 0),
        "deadline_ts": s.get("deadline_ts"),
        "slices": s.get("slices"),
        "preemptions": s.get("preemptions"),
        "program_cache": s.get("program_cache"),
        # post-slice device-memory watermark (obs/memory.py via the
        # scheduler's status write)
        "device_memory": s.get("device_memory"),
        # fleet fields (ISSUE 12): which server ran the last slice and
        # how many times a dead peer's lease was taken over — a ledger
        # that changed hands mid-sweep is still record-identical to a
        # solo run, and the report should say the handoff happened
        "server": s.get("server"),
        "takeovers": s.get("takeovers"),
    }
    # an ACTIVE tenant also reports what it is doing right now (phase
    # from its heartbeat's active-span field + current slice elapsed) —
    # one import, service-light (no jax)
    from mpi_opt_tpu.service.spool import live_phase

    live = live_phase(os.path.dirname(path), s)
    if live is not None:
        out.update(live)
    return out


def _mo_final_rows(records, spec):
    """Each member's LAST full-vector ok record: ``(recs, matrix)``.

    The Pareto view a report renders is the END state of the sweep —
    one point per member/trial at its highest journaled budget (PBT
    members re-evaluate every generation; SHA trials stop at different
    rungs). Records missing the vector (scalar sweeps never carry one)
    or holding a null entry (non-finite objective) never join the
    front."""
    import numpy as np

    last: dict = {}
    for r in records:
        v = r.get("scores")
        if (
            r["status"] != "ok"
            or v is None
            or len(v) != spec.m
            or any(x is None for x in v)
        ):
            continue
        key = r.get("member", r["trial_id"])
        cur = last.get(key)
        if cur is None or (r["step"], r["trial_id"]) >= (cur["step"], cur["trial_id"]):
            last[key] = r
    recs = [last[k] for k in sorted(last)]
    mat = np.asarray(
        [[float(x) for x in r["scores"]] for r in recs], dtype=np.float64
    ).reshape(len(recs), spec.m)
    return recs, mat


def _constrained_spec(spec, constraint: str):
    """``spec`` with one bound overridden from a ``--best-under`` string
    (``"params<=2e4"``). Raises LedgerError on an unknown objective or
    an operator that disagrees with its direction."""
    from mpi_opt_tpu.objectives import Objective, parse_constraint

    try:
        name, op, value = parse_constraint(constraint)
    except ValueError as e:
        raise LedgerError(str(e))
    if name not in spec.names:
        raise LedgerError(
            f"--best-under names {name!r} but this sweep's objectives are "
            f"{list(spec.names)}"
        )
    objs = []
    for o in spec.objectives:
        if o.name != name:
            objs.append(o)
            continue
        want = ">=" if o.direction == "max" else "<="
        if op != want:
            raise LedgerError(
                f"--best-under {constraint!r}: objective {name!r} is "
                f"{o.direction}imized, so its constraint must use {want!r}"
            )
        objs.append(Objective(name, o.direction, float(value)))
    from mpi_opt_tpu.objectives import ObjectiveSpec

    return ObjectiveSpec(tuple(objs))


def _mo_summary(header: dict, records, best_under: Optional[str]) -> Optional[dict]:
    """The multi-objective block of a report (None when the header
    carries no ``objective_spec``): final-state Pareto front, exact
    hypervolume, and — when asked — the typed ``--best-under`` answer
    (feasible / least_violation / diverged, never a crash)."""
    ospec = header.get("objective_spec")
    if not ospec:
        if best_under:
            raise LedgerError(
                "--best-under needs a multi-objective ledger (no "
                "objective_spec in header)"
            )
        return None
    import numpy as np

    from mpi_opt_tpu.objectives import (
        ObjectiveSpec,
        hypervolume,
        pareto_front_mask,
        select_best,
    )

    try:
        spec = ObjectiveSpec.from_spec(ospec)
    except (ValueError, TypeError, KeyError) as e:
        raise LedgerError(f"malformed objective_spec in header: {e}")
    recs, mat = _mo_final_rows(records, spec)
    norm = np.asarray(spec.normalize(mat), dtype=np.float64)
    mask = pareto_front_mask(norm)
    idx = np.flatnonzero(mask)
    out = {
        "objectives": ospec,
        "evaluated": len(recs),
        "front_size": int(mask.sum()),
        "front": [
            {
                "trial_id": recs[i]["trial_id"],
                "member": recs[i].get("member"),
                "step": recs[i]["step"],
                "scores": [float(v) for v in mat[i]],
                "params": recs[i]["params"],
            }
            for i in idx
        ],
        "hypervolume": float(hypervolume(norm[mask])) if len(idx) else 0.0,
    }
    if best_under:
        cspec = _constrained_spec(spec, best_under)
        sel = select_best(mat, cspec) if len(recs) else {
            "index": None, "kind": "diverged", "violation": None,
        }
        picked = None if sel["index"] is None else recs[int(sel["index"])]
        out["best_under"] = {
            "constraint": best_under,
            "kind": sel["kind"],
            "violation": sel["violation"],
            "trial_id": None if picked is None else picked["trial_id"],
            "scores": None
            if picked is None
            else [float(v) for v in picked["scores"]],
            "params": None if picked is None else picked["params"],
        }
    return out


def summarize_ledger(path: str, best_under: Optional[str] = None) -> dict:
    """One ledger -> its machine-readable report dict.

    Raises LedgerError for files the tolerant loader refuses (malformed
    mid-file records, missing header), and for a ``best_under``
    constraint that cannot apply (scalar ledger, unknown objective,
    operator against the objective's direction).
    """
    header, records, n_torn = read_ledger(path)
    if header is None:
        raise LedgerError(f"{path}: empty ledger (no header)")
    cfg = header.get("config", {})
    by_status = {"ok": 0, "failed": 0, "timeout": 0}
    retried = cache_hits = 0
    best: Optional[dict] = None
    trajectory: list[float] = []  # running best over journal order
    running = float("nan")
    wall_sum = 0.0
    for r in records:
        by_status[r["status"]] += 1
        if int(r.get("attempts") or 1) > 1:
            retried += 1
        if r.get("cached"):
            cache_hits += 1
        else:
            wall_sum += float(r.get("wall_s") or 0.0)
        if r["status"] == "ok" and r.get("score") is not None:
            s = float(r["score"])
            if best is None or s > float(best["score"]):
                best = r
                running = s
        trajectory.append(running)
    # journal timestamps bound the sweep's wall even across driver
    # restarts (each record carries an absolute ts)
    ts = [float(r["ts"]) for r in records if r.get("ts") is not None]
    span = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
    n = len(records)
    fused = None
    if cfg.get("mode") == "fused" or any("boundary" in r for r in records):
        # fused member journal: the per-boundary view operators actually
        # ask for — how many generations/rungs/batches are journaled and
        # how many members each one lost to divergence
        by_boundary, sizes, _problems, torn_final = scan_boundaries(records)
        order = sorted(by_boundary)
        fused = {
            "granularity": cfg.get("granularity"),
            "boundaries": len(order),
            "member_records": sum(len(by_boundary[b]) for b in order),
            "member_failures": [
                sum(1 for r in by_boundary[b].values() if r["status"] != "ok")
                for b in order
            ],
            "boundary_sizes": [sizes[b] for b in order],
            "torn_boundary": torn_final,
        }
    return {
        "path": path,
        "sweep_id": header.get("sweep_id"),
        "version": header.get("version"),
        "config": cfg,
        "trials": n,
        "by_status": by_status,
        "retried": retried,
        "cache_hits": cache_hits,
        "torn_tail_dropped": n_torn,
        "best": None
        if best is None
        else {
            "trial_id": best["trial_id"],
            "score": float(best["score"]),
            "step": best["step"],
            "params": best["params"],
        },
        "trajectory": trajectory,
        "trials_per_sec": round(n / span, 4) if span > 0 else None,
        "eval_wall_s": round(wall_sum, 3),
        "fused": fused,
        "multi_objective": _mo_summary(header, records, best_under),
        "service": _service_status(path),
    }


def _render_text(rep: dict) -> str:
    cfg = rep["config"]
    lines = [
        f"ledger {rep['path']}  (sweep {rep['sweep_id']}, schema v{rep['version']})",
        "  config: "
        + ", ".join(
            f"{k}={cfg[k]}"
            for k in ("algorithm", "workload", "backend", "seed")
            if k in cfg
        ),
        f"  trials: {rep['trials']}  "
        f"ok={rep['by_status']['ok']} failed={rep['by_status']['failed']} "
        f"timeout={rep['by_status']['timeout']} retried={rep['retried']} "
        f"cache_hits={rep['cache_hits']}",
    ]
    if rep.get("service"):
        s = rep["service"]
        pc = s.get("program_cache") or {}
        live = ""
        if s.get("state") == "running":
            live = (
                f" phase={s.get('phase')}"
                f" slice_elapsed={s.get('slice_elapsed_s')}s"
            )
        fleet = ""
        if s.get("priority"):
            fleet += f" prio={s['priority']}"
        if s.get("deadline_ts"):
            fleet += f" deadline_ts={s['deadline_ts']}"
        if s.get("server"):
            fleet += f" server={s['server']}"
        if s.get("takeovers"):
            fleet += f" takeovers={s['takeovers']}"
        lines.append(
            f"  service: tenant={s.get('tenant')} job={s.get('job')} "
            f"state={s.get('state')} slices={s.get('slices')} "
            f"preemptions={s.get('preemptions')} "
            f"cache={pc.get('hits', 0)}h/{pc.get('misses', 0)}m" + fleet + live
        )
    if rep["torn_tail_dropped"]:
        lines.append("  note: 1 torn tail line dropped (crash mid-append)")
    if rep.get("fused"):
        f = rep["fused"]
        gran = f.get("granularity") or "boundary"
        fails = f["member_failures"]
        tail = ""
        if len(fails) > 16:
            fails, tail = fails[:16], f" ... ({len(f['member_failures'])} total)"
        lines.append(
            f"  fused: {f['boundaries']} {gran} boundaries, "
            f"{f['member_records']} member records; failures/boundary: "
            f"{fails}{tail}"
        )
        if f.get("torn_boundary") is not None:
            lines.append(
                f"  note: boundary {f['torn_boundary']} is torn (killed "
                "mid-journal; --resume re-journals it)"
            )
    if rep.get("multi_objective"):
        m = rep["multi_objective"]
        obj_s = ", ".join(
            f"{o['name']}:{o['direction']}"
            + (
                ""
                if o.get("bound") is None
                else (">=" if o["direction"] == "max" else "<=") + str(o["bound"])
            )
            for o in m["objectives"]
        )
        lines.append(f"  objectives: {obj_s}")
        lines.append(
            f"  pareto: front {m['front_size']}/{m['evaluated']} evaluated, "
            f"hypervolume {m['hypervolume']:.6g}"
        )
        for fr in m["front"][:8]:
            lines.append(
                f"    trial {fr['trial_id']} @ step {fr['step']}  "
                f"scores {fr['scores']}"
            )
        if len(m["front"]) > 8:
            lines.append(f"    ... ({len(m['front'])} front points total)")
        if m.get("best_under"):
            bu = m["best_under"]
            if bu["trial_id"] is None:
                lines.append(
                    f"  best-under {bu['constraint']}: none (every evaluated "
                    "trial diverged)"
                )
            else:
                note = (
                    ""
                    if bu["kind"] == "feasible"
                    else f" [DEGRADED: nothing feasible; least violation "
                    f"{bu['violation']:.4g}]"
                )
                lines.append(
                    f"  best-under {bu['constraint']}: trial {bu['trial_id']} "
                    f"scores {bu['scores']}{note}"
                )
    if rep["best"] is None:
        lines.append("  best: none (no ok trial recorded)")
    else:
        b = rep["best"]
        lines.append(
            f"  best: trial {b['trial_id']} score {b['score']:.6f} "
            f"@ step {b['step']}  {json.dumps(b['params'])}"
        )
    spark = _sparkline(rep["trajectory"])
    if spark:
        lines.append(f"  best-so-far: {spark}")
    if rep["trials_per_sec"] is not None:
        lines.append(
            f"  throughput: {rep['trials_per_sec']} trials/s "
            f"(eval wall {rep['eval_wall_s']}s)"
        )
    return "\n".join(lines)


def replay_consistency(ledger_path: str, search_state: dict) -> list:
    """Cross-check a ledger journal against a restored snapshot's
    ``search`` item (fsck's replay-consistency gate): every trial the
    snapshot records as FINAL must hold a final record in the journal,
    because the driver fsyncs each record BEFORE reporting it to the
    algorithm — the journal can never lag the search state. A snapshot
    final missing from the journal means the pair is torn (mixed
    directories, a hand-edited journal) and a ``--ledger --resume``
    would replay into a state that is already ahead of it.

    Returns human-readable problems (empty = consistent).
    """
    try:
        _header, records, _n_torn = read_ledger(ledger_path)
    except (LedgerError, OSError) as e:
        return [f"ledger unreadable for cross-check: {e}"]
    journaled = {int(r["trial_id"]) for r in records}
    finals = {
        int(t["trial_id"])
        for t in search_state.get("algorithm", {}).get("trials", [])
        # 'done'/'failed' are terminal; 'stopped' (ASHA cut) trials also
        # completed an evaluation and were journaled before the cut
        if t.get("status") in ("done", "failed", "stopped")
    }
    missing = sorted(finals - journaled)
    if missing:
        return [
            f"snapshot records {len(missing)} final trial(s) absent from "
            f"the journal (trial ids {missing[:10]}"
            + ("...)" if len(missing) > 10 else ")")
        ]
    return []


def fused_replay_consistency(ledger_path: str, boundaries_done: int) -> list:
    """The boundary-granular twin of ``replay_consistency`` for FUSED
    sweeps (fsck's cross-check): every boundary the newest verified
    snapshot records as complete (``meta['boundaries_done']``) must be
    FULLY journaled, because the fused drivers journal each boundary's
    member records before saving its snapshot. A journaled prefix
    shorter than the snapshot's boundary count means the pair is torn
    (mixed directories, a hand-edited journal, or a ledger attached
    mid-sweep) and a ``--ledger --resume`` would refuse.

    Returns human-readable problems (empty = consistent).
    """
    try:
        _header, records, _n_torn = read_ledger(ledger_path)
    except (LedgerError, OSError) as e:
        return [f"ledger unreadable for cross-check: {e}"]
    by_boundary, sizes, problems, _torn_final = scan_boundaries(records)
    if problems:
        return [f"fused journal structure: {p}" for p in problems]
    n = 0
    while n in by_boundary and len(by_boundary[n]) == sizes[n]:
        n += 1
    if n < int(boundaries_done):
        return [
            f"snapshot records {boundaries_done} boundaries complete but "
            f"only {n} are fully journaled — the journal lags the snapshot "
            "it should never lag"
        ]
    return []


def report_main(argv=None) -> int:
    """The ``mpi_opt_tpu report`` subcommand (see cli.main dispatch)."""
    import argparse

    p = argparse.ArgumentParser(
        prog="mpi_opt_tpu report",
        description="render durable sweep ledgers (see README: sweep ledger)",
    )
    p.add_argument(
        "ledgers",
        nargs="+",
        metavar="TARGET",
        help="ledger JSONL path(s), or directories to discover ledgers "
        "under (e.g. a service --state-dir: all tenant journals render "
        "grouped by sweep identity)",
    )
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--best-under",
        metavar="CONSTRAINT",
        help="answer 'best trial subject to CONSTRAINT' over a "
        "multi-objective ledger, e.g. \"params<=2e4\" — typed result: "
        "feasible, or DEGRADED to the least-violating trial when nothing "
        "satisfies it (never a crash)",
    )
    p.add_argument(
        "--validate",
        action="store_true",
        help="strict schema check only: exit 1 on any malformed record "
        "(torn tail included); no report is rendered",
    )
    args = p.parse_args(argv)

    # directory targets expand to every discovered ledger underneath;
    # an empty directory is an operator error surfaced as exit 1 (an
    # audit that silently checked nothing would read as a green audit)
    expanded, rc_expand = [], 0
    for target in args.ledgers:
        if os.path.isdir(target):
            hits = discover_ledgers(target)
            if not hits:
                # stderr: --json's stdout is a single JSON object "for
                # machines" and a stray text line would break json.loads
                print(
                    f"{target}: no ledgers found under directory",
                    file=sys.stderr,
                )
                rc_expand = 1
            expanded.extend(hits)
        else:
            expanded.append(target)
    args.ledgers = expanded

    if args.validate:
        rc = rc_expand
        out = {}
        for path in args.ledgers:
            problems = validate_ledger(path)
            out[path] = problems
            if problems:
                rc = 1
            if not args.json:
                status = "ok" if not problems else "; ".join(problems)
                print(f"{path}: {status}")
        if args.json:
            print(json.dumps({"valid": rc == 0, "problems": out}))
        return rc

    reports = []
    rc = rc_expand
    for path in args.ledgers:
        try:
            reports.append(summarize_ledger(path, best_under=args.best_under))
        except (LedgerError, OSError) as e:
            print(f"{path}: {e}", file=sys.stderr)
            rc = 1
    if args.json:
        overall = None
        cands = [r["best"] for r in reports if r["best"] is not None]
        if cands:
            overall = max(cands, key=lambda b: b["score"])
        print(json.dumps({"ledgers": reports, "best": overall}))
        return rc
    for rep in reports:
        print(_render_text(rep))
    if len(reports) > 1:
        # the grouped service view: ledgers sharing a sweep identity
        # (workload + algorithm + space hash) are one logical family —
        # e.g. N tenants of the same search — and operators compare
        # within the family before across it
        groups: dict = {}
        for r in reports:
            cfg = r["config"]
            key = (cfg.get("workload"), cfg.get("algorithm"), cfg.get("space_hash"))
            groups.setdefault(key, []).append(r)
        print(f"sweep identities: {len(groups)}")
        # identity is (workload, algorithm, space_hash) but the label
        # shows only workload/algorithm — when two groups differ ONLY by
        # search space (the exact split the grouping exists to make),
        # a short hash suffix keeps their lines distinguishable
        pair_counts: dict = {}
        for w, a, _h in groups:
            pair_counts[(w, a)] = pair_counts.get((w, a), 0) + 1
        for (workload, algorithm, h), grp in sorted(
            groups.items(), key=lambda kv: (str(kv[0][0]), str(kv[0][1]), str(kv[0][2]))
        ):
            label = f"{workload}/{algorithm}"
            if pair_counts[(workload, algorithm)] > 1:
                label += f" (space {str(h)[:8]})"
            bests = [r["best"] for r in grp if r["best"] is not None]
            best_s = (
                f"best {max(b['score'] for b in bests):.6f}" if bests else "no best"
            )
            rates = [r["trials_per_sec"] for r in grp if r["trials_per_sec"]]
            rate_s = f", {round(sum(rates), 3)} trials/s" if rates else ""
            states = [
                r["service"]["state"] for r in grp if r.get("service") is not None
            ]
            state_s = (
                "  [" + " ".join(f"{s}:{states.count(s)}" for s in sorted(set(states))) + "]"
                if states
                else ""
            )
            print(
                f"  {label}: {len(grp)} ledger(s), "
                f"{sum(r['trials'] for r in grp)} trials, {best_s}{rate_s}{state_s}"
            )
        cands = [
            (r["path"], r["best"]) for r in reports if r["best"] is not None
        ]
        if cands:
            path, b = max(cands, key=lambda pb: pb[1]["score"])
            print(
                f"overall best: score {b['score']:.6f} "
                f"(trial {b['trial_id']} of {path})"
            )
    return rc
