"""Append-only, crash-safe journal of trial evaluations.

File format (one JSON object per line):

- line 1 — the HEADER record: ``{"kind": "header", "version": N,
  "sweep_id": ..., "config": {...}, "created_ts": ...}``. ``config``
  captures the sweep's identity (algorithm, workload, backend, seed,
  space_hash, capacity, ...): a resume whose live config differs is a
  DIFFERENT sweep and is refused, because replaying its records through
  a differently-configured algorithm would silently corrupt the search.
- every later line — one FINAL trial record: ``{"kind": "trial",
  "trial_id", "params" (canonical, see SearchSpace.canonical_params),
  "status" (ok|failed|timeout), "score" (null when non-finite — JSON has
  no NaN), "step", "error", "attempts", "wall_s", "cached", "ts"}``.
  FINAL means post-retry: the driver journals exactly one record per
  completed trial, after its FailurePolicy has resolved.

Durability contract: each record is flushed AND fsync'd before the
driver reports it to the algorithm, so the journal can never lag the
search state it will be replayed into. Recovery is tolerant of exactly
the failure append-fsync can produce — a TORN FINAL LINE (the process
died mid-write): the tail fragment is truncated away on load and the
journal continues from the last complete record. A malformed line
anywhere ELSE means the file was edited or mixed with another stream,
and loading refuses rather than guessing.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Optional, Sequence

import numpy as np

from mpi_opt_tpu.trial import TrialResult, failed_result

LEDGER_SCHEMA_VERSION = 1


class LedgerError(ValueError):
    """Malformed or incompatible ledger content."""


def _check_shape(rec, lineno: int) -> dict:
    if not isinstance(rec, dict) or "kind" not in rec:
        raise LedgerError(f"line {lineno}: not a ledger record (no 'kind')")
    return rec


def _check_trial_record(rec: dict, lineno: int) -> None:
    missing = [k for k in ("trial_id", "params", "status", "step") if k not in rec]
    if missing:
        raise LedgerError(f"line {lineno}: trial record missing {missing}")
    if rec["status"] not in ("ok", "failed", "timeout"):
        raise LedgerError(f"line {lineno}: unknown status {rec['status']!r}")
    if rec["status"] == "ok" and not isinstance(rec.get("score"), (int, float)):
        raise LedgerError(f"line {lineno}: ok record without a numeric score")


def read_ledger(path: str, strict: bool = False):
    """(header, trial_records, n_torn) from a ledger file.

    ``strict=False`` (load-for-resume): a torn FINAL line is dropped
    (n_torn=1) — the one shape an append-crash leaves behind. Torn
    means NOT-VALID-JSON specifically: a prefix of a longer JSON line
    can never itself parse (the closing brace is the last byte), so
    decode failure on the tail is the append-crash signature. A tail
    line that PARSES but fails schema checks was written whole by
    something else — edited, or another tool — and refuses to load
    like any other malformed line (truncating it would destroy a
    completed trial's data). ``strict=True`` (validate mode): every
    line must parse, including the tail.
    """
    header: Optional[dict] = None
    records: list[dict] = []
    with open(path, "r") as f:
        lines = f.read().split("\n")
    if lines and lines[-1] == "":
        lines.pop()  # the trailing newline of a cleanly-written file
    for i, raw in enumerate(lines):
        lineno = i + 1
        is_tail = i == len(lines) - 1
        try:
            rec = json.loads(raw)
        except json.JSONDecodeError as e:
            if strict or not is_tail:
                raise LedgerError(
                    f"line {lineno}: not valid JSON ({e.msg})"
                ) from None
            return header, records, 1
        _check_shape(rec, lineno)
        if rec["kind"] == "header":
            if lineno != 1:
                raise LedgerError(f"line {lineno}: header must be line 1")
            if int(rec.get("version", -1)) > LEDGER_SCHEMA_VERSION:
                raise LedgerError(
                    f"ledger schema v{rec['version']} is newer than this "
                    f"build's v{LEDGER_SCHEMA_VERSION}"
                )
            header = rec
        elif rec["kind"] == "trial":
            _check_trial_record(rec, lineno)
            records.append(rec)
        else:
            raise LedgerError(f"line {lineno}: unknown kind {rec['kind']!r}")
    if lines and header is None:
        raise LedgerError("line 1: not a ledger header")
    return header, records, 0


def validate_ledger(path: str) -> list[str]:
    """Strict schema check; returns human-readable problems (empty = ok)."""
    problems: list[str] = []
    try:
        header, records, _ = read_ledger(path, strict=True)
    except LedgerError as e:
        return [str(e)]
    except OSError as e:
        return [f"unreadable: {e}"]
    if header is None:
        problems.append("empty ledger (no header record)")
    seen: set = set()
    for rec in records:
        tid = rec["trial_id"]
        if tid in seen:
            problems.append(f"trial {tid}: duplicated final record")
        seen.add(tid)
    return problems


def result_from_record(rec: dict) -> TrialResult:
    """Reconstruct the FINAL TrialResult a trial record journals.

    Non-ok records come back through ``failed_result`` (the one
    construction point for failures), so a replayed failure is
    indistinguishable from a live one to the algorithm.
    """
    if rec["status"] != "ok":
        return failed_result(
            trial_id=int(rec["trial_id"]),
            step=int(rec["step"]),
            error=rec.get("error") or "replayed failure",
            status=rec["status"],
            wall_time=float(rec.get("wall_s") or 0.0),
        )
    return TrialResult(
        trial_id=int(rec["trial_id"]),
        score=float(rec["score"]),
        step=int(rec["step"]),
        wall_time=float(rec.get("wall_s") or 0.0),
        extra={"replayed": True},
    )


class SweepLedger:
    """One sweep's durable journal, opened for append.

    Loading truncates a torn tail line IN PLACE (so the next append
    starts on a clean line boundary) and exposes the completed records
    for replay. ``ensure_header`` writes the header on a fresh file and
    verifies identity on an existing one.

    ``read_only=True`` is the multi-process SPMD posture (rank-0-only
    journaling): non-zero ranks run the same deterministic driver loop
    over the SHARED journal — they must replay/verify it identically —
    but N ranks fsync-appending one file would interleave records and
    corrupt the stream, so only rank 0 writes. A read-only ledger keeps
    the full in-memory view (header checks, ``completed()``,
    ``record_trial`` bookkeeping) while never touching the file: no
    append handle, no torn-tail truncation (rank 0 owns repairs), no
    header/record writes.
    """

    def __init__(self, path: str, read_only: bool = False):
        self.path = os.path.abspath(path)
        self.read_only = bool(read_only)
        self.header: Optional[dict] = None
        self.records: list[dict] = []
        self.n_torn = 0
        if os.path.exists(self.path) and os.path.getsize(self.path) > 0:
            self.header, self.records, self.n_torn = read_ledger(self.path)
            if self.n_torn and not self.read_only:
                self._truncate_torn_tail()
        if self.read_only:
            self._file = None
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._file = open(self.path, "a")

    def _truncate_torn_tail(self) -> None:
        # keep exactly the bytes of the complete lines; the torn
        # fragment must not prefix the next append
        good = [json.dumps(self.header)] if self.header else []
        good += [json.dumps(r) for r in self.records]
        # rewrite-then-replace, not open('w'): a second crash here must
        # not tear the GOOD records too
        tmp = f"{self.path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write("".join(line + "\n" for line in good))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- identity ----------------------------------------------------------

    @property
    def sweep_id(self) -> Optional[str]:
        return None if self.header is None else self.header.get("sweep_id")

    def ensure_header(self, config: dict) -> None:
        """Write the header (fresh ledger) or verify it (existing one).

        ``config`` is the sweep's identity dict; on an existing ledger a
        mismatch on any shared key is refused — the caller is about to
        replay this journal through an algorithm configured differently
        than the one that wrote it.
        """
        if self.header is not None:
            stale = {
                k: (self.header.get("config", {}).get(k), v)
                for k, v in config.items()
                if self.header.get("config", {}).get(k) != v
            }
            if stale:
                diff = ", ".join(
                    f"{k}: ledger={a!r} vs run={b!r}" for k, (a, b) in stale.items()
                )
                raise LedgerError(
                    f"ledger {self.path} was written by a different sweep "
                    f"({diff}) — resume with the original configuration or "
                    "point --ledger at a fresh path"
                )
            return
        self.header = {
            "kind": "header",
            "version": LEDGER_SCHEMA_VERSION,
            "sweep_id": uuid.uuid4().hex[:12],
            "config": dict(config),
            "created_ts": round(time.time(), 4),
        }
        if not self.read_only:
            self._write_line(self.header)

    # -- append ------------------------------------------------------------

    def record_trial(
        self,
        result: TrialResult,
        canonical_params: dict,
        attempts: int = 1,
        cached: bool = False,
    ) -> dict:
        """Journal one FINAL result; durable (fsync) before returning."""
        if self.header is None:
            raise LedgerError("ledger has no header — call ensure_header first")
        score = float(result.score)
        rec = {
            "kind": "trial",
            "sweep_id": self.sweep_id,
            "trial_id": int(result.trial_id),
            "params": canonical_params,
            "status": result.status,
            # JSON has no NaN: non-finite scores journal as null, and
            # status carries the failure; result_from_record restores
            # the NaN-family score via failed_result
            "score": score if np.isfinite(score) else None,
            "step": int(result.step),
            "error": result.error,
            "attempts": int(attempts),
            "wall_s": round(float(result.wall_time), 4),
            "cached": bool(cached),
            "ts": round(time.time(), 4),
        }
        if not self.read_only:
            self._write_line(rec)
        # read-only ranks still track the record in memory: completed()
        # and the dedup views must agree with rank 0's across the gang
        self.records.append(rec)
        return rec

    def _write_line(self, rec: dict) -> None:
        self._file.write(json.dumps(rec) + "\n")
        self._file.flush()
        os.fsync(self._file.fileno())

    # -- replay view -------------------------------------------------------

    def completed(self) -> dict[int, dict]:
        """trial_id -> FINAL record (ok or failed) for replay-resume."""
        return {int(r["trial_id"]): r for r in self.records}

    def ok_records(self) -> Sequence[dict]:
        return [r for r in self.records if r["status"] == "ok"]

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
