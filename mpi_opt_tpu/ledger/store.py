"""Append-only, crash-safe journal of trial evaluations.

File format (one JSON object per line):

- line 1 — the HEADER record: ``{"kind": "header", "version": N,
  "sweep_id": ..., "config": {...}, "created_ts": ...}``. ``config``
  captures the sweep's identity (algorithm, workload, backend, seed,
  space_hash, capacity, ...): a resume whose live config differs is a
  DIFFERENT sweep and is refused, because replaying its records through
  a differently-configured algorithm would silently corrupt the search.
- every later line — one FINAL trial record: ``{"kind": "trial",
  "trial_id", "params" (canonical, see SearchSpace.canonical_params),
  "status" (ok|failed|timeout), "score" (null when non-finite — JSON has
  no NaN), "step", "error", "attempts", "wall_s", "cached", "ts"}``.
  FINAL means post-retry: the driver journals exactly one record per
  completed trial, after its FailurePolicy has resolved.

FUSED sweeps journal through the SAME schema at member granularity
(``ledger/fused.py``): their trial records additionally carry
``member`` (population/cohort row identity), ``boundary`` (the global
index of the natural boundary that produced the evaluation — PBT
generation, SHA/BOHB rung, TPE batch) and ``boundary_size`` (how many
member records that boundary journals), and their header ``config``
marks ``mode: "fused"`` plus the boundary ``granularity``. One boundary
is journaled as one contiguous block, so the only damage an append-kill
can leave is a TORN FINAL BOUNDARY (fewer than ``boundary_size``
records for the last boundary) — recoverable exactly like a torn tail
line, because the journal-before-snapshot ordering guarantees no
snapshot ever covers a partially-journaled boundary.

Durability contract: each record is flushed AND fsync'd before the
driver reports it to the algorithm (fused: before the boundary's
snapshot is saved), so the journal can never lag the search state it
will be replayed into. Recovery is tolerant of exactly the failures
append-fsync can produce — a TORN FINAL LINE (the process died
mid-write): the tail fragment is truncated away on load and the
journal continues from the last complete record; and, for fused
journals, a TORN FINAL BOUNDARY (the process died between a boundary's
member records), truncated the same way. A malformed line — or a
partially-journaled boundary — anywhere ELSE means the file was edited
or mixed with another stream, and loading refuses rather than guessing.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
import uuid
from typing import Optional, Sequence

import numpy as np

from mpi_opt_tpu.trial import TrialResult, failed_result

LEDGER_SCHEMA_VERSION = 1


class LedgerError(ValueError):
    """Malformed or incompatible ledger content."""


def sniff_header(path: str) -> Optional[dict]:
    """Line 1 parsed as a ledger header record, else None — the ONE
    home for the "is this .jsonl file a ledger?" convention that both
    ``report``'s directory discovery and ``fsck``'s sibling
    auto-detection gate on (a metrics stream is also one-JSON-per-line,
    so the kind check, not the extension, is what identifies a ledger).
    The first line is capped at 1 MB: a real header is a few hundred
    bytes, and an arbitrary single-line .jsonl file should cost a
    bounded read to reject."""
    try:
        with open(path, "r") as f:
            first = json.loads(f.readline(1_000_000))
    except (OSError, ValueError):
        return None
    if isinstance(first, dict) and first.get("kind") == "header":
        return first
    return None


def _check_shape(rec, lineno: int) -> dict:
    if not isinstance(rec, dict) or "kind" not in rec:
        raise LedgerError(f"line {lineno}: not a ledger record (no 'kind')")
    return rec


def _check_trial_record(rec: dict, lineno: int) -> None:
    missing = [k for k in ("trial_id", "params", "status", "step") if k not in rec]
    if missing:
        raise LedgerError(f"line {lineno}: trial record missing {missing}")
    if rec["status"] not in ("ok", "failed", "timeout"):
        raise LedgerError(f"line {lineno}: unknown status {rec['status']!r}")
    if rec["status"] == "ok" and not isinstance(rec.get("score"), (int, float)):
        raise LedgerError(f"line {lineno}: ok record without a numeric score")
    if rec.get("scores") is not None:
        # the optional multi-objective vector (ISSUE 17): absent on every
        # scalar record forever; when present it must be a list of
        # numbers — an ok record's objectives are all finite by the
        # journaling rule, so null entries only belong on failed records
        scores = rec["scores"]
        if not isinstance(scores, list) or not scores:
            raise LedgerError(
                f"line {lineno}: 'scores' must be a non-empty list when present"
            )
        bad = [
            s for s in scores
            if isinstance(s, bool)  # JSON true/false is drift, not a score
            or not (s is None or isinstance(s, (int, float)))
        ]
        if bad:
            raise LedgerError(
                f"line {lineno}: non-numeric entries in 'scores': {bad!r}"
            )
        if rec["status"] == "ok" and any(s is None for s in scores):
            raise LedgerError(
                f"line {lineno}: ok record with a null objective in 'scores'"
            )
    if "boundary" in rec:
        fused_missing = [k for k in ("member", "boundary_size") if k not in rec]
        if fused_missing:
            raise LedgerError(
                f"line {lineno}: fused member record missing {fused_missing}"
            )


def scan_boundaries(records: Sequence[dict]):
    """Group fused member records by boundary and judge the grouping.

    Returns ``(by_boundary, sizes, problems, torn_final)``:
    ``by_boundary`` maps boundary index -> {member: record}; ``sizes``
    maps boundary -> its declared ``boundary_size``; ``problems`` lists
    structural damage that append-crash CANNOT produce (a hand-edited
    or mixed file); ``torn_final`` is the final boundary's index when
    it is partially journaled — the ONE shape a mid-journal kill leaves
    (recoverable: the journal-before-snapshot ordering means no
    snapshot covers it) — else None.

    Rules enforced: fused and driver records never mix in one journal;
    boundary indices are non-decreasing and contiguous blocks (a
    boundary never resumes after another started); within a boundary,
    ``boundary_size`` is consistent, members are unique, and the count
    never exceeds the declared size; boundary 0 exists and indices have
    no gaps; only the FINAL boundary may be short.
    """
    by_boundary: dict[int, dict[int, dict]] = {}
    sizes: dict[int, int] = {}
    problems: list[str] = []
    last_b = None
    saw_driver = False
    for rec in records:
        if "boundary" not in rec:
            saw_driver = True
            if by_boundary:
                problems.append(
                    f"trial {rec['trial_id']}: driver record mixed into a "
                    "fused member journal"
                )
            continue
        if saw_driver and not by_boundary:
            # the mirror order (driver records first) is the same mixed
            # file and must be refused the same way
            problems.append(
                f"trial {rec['trial_id']}: fused member record mixed "
                "into a driver journal"
            )
        b = int(rec["boundary"])
        m = int(rec["member"])
        size = int(rec["boundary_size"])
        if last_b is not None and b < last_b:
            problems.append(
                f"boundary {b}: records out of order (after boundary {last_b})"
            )
        if b in by_boundary and last_b != b:
            problems.append(
                f"boundary {b}: non-contiguous (resumes after boundary {last_b})"
            )
        grp = by_boundary.setdefault(b, {})
        if b in sizes and sizes[b] != size:
            problems.append(
                f"boundary {b}: inconsistent boundary_size "
                f"({sizes[b]} vs {size})"
            )
        sizes.setdefault(b, size)
        if m in grp:
            problems.append(f"boundary {b}: member {m} journaled twice")
        grp[m] = rec
        if len(grp) > sizes[b]:
            problems.append(
                f"boundary {b}: {len(grp)} member records exceed the "
                f"declared boundary_size {sizes[b]}"
            )
        last_b = b
    torn_final = None
    if by_boundary:
        order = sorted(by_boundary)
        if order != list(range(order[-1] + 1)):
            problems.append(
                "boundary indices are not the contiguous range "
                f"0..{order[-1]}: missing "
                f"{sorted(set(range(order[-1] + 1)) - set(order))}"
            )
        for b in order:
            if len(by_boundary[b]) < sizes[b]:
                if b == last_b:
                    torn_final = b
                else:
                    problems.append(
                        f"boundary {b}: only {len(by_boundary[b])}/{sizes[b]} "
                        "member records journaled mid-file"
                    )
    return by_boundary, sizes, problems, torn_final


def read_ledger(path: str, strict: bool = False):
    """(header, trial_records, n_torn) from a ledger file.

    ``strict=False`` (load-for-resume): a torn FINAL line is dropped
    (n_torn=1) — the one shape an append-crash leaves behind. Torn
    means NOT-VALID-JSON specifically: a prefix of a longer JSON line
    can never itself parse (the closing brace is the last byte), so
    decode failure on the tail is the append-crash signature. A tail
    line that PARSES but fails schema checks was written whole by
    something else — edited, or another tool — and refuses to load
    like any other malformed line (truncating it would destroy a
    completed trial's data). ``strict=True`` (validate mode): every
    line must parse, including the tail.
    """
    header: Optional[dict] = None
    records: list[dict] = []
    with open(path, "r") as f:
        lines = f.read().split("\n")
    if lines and lines[-1] == "":
        lines.pop()  # the trailing newline of a cleanly-written file
    for i, raw in enumerate(lines):
        lineno = i + 1
        is_tail = i == len(lines) - 1
        try:
            rec = json.loads(raw)
        except json.JSONDecodeError as e:
            if strict or not is_tail:
                raise LedgerError(
                    f"line {lineno}: not valid JSON ({e.msg})"
                ) from None
            return header, records, 1
        _check_shape(rec, lineno)
        if rec["kind"] == "header":
            if lineno != 1:
                raise LedgerError(f"line {lineno}: header must be line 1")
            if int(rec.get("version", -1)) > LEDGER_SCHEMA_VERSION:
                raise LedgerError(
                    f"ledger schema v{rec['version']} is newer than this "
                    f"build's v{LEDGER_SCHEMA_VERSION}"
                )
            header = rec
        elif rec["kind"] == "trial":
            _check_trial_record(rec, lineno)
            records.append(rec)
        else:
            raise LedgerError(f"line {lineno}: unknown kind {rec['kind']!r}")
    if lines and header is None:
        raise LedgerError("line 1: not a ledger header")
    return header, records, 0


def validate_ledger(path: str) -> list[str]:
    """Strict schema check; returns human-readable problems (empty = ok)."""
    problems: list[str] = []
    try:
        header, records, _ = read_ledger(path, strict=True)
    except LedgerError as e:
        return [str(e)]
    except OSError as e:
        return [f"unreadable: {e}"]
    if header is None:
        problems.append("empty ledger (no header record)")
    seen: set = set()
    for rec in records:
        tid = rec["trial_id"]
        if tid in seen:
            problems.append(f"trial {tid}: duplicated final record")
        seen.add(tid)
    if any("boundary" in r for r in records):
        # fused member journal: the boundary-granular invariants are
        # part of the schema — a torn FINAL boundary is flagged here
        # (strict mode reports damage; the resume path self-heals it)
        _by, sizes, b_problems, torn_final = scan_boundaries(records)
        problems += b_problems
        if torn_final is not None:
            problems.append(
                f"boundary {torn_final}: torn ({len(_by[torn_final])}/"
                f"{sizes[torn_final]} member records — killed mid-journal; "
                "a --resume truncates and re-journals it)"
            )
    return problems


def result_from_record(rec: dict) -> TrialResult:
    """Reconstruct the FINAL TrialResult a trial record journals.

    Non-ok records come back through ``failed_result`` (the one
    construction point for failures), so a replayed failure is
    indistinguishable from a live one to the algorithm.
    """
    if rec["status"] != "ok":
        return failed_result(
            trial_id=int(rec["trial_id"]),
            step=int(rec["step"]),
            error=rec.get("error") or "replayed failure",
            status=rec["status"],
            wall_time=float(rec.get("wall_s") or 0.0),
        )
    return TrialResult(
        trial_id=int(rec["trial_id"]),
        score=float(rec["score"]),
        step=int(rec["step"]),
        wall_time=float(rec.get("wall_s") or 0.0),
        extra={"replayed": True},
    )


class SweepLedger:
    """One sweep's durable journal, opened for append.

    Loading truncates a torn tail line IN PLACE (so the next append
    starts on a clean line boundary) and exposes the completed records
    for replay. ``ensure_header`` writes the header on a fresh file and
    verifies identity on an existing one.

    ``read_only=True`` is the multi-process SPMD posture (rank-0-only
    journaling): non-zero ranks run the same deterministic driver loop
    over the SHARED journal — they must replay/verify it identically —
    but N ranks fsync-appending one file would interleave records and
    corrupt the stream, so only rank 0 writes. A read-only ledger keeps
    the full in-memory view (header checks, ``completed()``,
    ``record_trial`` bookkeeping) while never touching the file: no
    append handle, no torn-tail truncation (rank 0 owns repairs), no
    header/record writes.
    """

    def __init__(self, path: str, read_only: bool = False):
        self.path = os.path.abspath(path)
        self.read_only = bool(read_only)
        self.header: Optional[dict] = None
        self.records: list[dict] = []
        self.n_torn = 0
        self.n_torn_boundary = 0  # member records of a torn final boundary
        if os.path.exists(self.path) and os.path.getsize(self.path) > 0:
            self.header, self.records, self.n_torn = read_ledger(self.path)
            self._drop_torn_boundary()
            if (self.n_torn or self.n_torn_boundary) and not self.read_only:
                self._rewrite_complete_records()
        self._defer_fsync = False
        if self.read_only:
            self._file = None
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._file = open(self.path, "a")

    def _drop_torn_boundary(self) -> None:
        """Fused journals only: a partially-journaled FINAL boundary is
        the mid-journal-kill shape — drop its records so replay sees
        only complete boundaries (the interrupted boundary re-trains
        from its snapshot and re-journals identically; the ordering
        contract guarantees no snapshot covers the partial one). Any
        OTHER boundary damage cannot come from an append crash and
        refuses to load. Records are dropped from the in-memory view on
        every rank; only a writable (rank-0) ledger rewrites the file.
        """
        if not any("boundary" in r for r in self.records):
            return
        by_boundary, _sizes, problems, torn_final = scan_boundaries(self.records)
        if problems:
            raise LedgerError(
                f"{self.path}: fused boundary structure is damaged beyond "
                f"what an append crash can produce ({problems[0]}) — "
                "refusing to load"
            )
        if torn_final is None:
            return
        keep = [
            r for r in self.records
            if int(r.get("boundary", -1)) != torn_final
        ]
        self.n_torn_boundary += len(self.records) - len(keep)
        self.records = keep

    def drop_torn_boundary(self) -> int:
        """Self-heal a torn final boundary on an OPEN ledger: the
        in-process twin of the load-time truncation, for callers that
        re-enter a fused sweep with the same ledger object after an
        error escaped mid-boundary (the CLI's --retries does exactly
        this when a transient runtime failure strikes during a
        boundary's journaling) — without it, the re-run would
        misdiagnose the partial boundary as a sweep-shape divergence.
        Drops the records from memory AND rewrites the file (reopening
        the append handle — the rewrite replaces the inode). Returns
        how many records were dropped."""
        before = len(self.records)
        self._drop_torn_boundary()
        dropped = before - len(self.records)
        if dropped and not self.read_only and self._file is not None:
            self._file.close()
            self._rewrite_complete_records()
            self._file = open(self.path, "a")
        return dropped

    def _rewrite_complete_records(self) -> None:
        # keep exactly the bytes of the complete records (torn tail
        # fragment and torn-final-boundary lines dropped); the debris
        # must not prefix the next append
        good = [json.dumps(self.header)] if self.header else []
        good += [json.dumps(r) for r in self.records]
        # rewrite-then-replace, not open('w'): a second crash here must
        # not tear the GOOD records too
        tmp = f"{self.path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write("".join(line + "\n" for line in good))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- identity ----------------------------------------------------------

    @property
    def sweep_id(self) -> Optional[str]:
        return None if self.header is None else self.header.get("sweep_id")

    def ensure_header(self, config: dict, space_spec=None, objective_spec=None) -> None:
        """Write the header (fresh ledger) or verify it (existing one).

        ``config`` is the sweep's identity dict; on an existing ledger a
        mismatch on any shared key is refused — the caller is about to
        replay this journal through an algorithm configured differently
        than the one that wrote it.

        ``space_spec`` (``SearchSpace.spec()``) rides the header as a
        TOP-LEVEL key, deliberately outside ``config``: it is corpus
        metadata (the structural fingerprint ``corpus index`` uses for
        fuzzy matching between different-hash spaces), not identity —
        the hash in ``config`` already settles identity, and folding
        the spec into the checked dict would refuse every pre-upgrade
        ledger's resume over a key it never wrote.

        ``objective_spec`` (``ObjectiveSpec.spec()``, ISSUE 17) follows
        the same top-level pattern for multi-objective sweeps: the
        report/corpus layers read it to interpret each record's
        ``scores`` vector, while identity stays in ``config`` (the CLI
        puts the objective names there, so resuming a multi-objective
        ledger under different objectives is refused through the
        ordinary config gate). Scalar sweeps never write the key.
        """
        if self.header is not None:
            stale = {
                k: (self.header.get("config", {}).get(k), v)
                for k, v in config.items()
                if self.header.get("config", {}).get(k) != v
            }
            if stale:
                diff = ", ".join(
                    f"{k}: ledger={a!r} vs run={b!r}" for k, (a, b) in stale.items()
                )
                raise LedgerError(
                    f"ledger {self.path} was written by a different sweep "
                    f"({diff}) — resume with the original configuration or "
                    "point --ledger at a fresh path"
                )
            return
        self.header = {
            "kind": "header",
            "version": LEDGER_SCHEMA_VERSION,
            "sweep_id": uuid.uuid4().hex[:12],
            "config": dict(config),
            "created_ts": round(time.time(), 4),
        }
        if space_spec is not None:
            self.header["space_spec"] = space_spec
        if objective_spec is not None:
            self.header["objective_spec"] = objective_spec
        if not self.read_only:
            self._write_line(self.header)

    # -- append ------------------------------------------------------------

    @contextlib.contextmanager
    def batched(self):
        """Amortize the per-record fsync over a batch of appends: inside
        this block ``_write_line`` writes+flushes each record but defers
        the fsync; exit fsyncs ONCE, so the whole batch becomes durable
        together. This is the HTTP front door's journal-before-ack at
        batch granularity (the answer is published only after the block
        exits). Crash-safety shape: a kill mid-batch leaves a flushed
        prefix (page cache survives a process SIGKILL) and possibly a
        torn tail — exactly the damage the load-time torn-tail self-heal
        already recovers, and the client's idempotent retry re-journals
        whatever the prefix lost. Not reentrant; single-writer only
        (the front door's one executor thread)."""
        if self._defer_fsync:
            raise LedgerError("ledger.batched() does not nest")
        self._defer_fsync = True
        try:
            yield self
        finally:
            self._defer_fsync = False
            if self._file is not None:
                try:
                    self._file.flush()
                    os.fsync(self._file.fileno())
                except OSError as e:
                    from mpi_opt_tpu.utils import resources

                    if resources.is_storage_full(e):
                        raise resources.StorageFull(
                            "ledger batch fsync hit a full disk; free "
                            "disk space and relaunch with --resume",
                            path=self.path,
                        ) from e
                    raise

    def record_trial(
        self,
        result: TrialResult,
        canonical_params: dict,
        attempts: int = 1,
        cached: bool = False,
        meta: Optional[dict] = None,
    ) -> dict:
        """Journal one FINAL result; durable (fsync) before returning.

        Traced as one ``journal`` span per record (the driver path's
        per-trial fsync — fused member records instead share one span
        per boundary in train/common.journal_boundary, where a pop-1024
        generation would otherwise emit 1024 span lines)."""
        from mpi_opt_tpu.obs import trace

        if self.header is None:
            raise LedgerError("ledger has no header — call ensure_header first")
        score = float(result.score)
        rec = {
            "kind": "trial",
            "sweep_id": self.sweep_id,
            "trial_id": int(result.trial_id),
            "params": canonical_params,
            "status": result.status,
            # JSON has no NaN: non-finite scores journal as null, and
            # status carries the failure; result_from_record restores
            # the NaN-family score via failed_result
            "score": score if np.isfinite(score) else None,
            "step": int(result.step),
            "error": result.error,
            "attempts": int(attempts),
            "wall_s": round(float(result.wall_time), 4),
            "cached": bool(cached),
            "ts": round(time.time(), 4),
        }
        if meta:
            # extra provenance keys (the front door's idem_key/idem_op)
            # ride the record but may not shadow the trial schema
            for k, v in meta.items():
                if k not in rec:
                    rec[k] = v
        if not self.read_only:
            with trace.span("journal", n=1):
                self._write_line(rec)
        # read-only ranks still track the record in memory: completed()
        # and the dedup views must agree with rank 0's across the gang
        self.records.append(rec)
        return rec

    def record_member(
        self,
        *,
        trial_id: int,
        member: int,
        boundary: int,
        boundary_size: int,
        canonical_params: dict,
        score,
        step: int,
        scores=None,
    ) -> dict:
        """Journal one fused population member's boundary evaluation
        (``ledger/fused.py`` drives this); durable before returning.

        Status derives from the score's finiteness — the same rule the
        fused trainers' member-failure tallies apply: a non-finite
        member score is the fused divergence failure, journaled as
        ``failed`` with a null score so JSON stays strict.

        ``scores`` (optional raw objective vector, ISSUE 17): a
        non-finite value in ANY objective makes the whole record
        ``failed`` with null score/scores — the scalar ``score``
        remains authoritative (it is the spec-scalarized value), the
        vector rides beside it for the Pareto consumers. Scalar sweeps
        never pass it, so their records carry no ``scores`` key at all
        and stay byte-identical to pre-17 journaling.
        """
        if self.header is None:
            raise LedgerError("ledger has no header — call ensure_header first")
        score = float(score)
        finite = np.isfinite(score)
        if scores is not None:
            vec = [float(s) for s in scores]
            finite = finite and all(np.isfinite(v) for v in vec)
        rec = {
            "kind": "trial",
            "sweep_id": self.sweep_id,
            "trial_id": int(trial_id),
            "member": int(member),
            "boundary": int(boundary),
            "boundary_size": int(boundary_size),
            "params": canonical_params,
            "status": "ok" if finite else "failed",
            "score": score if finite else None,
            "step": int(step),
            "error": None
            if finite
            else (
                "non-finite member score"
                if scores is None
                else "non-finite member objective"
            ),
            "attempts": 1,
            # member evaluations share one fused boundary program; no
            # per-member wall exists (the boundary's wall lives in the
            # sweep result's launch_walls/gen_walls)
            "wall_s": 0.0,
            "cached": False,
            "ts": round(time.time(), 4),
        }
        if scores is not None:
            rec["scores"] = vec if finite else None
        if not self.read_only:
            self._write_line(rec)
        self.records.append(rec)
        return rec

    def _write_line(self, rec: dict) -> None:
        from mpi_opt_tpu.utils import resources

        try:
            # chaos seam (inject_enospc): inside the append+fsync path
            # so drills strike exactly where a real full disk would
            resources.disk_fault("ledger_fsync", self.path)
            self._file.write(json.dumps(rec) + "\n")
            self._file.flush()
            if not self._defer_fsync:
                os.fsync(self._file.fileno())
        except OSError as e:
            if resources.is_storage_full(e):
                # a full disk is an ANSWER, not a retryable blip: park
                # with the classified type (CLI -> EX_IOERR=74). The
                # append may have torn this line — the torn-tail
                # self-heal already recovers exactly that shape on the
                # post-free --resume
                raise resources.StorageFull(
                    "ledger journal append hit a full disk; free disk "
                    "space and relaunch with --resume",
                    path=self.path,
                ) from e
            raise

    # -- replay view -------------------------------------------------------

    def completed(self) -> dict[int, dict]:
        """trial_id -> FINAL record (ok or failed) for replay-resume."""
        return {int(r["trial_id"]): r for r in self.records}

    def ok_records(self) -> Sequence[dict]:
        return [r for r in self.records if r["status"] == "ok"]

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
