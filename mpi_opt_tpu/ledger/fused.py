"""Boundary-granular member journaling for fused on-device sweeps.

The fused drivers (train/fused_{pbt,asha,tpe,bohb}.py) evaluate whole
populations inside XLA programs, so there is no per-trial host loop to
journal from — their durable history used to live only in orbax
snapshots at launch/rung granularity. ``FusedJournal`` closes that gap:
at every natural boundary (PBT generation, SHA/BOHB rung, TPE batch)
rank 0 journals ONE record per population member into the same
versioned ``SweepLedger`` schema the driver path uses — member id,
canonical params (decoded from the member's unit row), score, budget,
and a status derived from the score's finiteness (the same non-finite
rule the fused member-failure tallies apply).

Ordering contract (the fused twin of the driver's fsync-before-report
invariant): a boundary's records are journaled BEFORE that boundary's
snapshot is saved, so the journal can never lag the snapshot it will
be replayed against. Consequences:

- the only append-crash damage shape is a torn FINAL boundary (no
  snapshot covers it — ``SweepLedger`` truncates it on load and the
  resumed sweep re-trains + re-journals it);
- on resume, every boundary the restored snapshot records as complete
  must already be fully journaled (``require_prefix``) — a journal
  BEHIND its snapshot is a hole in the audit trail that nothing can
  reconstruct, and is refused;
- a boundary that is re-computed on resume but already journaled is
  VERIFIED against the journal instead of re-written (fused resumes
  are deterministic): any divergence raises ``LedgerError``. The
  snapshot stays authoritative for optimizer state; the ledger stays
  authoritative for the audit trail.

Offsets make one ledger span composite sweeps: fused hyperband/BOHB
run one ``fused_sha`` per bracket, each journaling under its bracket's
``boundary_offset`` (global rung index), ``trial_offset`` (global
record index) and ``member_offset`` (global trial identity), so the
whole sweep reads as one contiguous boundary sequence.
"""

from __future__ import annotations

import numpy as np

from mpi_opt_tpu.ledger.store import LedgerError, SweepLedger, scan_boundaries


class FusedJournal:
    """One fused sweep's (or bracket's) member-granular journal view."""

    def __init__(
        self,
        ledger: SweepLedger,
        space,
        boundary_offset: int = 0,
        trial_offset: int = 0,
        member_offset: int = 0,
    ):
        self.ledger = ledger
        self.space = space
        self.boundary_offset = int(boundary_offset)
        self.trial_offset = int(trial_offset)
        self.member_offset = int(member_offset)
        self.written = 0  # member records appended this session
        self.verified = 0  # member records re-verified on resume
        # a fresh load already refused structurally-damaged journals and
        # truncated a torn final boundary — but an OPEN ledger re-entered
        # after an error escaped mid-boundary (the CLI's --retries path)
        # still holds the partial boundary in memory: apply the same
        # self-heal here, so the retry re-journals it instead of
        # misreading it as a sweep-shape divergence
        ledger.drop_torn_boundary()
        self._by_boundary, self._sizes, _problems, _torn = scan_boundaries(
            ledger.records
        )

    # -- resume consistency ------------------------------------------------

    def complete_prefix(self) -> int:
        """The largest N with boundaries [0, N) all fully journaled."""
        n = 0
        while n in self._by_boundary and len(self._by_boundary[n]) == self._sizes[n]:
            n += 1
        return n

    def boundary_done(self, b_local: int) -> bool:
        b = self.boundary_offset + int(b_local)
        return b in self._by_boundary and len(self._by_boundary[b]) == self._sizes[b]

    def require_prefix(self, n_local: int) -> None:
        """Refuse a resume whose snapshot is AHEAD of the journal: the
        snapshot records ``n_local`` boundaries (past this journal
        view's offset) complete, but the journal does not hold them all
        — an audit hole the sweep cannot reconstruct (those boundaries
        will never be re-computed). The inverse — journal ahead of
        snapshot — is fine: the re-trained boundaries verify against
        their records."""
        need = self.boundary_offset + int(n_local)
        have = self.complete_prefix()
        if have < need:
            raise LedgerError(
                f"{self.ledger.path}: snapshot records {need} boundaries "
                f"complete but only {have} are fully journaled — the ledger "
                "lags the snapshot it should never lag (mixed files, or a "
                "ledger attached mid-sweep). Point --ledger at the journal "
                "this sweep has written from its start, or at a fresh path "
                "without --resume"
            )

    # -- the per-boundary service point ------------------------------------

    def record_boundary(
        self, b_local: int, members, units, scores, step: int, scores_mo=None
    ) -> None:
        """Journal (or verify) one boundary's member records.

        ``members`` are the boundary's member identities (local — the
        journal applies ``member_offset``), ``units`` their unit-cube
        rows, ``scores`` their evaluation scores, ``step`` the budget
        the scores were measured at. First visit appends one fsync'd
        record per member; a re-computed boundary (resume) verifies
        status/score against the journal instead — divergence raises
        ``LedgerError`` (the journal belongs to a different trajectory).

        ``scores_mo`` (optional ``[n, m]`` raw objective matrix, ISSUE
        17) rides each record as its ``scores`` vector; ``scores``
        stays the authoritative scalarized value, so every scalar
        resume/fsck/warm-start consumer reads a multi-objective journal
        unchanged.
        """
        b = self.boundary_offset + int(b_local)
        members = [int(m) for m in np.asarray(members).tolist()]
        scores = np.asarray(scores, dtype=np.float64)
        units = np.asarray(units)
        if scores_mo is not None:
            scores_mo = np.asarray(scores_mo, dtype=np.float64)
        existing = self._by_boundary.get(b)
        if existing is not None:
            self._verify(b, members, scores, scores_mo)
            return
        # trial ids are the journal's record ordinals, derived from the
        # already-journaled boundaries of THIS view so a resume that
        # skipped straight past completed boundaries still numbers
        # identically to an uninterrupted run
        base = self.trial_offset + sum(
            len(self._by_boundary[k])
            for k in self._by_boundary
            if self.boundary_offset <= k < b
        )
        grp: dict[int, dict] = {}
        for i, m in enumerate(members):
            rec = self.ledger.record_member(
                trial_id=base + i,
                member=self.member_offset + m,
                boundary=b,
                boundary_size=len(members),
                canonical_params=self.space.canonical_params(
                    self.space.materialize_row(units[i])
                ),
                score=scores[i],
                step=step,
                scores=None if scores_mo is None else scores_mo[i],
            )
            grp[self.member_offset + m] = rec
        self._by_boundary[b] = grp
        self._sizes[b] = len(members)
        self.written += len(members)

    def _verify(self, b: int, members, scores, scores_mo=None) -> None:
        """The resume cross-check: a re-computed boundary must match its
        journal. Scores compare with a small tolerance (resumes are
        bit-identical on CPU, documented-equivalent where accelerator
        compiled-shape rounding differs); member sets and statuses
        compare exactly. When the re-computed boundary carries objective
        vectors, each journaled ``scores`` vector verifies the same way
        (a vector is only journaled on ok records, so nothing compares
        on failed ones)."""
        existing = self._by_boundary[b]
        if len(existing) != len(members):
            raise LedgerError(
                f"boundary {b}: journal holds {len(existing)} member records "
                f"but the sweep re-computed {len(members)} — the ledger "
                "belongs to a different sweep shape"
            )
        for i, m in enumerate(members):
            mg = self.member_offset + int(m)
            rec = existing.get(mg)
            if rec is None:
                raise LedgerError(
                    f"boundary {b}: member {mg} re-computed but not in the "
                    "journal — member sets diverge"
                )
            s = float(scores[i])
            finite = np.isfinite(s)
            if scores_mo is not None:
                finite = finite and bool(np.all(np.isfinite(scores_mo[i])))
            status = "ok" if finite else "failed"
            if rec["status"] != status:
                raise LedgerError(
                    f"boundary {b} member {mg}: journaled status "
                    f"{rec['status']!r} but the re-computed score is "
                    f"{s!r} — the ledger diverges from this sweep's "
                    "trajectory (different seed/config/data?)"
                )
            if status == "ok" and not np.isclose(
                float(rec["score"]), s, rtol=1e-5, atol=1e-6
            ):
                raise LedgerError(
                    f"boundary {b} member {mg}: journaled score "
                    f"{rec['score']} but re-computed {s} — the ledger "
                    "diverges from this sweep's trajectory"
                )
            if (
                status == "ok"
                and scores_mo is not None
                and rec.get("scores") is not None
            ):
                want = np.asarray([float(v) for v in rec["scores"]])
                got = np.asarray(scores_mo[i], dtype=np.float64)
                if want.shape != got.shape or not np.allclose(
                    want, got, rtol=1e-5, atol=1e-6
                ):
                    raise LedgerError(
                        f"boundary {b} member {mg}: journaled objective "
                        f"vector {want.tolist()} but re-computed "
                        f"{got.tolist()} — the ledger diverges from this "
                        "sweep's trajectory"
                    )
        self.verified += len(members)


def make_journal(ledger, space, **offsets):
    """``FusedJournal`` over ``ledger``, or None when no ledger is
    active — the one construction point the fused drivers share."""
    if ledger is None:
        return None
    return FusedJournal(ledger, space, **offsets)
