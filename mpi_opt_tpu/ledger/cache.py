"""Exact-match params -> result memo over ledger history.

HPO drivers re-see the same point more often than intuition suggests:
a killed driver re-suggests its deterministic stream on resume, TPE
exploitation collapses onto near-identical optima (discrete spaces make
them EXACTLY identical), and operators re-run sweeps with overlapping
seeds. An evaluation whose params match a journaled ok record to the
canonical byte is the same deterministic computation — skip it and
serve the recorded result.

Only ``ok`` results are ever cached: a failure may be transient (the
whole point of FailurePolicy retries), so serving a recorded failure
would make one unlucky worker death permanent for those params.

``CorpusCache`` (ISSUE 14) is the corpus-backed generalization: the
exact-hit semantics (params key + budget) stay byte-identical to
``EvalCache``'s, and a SECOND, separate lookup serves near matches —
the same params evaluated at a DIFFERENT budget, or a fuzzy-matched
prior record — as cheap low-fidelity EVIDENCE (``extra={"fidelity":
"prior"}``), never as a result substitute: a prior's score informs an
acquisition model or a client's triage, but the driver still pays for
the real evaluation.
"""

from __future__ import annotations

from typing import Optional, Sequence

from mpi_opt_tpu.space import SearchSpace
from mpi_opt_tpu.trial import TrialResult


class EvalCache:
    """params-key -> (score, step, wall_s), keyed canonically.

    The budget is part of the key: an ASHA trial evaluated to step 10 is
    NOT the same computation as the same params run to step 270, so a
    hit requires both the canonical params AND the granted budget to
    match the recorded evaluation's reached step.
    """

    def __init__(self, space: SearchSpace):
        self.space = space
        self._memo: dict[tuple[str, int], dict] = {}
        self.hits = 0

    def _key(self, params: dict, budget: int) -> tuple[str, int]:
        return (self.space.params_key(params), int(budget))

    def seed_from(self, records: Sequence[dict]) -> int:
        """Load ok trial records (ledger JSON shape); returns count."""
        n = 0
        for rec in records:
            if rec.get("status") != "ok" or rec.get("score") is None:
                continue
            self._memo[self._key(rec["params"], rec["step"])] = {
                "score": float(rec["score"]),
                "step": int(rec["step"]),
                "wall_s": float(rec.get("wall_s") or 0.0),
            }
            n += 1
        return n

    def put(self, params: dict, result: TrialResult) -> None:
        if not result.ok:
            return  # never cache non-ok results
        self._memo[self._key(params, result.step)] = {
            "score": float(result.score),
            "step": int(result.step),
            "wall_s": float(result.wall_time),
        }

    def get(self, params: dict, budget: int, trial_id: int) -> Optional[TrialResult]:
        """A hit, rebuilt as an ok result under the asking trial's id."""
        found = self._memo.get(self._key(params, budget))
        if found is None:
            return None
        self.hits += 1
        return TrialResult(
            trial_id=trial_id,
            score=found["score"],
            step=found["step"],
            wall_time=0.0,  # the recorded wall was paid by the original
            extra={"cache_hit": True, "cached_wall_s": found["wall_s"]},
        )

    def __len__(self) -> int:
        return len(self._memo)


class CorpusCache(EvalCache):
    """EvalCache plus a near-match prior view over corpus history.

    Two stores, two truths: the inherited exact memo answers "this
    exact computation already ran" (``get``, unchanged to the byte);
    the prior store answers "this POINT has been seen at some budget"
    (``get_prior``) — same-space/different-budget records, and
    fuzzy-matched records another space's ledger contributed, keyed by
    canonical params alone. A prior is evidence, not a result: it
    carries ``extra={"fidelity": "prior"}`` and the budget it was
    actually measured at, and callers (the suggestion service's
    ``lookup`` op, acquisition warm starts) must treat it as a
    low-fidelity hint, never journal it as this sweep's evaluation.
    Highest-budget evidence wins when one point was seen at several
    budgets — the closest thing the corpus holds to the truth.
    """

    def __init__(self, space: SearchSpace):
        super().__init__(space)
        self._prior: dict[str, dict] = {}
        self.prior_hits = 0

    def seed_prior(self, records: Sequence[dict], fuzzy: bool = False) -> int:
        """Load ok records as near-match evidence; returns count added.

        ``fuzzy=True`` marks records contributed by a different-hash
        (fingerprint-matched) ledger — they never displace same-space
        evidence for the same point, and the served extra says which
        kind of prior the caller is leaning on."""
        n = 0
        for rec in records:
            if rec.get("status") != "ok" or rec.get("score") is None:
                continue
            try:
                key = self.space.params_key(rec["params"])
            except KeyError:
                continue  # fuzzy record missing a live dim: not evidence here
            cur = self._prior.get(key)
            if cur is not None and (
                (cur["fuzzy"] is False and fuzzy)
                or (cur["fuzzy"] == fuzzy and cur["step"] >= int(rec["step"]))
            ):
                continue
            self._prior[key] = {
                "score": float(rec["score"]),
                "step": int(rec["step"]),
                "fuzzy": bool(fuzzy),
            }
            n += 1
        return n

    def get_prior(self, params: dict, trial_id: int) -> Optional[TrialResult]:
        """Near-match evidence for ``params`` at ANY budget, or None.

        The result is deliberately NOT ok-shaped-for-substitution: the
        score/step are the prior evaluation's, ``extra`` declares the
        fidelity, and the caller decides what a low-fidelity fact is
        worth. Exact hits are the exclusive business of ``get``."""
        try:
            found = self._prior.get(self.space.params_key(params))
        except KeyError:
            return None
        if found is None:
            return None
        self.prior_hits += 1
        return TrialResult(
            trial_id=trial_id,
            score=found["score"],
            step=found["step"],
            wall_time=0.0,
            extra={
                "fidelity": "prior",
                "prior_kind": "fuzzy" if found["fuzzy"] else "budget",
            },
        )

    def put(self, params: dict, result: TrialResult) -> None:
        super().put(params, result)
        if result.ok:
            # a live ok result is same-space evidence for the prior
            # view too (newer and never fuzzy, so it wins per the
            # seed_prior rule applied directly)
            key = self.space.params_key(params)
            cur = self._prior.get(key)
            if cur is None or cur["fuzzy"] or cur["step"] <= int(result.step):
                self._prior[key] = {
                    "score": float(result.score),
                    "step": int(result.step),
                    "fuzzy": False,
                }

    @property
    def n_prior(self) -> int:
        return len(self._prior)
