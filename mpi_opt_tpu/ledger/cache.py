"""Exact-match params -> result memo over ledger history.

HPO drivers re-see the same point more often than intuition suggests:
a killed driver re-suggests its deterministic stream on resume, TPE
exploitation collapses onto near-identical optima (discrete spaces make
them EXACTLY identical), and operators re-run sweeps with overlapping
seeds. An evaluation whose params match a journaled ok record to the
canonical byte is the same deterministic computation — skip it and
serve the recorded result.

Only ``ok`` results are ever cached: a failure may be transient (the
whole point of FailurePolicy retries), so serving a recorded failure
would make one unlucky worker death permanent for those params.
"""

from __future__ import annotations

from typing import Optional, Sequence

from mpi_opt_tpu.space import SearchSpace
from mpi_opt_tpu.trial import TrialResult


class EvalCache:
    """params-key -> (score, step, wall_s), keyed canonically.

    The budget is part of the key: an ASHA trial evaluated to step 10 is
    NOT the same computation as the same params run to step 270, so a
    hit requires both the canonical params AND the granted budget to
    match the recorded evaluation's reached step.
    """

    def __init__(self, space: SearchSpace):
        self.space = space
        self._memo: dict[tuple[str, int], dict] = {}
        self.hits = 0

    def _key(self, params: dict, budget: int) -> tuple[str, int]:
        return (self.space.params_key(params), int(budget))

    def seed_from(self, records: Sequence[dict]) -> int:
        """Load ok trial records (ledger JSON shape); returns count."""
        n = 0
        for rec in records:
            if rec.get("status") != "ok" or rec.get("score") is None:
                continue
            self._memo[self._key(rec["params"], rec["step"])] = {
                "score": float(rec["score"]),
                "step": int(rec["step"]),
                "wall_s": float(rec.get("wall_s") or 0.0),
            }
            n += 1
        return n

    def put(self, params: dict, result: TrialResult) -> None:
        if not result.ok:
            return  # never cache non-ok results
        self._memo[self._key(params, result.step)] = {
            "score": float(result.score),
            "step": int(result.step),
            "wall_s": float(result.wall_time),
        }

    def get(self, params: dict, budget: int, trial_id: int) -> Optional[TrialResult]:
        """A hit, rebuilt as an ok result under the asking trial's id."""
        found = self._memo.get(self._key(params, budget))
        if found is None:
            return None
        self.hits += 1
        return TrialResult(
            trial_id=trial_id,
            score=found["score"],
            step=found["step"],
            wall_time=0.0,  # the recorded wall was paid by the original
            extra={"cache_hit": True, "cached_wall_s": found["wall_s"]},
        )

    def __len__(self) -> int:
        return len(self._memo)
