"""Durable sweep ledger: journaled trial history (SURVEY.md §5).

The coordinator's trial history IS the product of a long HPO sweep, and
this package makes it durable at TRIAL granularity: ``store.SweepLedger``
appends one fsync'd JSONL record per FINAL TrialResult, the driver
replays completed records through the algorithm on resume
(``run_search(ledger=...)``), ``cache.EvalCache`` skips re-evaluating
exactly-seen params, ``warmstart`` feeds a prior sweep's ledger into a
new algorithm as observations, and ``report`` renders one-or-many
ledgers for operators. Coarser-grained orbax snapshots
(``utils.checkpoint``) keep backend/train-state duty; the ledger covers
the gap between them — a crash between snapshots loses no completed
evaluation.
"""

from mpi_opt_tpu.ledger.cache import CorpusCache, EvalCache
from mpi_opt_tpu.ledger.fused import FusedJournal, make_journal
from mpi_opt_tpu.ledger.store import (
    LEDGER_SCHEMA_VERSION,
    LedgerError,
    SweepLedger,
    read_ledger,
    scan_boundaries,
    validate_ledger,
)
from mpi_opt_tpu.ledger.warmstart import warm_start

__all__ = [
    "CorpusCache",
    "EvalCache",
    "FusedJournal",
    "LEDGER_SCHEMA_VERSION",
    "LedgerError",
    "SweepLedger",
    "make_journal",
    "read_ledger",
    "scan_boundaries",
    "validate_ledger",
    "warm_start",
]
