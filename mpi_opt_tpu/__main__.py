from mpi_opt_tpu.cli import main

raise SystemExit(main())
