"""Shared array idioms for the decision kernels."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rank_descending(scores: jax.Array, valid: jax.Array | None = None):
    """Dense descending rank of each score (0 = best).

    Invalid entries (and NaNs, which sort last under jnp.argsort) rank
    after all valid finite entries.

    Returns:
        (rank: int32[n], order: int32[n]) — ``order`` sorts scores
        descending; ``rank = argsort(order)`` is its inverse.
    """
    masked = scores if valid is None else jnp.where(valid, scores, -jnp.inf)
    order = jnp.argsort(-masked)
    rank = jnp.argsort(order)
    return rank, order
