"""Fused GroupNorm(+ReLU) as a Pallas TPU kernel, with custom VJP.

Why this exists (PERF_NOTES round 4 → round 5): the config-3 ledger
refuted a Pallas GN for the SmallCNN (C=32 pays a 4x lane-fill penalty
and XLA was already within 1.33x of the 5-pass bandwidth floor), but
flagged the calculus as different for C >= 128 — exactly ResNet-18's
stages (64..512 channels). Two structural wins are available there:

1. **Pass count.** XLA compiles GN fwd+bwd to ~6.7 full activation
   passes (measured, probe_gn_floor2). This kernel's contract is the
   analytic minimum: fwd reads x and writes y (2 passes; group stats
   ride along in VMEM), bwd reads x and dy and writes dx (3 passes) —
   the ReLU mask is RECOMPUTED from (x, mean, rstd, gamma, beta)
   inside the bwd kernel instead of re-reading y, so the fused
   GN+ReLU pair costs the same 5 passes a bare GN floors at.
2. **Fusion.** ReLU (and its backward mask) disappears into the same
   passes — XLA fuses elementwise chains well, but the relu backward's
   extra y read survives in its schedules.

Layout: channel-last ``[B, H, W, C]`` activations (the models-package
convention), one sample per grid step; the whole per-sample activation
fits VMEM at every ResNet-18 stage (max 128 KB bf16 at stage 0).
Stats are computed in f32 regardless of the activation dtype (same as
``flax.linen.GroupNorm``'s default promotion). ``C % num_groups == 0``
is required, as in flax.

``pl.pallas_call`` has a batching rule, so the population trainer's
``vmap`` over members simply prepends a grid dimension — one kernel
serves the vmapped population path unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_INTERPRET = False  # tests flip this for CPU interpret-mode runs


def _group_matrices(c: int, groups: int):
    """(M [c,g], MT [g,c]) 0/1 group-membership matrices, built from
    2-D iota inside the kernel. Grouped channel reductions become tiny
    f32 matmuls ([1,c]@[c,g] collapse, [1,g]@[g,c] broadcast-back):
    Mosaic cannot shape-cast the LANE dimension (reshape [s,c] ->
    [s,g,gs] fails to lower), and matmul against a membership matrix is
    both supported and exact in f32."""
    gs = c // groups
    ci = jax.lax.broadcasted_iota(jnp.int32, (c, groups), 0)
    gi = jax.lax.broadcasted_iota(jnp.int32, (c, groups), 1)
    m = (ci // gs == gi).astype(jnp.float32)
    cit = jax.lax.broadcasted_iota(jnp.int32, (groups, c), 1)
    git = jax.lax.broadcasted_iota(jnp.int32, (groups, c), 0)
    mt = (cit // gs == git).astype(jnp.float32)
    return m, mt


def _fwd_kernel(x_ref, gamma_ref, beta_ref, y_ref, mean_ref, rstd_ref,
                *, groups: int, eps: float, relu: bool):
    """One block of B samples: y = [relu](gn(x)); per-sample group
    stats ride along ([B,s,c] blocks — per-sample grids drowned in
    grid-step overhead, measured 2.2x WORSE end-to-end)."""
    bb, s, c = x_ref.shape
    x = x_ref[:].astype(jnp.float32)  # [bb, s, c]
    m, mt = _group_matrices(c, groups)
    n = s * (c // groups)
    colsum = jnp.sum(x, axis=1)  # [bb, c]
    colsq = jnp.sum(jnp.square(x), axis=1)
    mean = jnp.dot(colsum, m, preferred_element_type=jnp.float32) / n  # [bb, g]
    var = jnp.dot(colsq, m, preferred_element_type=jnp.float32) / n - jnp.square(mean)
    rstd = jax.lax.rsqrt(var + eps)
    meanc = jnp.dot(mean, mt, preferred_element_type=jnp.float32)  # [bb, c]
    rstdc = jnp.dot(rstd, mt, preferred_element_type=jnp.float32)
    gamma = gamma_ref[:].astype(jnp.float32)  # [1, c]
    beta = beta_ref[:].astype(jnp.float32)
    y = (x - meanc[:, None, :]) * rstdc[:, None, :] * gamma[None, :, :] + beta[None, :, :]
    if relu:
        y = jnp.maximum(y, 0.0)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean.reshape(bb, 1, groups)
    rstd_ref[:] = rstd.reshape(bb, 1, groups)


def _bwd_kernel(x_ref, dy_ref, gamma_ref, beta_ref, mean_ref, rstd_ref,
                dx_ref, dgamma_ref, dbeta_ref,
                *, groups: int, relu: bool):
    """One sample: dx plus THIS sample's dgamma/dbeta partials.

    The ReLU mask is recomputed from the saved stats (z > 0 with
    z = gamma*xhat + beta) rather than re-read from y — that is the
    pass the fusion saves.
    """
    bb, s, c = x_ref.shape
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    gamma = gamma_ref[:].astype(jnp.float32)  # [1, c]
    m, mt = _group_matrices(c, groups)
    n = s * (c // groups)
    mean = mean_ref[:].reshape(bb, groups)
    rstd = rstd_ref[:].reshape(bb, groups)
    meanc = jnp.dot(mean, mt, preferred_element_type=jnp.float32)[:, None, :]
    rstdc = jnp.dot(rstd, mt, preferred_element_type=jnp.float32)[:, None, :]
    xhat = (x - meanc) * rstdc
    if relu:
        z = xhat * gamma[None, :, :] + beta_ref[:].astype(jnp.float32)[None, :, :]
        dy = jnp.where(z > 0.0, dy, 0.0)
    dgamma_ref[:] = jnp.sum(dy * xhat, axis=1).reshape(bb, 1, c)
    dbeta_ref[:] = jnp.sum(dy, axis=1).reshape(bb, 1, c)
    # dz = dy * gamma; per group: dx = rstd*(dz - mean(dz) - xhat*mean(dz*xhat))
    dz = dy * gamma[None, :, :]
    s1 = jnp.dot(jnp.sum(dz, axis=1), m, preferred_element_type=jnp.float32)
    s2 = jnp.dot(jnp.sum(dz * xhat, axis=1), m, preferred_element_type=jnp.float32)
    m1c = jnp.dot(s1 / n, mt, preferred_element_type=jnp.float32)[:, None, :]
    m2c = jnp.dot(s2 / n, mt, preferred_element_type=jnp.float32)[:, None, :]
    dx = rstdc * (dz - m1c - xhat * m2c)
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _flatten(x):
    b = x.shape[0]
    c = x.shape[-1]
    return x.reshape(b, -1, c)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def group_norm_relu(x, gamma, beta, groups: int = 32, eps: float = 1e-6,
                    relu: bool = True):
    """Fused GroupNorm(+ReLU) over channel-last ``[B, ..., C]``."""
    y, _, _ = _forward(x, gamma, beta, groups, eps, relu)
    return y


def _block_rows(b: int, s: int, c: int, elems: int = 1 << 19) -> int:
    """Samples per block: the largest divisor of b keeping the block's
    f32 working set near ~4 MB of VMEM (x + y + temporaries fit the
    ~16 MB budget with double buffering)."""
    # elems: per-buffer f32 element budget. Measured ceilings on the
    # v5e's 16 MB scoped vmem: fwd [16,1024,64] OOMed at 16.03M (so
    # fwd runs at 1<<19 ~ 2MB/buffer); bwd carries x AND dy AND dx
    # plus their f32 copies and OOMed at 23.7M with fwd's budget, so
    # it runs at 1<<18
    target = max(1, elems // (s * c))
    bb = 1
    for cand in range(1, b + 1):
        if b % cand == 0 and cand <= target:
            bb = cand
    return bb


def _forward(x, gamma, beta, groups, eps, relu):
    xf = _flatten(x)
    b, s, c = xf.shape
    if c % groups:
        # _group_matrices floor-divides (gs = c // groups): a
        # non-dividing group count would build a wrong membership
        # matrix and silently normalize over the wrong channels —
        # refuse exactly where flax.linen.GroupNorm does
        raise ValueError(
            f"number of channels ({c}) must be divisible by num_groups "
            f"({groups})"
        )
    bb = _block_rows(b, s, c)
    g2 = gamma.reshape(1, c)
    b2 = beta.reshape(1, c)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, groups=groups, eps=eps, relu=relu),
        grid=(b // bb,),
        in_specs=[
            pl.BlockSpec((bb, s, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bb, s, c), lambda i: (i, 0, 0)),
            # singleton middle axis: Mosaic requires the block's last
            # two dims to be (8,128)-divisible OR equal to the array's —
            # [b,1,G] blocks as (bb,1,G) satisfy the 'equal' arm (and
            # keep doing so under vmap's prepended member dimension)
            pl.BlockSpec((bb, 1, groups), lambda i: (i, 0, 0)),
            pl.BlockSpec((bb, 1, groups), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, s, c), x.dtype),
            jax.ShapeDtypeStruct((b, 1, groups), jnp.float32),
            jax.ShapeDtypeStruct((b, 1, groups), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(xf, g2, b2)
    return y.reshape(x.shape), mean, rstd


def _fwd_rule(x, gamma, beta, groups, eps, relu):
    y, mean, rstd = _forward(x, gamma, beta, groups, eps, relu)
    return y, (x, gamma, beta, mean, rstd)


def _bwd_rule(groups, eps, relu, res, dy):
    x, gamma, beta, mean, rstd = res
    xf = _flatten(x)
    dyf = _flatten(dy)
    b, s, c = xf.shape
    bb = _block_rows(b, s, c, elems=1 << 18)
    g2 = gamma.reshape(1, c)
    be2 = beta.reshape(1, c)
    dx, dgamma, dbeta = pl.pallas_call(
        functools.partial(_bwd_kernel, groups=groups, relu=relu),
        grid=(b // bb,),
        in_specs=[
            pl.BlockSpec((bb, s, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((bb, s, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((bb, 1, groups), lambda i: (i, 0, 0)),
            pl.BlockSpec((bb, 1, groups), lambda i: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bb, s, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((bb, 1, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((bb, 1, c), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, s, c), x.dtype),
            jax.ShapeDtypeStruct((b, 1, c), jnp.float32),
            jax.ShapeDtypeStruct((b, 1, c), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(xf, dyf, g2, be2, mean, rstd)
    # the tiny [B, C] partial reduction stays in XLA: it is bytes-free
    # relative to the activation passes and fuses with whatever follows
    return (
        dx.reshape(x.shape),
        jnp.sum(dgamma, axis=(0, 1)).astype(gamma.dtype),
        jnp.sum(dbeta, axis=(0, 1)).astype(beta.dtype),
    )


group_norm_relu.defvjp(_fwd_rule, _bwd_rule)


def reference_group_norm_relu(x, gamma, beta, groups=32, eps=1e-6, relu=True):
    """Pure-jnp reference for correctness tests."""
    b = x.shape[0]
    c = x.shape[-1]
    xf = x.reshape(b, -1, c).astype(jnp.float32)
    s = xf.shape[1]
    xg = xf.reshape(b, s, groups, c // groups)
    mean = xg.mean(axis=(1, 3), keepdims=True)
    var = xg.var(axis=(1, 3), keepdims=True)
    xhat = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(b, s, c)
    y = xhat * gamma.astype(jnp.float32) + beta.astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.reshape(x.shape).astype(x.dtype)
