"""ASHA rung reduction as an on-device top-k.

Reference behavior (SURVEY.md §2 row 4; reference unreadable): ASHA
promotes the top 1/eta of trials at each rung to the next budget level
and early-stops the rest, asynchronously across MPI ranks. On TPU the
whole rung cohort is one population axis, so the reduction is a single
``lax.top_k`` — no Allgather, no host round-trip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from mpi_opt_tpu.ops.common import rank_descending


def asha_rungs(min_budget: int, max_budget: int, eta: int) -> list[int]:
    """Budget ladder [min_budget, min_budget*eta, ...] up to max_budget."""
    if min_budget < 1 or eta < 2 or max_budget < min_budget:
        raise ValueError("need min_budget>=1, eta>=2, max_budget>=min_budget")
    rungs = []
    b = min_budget
    while b < max_budget:
        rungs.append(b)
        b *= eta
    rungs.append(max_budget)
    return rungs


def asha_cut(scores: jax.Array, eta: int, valid: jax.Array | None = None):
    """Select the top ceil(n_valid/eta) of a rung cohort.

    Args:
        scores: ``float32[n]`` objective values, higher is better.
        eta: reduction factor (>=2).
        valid: optional ``bool[n]``; invalid slots never promote.

    Returns:
        (promote: bool[n], order: int32[n]) — ``promote[i]`` is True iff
        member i survives the cut; ``order`` is the index array sorting
        scores descending (useful for gathers). Jittable; ``n`` and
        ``eta`` are static.
    """
    n = scores.shape[0]
    if valid is None:
        valid = jnp.ones((n,), dtype=bool)
    rank, order = rank_descending(scores, valid)
    n_valid = jnp.sum(valid)
    k = jnp.ceil(n_valid / eta).astype(jnp.int32)  # dynamic but bounded by n
    promote = (rank < k) & valid
    return promote, order


def asha_cut_mo(
    norm_scores: jax.Array,  # float32[n, m] maximize-form objective matrix
    eta: int,
    valid: jax.Array | None = None,
    norm_bounds=None,  # float32[m] maximize-form bounds, or None
):
    """Multi-objective rung cut: promote by Pareto rank, not scalar top-k.

    The cohort is ranked by :func:`~mpi_opt_tpu.objectives.pareto.
    pareto_score` (non-dominated front, then crowding distance, with
    constraint-aware degradation below every feasible member) and the
    same top-``ceil(n_valid/eta)`` rule as :func:`asha_cut` applies to
    that effective scalar — one compiled reduction, no host
    round-trip. Returns ``(promote, order, eff)`` where ``eff`` is the
    effective ``float32[n]`` selection score (also the rung's
    journaled-scalar tiebreak witness).
    """
    from mpi_opt_tpu.objectives.pareto import pareto_score

    eff = pareto_score(norm_scores, valid=valid, norm_bounds=norm_bounds)
    promote, order = asha_cut(eff, eta, valid)
    return promote, order, eff


def asha_top_k_dense(scores: jax.Array, k: int):
    """Static-k variant for fully-populated rungs: plain ``lax.top_k``."""
    vals, idx = lax.top_k(scores, k)
    return vals, idx
