"""Tree-structured Parzen Estimator with a fully vectorized acquisition.

Reference behavior (SURVEY.md §2 row 6; reference unreadable): TPE
splits observed trials into good/bad by a score quantile, fits Parzen
KDEs l(x) (good) and g(x) (bad), and suggests points maximizing
l(x)/g(x).

TPU-native design decisions:

- **Fixed-shape observation buffer.** Observations live in a ring buffer
  ``obs_unit: float32[M, d]`` with ``valid: bool[M]`` so the whole
  suggest step compiles ONCE (no recompiles as history grows — the
  classic Python TPE refits sklearn KDEs per call).
- **Vectorized acquisition.** Candidates are sampled from the good
  mixture and all scored in one batched computation (the config-4
  workload: score thousands of candidates per suggest). The density
  evaluation is a single ``[C, M, d]`` broadcast — MXU/VPU friendly,
  no Python loop over candidates.
- Everything is in unit-cube space; discrete dims are smoothed as
  continuous here and re-quantized by ``Domain.from_unit`` at the edge.

Bandwidths use Silverman's rule per dim over the respective subset,
floored to keep the mixture proper when points coincide.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from mpi_opt_tpu.ops.common import rank_descending


@dataclasses.dataclass(frozen=True)
class TPEConfig:
    gamma: float = 0.25  # top quantile regarded as "good"
    n_candidates: int = 1024  # candidates scored per suggest call
    # Minimum KDE bandwidth in unit space. Deliberately wide: Silverman
    # on a converged good-set collapses, and a collapsed l(x) can never
    # propose outside the incumbent cluster (on quadratic + branin test
    # functions, floor 0.15 beat 0.03 by ~7x in final regret).
    bw_floor: float = 0.15
    bw_scale: float = 1.06  # Silverman factor
    prior_weight: float = 1.0  # weight of the uniform prior component
    # Fraction of candidates drawn uniformly from the cube rather than
    # from the good mixture. Without this the search self-traps: once a
    # cluster of observations forms, candidates only appear near it and
    # unexplored regions (whose acquisition log((nb+1)/(ng+1)) > 0 is
    # competitive) are never even scored.
    uniform_frac: float = 0.1
    # Batched-suggest diversity. This framework's suggest batches are
    # population-sized; a plain top-k of one candidate set returns
    # near-duplicates from the acquisition's strongest mode (k similar
    # trials = k-1 wasted evaluations). With diversify_bw > 0 selection
    # is greedy-with-repulsion: after each pick, candidates within
    # ~diversify_bw (unit space) are penalized by a Gaussian bump of
    # height diversify_weight (in acquisition log-units), so later
    # picks come from distinct modes. n_suggest=1 is unaffected.
    diversify_bw: float = 0.1
    diversify_weight: float = 5.0


def _masked_moments(x, w):
    """Weighted mean/std along axis 0. w: [M] nonneg, x: [M, d]."""
    wsum = jnp.maximum(w.sum(), 1e-9)
    mean = (w[:, None] * x).sum(0) / wsum
    var = (w[:, None] * (x - mean) ** 2).sum(0) / wsum
    return mean, jnp.sqrt(var)


def _log_mixture(x, centers, w, bw, prior_weight):
    """log density of x under masked Gaussian mixture + uniform prior.

    x: [C, d]; centers: [M, d]; w: [M] (0 for invalid); bw: [d].
    Uniform-on-[0,1] prior acts as one extra component with weight
    ``prior_weight`` (its log-density is 0 per dim).
    Returns [C].
    """
    # [C, M, d] broadcast — the hot tensor; C and M are static.
    z = (x[:, None, :] - centers[None, :, :]) / bw[None, None, :]
    log_comp = (-0.5 * z**2 - jnp.log(bw)[None, None, :] - 0.5 * jnp.log(2 * jnp.pi)).sum(-1)
    logw = jnp.where(w > 0, jnp.log(jnp.maximum(w, 1e-30)), -jnp.inf)
    total = w.sum() + prior_weight
    # prior component: log-density 0 over the unit cube
    stacked = jnp.concatenate(
        [log_comp + logw[None, :], jnp.full((x.shape[0], 1), jnp.log(prior_weight + 1e-30))],
        axis=1,
    )
    return jax.scipy.special.logsumexp(stacked, axis=1) - jnp.log(total)


def tpe_suggest(
    key: jax.Array,
    obs_unit: jax.Array,  # float32[M, d] ring buffer of observed points
    obs_scores: jax.Array,  # float32[M], higher is better
    valid: jax.Array,  # bool[M]
    n_suggest: int,
    cfg: TPEConfig = TPEConfig(),
):
    """Suggest ``n_suggest`` unit-cube points maximizing l(x)/g(x).

    Fully jittable with static shapes; with an empty buffer it degrades
    gracefully to uniform sampling through the prior component.

    Returns:
        suggestions: float32[n_suggest, d]
        acq: float32[n_suggest] — log l - log g of each suggestion.
    """
    M, d = obs_unit.shape
    k_uni, k_pick, k_jitter = jax.random.split(key, 3)

    n_valid = valid.sum()
    n_good = jnp.maximum(1, jnp.ceil(cfg.gamma * n_valid)).astype(jnp.int32)

    rank, _ = rank_descending(obs_scores, valid)
    good_w = ((rank < n_good) & valid).astype(jnp.float32)
    bad_w = ((rank >= n_good) & valid).astype(jnp.float32)

    # Silverman bandwidth per subset, per dim (floored)
    def bw_of(w):
        m = jnp.maximum(w.sum(), 1.0)
        _, std = _masked_moments(obs_unit, w)
        return jnp.clip(cfg.bw_scale * std * m ** (-1.0 / (d + 4)), cfg.bw_floor, 1.0)

    bw_g, bw_b = bw_of(good_w), bw_of(bad_w)

    # sample candidates from the good mixture (+ prior): pick a good
    # center (or the prior) proportionally to weight, add bw noise.
    total_g = good_w.sum() + cfg.prior_weight
    probs = jnp.concatenate([good_w, jnp.array([cfg.prior_weight])]) / total_g
    comp = jax.random.choice(k_pick, M + 1, (cfg.n_candidates,), p=probs)
    centers = jnp.concatenate([obs_unit, jnp.full((1, d), 0.5)], axis=0)[comp]
    widths = jnp.where((comp < M)[:, None], bw_g[None, :], 0.5)  # prior ~ wide
    cand = centers + jax.random.normal(k_jitter, (cfg.n_candidates, d)) * widths
    # exploration quota: first uniform_frac of candidates are uniform draws
    n_uni = int(round(cfg.n_candidates * cfg.uniform_frac))
    is_uni = (jnp.arange(cfg.n_candidates) < n_uni)[:, None]
    cand = jnp.where(is_uni, jax.random.uniform(k_uni, (cfg.n_candidates, d)), cand)
    cand = jnp.clip(cand, 0.0, 1.0)

    acq = _log_mixture(cand, obs_unit, good_w, bw_g, cfg.prior_weight) - _log_mixture(
        cand, obs_unit, bad_w, bw_b, cfg.prior_weight
    )
    if n_suggest > 1 and cfg.diversify_bw > 0:
        top_idx = _diverse_top_k(cand, acq, n_suggest, cfg.diversify_bw, cfg.diversify_weight)
        return cand[top_idx], acq[top_idx]
    top_acq, top_idx = jax.lax.top_k(acq, n_suggest)
    return cand[top_idx], top_acq


def _diverse_top_k(cand, acq, k: int, bw: float, weight: float):
    """Greedy diversified selection: argmax, repel, repeat.

    A scan of k steps over the [C] acquisition vector; each pick
    subtracts a Gaussian repulsion (height ``weight``, width ``bw`` in
    unit space) around itself, so the running argmax walks distinct
    acquisition modes instead of re-picking one mode's shoulder.
    Returns int32[k] candidate indices (first pick == plain argmax).
    """

    def pick(acq_cur, _):
        i = jnp.argmax(acq_cur)
        d2 = ((cand - cand[i]) ** 2).sum(-1)
        penalty = weight * jnp.exp(-0.5 * d2 / (bw * bw))
        acq_cur = (acq_cur - penalty).at[i].set(-jnp.inf)
        return acq_cur, i

    _, idx = jax.lax.scan(pick, acq, None, length=k)
    return idx
