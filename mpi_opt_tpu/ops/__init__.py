"""Pure-array decision kernels (the device-side half of each algorithm).

In the reference, search decisions (ASHA rung cuts, PBT exploit/explore,
TPE acquisition) happen host-side after an ``MPI_Allgather`` of scores
(SURVEY.md §3; reference unreadable — contract from BASELINE.json).
Here each decision is a pure function over arrays so it can run *inside*
the jitted population step on TPU: scores never leave the chip between
generations, and the decision costs one ``lax.top_k`` instead of a
collective + host round-trip.

All kernels follow the convention **higher score is better**; callers
negate losses.
"""

from mpi_opt_tpu.ops.asha import asha_cut, asha_rungs
from mpi_opt_tpu.ops.pbt import pbt_exploit_explore, PBTConfig
from mpi_opt_tpu.ops.tpe import tpe_suggest, TPEConfig

__all__ = [
    "asha_cut",
    "asha_rungs",
    "pbt_exploit_explore",
    "PBTConfig",
    "tpe_suggest",
    "TPEConfig",
]
