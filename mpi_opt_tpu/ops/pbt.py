"""PBT exploit/explore as pure array ops over a population axis.

Reference behavior (SURVEY.md §2 row 5; reference unreadable): PBT ranks
the population after each generation; the bottom truncation-fraction
copies weights + hyperparameters from a random top performer (exploit)
and perturbs the copied hyperparameters (explore). In the reference this
is an ``MPI_Allgather`` of scores followed by per-rank decisions and
point-to-point weight transfers.

TPU-native design: the decision is computed here as a source-index map
``src_idx: int32[n]`` — member i should continue from member
``src_idx[i]``'s state (``src_idx[i] == i`` for survivors). The backend
then realises the exploit as ONE gather along the population axis:

    pop_state = jax.tree.map(lambda x: x[src_idx], pop_state)

which XLA lowers to an on-device gather (or an all-to-all over a sharded
mesh axis) — weights never touch the host.

Explore perturbs in unit-cube space: continuous dims get truncated
Gaussian noise (equivalently a multiplicative perturbation for
log-uniform domains, since they are log-affine in unit space); discrete
dims resample with probability ``resample_prob``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from mpi_opt_tpu.ops.common import rank_descending


@dataclasses.dataclass(frozen=True)
class PBTConfig:
    truncation_frac: float = 0.25  # bottom frac exploits, top frac is source pool
    perturb_scale: float = 0.15  # stddev of unit-space Gaussian perturbation
    resample_prob: float = 0.1  # per-discrete-dim chance to resample on explore


def pbt_exploit_explore(
    key: jax.Array,
    unit: jax.Array,  # float32[n, d] population hparams, unit cube
    scores: jax.Array,  # float32[n], higher is better
    discrete_mask: jax.Array,  # bool[d]
    cfg: PBTConfig = PBTConfig(),
):
    """One PBT generation decision.

    Returns:
        new_unit: float32[n, d] — hparams after exploit+explore.
        src_idx: int32[n] — state-source map for the weight gather.
        exploited: bool[n] — which members were replaced.

    Fully jittable; ``n``, ``d`` and ``cfg`` are static.
    """
    return _exploit_explore(key, unit, scores, discrete_mask, cfg)


def pbt_exploit_explore_mo(
    key: jax.Array,
    unit: jax.Array,  # float32[n, d]
    norm_scores: jax.Array,  # float32[n, m] maximize-form objective matrix
    discrete_mask: jax.Array,  # bool[d]
    cfg: PBTConfig = PBTConfig(),
    norm_bounds=None,  # float32[m] maximize-form bounds, or None
):
    """Multi-objective PBT decision: truncation-exploit by Pareto rank.

    Identical mechanics to :func:`pbt_exploit_explore` — same key
    splits, same truncation/perturb/resample ops — except the
    population is ranked by :func:`~mpi_opt_tpu.objectives.pareto.
    pareto_score` (non-dominated front, then crowding, with
    constraint-aware degradation) instead of a scalar. Stays a single
    compiled boundary op. Returns the scalar triple plus the effective
    selection scores ``float32[n]`` for observability.
    """
    from mpi_opt_tpu.objectives.pareto import pareto_score

    eff = pareto_score(norm_scores, norm_bounds=norm_bounds)
    new_unit, src_idx, bottom = _exploit_explore(
        key, unit, eff, discrete_mask, cfg
    )
    return new_unit, src_idx, bottom, eff


def _exploit_explore(key, unit, scores, discrete_mask, cfg):
    """Shared exploit/explore body; ``scores`` is whatever effective
    scalar ranks the population (raw score, or a Pareto effective
    score). The op sequence here is the PR-16 scalar sequence verbatim
    — the scalar path's bit-identity (PERF_NOTES round 6) hangs on the
    key-split order and op order not changing."""
    n, d = unit.shape
    k_src, k_noise, k_resample, k_resample_val = jax.random.split(key, 4)

    n_cut = max(1, int(round(n * cfg.truncation_frac)))
    rank, order = rank_descending(scores)

    bottom = rank >= (n - n_cut)  # losers: exploit
    # each member draws a uniformly-random member of the top cut
    src_choice = order[jax.random.randint(k_src, (n,), 0, n_cut)]
    src_idx = jnp.where(bottom, src_choice, jnp.arange(n))

    copied = unit[src_idx]

    # explore: truncated-Gaussian jitter on continuous dims
    noise = jax.random.normal(k_noise, (n, d)) * cfg.perturb_scale
    perturbed = jnp.clip(copied + noise, 0.0, 1.0)
    # discrete dims: occasional uniform resample instead of jitter
    resample = jax.random.uniform(k_resample, (n, d)) < cfg.resample_prob
    fresh = jax.random.uniform(k_resample_val, (n, d))
    disc = jnp.where(resample, fresh, copied)
    explored = jnp.where(discrete_mask[None, :], disc, perturbed)

    new_unit = jnp.where(bottom[:, None], explored, unit)
    return new_unit, src_idx, bottom
