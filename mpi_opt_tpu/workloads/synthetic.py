"""Synthetic analytic workload for tests and algorithm benchmarks.

Gradient descent on the quadratic loss 0.5*||w||^2 has the closed form
w_t = w_0 * (1 - lr)^t — convergent for lr in (0, 2), optimal at lr=1.
Training ``steps`` is therefore O(1) regardless of budget, which makes
this workload ideal for exercising ASHA budget ladders, PBT inheritance
and TPE convergence without any real compute. Score = -loss (higher is
better), with a mild penalty making ``reg`` matter too.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from mpi_opt_tpu.space import LogUniform, SearchSpace, Uniform
from mpi_opt_tpu.workloads import register
from mpi_opt_tpu.workloads.base import Workload


@dataclasses.dataclass
class QuadState:
    w: np.ndarray
    steps: int = 0


@register
class Quadratic(Workload):
    name = "quadratic"

    def __init__(self, dim: int = 8):
        self.dim = dim

    def default_space(self) -> SearchSpace:
        return SearchSpace(
            {
                "lr": LogUniform(1e-3, 4.0),  # upper range diverges: real failure mode
                "reg": Uniform(0.0, 1.0),
            }
        )

    def init_state(self, params: dict, seed: int) -> QuadState:
        rng = np.random.default_rng(seed)
        return QuadState(w=rng.normal(size=self.dim).astype(np.float64))

    def train(self, state: QuadState, params: dict, steps: int, seed: int):
        lr = float(params["lr"])
        reg = float(params["reg"])
        factor = (1.0 - lr) ** steps  # may exceed 1 in magnitude: divergence
        # cap to keep scores finite even for wildly divergent members
        w = np.clip(state.w * factor, -1e6, 1e6)
        new = QuadState(w=w, steps=state.steps + steps)
        loss = 0.5 * float(np.sum(w**2)) + 0.1 * (reg - 0.3) ** 2
        return new, -loss
