"""Config-4 workload: surrogate-model sweeps on UCI tabular data.

BASELINE.json configs[3]: "Vectorized TPE acquisition, 256-trial
surrogate-model sweep on UCI tabular". The tunable surrogate is a small
MLP over tabular features (sklearn's offline UCI-derived sets — wine,
breast_cancer; see data package docstring for the no-network policy).
The interesting half of this config is the TPE side: the acquisition
scores thousands of candidates in one batched computation
(ops/tpe.py), and trials are cheap, so suggest-throughput dominates.
"""

from __future__ import annotations

from mpi_opt_tpu.models import MLP
from mpi_opt_tpu.space import LogUniform, SearchSpace, Uniform
from mpi_opt_tpu.workloads import register
from mpi_opt_tpu.workloads.base import PopulationWorkload


@register
class TabularMLP(PopulationWorkload):
    name = "tabular_mlp"
    dataset = "breast_cancer"
    batch_size = 128
    augment = False
    default_n_train = None  # sklearn sets have fixed sizes
    default_n_val = None

    def __init__(self, dataset: str = "breast_cancer"):
        super().__init__()
        self.dataset = dataset
        if dataset not in ("breast_cancer", "wine"):
            raise ValueError(
                f"tabular_mlp supports classification sets breast_cancer/wine, got {dataset!r}"
            )

    def _model(self, n_classes):
        return MLP(hidden=64, n_classes=n_classes)

    def default_space(self) -> SearchSpace:
        return SearchSpace(
            {
                "lr": LogUniform(1e-4, 1.0),
                "momentum": Uniform(0.0, 0.99),
                "weight_decay": LogUniform(1e-7, 1e-1),
            }
        )
