"""NN vision workloads: configs 2 (MLP/Fashion-MNIST) and 3 (CNN/CIFAR-10).

The population protocol + CPU parity path live in
``workloads.base.PopulationWorkload``; these classes bind model,
dataset, and search space. The space covers optimizer + augmentation-
schedule hparams; PBT mutates all of them (BASELINE config 3: "lr + aug
schedule").
"""

from __future__ import annotations

from mpi_opt_tpu.models import MLP, ResNet18, SmallCNN
from mpi_opt_tpu.space import LogUniform, SearchSpace, Uniform
from mpi_opt_tpu.workloads import register
from mpi_opt_tpu.workloads.base import PopulationWorkload


class _VisionWorkload(PopulationWorkload):
    def default_space(self) -> SearchSpace:
        return SearchSpace(
            {
                "lr": LogUniform(1e-3, 1.0),
                "momentum": Uniform(0.5, 0.99),
                "weight_decay": LogUniform(1e-6, 1e-2),
                "flip_prob": Uniform(0.0, 0.5),
                "shift": Uniform(0.0, 4.0),
            }
        )


@register
class FashionMLP(_VisionWorkload):
    """Config 2: 2-layer MLP on (synthetic) Fashion-MNIST."""

    name = "fashion_mlp"
    dataset = "fashion_mnist"

    def _model(self, n_classes):
        return MLP(hidden=128, n_classes=n_classes)


@register
class Cifar10CNN(_VisionWorkload):
    """Config 3: small CNN on (synthetic) CIFAR-10 — the PBT target."""

    name = "cifar10_cnn"
    dataset = "cifar10"

    def _model(self, n_classes):
        return SmallCNN(n_classes=n_classes)


@register
class Cifar100CNN(_VisionWorkload):
    """CIFAR-100-shaped variant of the small CNN (cheap stand-in)."""

    name = "cifar100_cnn"
    dataset = "cifar100"

    def _model(self, n_classes):
        return SmallCNN(n_classes=n_classes, width=64)


@register
class Cifar100ResNet18(_VisionWorkload):
    """Config 5: ResNet-18 on (synthetic) CIFAR-100, PBT pop=1024.

    The full population only fits HBM sharded over a mesh's 'pop' axis
    or capped per chip — see models/resnet.py for the memory math.
    ``remat`` (on by default) bounds activation memory so the population
    cap is set by param+momentum residency, not by the backward pass;
    ``width``/``stage_sizes`` shrink the model for CPU-mesh dry runs.
    """

    name = "cifar100_resnet18"
    dataset = "cifar100"
    batch_size = 128

    def __init__(self, n_train=None, n_val=None, width: int = 64, remat: bool = True):
        super().__init__(n_train=n_train, n_val=n_val)
        self.width = width
        self.remat = remat

    def _model(self, n_classes):
        return ResNet18(n_classes=n_classes, width=self.width, remat=self.remat)
