"""NN workloads: configs 2 (MLP/Fashion-MNIST) and 3 (CNN/CIFAR-10).

Each exposes both evaluation protocols:
- the population protocol (``make_trainer``/``make_hparams``/``data``)
  consumed by the TPU backend — the fast path;
- the generic stateless ``evaluate`` (single member, n=1 population) so
  the same workload runs on the CPU process-pool backend, which is the
  in-container stand-in for the reference's per-rank MPI evaluation and
  the baseline bench.py compares against.

The search space covers optimizer + augmentation-schedule hparams; PBT
mutates all of them (BASELINE config 3: "lr + aug schedule").
"""

from __future__ import annotations

import numpy as np

from mpi_opt_tpu.data import load_dataset
from mpi_opt_tpu.models import MLP, SmallCNN
from mpi_opt_tpu.space import LogUniform, SearchSpace, Uniform
from mpi_opt_tpu.train import OptHParams, PopulationTrainer
from mpi_opt_tpu.workloads import register
from mpi_opt_tpu.workloads.base import Workload


class _VisionWorkload(Workload):
    dataset: str = ""
    batch_size: int = 256
    augment: bool = True

    def __init__(self, n_train: int = 16384, n_val: int = 2048):
        self.n_train = n_train
        self.n_val = n_val
        self._data = None

    # -- population protocol ---------------------------------------------

    def _model(self, n_classes: int):
        raise NotImplementedError

    def data(self) -> dict:
        if self._data is None:
            self._data = load_dataset(self.dataset, n_train=self.n_train, n_val=self.n_val)
        return self._data

    def make_trainer(self, member_chunk: int = 0) -> PopulationTrainer:
        model = self._model(self.data()["n_classes"])
        return PopulationTrainer(
            apply_fn=lambda params, x: model.apply({"params": params}, x),
            init_fn=lambda rng, sample_x: model.init(rng, sample_x)["params"],
            batch_size=self.batch_size,
            augment=self.augment,
            member_chunk=member_chunk,
        )

    def make_hparams(self, values: dict) -> OptHParams:
        """Typed value arrays (from SearchSpace.from_unit) -> OptHParams."""
        import jax.numpy as jnp

        zeros = jnp.zeros_like(values["lr"])
        return OptHParams(
            lr=values["lr"],
            momentum=values["momentum"],
            weight_decay=values["weight_decay"],
            flip_prob=values.get("flip_prob", zeros),
            shift=values.get("shift", zeros),
        )

    def default_space(self) -> SearchSpace:
        return SearchSpace(
            {
                "lr": LogUniform(1e-3, 1.0),
                "momentum": Uniform(0.5, 0.99),
                "weight_decay": LogUniform(1e-6, 1e-2),
                "flip_prob": Uniform(0.0, 0.5),
                "shift": Uniform(0.0, 4.0),
            }
        )

    # -- stateless protocol (CPU pool parity path) -----------------------

    def evaluate(self, params: dict, budget: int, seed: int) -> float:
        """Single-trial from-scratch training (the per-rank unit of work
        in the reference's MPI design); n=1 population on whatever
        backend jax defaults to in this process (CPU in pool workers).

        The trainer and device-resident arrays are cached on the
        instance: train_segment is jitted with ``self`` static, so a
        fresh trainer per call would recompile every trial.
        """
        import jax
        import jax.numpy as jnp

        if not hasattr(self, "_eval_cache"):
            d = self.data()
            self._eval_cache = (
                self.make_trainer(),
                self.default_space(),
                jnp.asarray(d["train_x"]),
                jnp.asarray(d["train_y"]),
                jnp.asarray(d["val_x"]),
                jnp.asarray(d["val_y"]),
            )
        trainer, unit_space, train_x, train_y, val_x, val_y = self._eval_cache
        row = unit_space.params_to_unit(params)
        values = unit_space.from_unit(jnp.asarray(row)[None, :])
        hp = self.make_hparams(values)
        key = jax.random.key(seed)
        k_init, k_train = jax.random.split(key)
        state = trainer.init_population(k_init, train_x[:2], 1)
        state, _ = trainer.train_segment(state, hp, train_x, train_y, k_train, int(budget))
        acc = trainer.eval_population(state, val_x, val_y)
        return float(acc[0])


@register
class FashionMLP(_VisionWorkload):
    """Config 2: 2-layer MLP on (synthetic) Fashion-MNIST."""

    name = "fashion_mlp"
    dataset = "fashion_mnist"

    def _model(self, n_classes):
        return MLP(hidden=128, n_classes=n_classes)


@register
class Cifar10CNN(_VisionWorkload):
    """Config 3: small CNN on (synthetic) CIFAR-10 — the PBT target."""

    name = "cifar10_cnn"
    dataset = "cifar10"

    def _model(self, n_classes):
        return SmallCNN(n_classes=n_classes)


@register
class Cifar100CNN(_VisionWorkload):
    """CIFAR-100-shaped variant (config 5 uses ResNet-18; see resnet.py)."""

    name = "cifar100_cnn"
    dataset = "cifar100"

    def _model(self, n_classes):
        return SmallCNN(n_classes=n_classes, width=64)
