"""NN vision workloads: configs 2 (MLP/Fashion-MNIST) and 3 (CNN/CIFAR-10).

The population protocol + CPU parity path live in
``workloads.base.PopulationWorkload``; these classes bind model,
dataset, and search space. The space covers optimizer + augmentation-
schedule hparams; PBT mutates all of them (BASELINE config 3: "lr + aug
schedule").
"""

from __future__ import annotations

from mpi_opt_tpu.models import MLP, ResNet18, SmallCNN
from mpi_opt_tpu.space import LogUniform, SearchSpace, Uniform
from mpi_opt_tpu.workloads import register
from mpi_opt_tpu.workloads.base import PopulationWorkload


class _VisionWorkload(PopulationWorkload):
    def default_space(self) -> SearchSpace:
        return SearchSpace(
            {
                "lr": LogUniform(1e-3, 1.0),
                "momentum": Uniform(0.5, 0.99),
                "weight_decay": LogUniform(1e-6, 1e-2),
                "flip_prob": Uniform(0.0, 0.5),
                "shift": Uniform(0.0, 4.0),
            }
        )


@register
class FashionMLP(_VisionWorkload):
    """Config 2: 2-layer MLP on (synthetic) Fashion-MNIST."""

    name = "fashion_mlp"
    dataset = "fashion_mnist"

    def _model(self, n_classes):
        return MLP(hidden=128, n_classes=n_classes)


@register
class Cifar10CNN(_VisionWorkload):
    """Config 3: small CNN on (synthetic) CIFAR-10 — the PBT target."""

    name = "cifar10_cnn"
    dataset = "cifar10"

    def _model(self, n_classes):
        return SmallCNN(n_classes=n_classes)


@register
class Cifar100CNN(_VisionWorkload):
    """CIFAR-100-shaped variant of the small CNN (cheap stand-in)."""

    name = "cifar100_cnn"
    dataset = "cifar100"

    def _model(self, n_classes):
        return SmallCNN(n_classes=n_classes, width=64)


@register
class Cifar100ResNet18(_VisionWorkload):
    """Config 5: ResNet-18 on (synthetic) CIFAR-100, PBT pop=1024.

    The full population only fits HBM sharded over a mesh's 'pop' axis
    or capped per chip — see models/resnet.py for the memory math.
    ``remat`` is OFF by default since round 5: at the measured
    single-chip envelope (pop<=64, member_chunk=8) the stored-backward
    activations fit alongside the pool, and dropping the recompute is
    an 18% segment-wall win (18.98 -> 15.53 s; full fused 2-gen sweep
    42.1 -> 35.3 s, PERF_NOTES round 5). Turn it back on for heavier
    per-chip loads (bigger member_chunk x batch, or if a future chip
    cap raises the resident population). ``width``/``stage_sizes``
    shrink the model for CPU-mesh dry runs.
    """

    name = "cifar100_resnet18"
    dataset = "cifar100"
    batch_size = 128

    def __init__(
        self,
        n_train=None,
        n_val=None,
        width: int = 64,
        remat: bool = False,
        pallas_gn: bool = False,
    ):
        super().__init__(n_train=n_train, n_val=n_val)
        self.width = width
        self.remat = remat
        # pallas_gn swaps nn.GroupNorm for the fused Pallas GN+ReLU
        # kernel (ops/pallas_gn.py). Constructor-only, no env hook: a
        # hidden env switch could silently change model numerics across
        # a checkpoint resume (the param trees are identical by design,
        # so nothing would refuse). Measured 1.86x SLOWER than XLA's GN
        # at these shapes (PERF_NOTES round 5) — kept as the tested
        # Pallas exhibit, not a recommended path.
        self.pallas_gn = pallas_gn

    def _model(self, n_classes):
        return ResNet18(
            n_classes=n_classes, width=self.width, remat=self.remat,
            pallas_gn=self.pallas_gn,
        )
