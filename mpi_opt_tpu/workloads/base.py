"""Workload protocol."""

from __future__ import annotations

import abc
from typing import Any, Optional

from mpi_opt_tpu.space import SearchSpace


class Workload(abc.ABC):
    """A tunable training task.

    Subclasses must implement ``default_space`` and at least one of the
    two evaluation protocols. ``evaluate`` has a default implementation
    in terms of the stateful protocol.
    """

    name: str = "base"

    @abc.abstractmethod
    def default_space(self) -> SearchSpace:
        ...

    # -- stateful protocol (optional) ------------------------------------

    def init_state(self, params: dict, seed: int) -> Any:
        raise NotImplementedError(f"{self.name} has no stateful protocol")

    def train(self, state: Any, params: dict, steps: int, seed: int):
        """Advance training by ``steps``; returns (state, score)."""
        raise NotImplementedError(f"{self.name} has no stateful protocol")

    @property
    def stateful(self) -> bool:
        return type(self).train is not Workload.train

    # -- stateless protocol ----------------------------------------------

    def evaluate(self, params: dict, budget: int, seed: int) -> float:
        state = self.init_state(params, seed)
        _, score = self.train(state, params, budget, seed)
        return float(score)
