"""Workload protocol."""

from __future__ import annotations

import abc
from typing import Any, Optional

from mpi_opt_tpu.space import SearchSpace


class Workload(abc.ABC):
    """A tunable training task.

    Subclasses must implement ``default_space`` and at least one of the
    two evaluation protocols. ``evaluate`` has a default implementation
    in terms of the stateful protocol.
    """

    name: str = "base"

    @abc.abstractmethod
    def default_space(self) -> SearchSpace:
        ...

    # -- stateful protocol (optional) ------------------------------------

    def init_state(self, params: dict, seed: int) -> Any:
        raise NotImplementedError(f"{self.name} has no stateful protocol")

    def train(self, state: Any, params: dict, steps: int, seed: int):
        """Advance training by ``steps``; returns (state, score)."""
        raise NotImplementedError(f"{self.name} has no stateful protocol")

    @property
    def stateful(self) -> bool:
        return type(self).train is not Workload.train

    # -- stateless protocol ----------------------------------------------

    def evaluate(self, params: dict, budget: int, seed: int) -> float:
        state = self.init_state(params, seed)
        _, score = self.train(state, params, budget, seed)
        return float(score)

    # -- multi-objective protocol (ISSUE 17) ------------------------------

    def objective_metrics(self) -> tuple[str, ...]:
        """Metric names the workload's multi-metric eval path can
        produce (empty = scalar-only). An ``--objectives`` spec must
        draw every name from this set; the CLI validates before
        anything compiles."""
        return ()

    def evaluate_multi(self, params: dict, budget: int, seed: int, names) -> dict:
        """Stateless multi-metric evaluation: ``{name: float}`` for the
        requested metric names (each from ``objective_metrics``)."""
        raise NotImplementedError(f"{self.name} has no multi-metric eval path")


def resolve_momentum_dtype():
    """The single resolution point for the momentum STORAGE dtype knob
    (probes/probe_bf16_momentum.py A/B): the env var, else None (= match
    params, f32). workload_arrays' trainer cache key and make_trainer
    must see the SAME value — resolving it twice independently is how a
    stale-dtype trainer gets silently served from the cache. The value
    is normalized through ``jnp.dtype`` so alias spellings ('f4',
    'float32') compare equal in checkpoint configs and cache keys."""
    import os

    raw = os.environ.get("MPI_OPT_TPU_MOMENTUM_DTYPE")
    if not raw:
        return None
    import jax.numpy as jnp

    return str(jnp.dtype(raw))


class PopulationWorkload(Workload):
    """Workloads evaluable as rows of a vmapped population (NN models).

    Subclasses set ``dataset``, ``batch_size``, ``augment`` and implement
    ``_model(n_classes)``; they get the population protocol consumed by
    the TPU backend (``data``/``make_trainer``/``make_hparams``) plus a
    stateless ``evaluate`` (n=1 population, runs on whatever platform the
    process defaults to — CPU in pool workers), which is the per-rank
    parity path mirroring the reference's MPIWorker unit of work.
    """

    dataset: str = ""
    batch_size: int = 256
    augment: bool = True
    # synthetic sets are subsettable; sklearn loaders have fixed sizes
    # (subclasses with fixed-size data set these to None)
    default_n_train: int | None = 16384
    default_n_val: int | None = 2048

    def __init__(self, n_train: int | None = None, n_val: int | None = None):
        self.n_train = n_train if n_train is not None else self.default_n_train
        self.n_val = n_val if n_val is not None else self.default_n_val
        self._data = None

    def _model(self, n_classes: int):
        raise NotImplementedError

    def data(self) -> dict:
        if self._data is None:
            from mpi_opt_tpu.data import load_dataset

            kwargs = {}
            if self.n_train is not None:
                kwargs = {"n_train": self.n_train, "n_val": self.n_val}
            self._data = load_dataset(self.dataset, **kwargs)
        return self._data

    def make_trainer(
        self, member_chunk: int = 0, donate: bool = True, mesh=None, momentum_dtype=None
    ):
        import jax.numpy as jnp

        from mpi_opt_tpu.train import PopulationTrainer

        model = self._model(self.data()["n_classes"])
        if momentum_dtype is None:
            momentum_dtype = resolve_momentum_dtype()
        return PopulationTrainer(
            apply_fn=lambda params, x: model.apply({"params": params}, x),
            init_fn=lambda rng, sample_x: model.init(rng, sample_x)["params"],
            batch_size=self.batch_size,
            augment=self.augment,
            member_chunk=member_chunk,
            donate=donate,
            mesh=mesh,
            momentum_dtype=jnp.dtype(momentum_dtype) if momentum_dtype else None,
        )

    def make_hparams(self, values: dict):
        import jax.numpy as jnp

        from mpi_opt_tpu.train import OptHParams

        zeros = jnp.zeros_like(values["lr"])
        return OptHParams(
            lr=values["lr"],
            momentum=values["momentum"],
            weight_decay=values["weight_decay"],
            flip_prob=values.get("flip_prob", zeros),
            shift=values.get("shift", zeros),
        )

    def _eval_state(self, params: dict, budget: int, seed: int):
        """Shared n=1 from-scratch training for the stateless eval paths."""
        import jax
        import jax.numpy as jnp

        if not hasattr(self, "_eval_cache"):
            d = self.data()
            self._eval_cache = (
                self.make_trainer(),
                self.default_space(),
                jnp.asarray(d["train_x"]),
                jnp.asarray(d["train_y"]),
                jnp.asarray(d["val_x"]),
                jnp.asarray(d["val_y"]),
            )
        trainer, unit_space, train_x, train_y, val_x, val_y = self._eval_cache
        row = unit_space.params_to_unit(params)
        values = unit_space.from_unit(jnp.asarray(row)[None, :])
        hp = self.make_hparams(values)
        key = jax.random.key(seed)
        k_init, k_train = jax.random.split(key)
        state = trainer.init_population(k_init, train_x[:2], 1)
        state, _ = trainer.train_segment(state, hp, train_x, train_y, k_train, int(budget))
        return trainer, state, val_x, val_y

    def evaluate(self, params: dict, budget: int, seed: int) -> float:
        """Single-trial from-scratch training; see class docstring.

        The trainer and device arrays are cached on the instance —
        train_segment is jitted with ``self`` static, so a fresh trainer
        per call would recompile every trial.
        """
        trainer, state, val_x, val_y = self._eval_state(params, budget, seed)
        acc = trainer.eval_population(state, val_x, val_y)
        return float(acc[0])

    def objective_metrics(self) -> tuple[str, ...]:
        from mpi_opt_tpu.train.common import POPULATION_METRICS

        return POPULATION_METRICS

    def evaluate_multi(self, params: dict, budget: int, seed: int, names) -> dict:
        """Multi-metric twin of ``evaluate``: one n=1 training run, then
        the same per-member metric columns the fused path computes
        (``train.common.eval_population_objectives``), so driver-path
        and fused-path objective values agree by construction."""
        from mpi_opt_tpu.train.common import eval_population_objectives

        trainer, state, val_x, val_y = self._eval_state(params, budget, seed)
        mo = eval_population_objectives(trainer, state, val_x, val_y, tuple(names))
        return {name: float(mo[0, j]) for j, name in enumerate(names)}
