"""Config-1 workload: sklearn LogisticRegression on the digits dataset.

BASELINE.json configs[0]: "Random search, 16 trials, sklearn
LogisticRegression on digits (single-process CPU ref)". This workload
stays on the CPU path by design — it exists for parity with the
reference's sklearn-estimator adapter (SURVEY.md §2 row 10), and as the
minimum end-to-end slice.

Budget semantics: ``budget`` = ``max_iter`` for the lbfgs solver.
"""

from __future__ import annotations

import warnings

import numpy as np

from mpi_opt_tpu.space import Choice, LogUniform, SearchSpace, Uniform
from mpi_opt_tpu.workloads import register
from mpi_opt_tpu.workloads.base import PopulationWorkload, Workload

_CACHE = {}


def _data(seed: int):
    """Fixed train/val split; cached across trials in a worker process."""
    if seed not in _CACHE:
        from sklearn.datasets import load_digits
        from sklearn.model_selection import train_test_split

        d = load_digits()
        x = d.data.astype(np.float32) / 16.0
        _CACHE[seed] = train_test_split(
            x, d.target, test_size=0.25, random_state=seed, stratify=d.target
        )
    return _CACHE[seed]


@register
class DigitsLogReg(Workload):
    name = "digits"

    def default_space(self) -> SearchSpace:
        return SearchSpace(
            {
                "C": LogUniform(1e-4, 1e2),
                "tol": LogUniform(1e-6, 1e-2),
                "fit_intercept": Choice([True, False]),
            }
        )

    def evaluate(self, params: dict, budget: int, seed: int) -> float:
        from sklearn.linear_model import LogisticRegression

        xtr, xva, ytr, yva = _data(seed)
        clf = LogisticRegression(
            C=float(params["C"]),
            tol=float(params["tol"]),
            fit_intercept=bool(params["fit_intercept"]),
            max_iter=max(1, int(budget)),
            solver="lbfgs",
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # ConvergenceWarning at low budgets
            clf.fit(xtr, ytr)
        return float(clf.score(xva, yva))

    # -- multi-objective protocol (ISSUE 17) ------------------------------

    def objective_metrics(self) -> tuple[str, ...]:
        return ("accuracy", "params", "latency")

    def evaluate_multi(self, params: dict, budget: int, seed: int, names) -> dict:
        """Driver-path multi-metric eval: ``params`` = the classifier's
        effective (non-negligible-coefficient) parameter count, which a
        stronger L2 (smaller ``C``) actually shrinks; ``latency`` = the
        2-MACs-per-effective-weight inference proxy the population
        workloads use, in pseudo-ms."""
        from sklearn.linear_model import LogisticRegression

        xtr, xva, ytr, yva = _data(seed)
        clf = LogisticRegression(
            C=float(params["C"]),
            tol=float(params["tol"]),
            fit_intercept=bool(params["fit_intercept"]),
            max_iter=max(1, int(budget)),
            solver="lbfgs",
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            clf.fit(xtr, ytr)
        eff = float(np.sum(np.abs(clf.coef_) > 1e-3))
        if clf.fit_intercept:
            eff += float(np.sum(np.abs(clf.intercept_) > 1e-3))
        out = {}
        for name in names:
            if name == "accuracy":
                out[name] = float(clf.score(xva, yva))
            elif name == "params":
                out[name] = eff
            elif name == "latency":
                out[name] = 2e-6 * float(np.sum(np.abs(clf.coef_) > 1e-2))
            else:
                raise ValueError(f"unknown digits objective {name!r}")
        return out


@register
class DigitsMLP(PopulationWorkload):
    """Population twin of the digits workload: a small MLP over the same
    8x8 sklearn digits features, giving the fused drivers a digits-class
    multi-objective target (BENCH config 8) that trains in seconds — the
    accuracy/params trade-off is real here because weight decay is in
    the search space."""

    name = "digits_mlp"
    dataset = "digits"
    batch_size = 128
    augment = False
    default_n_train = None  # sklearn set has a fixed size
    default_n_val = None

    def _model(self, n_classes):
        from mpi_opt_tpu.models import MLP

        return MLP(hidden=32, n_classes=n_classes)

    def default_space(self) -> SearchSpace:
        return SearchSpace(
            {
                "lr": LogUniform(1e-4, 1.0),
                "momentum": Uniform(0.0, 0.99),
                "weight_decay": LogUniform(1e-7, 1e-1),
            }
        )
