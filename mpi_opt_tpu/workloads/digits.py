"""Config-1 workload: sklearn LogisticRegression on the digits dataset.

BASELINE.json configs[0]: "Random search, 16 trials, sklearn
LogisticRegression on digits (single-process CPU ref)". This workload
stays on the CPU path by design — it exists for parity with the
reference's sklearn-estimator adapter (SURVEY.md §2 row 10), and as the
minimum end-to-end slice.

Budget semantics: ``budget`` = ``max_iter`` for the lbfgs solver.
"""

from __future__ import annotations

import warnings

import numpy as np

from mpi_opt_tpu.space import Choice, LogUniform, SearchSpace
from mpi_opt_tpu.workloads import register
from mpi_opt_tpu.workloads.base import Workload

_CACHE = {}


def _data(seed: int):
    """Fixed train/val split; cached across trials in a worker process."""
    if seed not in _CACHE:
        from sklearn.datasets import load_digits
        from sklearn.model_selection import train_test_split

        d = load_digits()
        x = d.data.astype(np.float32) / 16.0
        _CACHE[seed] = train_test_split(
            x, d.target, test_size=0.25, random_state=seed, stratify=d.target
        )
    return _CACHE[seed]


@register
class DigitsLogReg(Workload):
    name = "digits"

    def default_space(self) -> SearchSpace:
        return SearchSpace(
            {
                "C": LogUniform(1e-4, 1e2),
                "tol": LogUniform(1e-6, 1e-2),
                "fit_intercept": Choice([True, False]),
            }
        )

    def evaluate(self, params: dict, budget: int, seed: int) -> float:
        from sklearn.linear_model import LogisticRegression

        xtr, xva, ytr, yva = _data(seed)
        clf = LogisticRegression(
            C=float(params["C"]),
            tol=float(params["tol"]),
            fit_intercept=bool(params["fit_intercept"]),
            max_iter=max(1, int(budget)),
            solver="lbfgs",
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # ConvergenceWarning at low budgets
            clf.fit(xtr, ytr)
        return float(clf.score(xva, yva))
