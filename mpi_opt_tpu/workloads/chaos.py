"""Seeded fault-injection harness: wrap any workload in configured chaos.

The trial-level fault-tolerance layer (TrialResult.status, the CPU
backend's per-job reaping, driver.FailurePolicy) is only trustworthy if
it can be EXERCISED on demand — HPO's whole premise is that some trials
fail (extreme hyperparameters are part of the search space), but
organic failures are rare and unseeded. ``ChaosWorkload`` injects the
production failure shapes at configured probabilities:

- ``exc``:  the evaluation raises (bad hyperparameter -> OOM, sklearn
  convergence error, assertion in user code)
- ``nan``:  training "succeeds" but the score is NaN (diverged loss)
- ``hang``: the evaluation blocks (deadlocked worker, wedged I/O) —
  reaped by the CPU backend's per-trial timeout
- ``crash``: the WORKER PROCESS dies hard (os._exit: segfault/OOM-kill
  stand-in) — its queued result never arrives, so this too is reaped
  by the per-trial timeout, and the backend recycles the pool
- ``slow``: the evaluation takes extra wall time (straggler rank)
- ``preempt``: delivers SIGTERM to the evaluating process itself
  mid-evaluation — the platform-preemption stand-in that makes the
  graceful-shutdown protocol (health/shutdown.py) fault-injectable.
  Where evaluation runs in the DRIVER process (inline / in-parent
  stateful paths) the installed handler turns it into a graceful
  drain: the trial completes, the sweep flushes and exits
  EX_TEMPFAIL (75). In a pool / isolated worker the signal simply
  kills that worker (default disposition) — a crash-shaped outcome,
  reaped like ``crash``.

Determinism contract: whether a trial is faulted is a pure function of
``(chaos_seed, params)`` via a SHA-256 draw — stable across processes
(pool workers reconstruct the wrapper by registry name), across runs,
and independent of scheduling. A faulted trial is therefore faulted on
every retry too: chaos models DETERMINISTIC failures (the
hyperparameters themselves are poison). Clean trials score exactly what
the inner workload scores, so a chaos sweep's best trial matches the
clean sweep's best whenever the clean winner isn't in the faulted
fraction — the property the determinism test pins.

Registry shape: ``get_workload("chaos", inner="quadratic", exc=0.2)``.
The CPU backend's pool workers rebuild workloads from
``(name, workload_kwargs)``, so the CLI passes the same kwargs dict to
both the wrapper construction and the backend (see cli.main).
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import time

from mpi_opt_tpu.space import SearchSpace
from mpi_opt_tpu.workloads import get_workload, register
from mpi_opt_tpu.workloads.base import Workload


class ChaosInjectedError(RuntimeError):
    """The exception ``exc`` faults raise — distinct so tests and log
    readers can tell injected failures from organic ones."""


def parse_chaos_spec(spec: str) -> dict:
    """``"exc=0.1,nan=0.05,hang=0.02,slow=0.1,seed=7"`` -> kwargs for
    ChaosWorkload. Unknown keys are rejected loudly (a typoed fault name
    silently injecting nothing would fake a green chaos drill)."""
    out: dict = {}
    numeric = {
        "exc": float, "nan": float, "hang": float, "crash": float,
        "slow": float, "preempt": float, "hang_s": float, "slow_s": float,
        "seed": int,
    }
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"chaos spec entry {part!r} is not key=value "
                f"(known keys: {sorted(numeric)})"
            )
        k, v = part.split("=", 1)
        k = k.strip().replace("-", "_")
        if k not in numeric:
            raise ValueError(
                f"unknown chaos key {k!r} (known: {sorted(numeric)})"
            )
        out[k] = numeric[k](v)
    for p in ("exc", "nan", "hang", "crash", "slow", "preempt"):
        if not 0.0 <= out.get(p, 0.0) <= 1.0:
            raise ValueError(f"chaos probability {p}={out[p]} outside [0, 1]")
    return out


# -- snapshot-corruption injectors (torn_save / corrupt_save faults) --------
#
# The per-trial faults above exercise the TRIAL failure layer; these two
# exercise the SNAPSHOT integrity layer (utils/integrity.py): what a
# SIGKILL mid-async-save (torn_save) or silent bit-rot (corrupt_save)
# leaves inside the latest orbax step directory. They are direct-call
# helpers, not probability faults — corruption strikes the durable
# state between runs, not an evaluation — and deterministic given
# (directory contents, seed) so resume drills can pin exact outcomes.


def _committed_step_dirs(checkpoint_dir: str) -> list:
    """(step, path) for every committed orbax step under
    ``checkpoint_dir`` (recursive: hyperband nests per-bracket roots).
    Enumeration is delegated to utils.integrity so the injectors strike
    exactly the steps fsck audits — one home for the orbax commit-marker
    convention."""
    from mpi_opt_tpu.utils.integrity import _committed_steps, find_checkpoint_roots

    out = []
    for root in find_checkpoint_roots(checkpoint_dir):
        out.extend(
            (s, os.path.join(root, str(s))) for s in _committed_steps(root)
        )
    return sorted(out)


def _corruption_target(step_dir: str) -> str:
    """The file a fault strikes: the LARGEST regular file in the step
    (ties broken by path) — in any real snapshot that is array data,
    the payload whose rot matters most; in toy snapshots it may be the
    manifest itself, which verification must also survive."""
    candidates = []
    for root, _dirs, files in os.walk(step_dir):
        for f in files:
            p = os.path.join(root, f)
            candidates.append((os.path.getsize(p), p))
    if not candidates:
        raise ValueError(f"no files to corrupt under {step_dir}")
    # largest first; the path tiebreak keeps the pick stable when sizes
    # collide (sort ascending, take last => greatest (size, path))
    return sorted(candidates)[-1][1]


def _resolve_step_dir(checkpoint_dir: str, step) -> str:
    steps = _committed_step_dirs(checkpoint_dir)
    if not steps:
        raise ValueError(f"no committed snapshot steps under {checkpoint_dir}")
    if step is None:
        return steps[-1][1]
    for s, path in steps:
        if s == int(step):
            return path
    raise ValueError(f"step {step} not found under {checkpoint_dir}")


def inject_torn_save(checkpoint_dir: str, seed: int = 0, step=None) -> str:
    """Truncate a file inside the latest (or given) committed step dir —
    the shape a SIGKILL mid-async-save leaves behind. The cut point is a
    seeded draw over the file's interior so repeated drills vary the
    tear without losing determinism. Returns the mangled path."""
    path = _corruption_target(_resolve_step_dir(checkpoint_dir, step))
    size = os.path.getsize(path)
    h = hashlib.sha256(f"torn:{seed}".encode()).digest()
    cut = 1 + int.from_bytes(h[:8], "big") % max(size - 1, 1)
    with open(path, "r+b") as f:
        f.truncate(cut)
    return path


def inject_corrupt_save(checkpoint_dir: str, seed: int = 0, step=None) -> str:
    """Flip one bit inside the latest (or given) committed step dir —
    the silent bit-rot shape only content digests can catch. Seeded
    offset/bit, deterministic per (directory contents, seed). Returns
    the mangled path."""
    path = _corruption_target(_resolve_step_dir(checkpoint_dir, step))
    size = os.path.getsize(path)
    h = hashlib.sha256(f"corrupt:{seed}".encode()).digest()
    off = int.from_bytes(h[:8], "big") % size
    bit = h[8] % 8
    with open(path, "r+b") as f:
        f.seek(off)
        byte = f.read(1)
        f.seek(off)
        f.write(bytes([byte[0] ^ (1 << bit)]))
    return path


# -- resource-exhaustion injectors (device OOM / disk full, ISSUE 13) -------
#
# Two more direct-call injectors in the inject_torn_save style: install
# a seeded deterministic schedule, drive the drill, uninstall in a
# finally. ``inject_enospc`` strikes the atomic-write/fsync paths of
# the DURABLE layers (snapshot save enqueue, ledger journal fsync) via
# the resource layer's disk-fault seam — the shape a filling disk
# presents; ``inject_oom`` raises a synthetic XLA RESOURCE_EXHAUSTED at
# a chosen guarded fused-launch ordinal (resident launch or wave) via
# the launch seam, exercising the REAL classification path
# (utils/resources.py type gate included) and the wave scheduler's
# --oom-backoff re-run.


class DiskFullInjector:
    """The schedule ``inject_enospc`` installs into
    ``utils.resources``' disk-fault seam. Counts every seam op per kind
    ("snapshot_save" / "ledger_fsync") and raises a classified
    ``StorageFull`` (ENOSPC) on the scheduled ordinals; ``fail_from``
    makes every op at/after that ordinal fail — the disk-stays-full
    shape drill B needs (the prune retry must ALSO hit the wall).
    Thread-safe (orbax save enqueues and the main loop share the
    seam)."""

    def __init__(
        self,
        fail: int = 0,
        seed: int = 0,
        ops_window: int | None = None,
        fail_from: int | None = None,
        op: str | None = None,
    ):
        import threading

        self._lock = threading.Lock()
        self._counts: dict = {}
        self._op = op  # None = every seam kind
        self._fail_from = fail_from
        self._fail = SpoolFaultInjector._schedule("disk", fail, seed, ops_window)
        self.faults_fired = 0

    def __call__(self, op: str, path: str) -> None:
        if self._op is not None and op != self._op:
            return
        with self._lock:
            ordinal = self._counts.get(op, 0)
            self._counts[op] = ordinal + 1
            fire = ordinal in self._fail or (
                self._fail_from is not None and ordinal >= self._fail_from
            )
            if fire:
                self.faults_fired += 1
        if fire:
            from mpi_opt_tpu.utils.resources import storage_full_error

            raise storage_full_error(path, op=f"chaos-injected {op} (op {ordinal})")


def inject_enospc(
    fail: int = 0,
    seed: int = 0,
    ops_window: int | None = None,
    fail_from: int | None = None,
    op: str | None = None,
):
    """Install a seeded, deterministic ENOSPC schedule on the durable
    layers' atomic-write/fsync seam (``utils.resources.disk_fault``:
    snapshot saves + ledger fsyncs). Returns ``(injector, uninstall)``
    — call ``uninstall()`` when the drill is over (tests in a finally).
    ``fail_from=N`` fails every op at/after ordinal N (disk fills and
    STAYS full — the prune-then-park drill); ``fail=n`` fails the first
    n (or a seeded sample of ``ops_window``); ``op`` restricts the
    schedule to one seam kind."""
    from mpi_opt_tpu.utils import resources

    injector = DiskFullInjector(
        fail=fail, seed=seed, ops_window=ops_window, fail_from=fail_from, op=op
    )
    resources.set_disk_fault_injector(injector)

    def uninstall() -> None:
        resources.set_disk_fault_injector(None)

    return injector, uninstall


class OOMInjector:
    """The schedule ``inject_oom`` installs into ``utils.resources``'
    launch seam: every guarded fused launch (resident launch / one
    wave) ticks one ordinal; the scheduled ordinals (1-based, matching
    "OOM at wave k") raise a synthetic RESOURCE_EXHAUSTED through the
    real classification funnel."""

    def __init__(self, at_launch: int = 1, n: int = 1, kind: str | None = None):
        import threading

        if at_launch < 1:
            raise ValueError(f"at_launch is 1-based, got {at_launch}")
        self._lock = threading.Lock()
        self._kind = kind  # None = any guarded launch ("launch"/"wave")
        self._fire_at = frozenset(range(at_launch, at_launch + max(1, n)))
        self.launches = 0
        self.faults_fired = 0

    def __call__(self, kind: str) -> None:
        if self._kind is not None and kind != self._kind:
            return
        with self._lock:
            self.launches += 1
            ordinal = self.launches
            fire = ordinal in self._fire_at
            if fire:
                self.faults_fired += 1
        if fire:
            from mpi_opt_tpu.utils.resources import synthetic_resource_exhausted

            raise synthetic_resource_exhausted(
                f"chaos: injected device OOM at {kind} ordinal {ordinal}"
            )


def inject_oom(at_launch: int = 1, n: int = 1, kind: str | None = None):
    """Install a deterministic device-OOM schedule on the fused launch
    seam: the ``at_launch``-th guarded launch (1-based; ``n``
    consecutive ordinals — n>1 drills repeated backoff) raises a
    synthetic XLA RESOURCE_EXHAUSTED. Returns ``(injector,
    uninstall)``. ``kind`` restricts to "launch" (resident) or "wave"."""
    from mpi_opt_tpu.utils import resources

    injector = OOMInjector(at_launch=at_launch, n=n, kind=kind)
    resources.set_launch_fault_injector(injector)

    def uninstall() -> None:
        resources.set_launch_fault_injector(None)

    return injector, uninstall


class RankKillInjector:
    """The schedule ``inject_rank_kill`` installs into
    ``utils.resources``' boundary seam (``train.common.launch_boundary``
    ticks it once per launch/rung/generation boundary): on the
    scheduled 1-based boundary ordinals, IF this process is the chosen
    rank, die by SIGKILL — no handlers, no atexit, no flushes, exactly
    the hard rank death that wedges an SPMD cohort's survivors in their
    next collective. Other ranks count the same ordinals and do
    nothing, so the drill is deterministic across the whole world.

    ``once_marker``: path of a sentinel file created (O_EXCL) just
    before dying. A coordinated ``--resume`` relaunch re-runs the same
    boundaries with the same injector spec — without the marker the
    restarted rank would be killed at the same ordinal forever, burning
    the retry budget on the drill itself. Marker present = already
    fired = don't fire again.
    """

    def __init__(
        self,
        rank: int = 0,
        at_boundary: int = 1,
        n: int = 1,
        once_marker: str | None = None,
    ):
        import threading

        if at_boundary < 1:
            raise ValueError(f"at_boundary is 1-based, got {at_boundary}")
        self._lock = threading.Lock()
        self._rank = int(rank)
        self._fire_at = frozenset(range(at_boundary, at_boundary + max(1, n)))
        self._once_marker = once_marker
        self.boundaries = 0
        self.faults_fired = 0

    def __call__(self, stage: str) -> None:
        with self._lock:
            self.boundaries += 1
            fire = self.boundaries in self._fire_at
        if not fire:
            return
        import jax

        if jax.process_index() != self._rank:
            return
        if self._once_marker is not None:
            try:
                fd = os.open(
                    self._once_marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY
                )
                os.close(fd)
            except FileExistsError:
                return  # already fired in a previous attempt
        with self._lock:
            self.faults_fired += 1
        os.kill(os.getpid(), signal.SIGKILL)


def parse_rank_kill_spec(spec: str) -> dict:
    """``"rank=1,at=3,n=1,marker=/tmp/m"`` -> ``inject_rank_kill``
    kwargs. Unknown keys are rejected loudly, same contract as
    ``parse_chaos_spec`` — a typoed drill spec injecting nothing would
    fake a green wedge drill."""
    out: dict = {}
    keys = {"rank": int, "at": int, "n": int, "marker": str}
    names = {"rank": "rank", "at": "at_boundary", "n": "n", "marker": "once_marker"}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"rank-kill spec entry {part!r} is not key=value "
                f"(known keys: {sorted(keys)})"
            )
        k, v = part.split("=", 1)
        k = k.strip()
        if k not in keys:
            raise ValueError(f"unknown rank-kill key {k!r} (known: {sorted(keys)})")
        out[names[k]] = keys[k](v)
    return out


def inject_rank_kill(
    rank: int = 0,
    at_boundary: int = 1,
    n: int = 1,
    once_marker: str | None = None,
):
    """Install a deterministic rank-death schedule on the boundary
    seam: at the ``at_boundary``-th launch/rung/generation boundary
    (1-based; ``n`` consecutive ordinals), the process whose
    ``jax.process_index()`` equals ``rank`` SIGKILLs itself. Returns
    ``(injector, uninstall)`` like ``inject_oom``; ``once_marker``
    makes the kill one-shot across coordinated restarts."""
    from mpi_opt_tpu.utils import resources

    injector = RankKillInjector(
        rank=rank, at_boundary=at_boundary, n=n, once_marker=once_marker
    )
    resources.set_boundary_fault_injector(injector)

    def uninstall() -> None:
        resources.set_boundary_fault_injector(None)

    return injector, uninstall


# -- spool-fault injectors (fleet federation, ISSUE 12) ---------------------
#
# The two injectors above strike durable state BETWEEN runs; these
# strike the service spool's metadata primitives WHILE a scheduler (or
# a whole fleet of them) is working: delayed/failed ``os.replace`` on
# status/lease/queue writes and EIO on status reads — the weather of a
# slow or contended shared filesystem, which is exactly the substrate a
# multi-server spool runs on. Direct-call style like ``inject_torn_save``
# (install, drive the drill, uninstall), deterministic by construction:
# faults fire on exact op ordinals, optionally chosen by a seeded draw
# over a window, never by wall clock or scheduling.


class SpoolFaultInjector:
    """The schedule ``inject_spool_faults`` installs into
    ``service.spool``'s fault seam. Counts every op per kind
    ("replace" / "read" / "list") and raises ``OSError(EIO)`` on the
    scheduled ordinals (read faults only strike status.json reads —
    the ISSUE's "EIO on status reads" shape — so job-spec parsing
    stays out of scope); ``replace_delay_s`` sleeps before every
    replace while installed (the slow-NFS shape). Thread-safe: the
    scheduler's staging/heartbeat threads share the seam."""

    def __init__(
        self,
        replace_fail: int = 0,
        read_fail: int = 0,
        replace_delay_s: float = 0.0,
        seed: int = 0,
        ops_window: int | None = None,
    ):
        import threading

        self.replace_delay_s = float(replace_delay_s)
        self._lock = threading.Lock()
        self._counts = {"replace": 0, "read": 0, "list": 0}
        self._fail = {
            "replace": self._schedule("replace", replace_fail, seed, ops_window),
            "read": self._schedule("read", read_fail, seed, ops_window),
        }
        self.faults_fired = {"replace": 0, "read": 0}

    @staticmethod
    def _schedule(kind: str, n: int, seed: int, window: int | None) -> frozenset:
        """Which op ordinals (0-based) fault: the first ``n`` when no
        window is given, else a seeded SHA-draw sample of ``n`` distinct
        ordinals from ``range(window)`` — deterministic per (kind,
        seed, n, window), independent of scheduling."""
        if n <= 0:
            return frozenset()
        if window is None or window <= n:
            return frozenset(range(n))
        picked: set = set()
        i = 0
        while len(picked) < n:
            h = hashlib.sha256(f"spool:{kind}:{seed}:{i}".encode()).digest()
            picked.add(int.from_bytes(h[:8], "big") % window)
            i += 1
        return frozenset(picked)

    def __call__(self, op: str, path: str) -> None:
        import errno
        import time as _time

        if op == "replace" and self.replace_delay_s > 0:
            _time.sleep(self.replace_delay_s)
        if op == "read" and not path.endswith("status.json"):
            return
        with self._lock:
            ordinal = self._counts.get(op, 0)
            self._counts[op] = ordinal + 1
            fire = ordinal in self._fail.get(op, ())
            if fire:
                self.faults_fired[op] += 1
        if fire:
            raise OSError(
                errno.EIO, f"chaos: injected spool {op} fault (op {ordinal})", path
            )


def inject_spool_faults(
    replace_fail: int = 0,
    read_fail: int = 0,
    replace_delay_s: float = 0.0,
    seed: int = 0,
    ops_window: int | None = None,
):
    """Install a seeded, deterministic fault schedule on the service
    spool's metadata ops. Returns ``(injector, uninstall)`` — call
    ``uninstall()`` when the drill is over (tests do it in a finally).
    The spool's bounded retry-with-jittered-backoff (spool.retry_io)
    absorbs schedules shorter than its attempt budget — the drill for
    "a contended shared filesystem degrades to latency, not crashes" —
    while a schedule longer than the budget surfaces the OSError, the
    drill for the failure path."""
    from mpi_opt_tpu.service import spool as spool_mod

    injector = SpoolFaultInjector(
        replace_fail=replace_fail,
        read_fail=read_fail,
        replace_delay_s=replace_delay_s,
        seed=seed,
        ops_window=ops_window,
    )
    spool_mod.set_fault_injector(injector)

    def uninstall() -> None:
        spool_mod.set_fault_injector(None)

    return injector, uninstall


# -- network-fault injectors (HTTP front door, ISSUE 16) --------------------
#
# The spool injectors above strike filesystem metadata; this one
# strikes the WIRE: the HTTP client transport's chaos seam
# (corpus/transport.net_fault) fires at the three places a real network
# fails — before the TCP connect ("connect": refused/reset), before the
# request body is written ("send": peer died between accept and read),
# and before the response is read ("read": torn reply, the
# did-it-execute ambiguity the idempotency key exists for). Same
# direct-call discipline: install, drive the drill, uninstall in a
# finally; faults fire on exact per-stage op ordinals from a seeded
# draw, never by wall clock.


class NetFaultInjector:
    """The schedule ``inject_net`` installs into
    ``corpus.transport``'s net-fault seam. Counts every transport op
    per stage ("connect" / "send" / "read") and fires the scheduled
    ordinals: connect/send ordinals raise :class:`transport.Unreachable`
    (connection refused), read ordinals raise
    :class:`transport.TornResponse` (reply died mid-flight — the
    request MAY have executed), and ``delay_s`` sleeps before every
    faulted-read's raise is decided, on its own seeded schedule
    (``delay`` ordinals), modeling the slow-reply shape. Thread-safe:
    bench/drill clients retry from many threads through one seam."""

    def __init__(
        self,
        refuse: int = 0,
        torn: int = 0,
        delay: int = 0,
        delay_s: float = 0.05,
        seed: int = 0,
        ops_window: int | None = None,
    ):
        import threading

        self.delay_s = float(delay_s)
        self._lock = threading.Lock()
        self._counts = {"connect": 0, "send": 0, "read": 0}
        self._fail = {
            "connect": SpoolFaultInjector._schedule("net-refuse", refuse, seed, ops_window),
            "read": SpoolFaultInjector._schedule("net-torn", torn, seed, ops_window),
        }
        self._delay = SpoolFaultInjector._schedule("net-delay", delay, seed, ops_window)
        self.faults_fired = {"refuse": 0, "torn": 0, "delay": 0}

    def __call__(self, stage: str, url: str) -> None:
        from mpi_opt_tpu.corpus.transport import TornResponse, Unreachable

        with self._lock:
            ordinal = self._counts.get(stage, 0)
            self._counts[stage] = ordinal + 1
            fire = ordinal in self._fail.get(stage, ())
            delay = stage == "read" and ordinal in self._delay
            if fire:
                self.faults_fired["refuse" if stage == "connect" else "torn"] += 1
            if delay:
                self.faults_fired["delay"] += 1
        if delay:
            time.sleep(self.delay_s)
        if not fire:
            return
        if stage == "connect":
            raise Unreachable(
                f"chaos: injected connection refused (op {ordinal}) to {url}"
            )
        raise TornResponse(
            f"chaos: injected torn response (op {ordinal}) from {url}"
        )


def inject_net(
    refuse: int = 0,
    torn: int = 0,
    delay: int = 0,
    delay_s: float = 0.05,
    seed: int = 0,
    ops_window: int | None = None,
):
    """Install a seeded, deterministic network-fault schedule on the
    HTTP transport seam. Returns ``(injector, uninstall)`` — call
    ``uninstall()`` when the drill is over (tests in a finally).
    ``refuse`` connect ordinals are refused, ``torn`` read ordinals
    tear the reply, ``delay`` read ordinals sleep ``delay_s`` first;
    with ``ops_window`` each schedule is a seeded sample of that window
    instead of the first n. The client's capped jittered retry absorbs
    schedules shorter than its attempt budget — and because every retry
    reuses its idempotency key, a torn-but-executed request is answered
    from the server's dedup window, which is exactly what the
    exactly-once drill pins."""
    from mpi_opt_tpu.corpus import transport

    injector = NetFaultInjector(
        refuse=refuse,
        torn=torn,
        delay=delay,
        delay_s=delay_s,
        seed=seed,
        ops_window=ops_window,
    )
    transport.set_net_fault_injector(injector)

    def uninstall() -> None:
        transport.set_net_fault_injector(None)

    return injector, uninstall


@register
class ChaosWorkload(Workload):
    name = "chaos"

    def __init__(
        self,
        inner: str = "quadratic",
        exc: float = 0.0,
        nan: float = 0.0,
        hang: float = 0.0,
        crash: float = 0.0,
        slow: float = 0.0,
        preempt: float = 0.0,
        hang_s: float = 600.0,
        slow_s: float = 0.25,
        seed: int = 0,
        inner_kwargs: dict | None = None,
    ):
        total = exc + nan + hang + crash + slow + preempt
        if total > 1.0:
            raise ValueError(
                f"chaos probabilities sum to {total} > 1 "
                "(exc+nan+hang+crash+slow+preempt)"
            )
        self.inner = get_workload(inner, **(inner_kwargs or {}))
        self.p_exc = exc
        self.p_nan = nan
        self.p_hang = hang
        self.p_crash = crash
        self.p_slow = slow
        self.p_preempt = preempt
        self.hang_s = hang_s
        self.slow_s = slow_s
        self.chaos_seed = seed

    def default_space(self) -> SearchSpace:
        return self.inner.default_space()

    # -- the seeded draw ---------------------------------------------------

    def fault_for(self, params: dict) -> str | None:
        """Which fault (if any) this trial draws: a pure function of
        (chaos_seed, cleaned params). SHA-256, not hash(): stable across
        processes regardless of PYTHONHASHSEED."""
        payload = json.dumps(
            [
                self.chaos_seed,
                sorted(
                    (k, repr(v))
                    for k, v in params.items()
                    if not k.startswith("__")
                ),
            ]
        )
        h = hashlib.sha256(payload.encode()).digest()
        u = int.from_bytes(h[:8], "big") / 2**64  # uniform [0, 1)
        edge = 0.0
        # preempt is LAST in the cascade on purpose: appending a new
        # fault keeps every existing (seed, params) draw identical when
        # its probability is 0, so the pinned counts in the determinism
        # drills survive the addition
        for fault, p in (
            ("exc", self.p_exc),
            ("nan", self.p_nan),
            ("hang", self.p_hang),
            ("crash", self.p_crash),
            ("slow", self.p_slow),
            ("preempt", self.p_preempt),
        ):
            edge += p
            if u < edge:
                return fault
        return None

    def _apply(self, fault: str | None, params: dict) -> None:
        """Pre-evaluation faults (exceptions and stalls)."""
        if fault == "exc":
            raise ChaosInjectedError(
                f"chaos: injected trial failure (seed={self.chaos_seed})"
            )
        if fault == "preempt":
            # the platform-preemption stand-in: SIGTERM to SELF. Under a
            # ShutdownGuard (driver process) this only sets the drain
            # flag and the evaluation CONTINUES — the trial completes,
            # gets journaled, and the sweep drains at the batch
            # boundary, so after a --resume the same trial replays
            # instead of re-preempting (the restart loop converges).
            os.kill(os.getpid(), signal.SIGTERM)
        elif fault == "hang":
            time.sleep(self.hang_s)
        elif fault == "crash":
            # the hard-death stand-in: no exception to catch, no result
            # queued — exactly what a segfaulted/OOM-killed worker looks
            # like to the parent
            os._exit(13)
        elif fault == "slow":
            time.sleep(self.slow_s)

    # -- stateless protocol ------------------------------------------------

    def evaluate(self, params: dict, budget: int, seed: int) -> float:
        fault = self.fault_for(params)
        self._apply(fault, params)
        score = self.inner.evaluate(params, budget, seed)
        return float("nan") if fault == "nan" else score

    # -- stateful protocol (delegated; faults fire in train) ---------------

    @property
    def stateful(self) -> bool:
        # NOT the base class's "did the subclass override train" probe:
        # this wrapper always defines train, but it is only genuinely
        # stateful when the inner workload is
        return self.inner.stateful

    def init_state(self, params: dict, seed: int):
        return self.inner.init_state(params, seed)

    def train(self, state, params: dict, steps: int, seed: int):
        fault = self.fault_for(params)
        self._apply(fault, params)
        state, score = self.inner.train(state, params, steps, seed)
        return state, (float("nan") if fault == "nan" else score)
