"""Workload registry: trial evaluators / model zoo (SURVEY.md §2 row 10).

A workload bundles a default search space with the train-and-score
functions the backends call. Two evaluation protocols:

- stateless: ``evaluate(params, budget, seed) -> score`` — train from
  scratch to ``budget``; what the reference's MPIWorker does per trial.
- stateful: ``init_state``/``train`` — resumable training for ASHA
  promotions and PBT inheritance without retraining from scratch.

NN workloads additionally expose the pieces the TPU population backend
vmaps (see mpi_opt_tpu/backends/tpu.py).
"""

from mpi_opt_tpu.workloads.base import Workload

_REGISTRY: dict[str, type] = {}


def register(cls):
    _REGISTRY[cls.name] = cls
    return cls


def get_workload(name: str, **kwargs) -> Workload:
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown workload {name!r}; available: {sorted(_REGISTRY)}") from None
    return cls(**kwargs)


def available() -> list[str]:
    return sorted(_REGISTRY)


# import for registration side effects (chaos last: it wraps the others)
from mpi_opt_tpu.workloads import digits, synthetic, tabular, vision  # noqa: E402,F401
from mpi_opt_tpu.workloads import chaos  # noqa: E402,F401

__all__ = ["Workload", "register", "get_workload", "available"]
