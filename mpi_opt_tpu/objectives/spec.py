"""ObjectiveSpec: named objectives, directions, constraint bounds.

The spec is the durable contract of a multi-objective sweep, the same
way ``SearchSpace.spec()`` is for the search space: it is parsed once
from the CLI, carried in the ledger header top-level beside
``space_spec`` (metadata, NOT part of the config identity — a header
written by an older binary simply lacks it), and handed to the fused
drivers as a static jit argument (both dataclasses are frozen and
tuple-backed, so the spec hashes).

Syntax (``--objectives``)::

    accuracy:max,params:min<=2e4,latency:min

One comma-separated item per objective: ``name[:direction][OP bound]``.
``direction`` is ``max`` (default) or ``min``; the optional constraint
operator must agree with the direction (``>=`` for max, ``<=`` for
min) so feasibility is never ambiguous: a bounded objective is
feasible when it is at least as good as its bound.

Normalization: every kernel in :mod:`.pareto` works in *maximize form*
— scores multiplied by per-objective signs (+1 max, -1 min) so "bigger
is better" uniformly, and bounds mapped the same way (feasible ⇔
normalized value ≥ normalized bound). The first objective is primary:
:meth:`ObjectiveSpec.scalarize` returns its normalized value, which is
what vector records journal as their scalar ``score`` — every
higher-is-better consumer (resume verify, warm-start seeding, report
"best") works on vector sweeps without change.
"""

from __future__ import annotations

import dataclasses
import re

import numpy as np

_DIRECTIONS = ("max", "min")

#: one constraint clause, shared with ``report --best-under``
_CONSTRAINT_RE = re.compile(r"^\s*([A-Za-z_][\w.-]*)\s*(<=|>=)\s*([^\s]+)\s*$")


@dataclasses.dataclass(frozen=True)
class Objective:
    """One named objective: a direction and an optional feasibility bound.

    ``bound`` is in raw metric units; feasibility is direction-aware
    (``max``: value >= bound, ``min``: value <= bound).
    """

    name: str
    direction: str = "max"
    bound: float | None = None

    def __post_init__(self):
        if not self.name or not re.match(r"^[A-Za-z_][\w.-]*$", self.name):
            raise ValueError(f"bad objective name: {self.name!r}")
        if self.direction not in _DIRECTIONS:
            raise ValueError(
                f"objective {self.name!r}: direction must be max|min, "
                f"got {self.direction!r}"
            )
        if self.bound is not None and not np.isfinite(self.bound):
            raise ValueError(f"objective {self.name!r}: bound must be finite")

    @property
    def sign(self) -> float:
        return 1.0 if self.direction == "max" else -1.0


@dataclasses.dataclass(frozen=True)
class ObjectiveSpec:
    """An ordered tuple of objectives; the first is primary."""

    objectives: tuple[Objective, ...]

    def __post_init__(self):
        if len(self.objectives) < 1:
            raise ValueError("objective spec needs at least one objective")
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")

    # -- identity ----------------------------------------------------

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(o.name for o in self.objectives)

    @property
    def m(self) -> int:
        return len(self.objectives)

    @property
    def has_bounds(self) -> bool:
        return any(o.bound is not None for o in self.objectives)

    def index(self, name: str) -> int:
        try:
            return self.names.index(name)
        except ValueError:
            raise KeyError(
                f"unknown objective {name!r}; spec has {list(self.names)}"
            ) from None

    # -- durable form (ledger header, checkpoint config) -------------

    def spec(self) -> list:
        """Plain-data form for the ledger header (beside ``space_spec``)."""
        out = []
        for o in self.objectives:
            d = {"name": o.name, "direction": o.direction}
            if o.bound is not None:
                d["bound"] = float(o.bound)
            out.append(d)
        return out

    @classmethod
    def from_spec(cls, spec: list) -> "ObjectiveSpec":
        objs = []
        for d in spec:
            objs.append(
                Objective(
                    name=str(d["name"]),
                    direction=str(d.get("direction", "max")),
                    bound=None if d.get("bound") is None else float(d["bound"]),
                )
            )
        return cls(objectives=tuple(objs))

    # -- CLI syntax --------------------------------------------------

    @classmethod
    def parse(cls, text: str) -> "ObjectiveSpec":
        """Parse ``"accuracy:max,params:min<=2e4"`` (see module doc)."""
        objs = []
        for raw in text.split(","):
            item = raw.strip()
            if not item:
                raise ValueError(f"empty objective in {text!r}")
            bound = None
            op = None
            m = re.search(r"(<=|>=)", item)
            if m:
                op = m.group(1)
                item, bound_text = item[: m.start()], item[m.end() :]
                try:
                    bound = float(bound_text)
                except ValueError:
                    raise ValueError(
                        f"bad bound {bound_text!r} in objective {raw.strip()!r}"
                    ) from None
            item = item.strip()
            if ":" in item:
                name, direction = item.split(":", 1)
                name, direction = name.strip(), direction.strip()
            else:
                name, direction = item, "max"
            if op is not None:
                want = ">=" if direction == "max" else "<="
                if op != want:
                    raise ValueError(
                        f"objective {name!r}: constraint operator {op!r} "
                        f"contradicts direction {direction!r} (use {want!r}: "
                        "a bound means 'at least this good')"
                    )
            objs.append(Objective(name=name, direction=direction, bound=bound))
        return cls(objectives=tuple(objs))

    # -- maximize-form transforms ------------------------------------

    def signs(self) -> np.ndarray:
        return np.asarray([o.sign for o in self.objectives], dtype=np.float32)

    def normalize(self, scores):
        """Raw ``[..., m]`` scores → maximize form (works for np and jnp:
        the signs array broadcasts under either namespace)."""
        return scores * self.signs()

    def norm_bounds(self) -> np.ndarray:
        """Maximize-form bounds, ``-inf`` where unconstrained (every
        finite value is feasible against ``-inf``)."""
        out = np.full((self.m,), -np.inf, dtype=np.float32)
        for j, o in enumerate(self.objectives):
            if o.bound is not None:
                out[j] = o.sign * o.bound
        return out

    def scalarize(self, scores):
        """Normalized primary objective — the scalar ``score`` vector
        records journal (higher is better by construction)."""
        return scores[..., 0] * self.objectives[0].sign


def parse_constraint(text: str) -> tuple[str, str, float]:
    """Parse one ``report --best-under`` clause: ``"params<=2e4"`` →
    ``("params", "<=", 20000.0)``."""
    m = _CONSTRAINT_RE.match(text)
    if not m:
        raise ValueError(
            f"bad constraint {text!r}; expected NAME<=VALUE or NAME>=VALUE"
        )
    name, op, val = m.group(1), m.group(2), m.group(3)
    try:
        value = float(val)
    except ValueError:
        raise ValueError(f"bad constraint value {val!r} in {text!r}") from None
    return name, op, value
