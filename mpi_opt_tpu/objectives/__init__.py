"""Multi-objective & constrained search (ISSUE 17).

The scalar sweep engine answers "what is the best score"; production
queries are vector-valued — "best accuracy under a params budget", "the
accuracy/latency trade-off curve". This package is the whole subsystem
in two modules:

- :mod:`.spec` — :class:`ObjectiveSpec`: named objectives with
  directions and optional constraint bounds, parsed from the CLI
  (``--objectives "accuracy:max,params:min<=2e4"``), carried in the
  ledger header beside ``space_spec``, and hashable so it rides fused
  drivers as a static jit argument.
- :mod:`.pareto` — the jit-safe non-dominated-sort kernels
  (:func:`pareto_rank`, :func:`crowding_distance`,
  :func:`pareto_score`) that generalize the fused boundary ops, plus
  the host-side front/:func:`hypervolume` helpers the report and
  corpus layers consume, and the constraint-aware
  :func:`select_best` (best feasible, with typed degradation to the
  least-violating member when nothing is feasible yet).

Everything selection-shaped reduces to one rule: :func:`pareto_score`
folds (feasibility, Pareto rank, crowding) into a single effective
scalar whose descending order IS the multi-objective preference order,
so every scalar selection site (PBT truncation-exploit, SHA rung cut,
winner picks) generalizes by swapping the score vector it ranks — no
new control flow, no host round-trip.
"""

from mpi_opt_tpu.objectives.pareto import (
    crowding_distance,
    hypervolume,
    pareto_front_mask,
    pareto_rank,
    pareto_score,
    select_best,
)
from mpi_opt_tpu.objectives.spec import Objective, ObjectiveSpec, parse_constraint

__all__ = [
    "Objective",
    "ObjectiveSpec",
    "parse_constraint",
    "pareto_rank",
    "crowding_distance",
    "pareto_score",
    "pareto_front_mask",
    "hypervolume",
    "select_best",
]
