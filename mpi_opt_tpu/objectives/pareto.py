"""Pareto-front kernels: jit-safe ranking + host-side front/volume math.

Three device kernels (plain ``jax.numpy``, static shapes, no host
round-trip — they compile into the fused boundary ops the same way
``rank_descending`` does):

- :func:`pareto_rank` — exact non-dominated-sort front index. The
  front number of a point equals the longest chain of dominators
  ending at it, so ``n`` Bellman iterations over the O(n²) dominance
  matrix (``lax.fori_loop``) produce the exact NSGA-II fronts without
  any data-dependent control flow.
- :func:`crowding_distance` — per-front crowding (normalized neighbor
  gaps per objective, front boundaries → ``inf``), computed with one
  composite (front-major, value) sort per objective.
- :func:`pareto_score` — the effective scalar that generalizes every
  scalar selection site: feasible points order by ``-rank`` then
  crowding (squashed into ``[0, 0.5]`` so it never crosses a rank
  boundary), infeasible-but-finite points sit strictly below every
  feasible one ordered by least constraint violation (the typed
  degradation rule, computed inside jit), and non-finite points are
  ``-inf``. ``rank_descending(pareto_score(...))`` IS multi-objective
  selection.

Host-side (numpy, report/corpus/summary consumers):
:func:`pareto_front_mask`, :func:`hypervolume` (exact recursive
slicing, deterministic reference point = per-objective front minimum),
and :func:`select_best` (typed best-feasible winner pick).

All kernels work in maximize form (see :mod:`.spec`); population sizes
here are sweep populations (tens to a few hundred), so the O(n²·m)
dominance matrix is trivially small next to one train segment.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "pareto_rank",
    "crowding_distance",
    "pareto_score",
    "pareto_front_mask",
    "hypervolume",
    "select_best",
]


def _ok_mask(norm_scores, valid):
    finite = jnp.all(jnp.isfinite(norm_scores), axis=-1)
    return finite if valid is None else finite & jnp.asarray(valid)


def pareto_rank(norm_scores, valid=None):
    """Exact non-dominated-sort front index per row (0 = Pareto front).

    ``norm_scores``: ``[n, m]`` maximize-form scores. Rows that are
    non-finite in any objective (or masked by ``valid``) get rank
    ``n`` — strictly after every real front.
    """
    n = norm_scores.shape[0]
    ok = _ok_mask(norm_scores, valid)
    s = jnp.where(ok[:, None], norm_scores.astype(jnp.float32), -jnp.inf)
    # dom[j, i]: j dominates i (>= everywhere, > somewhere, both alive)
    ge = jnp.all(s[:, None, :] >= s[None, :, :], axis=-1)
    gt = jnp.any(s[:, None, :] > s[None, :, :], axis=-1)
    dom = ge & gt & ok[:, None] & ok[None, :]

    def body(_, r):
        best = jnp.max(jnp.where(dom, r[:, None] + 1, 0), axis=0)
        return jnp.maximum(r, best)

    rank = jax.lax.fori_loop(0, n, body, jnp.zeros((n,), jnp.int32))
    return jnp.where(ok, rank, n)


def crowding_distance(norm_scores, rank, valid=None):
    """NSGA-II crowding distance within each front (higher = lonelier).

    Per objective the values are min-max normalized over live rows,
    then each row's gap to its two same-front neighbors is summed;
    front-boundary rows (and invalid rows) are ``inf``. One
    ``argsort`` per objective on a composite (front, value) key keeps
    fronts contiguous without data-dependent shapes.
    """
    n, m = norm_scores.shape
    ok = _ok_mask(norm_scores, valid)
    s = norm_scores.astype(jnp.float32)
    rr = rank.astype(jnp.float32)
    lo = jnp.min(jnp.where(ok[:, None], s, jnp.inf), axis=0)
    hi = jnp.max(jnp.where(ok[:, None], s, -jnp.inf), axis=0)
    span = jnp.maximum(hi - lo, 1e-12)
    d = jnp.zeros((n,), jnp.float32)
    for j in range(m):  # m is static
        vn = jnp.where(ok, (s[:, j] - lo[j]) / span[j], 0.0)
        order = jnp.argsort(rr * 2.0 + vn)  # vn ∈ [0,1] < front stride 2
        r_s = rank[order]
        v_s = vn[order]
        prev_same = jnp.concatenate(
            [jnp.zeros((1,), bool), r_s[1:] == r_s[:-1]]
        )
        next_same = jnp.concatenate(
            [r_s[:-1] == r_s[1:], jnp.zeros((1,), bool)]
        )
        prev_v = jnp.concatenate([v_s[:1], v_s[:-1]])
        next_v = jnp.concatenate([v_s[1:], v_s[-1:]])
        gap = jnp.where(prev_same & next_same, next_v - prev_v, jnp.inf)
        d = d + jnp.zeros((n,), jnp.float32).at[order].set(gap)
    return jnp.where(ok, d, jnp.inf)


def _violation(norm_scores, norm_bounds):
    """Summed scale-normalized constraint violation per row (0 when
    feasible). Unconstrained objectives carry ``-inf`` bounds and
    contribute nothing."""
    b = jnp.asarray(norm_bounds, jnp.float32)
    s = norm_scores.astype(jnp.float32)
    scale = jnp.maximum(jnp.abs(b), 1.0)
    per = jnp.where(
        jnp.isfinite(b)[None, :],
        jnp.maximum(b[None, :] - s, 0.0) / scale[None, :],
        0.0,
    )
    return jnp.sum(per, axis=-1)


def pareto_score(norm_scores, valid=None, norm_bounds=None):
    """The effective selection scalar (see module docstring).

    Descending order of the result is the multi-objective preference
    order: feasible fronts first (crowding breaks ties inside a
    front), then infeasible-but-finite rows by least violation — the
    typed degradation when nothing is feasible yet — then ``-inf``
    for diverged rows.
    """
    n = norm_scores.shape[0]
    ok = _ok_mask(norm_scores, valid)
    if norm_bounds is None:
        feasible = ok
        violation = jnp.zeros((n,), jnp.float32)
    else:
        b = jnp.asarray(norm_bounds, jnp.float32)
        sane = jnp.where(ok[:, None], norm_scores.astype(jnp.float32), -jnp.inf)
        feasible = ok & jnp.all(
            jnp.where(jnp.isfinite(b)[None, :], sane >= b[None, :], True),
            axis=-1,
        )
        violation = _violation(sane, b)
    rank = pareto_rank(norm_scores, valid=feasible)
    crowd = crowding_distance(norm_scores, rank, valid=feasible)
    squash = jnp.where(jnp.isfinite(crowd), crowd / (1.0 + crowd), 1.0)
    eff_feasible = -rank.astype(jnp.float32) + 0.5 * squash
    # every feasible eff > -n; infeasible strictly below, by violation
    eff_infeasible = -(n + 1.0) - violation
    return jnp.where(
        feasible, eff_feasible, jnp.where(ok, eff_infeasible, -jnp.inf)
    )


# -- host side (report / corpus / winner picks) ---------------------------


def pareto_front_mask(norm_scores, valid=None) -> np.ndarray:
    """Boolean mask of non-dominated rows (host numpy; rows non-finite
    in any objective are never on the front)."""
    s = np.asarray(norm_scores, dtype=np.float64)
    if s.ndim != 2:
        raise ValueError(f"expected [n, m] scores, got shape {s.shape}")
    ok = np.all(np.isfinite(s), axis=-1)
    if valid is not None:
        ok = ok & np.asarray(valid, dtype=bool)
    masked = np.where(ok[:, None], s, -np.inf)
    ge = np.all(masked[:, None, :] >= masked[None, :, :], axis=-1)
    gt = np.any(masked[:, None, :] > masked[None, :, :], axis=-1)
    dom = ge & gt & ok[:, None] & ok[None, :]
    return ok & ~np.any(dom, axis=0)


def hypervolume(front, ref=None) -> float:
    """Exact hypervolume of a maximize-form front (recursive slicing).

    ``ref`` defaults to the per-objective minimum over the (finite)
    front — deterministic, so the same front always reports the same
    volume; boundary points then contribute zero in the dimension they
    anchor, which is the usual convention for a self-referenced front.
    """
    pts = np.asarray(front, dtype=np.float64)
    if pts.size == 0:
        return 0.0
    if pts.ndim != 2:
        raise ValueError(f"expected [n, m] front, got shape {pts.shape}")
    pts = pts[np.all(np.isfinite(pts), axis=-1)]
    if len(pts) == 0:
        return 0.0
    ref = pts.min(axis=0) if ref is None else np.asarray(ref, dtype=np.float64)
    pts = np.maximum(pts, ref)

    def _hv(p: np.ndarray, r: np.ndarray) -> float:
        if len(p) == 0:
            return 0.0
        if r.shape[0] == 1:
            return float(max(0.0, p[:, 0].max() - r[0]))
        p = p[np.argsort(-p[:, 0], kind="stable")]
        vol = 0.0
        for i in range(len(p)):
            right = p[i + 1, 0] if i + 1 < len(p) else r[0]
            width = p[i, 0] - right
            if width > 0.0:
                vol += width * _hv(p[: i + 1, 1:], r[1:])
        return vol

    return _hv(pts, ref)


def select_best(scores, spec) -> dict:
    """Constraint-aware winner pick over raw ``[n, m]`` scores (host).

    Typed result: ``kind`` is ``"feasible"`` (best normalized-primary
    among feasible rows), ``"least_violation"`` (nothing feasible yet —
    degrade to the least-violating finite row, primary breaks ties), or
    ``"diverged"`` (no finite row at all; ``index`` is None).
    """
    raw = np.asarray(scores, dtype=np.float64)
    norm = np.asarray(spec.normalize(raw), dtype=np.float64)
    primary = np.asarray(spec.scalarize(raw), dtype=np.float64)
    ok = np.all(np.isfinite(norm), axis=-1)
    if not np.any(ok):
        return {"index": None, "kind": "diverged", "violation": None}
    b = spec.norm_bounds()
    sane = np.where(ok[:, None], norm, -np.inf)
    feasible = ok & np.all(
        np.where(np.isfinite(b)[None, :], sane >= b[None, :], True), axis=-1
    )
    if np.any(feasible):
        idx = int(np.argmax(np.where(feasible, primary, -np.inf)))
        return {"index": idx, "kind": "feasible", "violation": 0.0}
    scale = np.maximum(np.abs(b), 1.0)
    per = np.where(
        np.isfinite(b)[None, :],
        np.maximum(b[None, :] - sane, 0.0) / scale[None, :],
        0.0,
    )
    viol = np.where(ok, per.sum(axis=-1), np.inf)
    # least violation wins; primary breaks exact ties deterministically
    best_v = viol.min()
    tied = ok & np.isclose(viol, best_v, rtol=0.0, atol=0.0)
    idx = int(np.argmax(np.where(tied, primary, -np.inf)))
    return {"index": idx, "kind": "least_violation", "violation": float(viol[idx])}
