"""Trial bookkeeping shared by the host-side driver and backends.

In the reference, a "trial" is the unit of work sent from the Coordinator
to an MPIWorker rank (SURVEY.md §1; reference unreadable). Here a Trial
is a host-side record; on the TPU backend an entire population of trials
lives on-device as one unit-cube matrix and these records are only the
host-visible ledger.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Any, Optional

import numpy as np


class TrialStatus(enum.Enum):
    PENDING = "pending"
    RUNNING = "running"
    PAUSED = "paused"  # ASHA: waiting at a rung for promotion decision
    STOPPED = "stopped"  # early-stopped (ASHA cut / PBT replaced)
    DONE = "done"
    FAILED = "failed"  # evaluation raised/hung/diverged; never a best() pick


@dataclasses.dataclass
class Trial:
    trial_id: int
    params: dict[str, Any]  # typed values (host view)
    unit: np.ndarray  # unit-cube row, the canonical representation
    budget: int = 0  # steps/epochs granted so far (ASHA rung budget)
    rung: int = 0  # current ASHA rung
    status: TrialStatus = TrialStatus.PENDING
    score: Optional[float] = None  # best/latest objective value
    history: list = dataclasses.field(default_factory=list)
    created_at: float = dataclasses.field(default_factory=time.time)
    error: Optional[str] = None  # last failure message (status FAILED)

    def record(self, score: float, step: int) -> None:
        self.score = float(score)
        self.history.append((int(step), float(score)))


@dataclasses.dataclass
class TrialResult:
    """One evaluation outcome.

    ``status`` is the per-trial failure contract shared by every
    backend: ``"ok"`` (score is meaningful), ``"failed"`` (evaluation
    raised, or the score came back non-finite), or ``"timeout"`` (the
    evaluation exceeded the backend's per-trial deadline and was
    reaped). Non-ok results carry a NaN/non-finite ``score`` plus a
    human-readable ``error``, so every existing isfinite gate
    (``best_finite``, BOHB's ObsStore) also holds without consulting
    ``status``.
    """

    trial_id: int
    score: float
    step: int
    wall_time: float = 0.0
    extra: dict = dataclasses.field(default_factory=dict)
    status: str = "ok"  # "ok" | "failed" | "timeout"
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.status == "ok"


def failed_result(
    trial_id: int,
    step: int,
    error: str,
    status: str = "failed",
    score: float = float("nan"),
    wall_time: float = 0.0,
) -> TrialResult:
    """The one construction point for non-ok results, so every backend
    reports failures with the same shape (NaN-family score + status +
    error) and the driver/algorithm handling cannot drift per backend."""
    if status not in ("failed", "timeout"):
        raise ValueError(f"failure status must be failed|timeout, got {status!r}")
    # a non-finite score (the diverged value itself) is kept as the flag;
    # a finite one is forced to NaN so no failed result can ever win an
    # isfinite-gated comparison
    score = float(score)
    if np.isfinite(score):
        score = float("nan")
    return TrialResult(
        trial_id=trial_id,
        score=score,
        step=step,
        wall_time=wall_time,
        status=status,
        error=str(error)[:500],
    )
