"""Trial bookkeeping shared by the host-side driver and backends.

In the reference, a "trial" is the unit of work sent from the Coordinator
to an MPIWorker rank (SURVEY.md §1; reference unreadable). Here a Trial
is a host-side record; on the TPU backend an entire population of trials
lives on-device as one unit-cube matrix and these records are only the
host-visible ledger.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Any, Optional

import numpy as np


class TrialStatus(enum.Enum):
    PENDING = "pending"
    RUNNING = "running"
    PAUSED = "paused"  # ASHA: waiting at a rung for promotion decision
    STOPPED = "stopped"  # early-stopped (ASHA cut / PBT replaced)
    DONE = "done"


@dataclasses.dataclass
class Trial:
    trial_id: int
    params: dict[str, Any]  # typed values (host view)
    unit: np.ndarray  # unit-cube row, the canonical representation
    budget: int = 0  # steps/epochs granted so far (ASHA rung budget)
    rung: int = 0  # current ASHA rung
    status: TrialStatus = TrialStatus.PENDING
    score: Optional[float] = None  # best/latest objective value
    history: list = dataclasses.field(default_factory=list)
    created_at: float = dataclasses.field(default_factory=time.time)

    def record(self, score: float, step: int) -> None:
        self.score = float(score)
        self.history.append((int(step), float(score)))


@dataclasses.dataclass
class TrialResult:
    trial_id: int
    score: float
    step: int
    wall_time: float = 0.0
    extra: dict = dataclasses.field(default_factory=dict)
