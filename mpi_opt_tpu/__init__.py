"""mpi_opt_tpu — a TPU-native hyperparameter-optimization framework.

A from-scratch re-design of the capabilities of ``quantummind/mpi_opt``
(an MPI coordinator/worker HPO framework; see SURVEY.md — the reference
mount was empty at survey time, so the capability surface is taken from
BASELINE.json) built TPU-first:

- trial evaluation is a single vmapped population kernel
  ``jax.jit(jax.vmap(train_step))`` over a population axis, instead of
  per-rank MPI workers;
- PBT exploit/explore and ASHA rung reductions are ``lax.top_k`` /
  gathers executed on-device, instead of ``MPI_Allgather`` + per-rank
  decisions;
- scaling is a ``jax.sharding.Mesh(('pop', 'data'))`` with XLA
  collectives over ICI/DCN, instead of MPI process blocks.

Public surface:
    SearchSpace, Domain subclasses      — mpi_opt_tpu.space
    Trial records                       — mpi_opt_tpu.trial
    decision kernels (asha, pbt, tpe)   — mpi_opt_tpu.ops
    algorithms / backends / driver / CLI — see README; added incrementally
"""

__version__ = "0.1.0"

from mpi_opt_tpu.space import (
    SearchSpace,
    Uniform,
    LogUniform,
    IntUniform,
    Choice,
)
from mpi_opt_tpu.trial import Trial, TrialResult, TrialStatus

__all__ = [
    "SearchSpace",
    "Uniform",
    "LogUniform",
    "IntUniform",
    "Choice",
    "Trial",
    "TrialResult",
    "TrialStatus",
    "__version__",
]
