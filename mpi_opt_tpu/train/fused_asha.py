"""Fused successive halving: ASHA's rung reductions on-device.

Reference behavior being replaced (SURVEY.md §2 row 4; BASELINE.json
north_star: "ASHA rung reductions become lax.top_k over a device mesh
instead of MPI_Allgather"): the reference promotes trials through budget
rungs asynchronously because its workers are independent MPI ranks and
waiting for a rung to fill would idle them. On a TPU the whole cohort
trains in lockstep as one vmapped population, so the *synchronous*
variant (successive halving) is the natural execution: train every
member to the rung budget, evaluate, cut to the top 1/eta with
``ops.asha.asha_cut``, gather the survivors into a smaller population,
continue. Stragglers don't exist — every member advances in the same
XLA program — which is exactly why the async relaxation isn't needed.

Per rung there is ONE host sync (the cut indices come back to update the
tiny trial ledger); population shapes shrink eta-fold per rung, so a
sweep compiles at most len(rungs) train/eval program pairs, all cached
across sweeps.

The cut itself (`_cut_and_gather`) is a jitted kernel: ``asha_cut``
ranks the cohort, the top-k slice of its descending order picks the
survivors, and the same index vector gathers member states — the MPI
Allgather + per-rank promotion decisions + state re-dispatch of the
reference collapse into one on-device top-k + gather.
"""

from __future__ import annotations

import functools
import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from mpi_opt_tpu.obs import memory, trace
from mpi_opt_tpu.ops.asha import asha_cut, asha_cut_mo, asha_rungs
from mpi_opt_tpu.train.common import (
    eval_population_objectives,
    finite_winner,
    journal_boundary,
    journal_require_prefix,
    launch_boundary,
    make_fused_journal,
    momentum_dtype_str,
    segment_flops_hint,
    workload_arrays,
)

# the shared fault-tolerant wave executor (train/engine.py): wave
# scheduling, host-pool staging, OOM backoff, drain/heartbeat — this
# module supplies only SHA's boundary op (the rung cut). The private
# ``_run_wave`` alias is this module's chaos-drill seam, mirroring
# fused_pbt's.
from mpi_opt_tpu.train.engine import (
    WaveRunner,
    boundary_span,
    resolve_wave_size,
)
from mpi_opt_tpu.train.engine import run_wave as _run_wave
from mpi_opt_tpu.train.population import PopState
from mpi_opt_tpu.utils import profiling


@functools.partial(jax.jit, static_argnames=("trainer", "eta", "k"))
def _cut_and_gather(trainer, state, unit, scores, eta: int, k: int):
    """One rung reduction: rank, keep the top k, gather their states.

    ``k`` is static (rung cohort sizes are known ahead of time), so the
    survivor population has a fixed shape for the next rung's program.
    Returns (survivor_state, survivor_unit, keep_idx, promote_mask).
    """
    promote, order = asha_cut(scores, eta)
    keep = order[:k]
    return trainer.gather_members(state, keep), unit[keep], keep, promote


@functools.partial(jax.jit, static_argnames=("trainer", "eta", "k"))
def _cut_and_gather_mo(trainer, state, unit, norm_scores, eta: int, k: int, norm_bounds=None):
    """The rung reduction's multi-objective twin (ISSUE 17): rank by
    ``pareto_score`` (front index, crowding tie-break, constraint
    degradation) instead of the raw scalar, then keep/gather exactly as
    the scalar cut does — the Pareto selection stays inside the same
    compiled boundary program, no extra host round-trip."""
    promote, order, _eff = asha_cut_mo(norm_scores, eta, norm_bounds=norm_bounds)
    keep = order[:k]
    return trainer.gather_members(state, keep), unit[keep], keep, promote


@functools.partial(jax.jit, static_argnames=("eta", "k"))
def _wave_cut(unit, scores, eta: int, k: int):
    """The rung cut for wave-scheduled cohorts: rank + keep exactly as
    ``_cut_and_gather`` does, minus the on-device state gather — the
    survivor-weight copy is realized LAZILY by the next rung's stage-in
    indexing the host pool with ``keep`` (train/staging.py; the
    ``fused_pbt._wave_exploit`` precedent: a separate-jit boundary op
    preserves CPU bit-identity with the fused one). Returns
    (survivor_unit, keep_idx)."""
    _promote, order = asha_cut(scores, eta)
    keep = order[:k]
    return unit[keep], keep


def sha_cohort_sizes(n_trials: int, n_rungs: int, eta: int, round_to: int = 1) -> list[int]:
    """Population size at each rung: n, ceil(n/eta), ... (>=1).

    ``round_to`` rounds survivor counts up to a multiple (a sharded
    population must stay divisible by the mesh's 'pop' axis).
    """
    sizes = [n_trials]
    for _ in range(n_rungs - 1):
        k = -(-sizes[-1] // eta)  # ceil
        k = min(sizes[-1], -(-k // round_to) * round_to)
        sizes.append(max(k, 1))
    return sizes


def fused_sha(  # sweeplint: barrier(rung host loop: gathers cohort scores for the rung cut + journal)
    workload,
    n_trials: int,
    min_budget: int = 10,
    max_budget: int = 270,
    eta: int = 3,
    seed: int = 0,
    member_chunk: int = 0,
    mesh=None,
    round_to: int = 1,
    checkpoint_dir: str = None,
    init_unit=None,
    ledger=None,
    boundary_offset: int = 0,
    trial_offset: int = 0,
    member_offset: int = 0,
    warm_obs=None,
    objectives=None,
    wave_size=0,
    oom_backoff: int = 2,
):
    """Run a whole successive-halving sweep with on-device rung cuts.

    ``wave_size`` (int or ``'auto'``; the carried PR-4 follow-up, via
    the shared engine) schedules each RUNG's cohort as resident waves
    through a host pool when it exceeds device residency — per-rung
    re-cohorting: every rung gets a fresh pool sized to its (shrinking)
    cohort, and the cut's survivor gather is realized by the next
    rung's stage-in permutation. Bit-identical to resident mode for any
    wave size on the CPU backend (tested): hparams are mapped eagerly
    over the FULL cohort exactly as the resident rung does (then sliced
    per wave — slicing is exact), member/batch RNG windows the full
    split, init keys slice the same ``split(k_init, n)``, and the cut
    sees the same (scores, eta, k). ``oom_backoff`` extends the PBT
    wave-halving contract to rungs: a device OOM during a rung's waves
    halves the cap and re-runs THAT rung from wave 0, bit-identically.

    ``ledger`` journals one record per surviving trial per rung —
    pre-cut score at the rung's budget, the trial's unit params —
    BEFORE the rung's snapshot (ledger/fused.py); the three offsets
    place this sweep's boundaries/records/trial identities inside a
    composite journal (fused hyperband/BOHB give each bracket its
    global offsets). ``warm_obs`` (prior-ledger observations,
    cross-mode) seeds cohort row 0 with the prior best point — ignored
    when the caller supplies ``init_unit`` (model-based callers own
    their cohorts).

    Returns a dict with the best trial's score/params, per-rung sizes
    and budgets, and a per-trial ledger (stop rung + last score).

    ``init_unit`` (optional float[n_trials, dim] in the unit cube)
    replaces the uniform initial cohort — fused BOHB passes
    model-sampled configurations here. The checkpoint config records a
    digest of it, so a resume under different initial configurations is
    refused (deterministic callers like fused_bohb regenerate the same
    matrix, so their resumes still match).

    ``checkpoint_dir`` makes the sweep crash-recoverable at RUNG
    granularity (same failure model as fused_pbt's launch snapshots):
    after each rung's cut the surviving cohort (state, unit, RNG key)
    and the trial ledger are orbax-saved; a fresh call with the same
    arguments resumes at the next rung and — the key being part of the
    snapshot — produces the IDENTICAL result of an uninterrupted run.
    A config-mismatched checkpoint raises ValueError.

    ``objectives`` (an ``ObjectiveSpec``, ISSUE 17) turns every rung cut
    multi-objective: each rung evaluates the spec's metrics, cuts by
    ``pareto_score`` inside the compiled boundary op, and journals the
    scalarized primary score (authoritative) plus the raw objective
    vector per record. The scalar path is untouched.
    """
    from mpi_opt_tpu.parallel.mesh import fetch_global, place_pop, shard_popstate
    from mpi_opt_tpu.train.staging import population_pool, write_rows

    trainer, space, train_x, train_y, val_x, val_y = workload_arrays(
        workload, member_chunk, mesh
    )
    # wave scheduling (cohort > residency): the shared engine door
    # resolves ``auto``, pre-clamps explicit caps, refuses multi-process
    # (train/engine.py). A cap at or above the first rung's cohort means
    # everything fits — resident mode, the bit-identical baseline.
    wave_size = resolve_wave_size(
        trainer,
        train_x[:2],
        n_trials,
        wave_size=wave_size,
        mesh=mesh,
        oom_backoff=oom_backoff,
    )
    waves = 0 < wave_size < n_trials
    if waves and objectives is not None:
        raise ValueError(
            "wave scheduling is not supported with multi-objective "
            "sweeps yet; run resident (wave_size=0) or shard the "
            "cohort over a mesh"
        )
    norm_bounds = None
    if objectives is not None:
        supported = tuple(workload.objective_metrics())
        missing = [n for n in objectives.names if n not in supported]
        if missing:
            raise ValueError(
                f"workload {getattr(workload, 'name', type(workload).__name__)!r} "
                f"cannot evaluate objectives {missing}; supported: {supported}"
            )
        if objectives.has_bounds:
            norm_bounds = objectives.norm_bounds()
    rungs = asha_rungs(min_budget, max_budget, eta)
    if mesh is not None and round_to == 1:
        round_to = mesh.shape["pop"]
    sizes = sha_cohort_sizes(n_trials, len(rungs), eta, round_to)

    if init_unit is not None:
        init_unit = np.asarray(init_unit, dtype=np.float32)
        if init_unit.shape != (n_trials, space.dim):
            raise ValueError(
                f"init_unit shape {init_unit.shape} != ({n_trials}, {space.dim})"
            )

    key = jax.random.key(seed)
    k_init, k_unit, k_run = jax.random.split(key, 3)

    # host ledger: which original trial occupies each population row
    alive = np.arange(n_trials)
    stop_rung = np.zeros(n_trials, dtype=np.int32)
    last_score = np.full(n_trials, np.nan, dtype=np.float32)
    # every (trial, budget, score) observation, one entry per rung —
    # model-based callers (fused BOHB) consume ALL of a trial's scores,
    # not just the one at its stop rung
    rung_history: list = []

    # restore BEFORE initializing: a resumed sweep must not pay (or
    # transiently hold the memory of) a full-cohort init it discards
    snap = None
    restored = None
    start_rung = 0
    scores = None
    if checkpoint_dir is not None:
        from mpi_opt_tpu.utils.checkpoint import SweepCheckpointer

        ck_config = {
            "workload": getattr(workload, "name", type(workload).__name__),
            "n_trials": n_trials,
            "rungs": rungs,
            "sizes": sizes,
            "eta": eta,
            "seed": seed,
            "member_chunk": member_chunk,
            # carried-state structure (see fused_pbt): a resumed rung
            # must find momentum in the dtype it was saved with
            "momentum_dtype": momentum_dtype_str(),
            # the initial cohort defines the sweep: a resume whose
            # caller supplies different configurations is a
            # different search and must be refused
            "init_unit_digest": (
                None
                if init_unit is None
                else hashlib.sha1(init_unit.tobytes()).hexdigest()
            ),
        }
        if waves:
            # the wave split is part of a wave-scheduled sweep's
            # identity: its snapshots resume through host pools. Resident
            # configs deliberately DON'T write the key, so every
            # pre-existing SHA snapshot keeps resuming via the
            # ``setdefault(0)`` back-compat (utils/checkpoint.py) — and
            # a wave resume of a resident snapshot refuses cleanly
            # (0 != cap) instead of crashing in pool reconstruction.
            # The REQUESTED (resolved) cap, as in fused_pbt: an OOM
            # backoff's smaller execution cap lives in meta
            # (wave_size_run) and is adopted on resume below
            ck_config["wave_size"] = wave_size
        if objectives is not None:
            # objective identity shapes every cut (see fused_pbt); the
            # key is absent on scalar sweeps so pre-existing snapshots
            # keep resuming
            ck_config["objectives"] = objectives.spec()
        snap = SweepCheckpointer(checkpoint_dir, ck_config)
        restored = snap.restore_population_sweep()
        if restored is not None:
            state, unit, k_run, scores, meta = restored
            alive = np.asarray(meta["alive"], dtype=np.int64)
            stop_rung = np.asarray(meta["stop_rung"], dtype=np.int32)
            last_score = np.asarray(meta["last_score"], dtype=np.float32)
            start_rung = int(meta["rungs_done"])
            # pre-upgrade snapshots have no history; completed rungs'
            # stop-rung observations are still in last_score, so the
            # history is marked partial rather than fabricated
            rung_history = list(meta.get("rung_history", []))
            if waves:
                # adopt a prior attempt's OOM-settled execution cap
                # (meta wave_size_run): resuming at the requested size
                # would re-OOM a rung just to re-learn the answer
                run_wave_size = int(meta.get("wave_size_run", wave_size))
                # the snapshot's survivor cohort becomes the next rung's
                # host pool; its rows are already in cohort order, so
                # the stage-in permutation starts as the identity
                pool_front = {
                    "params": jax.tree.map(np.asarray, state.params),
                    "momentum": jax.tree.map(np.asarray, state.momentum),
                    "step": np.asarray(state.step),
                }
                perm = np.arange(len(alive))
                state = None
    journal = make_fused_journal(
        ledger,
        space,
        boundary_offset=boundary_offset,
        trial_offset=trial_offset,
        member_offset=member_offset,
    )
    journal_require_prefix(journal, start_rung)
    if restored is None:
        if init_unit is not None:
            unit = jax.numpy.asarray(init_unit)
        else:
            unit = space.sample_unit(k_unit, n_trials)
            if warm_obs:
                from mpi_opt_tpu.ledger.warmstart import best_observation

                bo = best_observation(warm_obs)
                if bo is not None:
                    # sampler-family warm start (mirrors driver ASHA's
                    # seeded first suggestion): one cohort row starts at
                    # the prior best; the rung cuts keep it only if it
                    # earns survival
                    unit = np.array(unit)
                    unit[0] = np.asarray(bo.unit, dtype=unit.dtype)
                    unit = jax.numpy.asarray(unit)
        if waves:
            # rung-0 members initialize on device per wave, windows of
            # the SAME ``split(k_init, n)`` the resident
            # ``init_population`` derives — weights are bit-identical
            member_keys = jax.random.split(k_init, n_trials)
            pool_front = None
            perm = np.arange(n_trials)
            state = None
        else:
            state = trainer.init_population(k_init, train_x[:2], n_trials)
    if mesh is not None:
        # datasets were already replicated over the mesh by workload_arrays
        if not waves:
            state = shard_popstate(state, mesh)
        unit = place_pop(unit, mesh)

    def record_rung(r: int, np_scores_r) -> None:
        """Ledger update for one rung's PRE-cut cohort — the single
        source for both the eager (checkpointed) and deferred-replay
        paths, which must produce identical result ledgers."""
        stop_rung[alive] = r
        last_score[alive] = np_scores_r
        rung_history.append(
            {
                "budget": int(rungs[r]),
                "trials": [int(i) for i in alive],
                "scores": [float(v) for v in np_scores_r],
            }
        )

    # Uncheckpointed sweeps DEFER every host fetch to one barrier after
    # the last rung: the per-rung score/keep values feed only the host
    # ledger (consumed after the sweep), so the rung programs can
    # dispatch back-to-back — the wall becomes device time instead of
    # launch + round-trip per rung (the tunnel charges 20-90 ms per
    # blocking fetch; a 4-rung config-2 sweep paid ~7 of them).
    # Checkpointed sweeps keep the per-rung fetch: each snapshot needs
    # host copies of the ledger at that rung. A fused JOURNAL forces the
    # eager path too: its records must be fsync-durable per rung (the
    # journal-before-snapshot ordering), which deferral would break.
    # Wave scheduling is eager by construction: every rung's scores land
    # on host through the staging writers.
    defer = snap is None and journal is None and not waves
    runner = None
    if waves:
        # the shared wave executor (train/engine.py) owns the staging
        # engine, the execution cap, and the OOM-backoff retry; the rung
        # loop below supplies SHA's shapes and boundary op. Starts at
        # the snapshot-adopted cap when resuming past a backoff.
        runner = WaveRunner(
            n_trials,
            run_wave_size if restored is not None else wave_size,
            oom_backoff=oom_backoff,
        )
    rung_scores_dev: list = []  # device scores per rung (pre-cut rows)
    rung_keep_dev: list = []  # device survivor indices per cut
    rung_mo_dev: list = []  # device [n, m] objective matrices (MO only)
    np_final_mo = None  # last rung's raw objective matrix (MO only)
    try:
        for r in range(start_rung, len(rungs)):
            budget = rungs[r]
            prev_budget = rungs[r - 1] if r > 0 else 0
            k_run, k_seg = jax.random.split(k_run)
            profiling.launch_tick()
            # eager mode's score fetch is the rung's completion barrier,
            # so the span's duration is real and carries flops for
            # achieved TF/s; deferred mode dispatches async (the span
            # measures dispatch — no flops attr, TF/s would be bogus)
            # hint probed OUTSIDE the span (its one-time cost must not
            # inflate the first rung's measured duration)...
            f = None if defer else segment_flops_hint(
                workload, sizes[r], budget - prev_budget
            )
            if waves:
                n_r = sizes[r]
                # EAGER unit->hparams mapping over the FULL cohort — the
                # resident rung maps eagerly before train_segment, so
                # the wave path must hand the programs the SAME values
                # (sliced per wave inside run_wave; slicing is exact) to
                # be bit-identical to it. This is NOT the PBT/TPE rule
                # (their resident programs map in-scan): each wave path
                # mirrors ITS resident twin.
                hp = workload.make_hparams(space.from_unit(unit))
                # per-rung re-cohorting: a fresh pool sized to THIS
                # rung's (shrinking) cohort; the previous rung's pool is
                # read through the cut's survivor permutation
                pool_back = population_pool(trainer, train_x[:2], n_r)
                scores_host = np.full((n_r,), np.nan, np.float32)

                def _writer(off, pool_back=pool_back, scores_host=scores_host):
                    def on_host(host):  # sweeplint: barrier(stage-out landing: writes fetched wave states + scores into the rung pool)
                        write_rows(pool_back, off, host["state"])
                        w_ = len(host["scores"])
                        scores_host[off : off + w_] = np.asarray(
                            host["scores"], np.float32
                        )

                    return on_host

                def _dispatch(
                    w, off, wl_, eng, r=r, k_seg=k_seg, hp=hp, n_r=n_r,
                    pool_front=pool_front, perm=perm,
                    budget=budget, prev_budget=prev_budget,
                ):
                    # ``_run_wave`` resolved at call time (module
                    # global) so the chaos drills' monkeypatch seam
                    # keeps working
                    return _run_wave(
                        trainer,
                        pool_front,
                        perm[off : off + wl_],
                        off,
                        None,  # unit/hparams_fn unused: hp mode
                        None,
                        train_x,
                        train_y,
                        val_x,
                        val_y,
                        k_seg,
                        budget - prev_budget,
                        n_r,
                        mesh,
                        eng,
                        init_keys=member_keys[off : off + wl_] if r == 0 else None,
                        sample_x=train_x[:2],
                        hp=hp,
                    )

                def _payload(st, sc):
                    return {
                        "state": {
                            "params": st.params,
                            "momentum": st.momentum,
                            "step": st.step,
                        },
                        "scores": sc,
                    }

                wave_scores = runner.run_interval(
                    n=n_r,
                    run_wave_fn=_dispatch,
                    payload_fn=_payload,
                    writer_fn=_writer,
                    scores_host=scores_host,
                    stage_label=lambda w, nw, r=r: (
                        f"sha rung {r + 1}/{len(rungs)} wave {w + 1}/{nw}"
                    ),
                    boundary_kwargs=lambda w, nw, r=r: {
                        "rung": r + 1,
                        "of": len(rungs),
                    },
                    # no mid-rung snapshots: SHA snapshots at rung
                    # granularity (a resume re-trains the interrupted
                    # rung; the journal verifies instead of re-writing)
                    midpoint_snapshot=None,
                    span_attrs=lambda nw, r=r, n_r=n_r: {
                        "launch": boundary_offset + r + 1,
                        "rung": r + 1,
                        "members": n_r,
                        "steps": budget - prev_budget,
                        "waves": nw,
                    },
                    flops=f,
                    notify_fields=(("rung", r + 1),),
                )
                mo = None
                np_mo = None
                # same device/host score pair the resident path holds:
                # the concat feeds the cut, the landed host copy feeds
                # the ledger (f32 round-trips exactly)
                scores = jnp.concatenate([jnp.asarray(s) for s in wave_scores])
                np_scores = scores_host.copy()
                record_rung(r, np_scores)
                if journal is not None:
                    journal_boundary(
                        journal, r, alive, fetch_global(unit), np_scores,
                        step=budget,
                    )
                # fall through to the shared rung cut below
            else:
                with trace.span(
                    "train",
                    launch=boundary_offset + r + 1,
                    rung=r + 1,
                    members=sizes[r],
                    steps=budget - prev_budget,
                ) as sp:
                    if objectives is not None:
                        # registered span attr: MO rungs are visible in
                        # the trace; the cut still runs on-device (no
                        # new sync)
                        sp["objectives"] = ",".join(objectives.names)
                    hp = workload.make_hparams(space.from_unit(unit))
                    state, _ = trainer.train_segment(
                        state, hp, train_x, train_y, k_seg, budget - prev_budget
                    )
                    if objectives is None:
                        mo = None
                        scores = trainer.eval_population(state, val_x, val_y)
                    else:
                        # each metric call is its own jitted program, so
                        # the dispatches stay async — the rung still
                        # pays at most the one host fetch the eager path
                        # always paid
                        mo = eval_population_objectives(
                            trainer, state, val_x, val_y, objectives.names
                        )
                        scores = objectives.scalarize(mo)
                    if defer:
                        rung_scores_dev.append(scores)
                        if mo is not None:
                            rung_mo_dev.append(mo)
                    else:
                        np_scores = fetch_global(scores)
                        # ...and attached only AFTER the fetch barrier:
                        # a rung that raised mid-span must not report
                        # full-rung FLOPs over a partial duration
                        if f:
                            sp["flops"] = f
                        # post-barrier device-memory watermark: the
                        # rung's cohort + activations just peaked
                        memory.note(sp)
                if not defer:
                    np_mo = None if mo is None else fetch_global(mo)
                    np_final_mo = np_mo if np_mo is not None else np_final_mo
                    record_rung(r, np_scores)
                    if journal is not None:
                        # one member record per PRE-cut survivor at this
                        # rung's budget, before the rung snapshot below
                        journal_boundary(
                            journal, r, alive, fetch_global(unit), np_scores,
                            step=budget, scores_mo=np_mo,
                        )
            if r < len(rungs) - 1:
                # boundary_span (train/engine.py): heartbeats from
                # inside the op, so a stall DURING the cut is attributed
                # to "boundary:rung_cut" by launch.py's stall report
                with boundary_span("rung_cut", rung=r + 1):
                    if waves:
                        # survivor weights are NOT gathered on device:
                        # the next rung's stage-in indexes the host pool
                        # with ``keep`` (the wave path's lazy gather)
                        unit, keep = _wave_cut(unit, scores, eta, sizes[r + 1])
                        if mesh is not None:
                            unit = place_pop(unit, mesh)
                        np_keep = fetch_global(keep)
                        alive = alive[np_keep]
                        np_scores = np_scores[np_keep]
                        perm = np.asarray(np_keep)
                    elif objectives is None:
                        state, unit, keep, _ = _cut_and_gather(
                            trainer, state, unit, scores, eta, sizes[r + 1]
                        )
                    else:
                        state, unit, keep, _ = _cut_and_gather_mo(
                            trainer,
                            state,
                            unit,
                            objectives.normalize(mo),
                            eta,
                            sizes[r + 1],
                            norm_bounds=norm_bounds,
                        )
                    if not waves and mesh is not None:
                        # re-place: the gather may leave survivors
                        # unsharded/skewed
                        state = shard_popstate(state, mesh)
                        unit = place_pop(unit, mesh)
                    if defer:
                        rung_keep_dev.append(keep)
                    elif not waves:
                        np_keep = fetch_global(keep)
                        alive = alive[np_keep]
                        # post-cut survivors' scores, for a
                        # resume-at-complete result (np_scores already
                        # holds this rung's fetch — re-fetching would pay
                        # an extra cross-process allgather per rung under
                        # multi-host)
                        np_scores = np_scores[np_keep]
            if waves:
                # the trained cohort now lives in this rung's pool: it
                # becomes the next rung's stage-in source (read through
                # ``perm``, the cut's survivor map)
                pool_front = pool_back
            if snap is not None:
                save_state = state
                if waves:
                    # materialize the CURRENT cohort (post-cut survivors;
                    # the full final cohort at the last rung) from the
                    # pool — fancy indexing copies, so the async orbax
                    # write can never see later in-place pool writes
                    sel = perm if r < len(rungs) - 1 else np.arange(sizes[r])
                    save_state = PopState(
                        params=jax.tree.map(lambda l: l[sel], pool_back["params"]),
                        momentum=jax.tree.map(lambda l: l[sel], pool_back["momentum"]),
                        step=pool_back["step"][sel],
                    )
                meta_extra = {
                    "rungs_done": r + 1,
                    # ledger cross-check unit (fsck, resume gate):
                    # GLOBAL boundary count complete at this snapshot
                    "boundaries_done": boundary_offset + r + 1,
                    "alive": alive.tolist(),
                    "stop_rung": stop_rung.tolist(),
                    "last_score": [float(v) for v in last_score],
                    "rung_history": rung_history,
                }
                if waves:
                    # the OOM-settled execution cap (adopted on resume)
                    meta_extra["wave_size_run"] = runner.wave_size
                # scores saved = the CURRENT cohort rows (post-cut when cut)
                snap.save_population_sweep(
                    r + 1, save_state, unit, k_run, np_scores,
                    meta_extra=meta_extra,
                )
            # heartbeat + graceful-shutdown drain: checkpointed sweeps
            # already snapshot every rung (nothing extra to flush);
            # uncheckpointed ones have no durable state — the drain
            # still honors the preemption promptly
            launch_boundary(
                f"sha rung {r + 1}/{len(rungs)}",
                final=r + 1 == len(rungs),
                rung=r + 1,
                of=len(rungs),
            )
    finally:
        if runner is not None:
            runner.close()
        if snap is not None:
            snap.close()

    final_np_scores = None
    if defer and rung_scores_dev:
        # the single host barrier: fetch every rung's scores/cuts in one
        # batched transfer and replay the ledger updates the eager path
        # did per rung
        from mpi_opt_tpu.parallel.mesh import fetch_global_batched

        fetched = fetch_global_batched(rung_scores_dev + rung_keep_dev + rung_mo_dev)
        ns, nk = len(rung_scores_dev), len(rung_keep_dev)
        np_rung_scores = fetched[:ns]
        np_keeps = fetched[ns : ns + nk]
        if rung_mo_dev:
            np_final_mo = fetched[-1]  # last rung's objective matrix
        final_np_scores = np_rung_scores[-1]  # last rung has no cut
        for r_off, np_scores in enumerate(np_rung_scores):
            r = start_rung + r_off
            record_rung(r, np_scores)
            if r < len(rungs) - 1:
                alive = alive[np_keeps[r_off]]

    np_unit = fetch_global(unit)
    final_scores = fetch_global(scores) if final_np_scores is None else final_np_scores
    # one diverged survivor (NaN, or +/-inf from an exploded loss) must
    # not hijack the bracket's best — argmax would return the NaN/+inf
    # row. Shared rule: train.common.finite_winner; the all-diverged
    # cohort reports non-finite/None with diverged=True, so no
    # arbitrary row masquerades as a meaningful winner
    best_row, diverged = finite_winner(final_scores)
    pareto = None
    if objectives is not None and np_final_mo is not None:
        from mpi_opt_tpu.objectives import (
            hypervolume,
            pareto_front_mask,
            select_best,
        )

        # constraint-aware winner (see fused_pbt): best FEASIBLE
        # survivor, typed degradation to least-violating when nothing is
        sel = select_best(np_final_mo, objectives)
        if sel["index"] is None:
            best_row, diverged = 0, True
        else:
            best_row, diverged = int(sel["index"]), False
        norm = objectives.normalize(np_final_mo)
        mask = pareto_front_mask(norm)
        front_rows = [int(i) for i in np.flatnonzero(mask)]
        pareto = {
            "front_size": len(front_rows),
            "front_members": [int(alive[i]) for i in front_rows],
            "front_scores": [
                [float(v) for v in np_final_mo[i]] for i in front_rows
            ],
            "hypervolume": float(hypervolume(norm[mask])) if front_rows else 0.0,
            "selection": sel["kind"],
            "violation": sel["violation"],
        }
    return {
        # diverged normalizes to NaN (not a raw +/-inf row) so library
        # callers can detect it uniformly across fused SHA/PBT/TPE
        "best_score": float("nan") if diverged else float(final_scores[best_row]),
        "best_params": None if diverged else space.materialize_row(np_unit[best_row]),
        "best_trial": None if diverged else int(alive[best_row]),
        "diverged": diverged,
        "rung_budgets": rungs,
        "rung_sizes": sizes,
        "stop_rung": stop_rung,
        "last_score": last_score,
        "rung_history": rung_history,
        # per-rung diverged-member tallies (ROADMAP open item): the
        # isfinite winner pick MASKS divergence, it must not HIDE it —
        # operators need to see how many members each rung lost. From
        # rung_history, so eager and deferred paths agree by
        # construction; a pre-upgrade resume with partial history
        # reports the rungs it has
        "member_failures": [
            int(np.sum(~np.isfinite(np.asarray(rh["scores"], dtype=np.float64))))
            for rh in rung_history
        ],
        "n_trials": n_trials,
        "journal": None
        if journal is None
        else {"written": journal.written, "verified": journal.verified},
        # multi-objective extras (ISSUE 17, see fused_pbt): None on
        # scalar sweeps and on a resume that restarted past the final
        # rung (``report`` recomputes the front from the ledger then)
        "objectives": None if objectives is None else list(objectives.names),
        "pareto": pareto,
        # wave-scheduling observability (the same keys every
        # wave-scheduled driver reports — train/engine.py): settled
        # execution split, OOM halvings, staged bytes, overlap
        **({} if runner is None else runner.result_extras()),
    }


def _bracket_cohort(checkpoint_dir, b: int, n: int, tag: str, cohort_fn):  # sweeplint: barrier(bracket cohort cache: materializes suggested units to disk)
    """Sample bracket ``b``'s initial cohort — durably, when the sweep
    is checkpointed. The sampled matrix is persisted next to the
    bracket snapshots and REUSED on resume: regenerating it would
    couple resume correctness to bit-identical model-sampling replay
    across processes/JAX versions, where any numeric drift makes
    fused_sha's cohort digest permanently refuse an otherwise-valid
    checkpoint with no recovery path (ADVICE r3). The digest check
    stays as defense-in-depth — the persisted cohort always matches it.
    """
    import os

    path = None
    if checkpoint_dir:
        path = os.path.join(checkpoint_dir, f"cohort_{b}.npz")
        if os.path.exists(path):
            with np.load(path) as z:
                cohort, n_model = np.array(z["cohort"]), int(z["n_model"])
                saved_tag = str(z["tag"])
            # validated HERE, not only by fused_sha's snapshot config
            # check: a crash after the cohort write but before the first
            # rung snapshot leaves no snapshot to refuse a reused dir,
            # so the cohort file itself carries the sweep's identity
            # (workload/plan/seed tag + row count). The cohort's VALUES
            # are deliberately not part of the identity — the persisted
            # matrix IS the sweep's sampling record; model hyperparams
            # (random_fraction, TPEConfig) only shaped how it was drawn.
            if cohort.shape[0] != n or saved_tag != tag:
                raise ValueError(
                    f"persisted cohort for bracket {b} is from a different "
                    f"sweep ({cohort.shape[0]} rows, tag {saved_tag!r}; "
                    f"expected {n} rows, tag {tag!r}) — use a fresh "
                    "checkpoint dir"
                )
            return cohort, n_model
    cohort, n_model = cohort_fn(b, n)
    if path is not None:
        os.makedirs(checkpoint_dir, exist_ok=True)
        # write-then-rename: a crash mid-write must not leave a torn
        # cohort file that a resume would trust. The tmp name is
        # RANK-UNIQUE: under multi-process SPMD every rank runs this
        # host code against the SHARED checkpoint dir, and two ranks
        # sharing one tmp path race each other (one rank's os.replace
        # steals the other's half-written file; the loser's replace
        # then raises FileNotFoundError). Ranks write identical bytes
        # (the cohort is drawn by deterministic SPMD-identical host
        # code), so last-replace-wins is correct.
        tmp = f"{path}.tmp{jax.process_index()}"
        with open(tmp, "wb") as f:
            np.savez(f, cohort=cohort, n_model=n_model, tag=np.asarray(tag))
        os.replace(tmp, path)
    return cohort, n_model


def fused_hyperband(
    workload,
    max_budget: int = 270,
    eta: int = 3,
    seed: int = 0,
    member_chunk: int = 0,
    mesh=None,
    round_to: int = 1,
    checkpoint_dir: str = None,
    cohort_fn=None,
    observe_fn=None,
    ledger=None,
    warm_obs=None,
    wave_size=0,
    oom_backoff: int = 2,
):
    """Hyperband with every bracket running as a fused on-device SHA.

    ``wave_size``/``oom_backoff`` pass straight through to each
    bracket's ``fused_sha``: the cap is resolved against every
    bracket's own cohort size (a small bracket that fits resident runs
    resident), and each bracket's rungs get the engine's wave
    scheduling + OOM wave-halving (train/engine.py).

    Brackets (algorithms.hyperband.bracket_plan) execute sequentially —
    each is one ``fused_sha`` sweep, so within a bracket the whole
    cohort trains/cuts on-device; between brackets there is one host
    transition. Bracket seeds match the host-side ``Hyperband``
    algorithm's (seed + 7919*b).

    ``cohort_fn(b, n) -> (unit[n, dim], n_model)`` / ``observe_fn(b,
    cohort, res)`` are the model hooks fused BOHB plugs in (sample each
    bracket's initial configurations; feed the results back). Plain
    Hyperband is the hookless case — ONE bracket loop serves both, so
    the seed scheme, per-bracket checkpoint layout, and best-pick can
    never drift between them.

    Returns the overall best plus a per-bracket summary.

    ``checkpoint_dir`` gives each bracket its own rung-checkpointed
    subdirectory (``bracket_0``, ...): a crash resumes inside the
    interrupted bracket, and brackets already complete replay instantly
    from their final snapshot.
    """
    import os

    from mpi_opt_tpu.algorithms.base import best_finite
    from mpi_opt_tpu.algorithms.hyperband import bracket_plan

    best = None
    brackets = []
    n_total = 0
    journal_totals = {"written": 0, "verified": 0}
    # wave observability aggregated across brackets (each bracket is its
    # own fused_sha with its own resolved cap — a small bracket that
    # fits resident contributes nothing): counters sum, the reported
    # wave_size is the largest settled cap any bracket ran under
    wave_totals = {
        "wave_size": 0,
        "n_waves": 0,
        "waves_run": 0,
        "oom_backoffs": 0,
        "staged_bytes": 0,
        "stage_transfer_s": 0.0,
        "stage_wait_s": 0.0,
        "stage_overlap_s": 0.0,
    }
    any_waves = False
    # the persisted-cohort identity: workload + bracket plan + seed
    # (everything that determines which search the cohorts belong to)
    tag = (
        f"{getattr(workload, 'name', type(workload).__name__)}"
        f"|R={max_budget}|eta={eta}|seed={seed}"
    )
    plan = bracket_plan(max_budget, eta)
    # one ledger spans the brackets: each fused_sha journals under its
    # bracket's GLOBAL offsets so the whole sweep reads as one
    # contiguous boundary sequence (ledger/fused.py). The offset math
    # mirrors fused_sha's own rung/size derivation exactly.
    boundary_off = trial_off = member_off = 0
    for b, (n, r) in enumerate(plan):
        if cohort_fn is None:
            cohort, n_model = None, None
        else:
            cohort, n_model = _bracket_cohort(checkpoint_dir, b, n, tag, cohort_fn)
        res = fused_sha(
            workload,
            n_trials=n,
            min_budget=r,
            max_budget=max_budget,
            eta=eta,
            seed=seed + 7919 * b,
            member_chunk=member_chunk,
            mesh=mesh,
            round_to=round_to,
            checkpoint_dir=(
                os.path.join(checkpoint_dir, f"bracket_{b}") if checkpoint_dir else None
            ),
            init_unit=cohort,
            ledger=ledger,
            boundary_offset=boundary_off,
            trial_offset=trial_off,
            member_offset=member_off,
            # model-based callers (BOHB) own their cohorts AND their
            # prior ingestion (ObsStore); only the hookless hyperband
            # seeds bracket cohorts with the prior best
            warm_obs=warm_obs if cohort_fn is None else None,
            wave_size=wave_size,
            oom_backoff=oom_backoff,
        )
        boundary_off += len(res["rung_budgets"])
        trial_off += sum(res["rung_sizes"])
        member_off += n
        if observe_fn is not None:
            observe_fn(b, cohort, res)
        n_total += n
        if res.get("journal"):
            journal_totals["written"] += res["journal"]["written"]
            journal_totals["verified"] += res["journal"]["verified"]
        summary = {
            "bracket": b,
            "n_trials": n,
            "start_budget": r,
            "rung_sizes": res["rung_sizes"],
            "rung_budgets": res["rung_budgets"],
            # .get: minimal bracket-result stubs (tests) and any cached
            # pre-upgrade result dicts simply report no tallies
            "member_failures": res.get("member_failures", []),
            "best_score": res["best_score"],
        }
        if cohort_fn is not None:
            summary["n_model_sampled"] = n_model
        if res.get("wave_size"):
            any_waves = True
            wave_totals["wave_size"] = max(wave_totals["wave_size"], res["wave_size"])
            for k in ("n_waves", "waves_run", "oom_backoffs", "staged_bytes"):
                wave_totals[k] += res[k]
            for k in ("stage_transfer_s", "stage_wait_s", "stage_overlap_s"):
                wave_totals[k] += res[k]
            summary["wave_size"] = res["wave_size"]
            summary["n_waves"] = res["n_waves"]
            summary["oom_backoffs"] = res["oom_backoffs"]
        brackets.append(summary)
        # bracket boundary: each bracket's final rung suppresses the
        # intra-sha drain (final=True there), so the between-bracket
        # check here is what lets a preemption land between brackets —
        # completed brackets replay instantly from their snapshots
        launch_boundary(
            f"hyperband bracket {b + 1}/{len(plan)}",
            final=b + 1 == len(plan),
            bracket=b + 1,
            of=len(plan),
        )
        # diverged brackets (non-finite best_score) never stick as the
        # overall winner — the ONE best-pick rule, shared with the host
        # path (see algorithms.base.best_finite); pairwise fold keeps
        # the first bracket when everything diverged
        if best is None:
            best = res
        else:
            best = best_finite([best, res], key=lambda r: r["best_score"])
    return {
        "best_score": best["best_score"],
        "best_params": best["best_params"],
        "brackets": brackets,
        # flattened across brackets in bracket order, so the CLI summary
        # can report one per-generation-shaped list for every fused algo
        "member_failures": [
            n for s in brackets for n in s["member_failures"]
        ],
        "n_trials": n_total,
        "journal": journal_totals if ledger is not None else None,
        **(wave_totals if any_waves else {}),
    }
