"""Fused BOHB: model-based Hyperband with on-device brackets.

The bracket execution IS ``fused_hyperband`` (one shared loop: seed
scheme, per-bracket rung-checkpoint layout, NaN-safe best-pick); this
module only supplies the two model hooks — sample each bracket's
initial cohort from a TPE model, feed every rung's results back. The
sampling rules match ``algorithms/bohb.py`` (random-fraction hedge,
highest-qualified-budget, n_min gate) and the bookkeeping is the SAME
``ObsStore`` helper, so the two BOHB implementations cannot drift.

The model work is a single batched ``tpe_suggest`` call per bracket —
the vectorized acquisition scores the whole cohort's candidates at
once, where the host-driver BOHB draws one suggestion per trial.

Observation bookkeeping: fused_sha's ``rung_history`` ledger records
every cohort's scores at every rung, so a trial promoted through three
rungs contributes three observations at three budgets — the same
observation set the host algorithm's ``report_batch`` accumulates.

Crash recovery: brackets checkpoint individually (rung granularity,
``bracket_b`` subdirectories), and each bracket's sampled cohort is
PERSISTED (``cohort_b.npz``, via ``fused_hyperband``'s bracket loop)
and reused on resume — resume correctness never depends on the model
regenerating bit-identical samples across processes/JAX versions.
fused_sha's cohort digest stays as defense-in-depth.
"""

from __future__ import annotations

import jax
import numpy as np

from mpi_opt_tpu.algorithms.bohb import ObsStore, default_n_min
from mpi_opt_tpu.ops.tpe import TPEConfig, tpe_suggest
from mpi_opt_tpu.train.common import workload_arrays
from mpi_opt_tpu.train.fused_asha import fused_hyperband


def fused_bohb(  # sweeplint: barrier(bracket host loop: files rung observations into the host-side ObsStore)
    workload,
    max_budget: int = 270,
    eta: int = 3,
    seed: int = 0,
    member_chunk: int = 0,
    mesh=None,
    round_to: int = 1,
    checkpoint_dir: str = None,
    random_fraction: float = 1 / 3,
    n_min: int | None = None,
    buffer_size: int = 512,
    cfg: TPEConfig = TPEConfig(),
    ledger=None,
    warm_obs=None,
    wave_size=0,
    oom_backoff: int = 2,
):
    """Returns the overall best plus per-bracket summaries (including
    how many of each cohort came from the model vs uniform).

    ``wave_size`` / ``oom_backoff`` pass straight through to each
    bracket's ``fused_sha`` (via ``fused_hyperband``): brackets whose
    cohorts exceed the cap run their rungs as host-staged waves through
    the shared engine, with the same bit-identity and OOM-backoff
    contract — the model hooks are untouched (the cohort is sampled on
    host either way, and rung observations come from the same
    ``rung_history`` ledger).

    ``ledger`` journals every bracket's rung evaluations at member
    granularity through ``fused_hyperband``'s per-bracket offsets.
    ``warm_obs`` (prior-ledger observations, cross-mode) files into the
    same per-budget ``ObsStore`` the rung results feed — the model can
    qualify (``n_min``) before the first bracket even runs, exactly the
    driver BOHB warm-start semantic."""
    _, space, *_ = workload_arrays(workload, member_chunk, mesh)
    if n_min is None:
        n_min = default_n_min(space.dim)
    obs = ObsStore(space.dim, buffer_size, n_min)
    if warm_obs:
        for o in warm_obs:
            if np.isfinite(float(o.score)):
                obs.add(int(o.budget), np.asarray(o.unit), float(o.score))
    suggest = jax.jit(tpe_suggest, static_argnames=("n_suggest", "cfg"))

    def cohort_fn(b: int, n: int):  # sweeplint: barrier(per-bracket re-suggest: the TPE acquisition completes on host by design)
        """(initial unit matrix, model-drawn count) for bracket b: model
        draws where a budget qualifies, uniform for the random fraction
        (and always before any budget qualifies)."""
        key = jax.random.fold_in(jax.random.key(seed), 104729 + b)
        k_mask, k_rand, k_model = jax.random.split(key, 3)
        budget = obs.model_budget()
        # np.array (copy): asarray of a device array is a READ-ONLY view
        uniform = np.array(space.sample_unit(k_rand, n))
        if budget is None:
            return uniform, 0
        from_model = np.asarray(jax.random.uniform(k_mask, (n,)) >= random_fraction)
        n_model = int(from_model.sum())
        if n_model == 0:
            return uniform, 0
        s = obs.budgets[budget]
        # one batched, diversified acquisition call for the whole cohort.
        # n_suggest is STATIC under jit: requesting the deterministic
        # bracket size n (not the random n_model) keeps the compile
        # count bounded by the fixed bracket plan and cache-stable
        # across runs/resumes; the first n_model rows are used (the
        # batch is diversified, so any prefix is a valid draw set)
        from mpi_opt_tpu.train.engine import boundary_span

        # boundary_span (not a bare trace span): the beat inside it
        # attributes a stall during the acquisition to THIS op in
        # launch.py's stall report
        with boundary_span("suggest", bracket=b, n=n):
            sugg, _ = suggest(
                k_model, s["unit"], s["score"], s["valid"], n_suggest=n, cfg=cfg
            )
            cohort = uniform
            # the np.asarray conversion is the suggest's completion
            # barrier — inside the span so its duration is real
            cohort[from_model] = np.asarray(sugg)[:n_model]
        return cohort, n_model

    def observe_fn(b: int, cohort: np.ndarray, res: dict):
        # every rung's scores feed the model (ObsStore drops NaNs)
        for rung in res["rung_history"]:
            for i, sc in zip(rung["trials"], rung["scores"]):
                obs.add(rung["budget"], cohort[int(i)], float(sc))

    return fused_hyperband(
        workload,
        max_budget=max_budget,
        eta=eta,
        seed=seed,
        member_chunk=member_chunk,
        mesh=mesh,
        round_to=round_to,
        checkpoint_dir=checkpoint_dir,
        cohort_fn=cohort_fn,
        observe_fn=observe_fn,
        ledger=ledger,
        wave_size=wave_size,
        oom_backoff=oom_backoff,
        # priors already live in the ObsStore above; passing them down
        # would ALSO seed bracket cohorts (the hookless-hyperband
        # semantic) and double-count the prior
        warm_obs=None,
    )
